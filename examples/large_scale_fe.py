"""Large-d fixed-effect training through the (data x feat) grid engine.

Demonstrates the 1B-coefficient layout (docs/SCALING.md) end to end at a
size that fits wherever it runs: the sparse design matrix is tiled over a
2-D device mesh, coefficients stay feature-sharded for the whole L-BFGS
solve (no chip ever holds the full vector), and the per-tile sparse compute
runs the fused permutation engine (ops/fused_perm.py) on TPU or its XLA
fallback elsewhere.

Run on the 8-virtual-device CPU harness:

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/large_scale_fe.py --n-data 2 --n-feat 4

Scale up with --num-rows / --dim / --nnz-per-row on real hardware (the mesh
shape must divide the device count; routing prep is one-time host work).
"""

from __future__ import annotations

import argparse
import os
import sys
import time

import numpy as np

# runnable from a fresh checkout without installing the package
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--num-rows", type=int, default=1 << 15)
    ap.add_argument("--dim", type=int, default=1 << 16)
    ap.add_argument("--nnz-per-row", type=int, default=16)
    ap.add_argument("--n-data", type=int, default=2)
    ap.add_argument("--n-feat", type=int, default=4)
    ap.add_argument("--engine", default="fused", choices=["fused", "benes", "ell"])
    ap.add_argument("--max-iterations", type=int, default=40)
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()

    import jax

    # some TPU plugins override JAX_PLATFORMS at import time; an explicit
    # CPU request must win (same workaround as tests/conftest.py)
    if os.environ.get("JAX_PLATFORMS", "").strip() == "cpu":
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from photon_ml_tpu.evaluation.evaluators import area_under_roc_curve
    from photon_ml_tpu.losses.objective import make_glm_objective
    from photon_ml_tpu.losses.pointwise import LogisticLoss
    from photon_ml_tpu.ops.data import LabeledData
    from photon_ml_tpu.opt.config import (
        GlmOptimizationConfiguration,
        OptimizerConfig,
    )
    from photon_ml_tpu.opt.solve import solve
    from photon_ml_tpu.parallel.grid_features import (
        grid_from_coo,
        grid_mesh,
        shard_vector_data,
        shard_vector_feat,
    )

    n, d, k = args.num_rows, args.dim, args.nnz_per_row
    rng = np.random.default_rng(args.seed)
    rows = np.repeat(np.arange(n, dtype=np.int64), k)
    cols = rng.integers(0, d, n * k)
    vals = rng.standard_normal(n * k).astype(np.float32)
    w_true = (rng.standard_normal(d) * 0.3).astype(np.float32)
    z = (vals * w_true[cols]).reshape(n, k).sum(-1)
    y = (rng.random(n) < 1.0 / (1.0 + np.exp(-z))).astype(np.float32)

    mesh = grid_mesh(args.n_data, args.n_feat)
    print(f"mesh: {dict(mesh.shape)} over {len(jax.devices())} "
          f"{jax.devices()[0].platform} devices; engine={args.engine}")

    t0 = time.perf_counter()
    gf = grid_from_coo(rows, cols, vals, (n, d), mesh, engine=args.engine)
    print(f"routing/tiling prep: {time.perf_counter() - t0:.1f}s "
          f"(one-time, pattern-keyed cacheable)")

    y_pad = np.zeros(gf.num_rows, np.float32)
    y_pad[:n] = y
    wt_pad = np.zeros(gf.num_rows, np.float32)
    wt_pad[:n] = 1.0
    data = LabeledData.create(
        gf,
        shard_vector_data(jnp.asarray(y_pad), mesh),
        weights=shard_vector_data(jnp.asarray(wt_pad), mesh),
    )

    objective = make_glm_objective(LogisticLoss)
    cfg = GlmOptimizationConfiguration(
        optimizer_config=OptimizerConfig.lbfgs(
            max_iterations=args.max_iterations
        ),
        regularization_weight=1.0,
    )
    solver = jax.jit(
        lambda w0, dd: solve(objective, w0, dd, cfg, l2_weight=jnp.float32(1.0))
    )
    w0 = shard_vector_feat(jnp.zeros(gf.dim, jnp.float32), mesh)

    t0 = time.perf_counter()
    res = solver(w0, data)
    jax.block_until_ready(res.w)
    compile_and_first = time.perf_counter() - t0
    t0 = time.perf_counter()
    res = solver(w0, data)
    jax.block_until_ready(res.w)
    steady = time.perf_counter() - t0

    iters = int(res.iterations)
    scores = np.asarray(gf.matvec(res.w))[:n]
    auc = float(area_under_roc_curve(jnp.asarray(scores), jnp.asarray(y)))
    print(f"solve: {iters} iterations, loss {float(res.value):.1f}, "
          f"train AUC {auc:.4f}")
    print(f"wall: first(+compile) {compile_and_first:.1f}s, steady {steady:.2f}s "
          f"-> {n * iters / steady / 1e6:.2f}M example-passes/s")
    assert auc > 0.8


if __name__ == "__main__":
    main()
