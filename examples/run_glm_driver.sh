#!/bin/bash
# Single-GLM training driver invocation (the analog of the reference's
# examples/run_photon_ml_driver.sh spark-submit recipe — same workflow
# knobs, no Spark: the chips this process sees are the cluster).
#
# Usage: ./run_glm_driver.sh WORKING_ROOT
#   train data:  WORKING_ROOT/input/train   (TrainingExampleAvro or LibSVM)
#   test data:   WORKING_ROOT/input/test
#   outputs:     WORKING_ROOT/results
set -euo pipefail

ROOT="${1:?usage: $0 WORKING_ROOT}"

python -m photon_ml_tpu.cli.train_glm \
    --training-data-dirs "$ROOT/input/train" \
    --validation-data-dirs "$ROOT/input/test" \
    --task LOGISTIC_REGRESSION \
    --output-dir "$ROOT/results" \
    --regularization-weights 0.1 1 10 100 \
    --optimizer LBFGS \
    --regularization L2 \
    --normalization-type STANDARDIZATION \
    --diagnostic-mode ALL \
    --log-file "$ROOT/results/driver.log"
