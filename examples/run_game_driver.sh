#!/bin/bash
# GAME/GLMix training + scoring (the analog of the reference's
# cli.game.training / cli.game.scoring drivers on a TPU pod slice).
#
# Usage: ./run_game_driver.sh WORKING_ROOT [N_DATA [N_FEAT]]
#   game config: WORKING_ROOT/game.json  (see game.json.example)
#   train data:  WORKING_ROOT/input/train
#   test data:   WORKING_ROOT/input/test
#   model out:   WORKING_ROOT/results ; scores: WORKING_ROOT/scores
#
# N_DATA x N_FEAT devices form the training grid: examples shard over the
# data axis, coefficients over the feat axis (omit both for single-chip).
set -euo pipefail

ROOT="${1:?usage: $0 WORKING_ROOT [N_DATA [N_FEAT]]}"
N_DATA="${2:-0}"
N_FEAT="${3:-1}"

PARALLEL_FLAGS=()
if [ "$N_DATA" -gt 0 ]; then
  PARALLEL_FLAGS=(--parallel-data "$N_DATA" --parallel-feat "$N_FEAT")
fi

python -m photon_ml_tpu.cli.train_game \
    --train-data-dirs "$ROOT/input/train" \
    --validation-data-dirs "$ROOT/input/test" \
    --coordinate-config "$ROOT/game.json" \
    --task LOGISTIC_REGRESSION \
    --output-dir "$ROOT/results" \
    --evaluator AUC \
    --checkpoint-dir "$ROOT/checkpoints" \
    "${PARALLEL_FLAGS[@]}"

python -m photon_ml_tpu.cli.score_game \
    --data-dirs "$ROOT/input/test" \
    --model-dir "$ROOT/results/best" \
    --output-dir "$ROOT/scores" \
    --evaluator AUC
