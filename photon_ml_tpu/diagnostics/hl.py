"""Hosmer-Lemeshow goodness-of-fit test for logistic models.

Reference parity: diagnostics/hl/HosmerLemeshowDiagnostic.scala:29 — bin
predicted probabilities (DefaultPredictedProbabilityVersusObserved-
FrequencyBinner: bins = min(dim + 2, 0.9·√n + 0.1·log1p(n)); the reference
code uses FACTOR_A for both terms, contradicting its own named constant —
the named intent is implemented here), accumulate expected vs observed
positive/negative counts per bin, χ² with dof = bins − 2, p-value and the
standard confidence-level cutoffs.
"""

from __future__ import annotations

import dataclasses
import math
from typing import List, Tuple

import numpy as np
from scipy.stats import chi2

STANDARD_CONFIDENCE_LEVELS = [
    0.000001, 0.01, 0.05, 0.1, 0.2, 0.3, 0.4, 0.5,
    0.6, 0.7, 0.8, 0.9, 0.95, 0.99, 0.999999,
]
MINIMUM_EXPECTED_IN_BUCKET = 5


@dataclasses.dataclass(frozen=True)
class HistogramBin:
    """[lower, upper) predicted-probability range with expected/observed
    counts (reference PredictedProbabilityVersusObservedFrequency-
    HistogramBin)."""

    lower: float
    upper: float
    expected_pos: float
    expected_neg: float
    observed_pos: float
    observed_neg: float

    @property
    def count(self) -> float:
        return self.observed_pos + self.observed_neg


@dataclasses.dataclass
class HosmerLemeshowReport:
    bins: List[HistogramBin]
    chi_squared: float
    degrees_of_freedom: int
    # P[χ²_dof <= observed]: close to 1 ⇒ strong evidence of mis-calibration
    prob_at_chi_squared: float
    cutoffs: List[Tuple[float, float]]
    warnings: List[str]

    @property
    def p_value(self) -> float:
        """P[χ² >= observed | model calibrated]."""
        return 1.0 - self.prob_at_chi_squared


def default_bin_count(num_items: int, num_dimensions: int) -> int:
    by_dim = num_dimensions + 2
    by_data = int(0.9 * math.sqrt(num_items) + 0.1 * math.log1p(num_items))
    return max(3, min(by_dim, by_data))


def hosmer_lemeshow_diagnostic(
    predicted_probabilities,
    labels,
    num_dimensions: int,
    num_bins: int = None,
) -> HosmerLemeshowReport:
    """Equal-width probability bins over [0, 1]."""
    p = np.asarray(predicted_probabilities, dtype=np.float64)
    y = np.asarray(labels, dtype=np.float64) > 0.5
    n = len(p)
    if num_bins is None:
        num_bins = default_bin_count(n, num_dimensions)
    edges = np.linspace(0.0, 1.0, num_bins + 1)
    which = np.clip(np.digitize(p, edges[1:-1]), 0, num_bins - 1)

    bins: List[HistogramBin] = []
    warnings: List[str] = []
    chi_squared = 0.0
    for b in range(num_bins):
        sel = which == b
        cnt = int(sel.sum())
        exp_pos = float(p[sel].sum())
        exp_neg = cnt - exp_pos
        obs_pos = float(y[sel].sum())
        obs_neg = cnt - obs_pos
        hb = HistogramBin(
            lower=float(edges[b]), upper=float(edges[b + 1]),
            expected_pos=exp_pos, expected_neg=exp_neg,
            observed_pos=obs_pos, observed_neg=obs_neg,
        )
        bins.append(hb)
        if exp_pos > 0:
            chi_squared += (obs_pos - exp_pos) ** 2 / exp_pos
            if exp_pos < MINIMUM_EXPECTED_IN_BUCKET:
                warnings.append(
                    f"bin [{hb.lower:.3f},{hb.upper:.3f}): expected positive "
                    f"count {exp_pos:.2f} too small for a sound chi^2"
                )
        if exp_neg > 0:
            chi_squared += (obs_neg - exp_neg) ** 2 / exp_neg
            if exp_neg < MINIMUM_EXPECTED_IN_BUCKET:
                warnings.append(
                    f"bin [{hb.lower:.3f},{hb.upper:.3f}): expected negative "
                    f"count {exp_neg:.2f} too small for a sound chi^2"
                )

    dof = max(1, num_bins - 2)
    dist = chi2(dof)
    return HosmerLemeshowReport(
        bins=bins,
        chi_squared=float(chi_squared),
        degrees_of_freedom=dof,
        prob_at_chi_squared=float(dist.cdf(chi_squared)),
        cutoffs=[(lv, float(dist.ppf(lv))) for lv in STANDARD_CONFIDENCE_LEVELS],
        warnings=warnings,
    )
