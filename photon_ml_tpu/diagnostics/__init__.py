"""Model diagnostics: metrics, bootstrap CIs, learning curves,
Hosmer-Lemeshow calibration, Kendall-τ independence, feature importance,
and the logical→physical→HTML report engine.

Reference parity: photon-diagnostics module — Evaluation.scala:31,
BootstrapTraining.scala:29, diagnostics/fitting/FittingDiagnostic.scala:33,
diagnostics/hl/HosmerLemeshowDiagnostic.scala:29,
diagnostics/independence/KendallTauAnalysis.scala:26,
diagnostics/featureimportance/*, diagnostics/reporting/*.
"""

from photon_ml_tpu.diagnostics.evaluation import MetricsMap, evaluate_metrics
from photon_ml_tpu.diagnostics.bootstrap import (
    BootstrapReport,
    CoefficientSummary,
    bootstrap_training,
)
from photon_ml_tpu.diagnostics.fitting import FittingReport, fitting_diagnostic
from photon_ml_tpu.diagnostics.hl import (
    HosmerLemeshowReport,
    hosmer_lemeshow_diagnostic,
)
from photon_ml_tpu.diagnostics.independence import (
    KendallTauReport,
    kendall_tau_analysis,
    prediction_error_independence,
)
from photon_ml_tpu.diagnostics.feature_importance import (
    FeatureImportanceReport,
    expected_magnitude_importance,
    variance_importance,
)

__all__ = [
    "MetricsMap",
    "evaluate_metrics",
    "BootstrapReport",
    "CoefficientSummary",
    "bootstrap_training",
    "FittingReport",
    "fitting_diagnostic",
    "HosmerLemeshowReport",
    "hosmer_lemeshow_diagnostic",
    "KendallTauReport",
    "kendall_tau_analysis",
    "prediction_error_independence",
    "FeatureImportanceReport",
    "expected_magnitude_importance",
    "variance_importance",
]
