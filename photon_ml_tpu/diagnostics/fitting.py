"""Learning-curve (under/over-fit) diagnostic.

Reference parity: diagnostics/fitting/FittingDiagnostic.scala:33 — rows are
randomly tagged into NUM_TRAINING_PARTITIONS=10 slices; the last slice is
the hold-out; models are trained on growing prefixes of the rest (warm-
started across portions) and train-vs-holdout metric curves per λ reveal
fit problems. Requires numSamples > dim · MIN_SAMPLES_PER_PARTITION_PER_
DIMENSION (=1) to produce a report.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Sequence, Tuple

import numpy as np

from photon_ml_tpu.diagnostics.evaluation import MetricsMap

NUM_TRAINING_PARTITIONS = 10
MIN_SAMPLES_PER_PARTITION_PER_DIMENSION = 1


@dataclasses.dataclass
class FittingReport:
    """Per-λ learning curves: metric name → (portions %, train values,
    holdout values) (reference fitting/FittingReport.scala)."""

    metrics: Dict[str, Tuple[List[float], List[float], List[float]]]
    message: str = ""


def fitting_diagnostic(
    train_fn: Callable[[np.ndarray, Dict[float, object]], Dict[float, object]],
    eval_fn: Callable[[object, np.ndarray], MetricsMap],
    num_rows: int,
    dim: int,
    seed: int = 0,
) -> Dict[float, FittingReport]:
    """``train_fn(row_indices, warm_start) -> {λ: model}``;
    ``eval_fn(model, row_indices) -> metrics``. Returns λ → FittingReport
    (empty when the dataset is too small, like the reference)."""
    min_samples = dim * MIN_SAMPLES_PER_PARTITION_PER_DIMENSION
    if num_rows <= min_samples:
        return {}

    rng = np.random.default_rng(seed)
    tags = rng.integers(0, NUM_TRAINING_PARTITIONS, size=num_rows)
    holdout = np.flatnonzero(tags == NUM_TRAINING_PARTITIONS - 1)

    curves: Dict[float, Dict[str, Tuple[List[float], List[float], List[float]]]] = {}
    warm: Dict[float, object] = {}
    for max_tag in range(NUM_TRAINING_PARTITIONS - 1):
        subset = np.flatnonzero(tags <= max_tag)
        portion = 100.0 * len(subset) / num_rows
        models = train_fn(subset, warm)
        warm = models
        for lam, model in models.items():
            test_metrics = eval_fn(model, holdout)
            train_metrics = eval_fn(model, subset)
            by_metric = curves.setdefault(lam, {})
            for name, test_val in test_metrics.items():
                portions, train_vals, test_vals = by_metric.setdefault(
                    name, ([], [], [])
                )
                portions.append(portion)
                train_vals.append(train_metrics.get(name, float("nan")))
                test_vals.append(test_val)

    return {lam: FittingReport(metrics=m) for lam, m in curves.items()}
