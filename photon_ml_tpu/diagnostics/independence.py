"""Kendall-τ rank-correlation independence tests.

Reference parity: diagnostics/independence/KendallTauAnalysis.scala:26 —
concordant/discordant pair counts → τ-α, τ-β, z-score and two-sided p-value
(same formulas :63-77), with √n subsampling for large inputs; and
PredictionErrorIndependenceDiagnostic.scala (error vs prediction pairs).
The reference counts pairs with an O(n²) cartesian; here discordant pairs
are counted in O(n log n) by merge-sort inversion counting.
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

import numpy as np
from scipy.stats import norm


@dataclasses.dataclass
class KendallTauReport:
    num_concordant: int
    num_discordant: int
    num_items: int
    num_pairs: int
    effective_pairs: int
    tau_alpha: float
    tau_beta: float
    z_alpha: float
    # P[|Z| <= |z|]: close to 1 ⇒ strong evidence of DEPENDENCE (this is
    # what the reference calls pValue, KendallTauAnalysis.scala:74-75)
    prob_dependent: float
    message: str = ""

    @property
    def p_value(self) -> float:
        """Conventional two-sided p-value under H0 (independence) — the
        tail probability, matching HosmerLemeshowReport.p_value semantics."""
        return 1.0 - self.prob_dependent


def _count_inversions(a: np.ndarray) -> int:
    """Number of i<j with a[i] > a[j] (merge-sort, O(n log n))."""
    a = list(a)
    total = 0

    def sort(xs):
        nonlocal total
        if len(xs) <= 1:
            return xs
        mid = len(xs) // 2
        left, right = sort(xs[:mid]), sort(xs[mid:])
        out = []
        i = j = 0
        while i < len(left) and j < len(right):
            if left[i] <= right[j]:
                out.append(left[i]); i += 1
            else:
                total += len(left) - i
                out.append(right[j]); j += 1
        out.extend(left[i:]); out.extend(right[j:])
        return out

    sort(a)
    return total


def _tie_pairs(values: np.ndarray) -> int:
    _, counts = np.unique(values, return_counts=True)
    return int(np.sum(counts * (counts - 1) // 2))


def kendall_tau_analysis(
    a, b, max_items: int = None, seed: int = 0
) -> KendallTauReport:
    """τ test of independence between paired draws (a_i, b_i).

    With ``max_items`` (the reference subsamples ~√n of large RDDs), a
    uniform subsample bounds the O(n log n) work and normal-approx validity.
    """
    a = np.asarray(a, dtype=np.float64)
    b = np.asarray(b, dtype=np.float64)
    if len(a) != len(b):
        raise ValueError("a and b must be paired")
    n = len(a)
    if max_items is not None and n > max_items:
        idx = np.random.default_rng(seed).choice(n, size=max_items, replace=False)
        a, b = a[idx], b[idx]
        n = max_items

    # lexsort by (a, then b): within tied-a runs b is ascending, so those
    # pairs contribute no inversions — discordant pairs are exactly the
    # inversions of b in this order
    order = np.lexsort((b, a))
    b_sorted = b[order]
    num_pairs = n * (n - 1) // 2
    ties_a = _tie_pairs(a)
    ties_b = _tie_pairs(b)
    _, ab_counts = np.unique(np.stack([a, b], axis=1), axis=0, return_counts=True)
    ties_ab = int(np.sum(ab_counts * (ab_counts - 1) // 2))
    discordant = _count_inversions(b_sorted)
    # pairs tied in a contribute neither concordant nor discordant; with the
    # lexsort, tied-a runs are sorted by b so they add no inversions
    concordant = num_pairs - discordant - ties_a - ties_b + ties_ab
    effective = concordant + discordant

    tau_alpha = (
        (concordant - discordant) / effective if effective > 0 else 0.0
    )
    no_ties_a = num_pairs - ties_a
    no_ties_b = num_pairs - ties_b
    tau_beta = (
        (concordant - discordant) / np.sqrt(float(no_ties_a) * float(no_ties_b))
        if no_ties_a > 0 and no_ties_b > 0
        else 0.0
    )
    # z under H0 (KendallTauAnalysis.scala:70-73)
    a_const = 2.0 * (2.0 * n + 5.0)
    b_const = 9.0 * n * (n - 1.0)
    d = np.sqrt(a_const / b_const) if b_const > 0 else 1.0
    z_alpha = tau_alpha / d
    prob_dependent = float(norm.cdf(abs(z_alpha)) - norm.cdf(-abs(z_alpha)))

    message = ""
    if ties_a + ties_b > 0:
        message = (
            f"detected ties (a: {ties_a}, b: {ties_b}); the tau-alpha z/p "
            "over-estimates independence"
        )
    return KendallTauReport(
        num_concordant=int(concordant),
        num_discordant=int(discordant),
        num_items=n,
        num_pairs=int(num_pairs),
        effective_pairs=int(effective),
        tau_alpha=float(tau_alpha),
        tau_beta=float(tau_beta),
        z_alpha=float(z_alpha),
        prob_dependent=prob_dependent,
        message=message,
    )


def prediction_error_independence(
    scores, labels, max_items: int = None, seed: int = 0
) -> KendallTauReport:
    """Error vs prediction independence (reference
    PredictionErrorIndependenceDiagnostic): error = label − score."""
    scores = np.asarray(scores, dtype=np.float64)
    labels = np.asarray(labels, dtype=np.float64)
    return kendall_tau_analysis(
        scores, labels - scores, max_items=max_items, seed=seed
    )
