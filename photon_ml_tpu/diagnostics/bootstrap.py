"""Bootstrap training: coefficient and metric confidence intervals.

Reference parity: BootstrapTraining.scala:29 — draw ``num_samples``
with-replacement resamples, fit via a caller-supplied train function, then
aggregate per-coefficient summaries (CoefficientSummary.scala: min/max/mean/
std + quartile estimates) and per-metric distributions
(BootstrapTrainingDiagnostic.scala:26 importance/CI tables).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Sequence, Tuple

import numpy as np

from photon_ml_tpu.diagnostics.evaluation import MetricsMap


@dataclasses.dataclass(frozen=True)
class CoefficientSummary:
    """Distribution summary of one scalar across bootstrap fits
    (reference supervised/model/CoefficientSummary.scala; quartiles here are
    exact over the sample set rather than streaming estimates)."""

    min: float
    max: float
    mean: float
    std: float
    q1: float
    median: float
    q3: float

    @classmethod
    def from_samples(cls, samples: np.ndarray) -> "CoefficientSummary":
        s = np.asarray(samples, dtype=np.float64)
        q1, med, q3 = np.percentile(s, [25, 50, 75])
        return cls(
            min=float(s.min()), max=float(s.max()), mean=float(s.mean()),
            std=float(s.std(ddof=1)) if len(s) > 1 else 0.0,
            q1=float(q1), median=float(med), q3=float(q3),
        )

    def interval_contains_zero(self) -> bool:
        return self.min <= 0.0 <= self.max


@dataclasses.dataclass
class BootstrapReport:
    """Per-coefficient CIs + metric distributions + notable features
    (reference bootstrap/BootstrapReport.scala)."""

    coefficient_summaries: List[CoefficientSummary]
    metric_summaries: Dict[str, CoefficientSummary]
    # coefficients whose bootstrap interval straddles zero — candidates for
    # removal (reference 'importance analysis')
    zero_crossing_indices: np.ndarray


def bootstrap_training(
    train_fn: Callable[[np.ndarray], Tuple[np.ndarray, MetricsMap]],
    num_rows: int,
    num_samples: int = 16,
    portion: float = 1.0,
    seed: int = 0,
) -> BootstrapReport:
    """Run ``train_fn`` on ``num_samples`` with-replacement row resamples.

    ``train_fn(row_indices) -> (coefficient_vector, metrics)`` encapsulates
    the model fit + evaluation (the reference curries
    ModelTraining.trainGeneralizedLinearModel the same way).
    """
    if num_samples < 2:
        raise ValueError("bootstrapping needs at least 2 samples")
    rng = np.random.default_rng(seed)
    n_draw = max(1, int(portion * num_rows))
    coef_rows: List[np.ndarray] = []
    metric_rows: List[MetricsMap] = []
    for _ in range(num_samples):
        idx = rng.integers(0, num_rows, size=n_draw)
        w, metrics = train_fn(idx)
        coef_rows.append(np.asarray(w, dtype=np.float64))
        metric_rows.append(metrics)

    coefs = np.stack(coef_rows)  # [S, d]
    coefficient_summaries = [
        CoefficientSummary.from_samples(coefs[:, j])
        for j in range(coefs.shape[1])
    ]
    metric_summaries = {
        name: CoefficientSummary.from_samples(
            np.array([m[name] for m in metric_rows])
        )
        for name in metric_rows[0]
    }
    zero_crossing = np.array(
        [j for j, s in enumerate(coefficient_summaries)
         if s.interval_contains_zero()],
        dtype=np.int64,
    )
    return BootstrapReport(
        coefficient_summaries=coefficient_summaries,
        metric_summaries=metric_summaries,
        zero_crossing_indices=zero_crossing,
    )
