"""Report engine: physical report tree → HTML / plain text.

Reference parity: diagnostics/reporting/ — a logical report is transformed
into a physical tree (DocumentPhysicalReport → ChapterPhysicalReport →
SectionPhysicalReport → {SimpleText, BulletedList, NumberedList, Plot})
and rendered by a strategy (html/HTMLRenderStrategy.scala:23 emits XHTML
with numbered chapters/sections; text/StringRenderStrategy). The
reference rasterized XChart plots; here plots are inline SVG, dependency-
free and crisper.
"""

from __future__ import annotations

import dataclasses
import html as _html
from typing import List, Optional, Sequence, Tuple, Union


@dataclasses.dataclass
class SimpleText:
    text: str


@dataclasses.dataclass
class BulletedList:
    items: List[str]


@dataclasses.dataclass
class NumberedList:
    items: List[str]


@dataclasses.dataclass
class Table:
    headers: List[str]
    rows: List[Sequence]
    caption: str = ""


@dataclasses.dataclass
class Plot:
    """Line/scatter chart: multiple named series of (x, y) points."""

    title: str
    x_label: str
    y_label: str
    series: List[Tuple[str, Sequence[float], Sequence[float]]]
    width: int = 640
    height: int = 360


Item = Union[SimpleText, BulletedList, NumberedList, Table, Plot]


@dataclasses.dataclass
class Section:
    title: str
    items: List[Item] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class Chapter:
    title: str
    sections: List[Section] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class Document:
    title: str
    chapters: List[Chapter] = dataclasses.field(default_factory=list)


_SERIES_COLORS = ["#1f77b4", "#d62728", "#2ca02c", "#9467bd", "#ff7f0e", "#8c564b"]


def _svg_plot(p: Plot) -> str:
    """Minimal inline-SVG line chart with axes and a legend."""
    pad_l, pad_r, pad_t, pad_b = 60, 16, 28, 44
    iw = p.width - pad_l - pad_r
    ih = p.height - pad_t - pad_b
    xs = [x for _, sx, _ in p.series for x in sx]
    ys = [y for _, _, sy in p.series for y in sy if y == y]
    if not xs or not ys:
        return f"<p><em>{_html.escape(p.title)}: no data</em></p>"
    x0, x1 = min(xs), max(xs)
    y0, y1 = min(ys), max(ys)
    if x1 == x0:
        x1 = x0 + 1.0
    if y1 == y0:
        y1 = y0 + 1.0

    def X(v):
        return pad_l + (v - x0) / (x1 - x0) * iw

    def Y(v):
        return pad_t + ih - (v - y0) / (y1 - y0) * ih

    out = [
        f'<svg xmlns="http://www.w3.org/2000/svg" width="{p.width}" '
        f'height="{p.height}" viewBox="0 0 {p.width} {p.height}" '
        'style="background:#fff;font-family:sans-serif">'
    ]
    out.append(
        f'<text x="{p.width/2:.0f}" y="16" text-anchor="middle" '
        f'font-size="13" font-weight="bold">{_html.escape(p.title)}</text>'
    )
    # axes
    out.append(
        f'<line x1="{pad_l}" y1="{pad_t}" x2="{pad_l}" y2="{pad_t+ih}" '
        'stroke="#333"/>'
        f'<line x1="{pad_l}" y1="{pad_t+ih}" x2="{pad_l+iw}" y2="{pad_t+ih}" '
        'stroke="#333"/>'
    )
    for i in range(5):
        fx = x0 + (x1 - x0) * i / 4
        fy = y0 + (y1 - y0) * i / 4
        out.append(
            f'<text x="{X(fx):.1f}" y="{pad_t+ih+16}" text-anchor="middle" '
            f'font-size="10">{fx:.3g}</text>'
            f'<text x="{pad_l-6}" y="{Y(fy)+3:.1f}" text-anchor="end" '
            f'font-size="10">{fy:.3g}</text>'
        )
    out.append(
        f'<text x="{pad_l+iw/2:.0f}" y="{p.height-6}" text-anchor="middle" '
        f'font-size="11">{_html.escape(p.x_label)}</text>'
        f'<text x="14" y="{pad_t+ih/2:.0f}" text-anchor="middle" '
        f'font-size="11" transform="rotate(-90 14 {pad_t+ih/2:.0f})">'
        f'{_html.escape(p.y_label)}</text>'
    )
    for si, (name, sx, sy) in enumerate(p.series):
        color = _SERIES_COLORS[si % len(_SERIES_COLORS)]
        pts = [
            (X(x), Y(y)) for x, y in zip(sx, sy) if y == y
        ]
        if len(pts) > 1:
            d = " ".join(f"{x:.1f},{y:.1f}" for x, y in pts)
            out.append(
                f'<polyline points="{d}" fill="none" stroke="{color}" '
                'stroke-width="1.5"/>'
            )
        for x, y in pts:
            out.append(f'<circle cx="{x:.1f}" cy="{y:.1f}" r="2.5" fill="{color}"/>')
        out.append(
            f'<rect x="{pad_l+iw-130}" y="{pad_t+6+14*si}" width="10" '
            f'height="10" fill="{color}"/>'
            f'<text x="{pad_l+iw-116}" y="{pad_t+15+14*si}" font-size="10">'
            f'{_html.escape(name)}</text>'
        )
    out.append("</svg>")
    return "".join(out)


def _render_item_html(item: Item) -> str:
    if isinstance(item, SimpleText):
        return f"<p>{_html.escape(item.text)}</p>"
    if isinstance(item, BulletedList):
        inner = "".join(f"<li>{_html.escape(i)}</li>" for i in item.items)
        return f"<ul>{inner}</ul>"
    if isinstance(item, NumberedList):
        inner = "".join(f"<li>{_html.escape(i)}</li>" for i in item.items)
        return f"<ol>{inner}</ol>"
    if isinstance(item, Table):
        head = "".join(f"<th>{_html.escape(h)}</th>" for h in item.headers)
        body = "".join(
            "<tr>" + "".join(f"<td>{_html.escape(str(c))}</td>" for c in row) + "</tr>"
            for row in item.rows
        )
        cap = f"<caption>{_html.escape(item.caption)}</caption>" if item.caption else ""
        return (
            f'<table border="1" cellspacing="0" cellpadding="4">{cap}'
            f"<thead><tr>{head}</tr></thead><tbody>{body}</tbody></table>"
        )
    if isinstance(item, Plot):
        return _svg_plot(item)
    raise TypeError(f"unknown report item: {type(item)}")


def render_html(doc: Document) -> str:
    """Standalone HTML document with numbered chapters/sections (reference
    html/DocumentToHTMLRenderer + NumberingContext)."""
    parts = [
        "<!DOCTYPE html>",
        '<html><head><meta charset="utf-8"/>',
        f"<title>{_html.escape(doc.title)}</title>",
        "<style>body{font-family:sans-serif;margin:2em;max-width:60em}"
        "table{border-collapse:collapse;margin:1em 0}"
        "h1{border-bottom:2px solid #333}</style>",
        "</head><body>",
        f"<h1>{_html.escape(doc.title)}</h1>",
    ]
    for ci, chapter in enumerate(doc.chapters, 1):
        parts.append(f"<h2>{ci}. {_html.escape(chapter.title)}</h2>")
        for si, section in enumerate(chapter.sections, 1):
            parts.append(f"<h3>{ci}.{si}. {_html.escape(section.title)}</h3>")
            parts.extend(_render_item_html(item) for item in section.items)
    parts.append("</body></html>")
    return "\n".join(parts)


def render_text(doc: Document) -> str:
    """Plain-text rendering (reference text/StringRenderStrategy)."""
    lines = [doc.title, "=" * len(doc.title)]
    for ci, chapter in enumerate(doc.chapters, 1):
        lines.append(f"\n{ci}. {chapter.title}")
        for si, section in enumerate(chapter.sections, 1):
            lines.append(f"\n{ci}.{si}. {section.title}")
            for item in section.items:
                if isinstance(item, SimpleText):
                    lines.append(item.text)
                elif isinstance(item, (BulletedList, NumberedList)):
                    mark = "-" if isinstance(item, BulletedList) else "#"
                    lines.extend(f"  {mark} {i}" for i in item.items)
                elif isinstance(item, Table):
                    lines.append("  " + " | ".join(item.headers))
                    lines.extend(
                        "  " + " | ".join(str(c) for c in row) for row in item.rows
                    )
                elif isinstance(item, Plot):
                    lines.append(f"  [plot: {item.title}]")
    return "\n".join(lines)
