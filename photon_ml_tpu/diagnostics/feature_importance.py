"""Feature importance rankings.

Reference parity: diagnostics/featureimportance/ —
ExpectedMagnitudeFeatureImportanceDiagnostic.scala (importance =
|β_j · E|x_j||, falling back to |β_j| without a summary) and
VarianceFeatureImportanceDiagnostic.scala (importance = |β_j · Var(x_j)|);
AbstractFeatureImportanceDiagnostic ranks descending and keeps the top
MAX_RANKED_FEATURES plus an importance-vs-rank histogram.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

import numpy as np

from photon_ml_tpu.indexmap import IndexMap, NAME_TERM_DELIMITER

MAX_RANKED_FEATURES = 25


@dataclasses.dataclass
class FeatureImportanceReport:
    importance_type: str
    importance_description: str
    # top features: (name, term, index, importance), descending
    ranked_features: List[Tuple[str, str, int, float]]
    # rank percentile (0-100, step 10) -> importance at that rank
    rank_to_importance: Dict[float, float]


def _build_report(
    importance: np.ndarray,
    index_map: Optional[IndexMap],
    importance_type: str,
    description: str,
) -> FeatureImportanceReport:
    order = np.argsort(-importance, kind="stable")
    top = []
    for i in order[:MAX_RANKED_FEATURES]:
        key = index_map.get_feature_name(int(i)) if index_map else str(i)
        key = key if key is not None else str(i)
        name, _, term = key.partition(NAME_TERM_DELIMITER)
        top.append((name, term, int(i), float(importance[i])))
    n = len(importance)
    rank_to_importance = {
        float(pct): float(importance[order[min(n - 1, int(pct / 100.0 * n))]])
        for pct in range(0, 101, 10)
    }
    return FeatureImportanceReport(
        importance_type=importance_type,
        importance_description=description,
        ranked_features=top,
        rank_to_importance=rank_to_importance,
    )


def expected_magnitude_importance(
    coefficients,
    mean_abs=None,
    index_map: Optional[IndexMap] = None,
) -> FeatureImportanceReport:
    """|β_j| · E|x_j| (ExpectedMagnitude...Diagnostic.scala:45-58)."""
    w = np.asarray(coefficients, dtype=np.float64)
    scale = np.ones_like(w) if mean_abs is None else np.asarray(mean_abs)
    return _build_report(
        np.abs(w * scale),
        index_map,
        "Inner product expectation",
        "Expected magnitude of inner product contribution"
        if mean_abs is not None
        else "Magnitude of feature coefficient",
    )


def variance_importance(
    coefficients,
    variance=None,
    index_map: Optional[IndexMap] = None,
) -> FeatureImportanceReport:
    """|β_j| · Var(x_j) (Variance...Diagnostic.scala:45-58)."""
    w = np.asarray(coefficients, dtype=np.float64)
    scale = np.ones_like(w) if variance is None else np.asarray(variance)
    return _build_report(
        np.abs(w * scale),
        index_map,
        "Inner product variance",
        "Expected inner product variance contribution"
        if variance is not None
        else "Magnitude of feature coefficient",
    )
