"""Full model-diagnostic report assembly (model-diagnostic.html).

Reference parity: the legacy Driver's diagnose() stage (Driver.scala:472-)
which runs fitting / bootstrap / Hosmer-Lemeshow / error-independence /
feature-importance diagnostics per λ and renders one HTML document
(README.md:256-259).
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional

import numpy as np

from photon_ml_tpu.diagnostics.bootstrap import BootstrapReport
from photon_ml_tpu.diagnostics.evaluation import MetricsMap
from photon_ml_tpu.diagnostics.feature_importance import FeatureImportanceReport
from photon_ml_tpu.diagnostics.fitting import FittingReport
from photon_ml_tpu.diagnostics.hl import HosmerLemeshowReport
from photon_ml_tpu.diagnostics.independence import KendallTauReport
from photon_ml_tpu.diagnostics.reporting import (
    BulletedList,
    Chapter,
    Document,
    Plot,
    Section,
    SimpleText,
    Table,
    render_html,
)


def build_diagnostic_document(
    title: str,
    metrics: Optional[MetricsMap] = None,
    fitting: Optional[Dict[float, FittingReport]] = None,
    bootstrap: Optional[BootstrapReport] = None,
    hosmer_lemeshow: Optional[HosmerLemeshowReport] = None,
    independence: Optional[KendallTauReport] = None,
    importance: Optional[FeatureImportanceReport] = None,
    importance_variance: Optional[FeatureImportanceReport] = None,
    metric_vs_iteration: Optional[Dict[float, List[float]]] = None,
    metric_name: str = "metric",
) -> Document:
    doc = Document(title=title)

    if metric_vs_iteration:
        # reference validatePerIteration: the per-iteration tracked models'
        # validation metric, one series per regularization weight
        doc.chapters.append(Chapter("Metric vs iteration", [Section(
            "Validation metric of each tracked iteration's model",
            [Plot(
                title=f"{metric_name} vs optimizer iteration",
                x_label="iteration", y_label=metric_name,
                series=[
                    (f"lambda={lam:g}",
                     list(range(len(curve))), list(curve))
                    for lam, curve in sorted(metric_vs_iteration.items())
                ],
            )],
        )]))

    if metrics:
        doc.chapters.append(Chapter("Model metrics", [Section("Summary", [
            Table(
                headers=["Metric", "Value"],
                rows=[(k, f"{v:.6g}") for k, v in sorted(metrics.items())],
            )
        ])]))

    if fitting:
        sections = []
        for lam, rep in sorted(fitting.items()):
            items = []
            for metric, (portions, train_vals, test_vals) in rep.metrics.items():
                items.append(Plot(
                    title=f"{metric} vs training data portion",
                    x_label="% of data", y_label=metric,
                    series=[
                        ("train", portions, train_vals),
                        ("holdout", portions, test_vals),
                    ],
                ))
            sections.append(Section(f"lambda = {lam:g}", items))
        doc.chapters.append(Chapter("Fitting analysis (learning curves)", sections))

    if bootstrap:
        rows = [
            (name, f"{s.mean:.4g}", f"{s.std:.4g}",
             f"[{s.q1:.4g}, {s.q3:.4g}]", f"[{s.min:.4g}, {s.max:.4g}]")
            for name, s in bootstrap.metric_summaries.items()
        ]
        items = [
            Table(
                headers=["Metric", "Mean", "Std", "IQR", "Range"],
                rows=rows, caption="Bootstrapped metric distributions",
            ),
            SimpleText(
                f"{len(bootstrap.zero_crossing_indices)} coefficients have "
                "bootstrap intervals containing zero."
            ),
        ]
        doc.chapters.append(Chapter("Bootstrap analysis", [Section("Metrics", items)]))

    if hosmer_lemeshow:
        hl = hosmer_lemeshow
        mids = [(b.lower + b.upper) / 2 for b in hl.bins]
        obs_rate = [
            b.observed_pos / b.count if b.count else float("nan") for b in hl.bins
        ]
        items = [
            SimpleText(
                f"chi^2 = {hl.chi_squared:.4g} with {hl.degrees_of_freedom} "
                f"d.o.f.; P[chi^2 >= observed | calibrated] = {hl.p_value:.4g}"
            ),
            Plot(
                title="Calibration: observed positive rate vs predicted probability",
                x_label="predicted probability (bin center)",
                y_label="observed positive rate",
                series=[
                    ("observed", mids, obs_rate),
                    ("ideal", [0.0, 1.0], [0.0, 1.0]),
                ],
            ),
        ]
        if hl.warnings:
            items.append(BulletedList(hl.warnings[:10]))
        doc.chapters.append(Chapter("Hosmer-Lemeshow calibration",
                                    [Section("Goodness of fit", items)]))

    if independence:
        kt = independence
        doc.chapters.append(Chapter("Prediction-error independence", [Section(
            "Kendall tau", [
                Table(
                    headers=["Statistic", "Value"],
                    rows=[
                        ("tau-alpha", f"{kt.tau_alpha:.4g}"),
                        ("tau-beta", f"{kt.tau_beta:.4g}"),
                        ("z", f"{kt.z_alpha:.4g}"),
                        ("P[dependent]", f"{kt.prob_dependent:.4g}"),
                        ("p-value (H0: independent)", f"{kt.p_value:.4g}"),
                        ("concordant", kt.num_concordant),
                        ("discordant", kt.num_discordant),
                    ],
                ),
            ] + ([SimpleText(kt.message)] if kt.message else []),
        )]))

    importance_sections = [
        Section(rep.importance_description, [
            Table(
                headers=["Rank", "Name", "Term", "Importance"],
                rows=[
                    (r + 1, name, term, f"{imp:.4g}")
                    for r, (name, term, _, imp)
                    in enumerate(rep.ranked_features)
                ],
            ),
        ])
        for rep in (importance, importance_variance)
        if rep is not None
    ]
    if importance_sections:
        doc.chapters.append(Chapter("Feature importance", importance_sections))

    return doc


def write_diagnostic_report(path: str, document: Document) -> str:
    """Render to ``model-diagnostic.html`` under ``path`` (a directory)."""
    os.makedirs(path, exist_ok=True)
    out = os.path.join(path, "model-diagnostic.html")
    with open(out, "w") as f:
        f.write(render_html(document))
    return out
