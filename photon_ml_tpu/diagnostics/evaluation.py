"""Per-model metric maps.

Reference parity: Evaluation.scala:31-160 — regression metrics (RMSE, MAE,
MSE), binary-classification metrics (ROC AUC, PR AUC, peak F1), and per-task
log-likelihood losses (logistic, Poisson, squared, smoothed hinge). The
reference wrapped Spark MLlib's metric classes; here the math is direct
vectorized numpy over (scores, labels, weights).
"""

from __future__ import annotations

from typing import Dict

import numpy as np

from photon_ml_tpu.evaluation.evaluators import (
    _np_auc,
    _np_logistic,
    _np_poisson,
    _np_smoothed_hinge,
)
from photon_ml_tpu.types import TaskType

MetricsMap = Dict[str, float]

# metric names (reference Evaluation.scala:31-44)
ROOT_MEAN_SQUARED_ERROR = "RMSE"
MEAN_ABSOLUTE_ERROR = "MAE"
MEAN_SQUARED_ERROR = "MSE"
AREA_UNDER_ROC = "Area under ROC"
AREA_UNDER_PRECISION_RECALL = "Area under precision/recall"
PEAK_F1_SCORE = "Peak F1 score"
DATA_LOG_LIKELIHOOD = "Per-datum log likelihood"


def _sigmoid(z: np.ndarray) -> np.ndarray:
    return 0.5 * (1.0 + np.tanh(0.5 * z))


def _precision_recall_points(scores, labels, weights):
    """(precision, recall) at each distinct score threshold, descending
    (weighted; tied thresholds collapsed to their last cumulative point).
    Returns (None, None) with no positives."""
    scores = np.asarray(scores, dtype=np.float64)
    labels = np.asarray(labels, dtype=np.float64)
    w = np.ones_like(scores) if weights is None else np.asarray(weights, np.float64)
    pos = labels > 0.5
    total_pos = float(w[pos].sum())
    if total_pos == 0:
        return None, None
    order = np.argsort(-scores, kind="stable")
    tp = np.cumsum(np.where(pos[order], w[order], 0.0))
    fp = np.cumsum(np.where(~pos[order], w[order], 0.0))
    s_sorted = scores[order]
    last_of_tie = np.append(s_sorted[1:] != s_sorted[:-1], True)
    tp, fp = tp[last_of_tie], fp[last_of_tie]
    precision = tp / np.maximum(tp + fp, 1e-30)
    recall = tp / total_pos
    return precision, recall


def area_under_pr_curve(scores, labels, weights=None) -> float:
    """Weighted PR AUC by descending-score sweep (MLlib areaUnderPR
    semantics: trapezoid over (recall, precision), anchored at the first
    point's precision)."""
    precision, recall = _precision_recall_points(scores, labels, weights)
    if precision is None:
        return float("nan")
    r = np.concatenate([[0.0], recall])
    p = np.concatenate([[precision[0] if len(precision) else 1.0], precision])
    return float(np.sum((r[1:] - r[:-1]) * (p[1:] + p[:-1]) / 2.0))


def peak_f1(scores, labels, weights=None) -> float:
    """max_t F1(t) over all score thresholds (MLlib fMeasureByThreshold)."""
    precision, recall = _precision_recall_points(scores, labels, weights)
    if precision is None:
        return float("nan")
    f1 = 2 * precision * recall / np.maximum(precision + recall, 1e-30)
    return float(np.max(f1))


def evaluate_metrics(
    scores,
    labels,
    task: TaskType,
    weights=None,
) -> MetricsMap:
    """Metric map for raw margins ``scores`` (offsets already added).

    Regression tasks report RMSE/MAE/MSE on the mean prediction; logistic
    adds ROC-AUC, PR-AUC and peak F1 on the margin; each task reports its
    per-datum loss as the log-likelihood proxy (Evaluation.scala:55-160).
    """
    scores = np.asarray(scores, dtype=np.float64)
    labels = np.asarray(labels, dtype=np.float64)
    w = np.ones_like(scores) if weights is None else np.asarray(weights, np.float64)
    wsum = float(np.maximum(w.sum(), 1e-30))
    out: MetricsMap = {}

    if task is TaskType.LOGISTIC_REGRESSION:
        mean = _sigmoid(scores)
        out[AREA_UNDER_ROC] = _np_auc(scores, labels, w)
        out[AREA_UNDER_PRECISION_RECALL] = area_under_pr_curve(scores, labels, w)
        out[PEAK_F1_SCORE] = peak_f1(scores, labels, w)
        out[DATA_LOG_LIKELIHOOD] = -_np_logistic(scores, labels, w)
    elif task is TaskType.POISSON_REGRESSION:
        mean = np.exp(scores)
        out[DATA_LOG_LIKELIHOOD] = -_np_poisson(scores, labels, w)
    elif task is TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM:
        mean = scores
        out[DATA_LOG_LIKELIHOOD] = -_np_smoothed_hinge(scores, labels, w)
        out[AREA_UNDER_ROC] = _np_auc(scores, labels, w)
    else:
        mean = scores
        out[DATA_LOG_LIKELIHOOD] = -float(
            np.sum(w * (scores - labels) ** 2) / (2 * wsum)
        )

    err = mean - labels
    out[MEAN_SQUARED_ERROR] = float(np.sum(w * err * err) / wsum)
    out[ROOT_MEAN_SQUARED_ERROR] = float(np.sqrt(out[MEAN_SQUARED_ERROR]))
    out[MEAN_ABSOLUTE_ERROR] = float(np.sum(w * np.abs(err)) / wsum)
    return out
