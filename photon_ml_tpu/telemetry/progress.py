"""Convergence observability plane: per-coordinate / per-block progress
telemetry, the divergence watchdog, and convergence-report reconstruction.

Every surface built in PRs 5–6 observes *time*; this module observes
*optimization progress*. A :class:`ConvergenceTracker` records, per outer
iteration and per coordinate, the objective value, gradient norm,
coefficient-delta norm, and solver/line-search iteration counts, plus the
held-out metric trace and — on the streaming path — per-block partial
loss / partial gradient norm / duality-gap estimates (see
``streaming.solver.BlockStatsProbe``). Records stream to a
checksum-friendly JSONL ledger (``type: "progress"``; schema enforced by
``telemetry/validate.py``), feed ``progress.*`` counters/gauges in the
:class:`MetricsRegistry`, and stay resident in memory for the live
``/progress`` introspection endpoint.

The embedded **divergence watchdog** turns the same stream into a health
signal: a non-finite objective, an objective increase beyond tolerance, or
repeated line-search failure while the gradient is still large emits a
structured :class:`photon_ml_tpu.event.AnomalyEvent`, flips ``health()``
unhealthy (503 on ``/healthz``), and — with ``abort_on_divergence`` —
raises :class:`DivergenceError` so the driver aborts cleanly instead of
saving a garbage model.

``convergence_report`` reconstructs the ledger into iterations-to-
tolerance per coordinate, per-coordinate objective share, and
stall/plateau detection (``analyze_run --progress``); the per-block gap
estimates use the same first-order surrogate the DuHL gap scheduler
(``streaming/gapsched.py``, arxiv 1702.07005) schedules stochastic
epochs by, and its per-epoch visit decisions land here as ``schedule``
records via :meth:`ConvergenceTracker.record_schedule`.

Disabled-by-default contract: with no tracker attached, training runs the
identical programs and produces bitwise-identical models (same contract as
the tracer).
"""

from __future__ import annotations

import math
import threading
from typing import Any, Dict, List, Optional

from photon_ml_tpu.event import AnomalyEvent
from photon_ml_tpu.telemetry.metrics import MetricsRegistry, get_registry
from photon_ml_tpu.telemetry.sinks import RunLedger

__all__ = [
    "ConvergenceTracker",
    "DivergenceError",
    "convergence_report",
    "extract_progress_records",
    "format_progress_report",
    "iterations_to_target_metric",
]


class DivergenceError(RuntimeError):
    """Training diverged: the watchdog tripped and ``abort_on_divergence``
    is set. Carries the structured anomaly for the driver's error path."""

    def __init__(self, anomaly: Dict[str, Any]):
        super().__init__(
            f"training diverged: {anomaly.get('anomaly_kind')} at outer "
            f"iteration {anomaly.get('outer')} coordinate "
            f"{anomaly.get('coordinate')!r} "
            f"(objective={anomaly.get('objective')!r})"
        )
        self.anomaly = anomaly


class ConvergenceTracker:
    """Records optimization progress and watches for divergence.

    Thread-safe: the introspection server reads ``health()`` /
    ``progress_json()`` from handler threads while the training thread
    appends. With ``ledger_path`` the tracker owns a dedicated
    ``progress.jsonl`` ledger (meta start/finish records bracket the run);
    with ``ledger`` it rides along an existing run ledger and writes only
    ``progress`` records.
    """

    #: consecutive line-search failures (with a still-large gradient)
    #: before the watchdog calls it a stall
    DEFAULT_MAX_LINE_SEARCH_FAILURES = 3

    def __init__(
        self,
        ledger_path: Optional[str] = None,
        ledger: Optional[RunLedger] = None,
        registry: Optional[MetricsRegistry] = None,
        emitter=None,
        divergence_tolerance: float = 1e-3,
        max_line_search_failures: Optional[int] = None,
        line_search_grad_norm: float = 1.0,
        abort_on_divergence: bool = True,
        label: str = "progress",
    ):
        if ledger is not None and ledger_path is not None:
            raise ValueError("pass ledger_path or ledger, not both")
        self._owns_ledger = ledger is None and ledger_path is not None
        self.ledger = ledger
        if self._owns_ledger:
            self.ledger = RunLedger(ledger_path)
            self.ledger.write("meta", phase="start", label=label)
        self.registry = registry if registry is not None else get_registry()
        self.emitter = emitter
        self.divergence_tolerance = float(divergence_tolerance)
        self.max_line_search_failures = (
            self.DEFAULT_MAX_LINE_SEARCH_FAILURES
            if max_line_search_failures is None
            else int(max_line_search_failures)
        )
        self.line_search_grad_norm = float(line_search_grad_norm)
        self.abort_on_divergence = bool(abort_on_divergence)
        self._lock = threading.RLock()
        self.records: List[Dict[str, Any]] = []
        # full skew profiles (fragment timelines included) — the trimmed
        # ledger records keep only the per-pass/per-host aggregates
        self.cluster_passes: List[Dict[str, Any]] = []
        self.anomaly: Optional[Dict[str, Any]] = None
        self._last_objective: Optional[float] = None
        self._resilience_count = 0
        self._failure_sink = None
        self._ls_failures = 0
        self._phase = "training"
        self._closed = False

    # -- recording --------------------------------------------------------

    def _emit(self, record: Dict[str, Any]) -> None:
        """Append to the in-memory trace and the ledger (caller holds the
        lock); the ledger adds type/ts."""
        self.records.append(record)
        if self.ledger is not None:
            self.ledger.write("progress", **record)

    def record_coordinate(
        self,
        outer: int,
        coordinate: str,
        objective: float,
        loss: Optional[float] = None,
        regularization: Optional[float] = None,
        grad_norm: Optional[float] = None,
        coef_delta_norm: Optional[float] = None,
        solver_iterations: Optional[int] = None,
        line_search_trials: Optional[int] = None,
        convergence_reason: Optional[str] = None,
    ) -> None:
        """One coordinate update's progress point. Runs the watchdog; may
        raise :class:`DivergenceError` (after recording the anomaly)."""
        objective = float(objective)
        with self._lock:
            rec: Dict[str, Any] = {
                "kind": "coordinate",
                "outer": int(outer),
                "coordinate": str(coordinate),
                "objective": objective,
            }
            if loss is not None:
                rec["loss"] = float(loss)
            if regularization is not None:
                rec["regularization"] = float(regularization)
            if grad_norm is not None:
                rec["grad_norm"] = float(grad_norm)
            if coef_delta_norm is not None:
                rec["coef_delta_norm"] = float(coef_delta_norm)
            if solver_iterations is not None:
                rec["solver_iterations"] = int(solver_iterations)
            if line_search_trials is not None:
                rec["line_search_trials"] = int(line_search_trials)
            if convergence_reason is not None:
                rec["convergence_reason"] = str(convergence_reason)
            self._emit(rec)
            reg = self.registry
            reg.count("progress.coordinate_updates")
            reg.gauge("progress.objective", objective)
            reg.gauge(f"progress.{coordinate}.objective", objective)
            if grad_norm is not None:
                reg.gauge(f"progress.{coordinate}.grad_norm", float(grad_norm))
            if coef_delta_norm is not None:
                reg.gauge(
                    f"progress.{coordinate}.coef_delta_norm",
                    float(coef_delta_norm),
                )
            if solver_iterations is not None:
                reg.count("progress.solver_iterations", int(solver_iterations))
            if line_search_trials is not None:
                reg.count(
                    "progress.line_search_trials", int(line_search_trials)
                )
            self._watchdog(rec, grad_norm, convergence_reason)

    def record_validation(self, outer: int, coordinate: str, metric) -> None:
        with self._lock:
            self._emit({
                "kind": "validation",
                "outer": int(outer),
                "coordinate": str(coordinate),
                "metric": float(metric),
            })
            self.registry.gauge("progress.validation_metric", float(metric))

    def record_blocks(
        self, outer: int, coordinate: str, block_stats: List[Dict[str, Any]]
    ) -> None:
        """Per-block contributions of a streamed solve's final pass
        (``BlockStatsProbe.last_pass``). Gap estimates also land as
        ``stream.block_gap.<index>`` gauges — the DuHL scheduler seam."""
        with self._lock:
            for stat in block_stats:
                self._emit({
                    "kind": "block",
                    "outer": int(outer),
                    "coordinate": str(coordinate),
                    "block": int(stat["block"]),
                    "partial_loss": float(stat["partial_loss"]),
                    "partial_grad_norm": float(stat["partial_grad_norm"]),
                    "gap_estimate": float(stat["gap_estimate"]),
                })
                self.registry.gauge(
                    f"stream.block_gap.{int(stat['block'])}",
                    float(stat["gap_estimate"]),
                )
            if block_stats:
                gaps = [float(s["gap_estimate"]) for s in block_stats]
                self.registry.gauge("stream.block_gap_max", max(gaps))
                self.registry.gauge("stream.block_gap_sum", sum(gaps))
                self.registry.count("progress.block_records", len(block_stats))

    def record_schedule(
        self, outer: int, coordinate: str, decisions: List[Dict[str, Any]]
    ) -> None:
        """Per-epoch gap-scheduler decisions of a stochastic streamed solve
        (``GapScheduler.drain_decisions()``): how many blocks the epoch
        visited, how many were pure exploration picks, and the score
        spread the choice was made on."""
        with self._lock:
            for d in decisions:
                rec = {
                    "kind": "schedule",
                    "outer": int(outer),
                    "coordinate": str(coordinate),
                    "epoch": int(d["epoch"]),
                    "visited": int(d["visited"]),
                    "explored": int(d["explored"]),
                    "num_blocks": int(d["num_blocks"]),
                }
                for key in ("unvisited", "score_max", "score_mean"):
                    if key in d:
                        rec[key] = float(d[key])
                self._emit(rec)
            if decisions:
                self.registry.count(
                    "progress.schedule_records", len(decisions)
                )

    def record_residency(
        self, outer: int, coordinate: str, decisions: List[Dict[str, Any]]
    ) -> None:
        """Pin/evict decisions of the HBM residency plane
        (``ResidencyManager.drain_decisions()``): which block entered or
        left the device-resident set, on what staleness-decayed gap score
        (-1.0 = bootstrap pin, no measurement yet), and the H2D byte delta
        the decision implies for every later pass."""
        with self._lock:
            if self._closed:
                return
            for d in decisions:
                self._emit({
                    "kind": "residency",
                    "outer": int(outer),
                    "coordinate": str(coordinate),
                    "epoch": int(d["epoch"]),
                    "action": str(d["action"]),
                    "block": int(d["block"]),
                    "gap_score": float(d["gap_score"]),
                    "byte_delta": int(d["byte_delta"]),
                    "resident_blocks": int(d.get("resident_blocks", 0)),
                    "resident_bytes": int(d.get("resident_bytes", 0)),
                })
            if decisions:
                last = decisions[-1]
                self.registry.gauge(
                    "stream.residency.resident_blocks",
                    float(last.get("resident_blocks", 0)),
                )
                self.registry.gauge(
                    "stream.residency.resident_bytes",
                    float(last.get("resident_bytes", 0)),
                )
                self.registry.count(
                    "progress.residency_records", len(decisions)
                )

    def record_cluster(
        self, outer: int, coordinate: str, events: List[Dict[str, Any]]
    ) -> None:
        """Cluster-plane events of a distributed streamed solve
        (``ClusterCoordinator.drain_events()``): per-pass block rebalances,
        host losses, and reassignments. Host losses are degraded-but-
        recovered signals — they land in the ledger and counters but do
        not flip health (the job survived by design)."""
        with self._lock:
            if self._closed:
                return
            for ev in events:
                rec: Dict[str, Any] = {
                    "kind": "cluster",
                    "outer": int(outer),
                    "coordinate": str(coordinate),
                    "event": str(ev.get("event", "unknown")),
                }
                for key, val in ev.items():
                    if key != "event":
                        rec[key] = val
                self._emit(rec)
            if events:
                self.registry.count("progress.cluster_records", len(events))

    def record_cluster_passes(
        self, outer: int, coordinate: str, profiles: List[Dict[str, Any]]
    ) -> None:
        """Per-pass skew profiles of a distributed streamed solve
        (``ClusterCoordinator.drain_pass_profiles()``): one ``cluster_pass``
        record per pass (wall decomposed exactly into busy + allreduce
        wait + coordinator bubble, plus the straggler picture) and one
        ``host_pass`` record per (pass, host) with that host's measured
        busy/wall/blocks and its predicted-vs-actual work share. Full
        profiles (with fragment timelines) stay in ``self.cluster_passes``
        for the /cluster route and per-host trace-lane export."""
        with self._lock:
            if self._closed:
                return
            for p in profiles:
                hosts = p.get("hosts") or {}
                self._emit({
                    "kind": "cluster_pass",
                    "outer": int(outer),
                    "coordinate": str(coordinate),
                    "pass_id": int(p["pass_id"]),
                    "wall_s": float(p["wall_s"]),
                    "busy_s": float(p["busy_s"]),
                    "allreduce_wait_s": float(p["allreduce_wait_s"]),
                    "bubble_s": float(p["bubble_s"]),
                    "straggler_index": float(p.get("straggler_index", 1.0)),
                    "straggler_host": int(p.get("straggler_host", -1)),
                    "hosts": len(hosts),
                    "blocks": int(p.get("blocks", 0)),
                    "stray_partials": int(p.get("stray_partials", 0)),
                    "requeued_blocks": int(p.get("requeued_blocks", 0)),
                })
                for host in sorted(hosts, key=int):
                    h = hosts[host]
                    rec: Dict[str, Any] = {
                        "kind": "host_pass",
                        "outer": int(outer),
                        "coordinate": str(coordinate),
                        "pass_id": int(p["pass_id"]),
                        "host": int(host),
                        "busy_s": float(h.get("busy_s", 0.0)),
                        "wall_s": float(h.get("wall_s", 0.0)),
                        "blocks": int(h.get("blocks", 0)),
                        "frags": int(h.get("frags", 0)),
                        "decode_s": float(h.get("decode_s", 0.0)),
                        "solve_s": float(h.get("solve_s", 0.0)),
                        "reply_s": float(h.get("reply_s", 0.0)),
                        "h2d_bytes": int(h.get("h2d_bytes", 0)),
                    }
                    if h.get("predicted_share") is not None:
                        rec["predicted_share"] = float(h["predicted_share"])
                    if h.get("actual_share") is not None:
                        rec["actual_share"] = float(h["actual_share"])
                    self._emit(rec)
            if profiles:
                self.cluster_passes.extend(dict(p) for p in profiles)
                self.registry.count(
                    "progress.cluster_pass_records", len(profiles)
                )

    def cluster_json(self) -> Dict[str, Any]:
        """Payload for the live ``/cluster`` introspection route: the
        skew profiles recorded so far plus the latest straggler picture."""
        with self._lock:
            passes = [dict(p) for p in self.cluster_passes]
        doc: Dict[str, Any] = {"num_passes": len(passes), "passes": passes}
        if passes:
            last = passes[-1]
            doc["straggler_index_last"] = last.get("straggler_index")
            doc["straggler_host_last"] = last.get("straggler_host")
            doc["allreduce_wait_last_s"] = last.get("allreduce_wait_s")
        return doc

    def record_resilience(
        self,
        failure_kind: str,
        site: str,
        detail: str = "",
        outer: Optional[int] = None,
        coordinate: Optional[str] = None,
        block: Optional[int] = None,
    ) -> None:
        """One failure-plane event (retry exhaustion, skipped block,
        thread crash) as a ``resilience`` ledger record. These are the
        *recovered/degraded* signals: they count and persist but do NOT
        flip health — divergence anomalies keep that role."""
        with self._lock:
            if self._closed:
                return
            rec: Dict[str, Any] = {
                "kind": "resilience",
                "failure_kind": str(failure_kind),
                "site": str(site),
                "detail": str(detail),
            }
            if outer is not None:
                rec["outer"] = int(outer)
            if coordinate is not None:
                rec["coordinate"] = str(coordinate)
            if block is not None:
                rec["block"] = int(block)
            self._emit(rec)
            self._resilience_count += 1
            self.registry.count("progress.resilience_records")
        if self.emitter is not None:
            self.emitter.send_event(AnomalyEvent(
                kind=str(failure_kind),
                coordinate_id=str(coordinate) if coordinate else str(site),
                outer_iteration=int(outer) if outer is not None else -1,
                objective_value=float("nan"),
                detail={"site": str(site), "detail": str(detail)},
            ))

    def attach_failure_sink(self) -> None:
        """Subscribe this tracker to the process-global resilience failure
        stream: every ``record_failure`` lands in the progress ledger as a
        ``resilience`` record (detached automatically by :meth:`finish`)."""
        from photon_ml_tpu.resilience.failures import add_failure_sink

        if getattr(self, "_failure_sink", None) is not None:
            return

        def _sink(rec: Dict[str, Any]) -> None:
            self.record_resilience(
                rec.get("kind", "unknown"),
                rec.get("site", ""),
                rec.get("detail", ""),
                block=rec.get("block"),
            )

        self._failure_sink = _sink
        add_failure_sink(_sink)

    def detach_failure_sink(self) -> None:
        sink = getattr(self, "_failure_sink", None)
        if sink is None:
            return
        from photon_ml_tpu.resilience.failures import remove_failure_sink

        remove_failure_sink(sink)
        self._failure_sink = None

    # -- divergence watchdog ---------------------------------------------

    def _watchdog(
        self,
        rec: Dict[str, Any],
        grad_norm: Optional[float],
        convergence_reason: Optional[str],
    ) -> None:
        objective = rec["objective"]
        anomaly_kind = None
        detail: Dict[str, Any] = {}
        if not math.isfinite(objective):
            anomaly_kind = "non_finite_objective"
        elif self._last_objective is not None:
            allowed = self._last_objective + self.divergence_tolerance * max(
                1.0, abs(self._last_objective)
            )
            if objective > allowed:
                anomaly_kind = "objective_increase"
                detail = {
                    "previous_objective": self._last_objective,
                    "allowed_objective": allowed,
                }
        if anomaly_kind is None:
            # "line search failed" is ALSO what a converged solve reports
            # (no descent step improves on the optimum), so a failure only
            # counts toward the stall watchdog while the gradient says we
            # are still far from stationarity
            failed = (
                convergence_reason == "OBJECTIVE_NOT_IMPROVING"
                and grad_norm is not None
                and grad_norm > self.line_search_grad_norm
            )
            self._ls_failures = self._ls_failures + 1 if failed else 0
            if self._ls_failures >= self.max_line_search_failures:
                anomaly_kind = "line_search_stall"
                detail = {
                    "consecutive_failures": self._ls_failures,
                    "grad_norm": grad_norm,
                }
        if math.isfinite(objective):
            self._last_objective = objective
        if anomaly_kind is None:
            return
        self._trip(anomaly_kind, rec, detail)

    def _trip(
        self, anomaly_kind: str, rec: Dict[str, Any], detail: Dict[str, Any]
    ) -> None:
        anomaly = {
            "kind": "anomaly",
            "anomaly_kind": anomaly_kind,
            "outer": rec["outer"],
            "coordinate": rec["coordinate"],
            "objective": rec["objective"],
            "detail": detail,
        }
        self.anomaly = anomaly
        self._phase = "diverged"
        self._emit(anomaly)
        self.registry.count("progress.anomalies")
        if self.emitter is not None:
            self.emitter.send_event(AnomalyEvent(
                kind=anomaly_kind,
                coordinate_id=rec["coordinate"],
                outer_iteration=rec["outer"],
                objective_value=rec["objective"],
                detail=detail,
            ))
        if self.abort_on_divergence:
            raise DivergenceError(anomaly)

    # -- live introspection ----------------------------------------------

    @property
    def healthy(self) -> bool:
        return self.anomaly is None

    def health(self) -> Dict[str, Any]:
        """Payload for the ``/healthz`` endpoint (503 when unhealthy)."""
        with self._lock:
            last = None
            for rec in reversed(self.records):
                if rec["kind"] == "coordinate":
                    last = rec
                    break
            doc: Dict[str, Any] = {
                "healthy": self.anomaly is None,
                "phase": self._phase,
            }
            if last is not None:
                doc["outer"] = last["outer"]
                doc["coordinate"] = last["coordinate"]
                doc["objective"] = last["objective"]
            if self.anomaly is not None:
                doc["anomaly"] = dict(self.anomaly)
            if self._resilience_count:
                # recovered/degraded events: visible, but not unhealthy
                doc["resilience_events"] = self._resilience_count
            return doc

    def progress_json(self) -> Dict[str, Any]:
        """Payload for the ``/progress`` endpoint: the full in-memory
        trace plus health."""
        with self._lock:
            return {
                "healthy": self.anomaly is None,
                "phase": self._phase,
                "num_records": len(self.records),
                "records": [dict(r) for r in self.records],
                "anomaly": dict(self.anomaly) if self.anomaly else None,
            }

    def finish(self) -> None:
        """Mark training done and close an owned ledger (idempotent)."""
        self.detach_failure_sink()
        with self._lock:
            if self._closed:
                return
            self._closed = True
            if self._phase == "training":
                self._phase = "finished"
            if self._owns_ledger and self.ledger is not None:
                self.ledger.write(
                    "meta", phase="finish", num_records=len(self.records),
                    healthy=self.anomaly is None,
                )
                self.ledger.close()

    close = finish


# -- reconstruction (analyze_run --progress, the convergence sentinel) ----


def extract_progress_records(
    records: List[Dict[str, Any]],
) -> List[Dict[str, Any]]:
    """The ``progress`` records of a validated ledger, in write order."""
    return [r for r in records if r.get("type") == "progress"]


def iterations_to_target_metric(
    progress: List[Dict[str, Any]], target: float, higher_is_better: bool = True
) -> Optional[int]:
    """First outer iteration (1-based count of coordinate updates' outers)
    whose validation probe reaches ``target``; None if never reached."""
    for rec in progress:
        if rec.get("kind") != "validation":
            continue
        metric = rec["metric"]
        if (metric >= target) if higher_is_better else (metric <= target):
            return int(rec["outer"]) + 1
    return None


def _iters_to_tolerance(
    trace: List[tuple], final: float, tolerance: float
) -> Optional[int]:
    """1-based count of updates until the objective stays within
    ``tolerance`` (relative) of its final value."""
    scale = max(1.0, abs(final))
    for i, (_, obj) in enumerate(trace):
        if all(
            abs(o - final) <= tolerance * scale for _, o in trace[i:]
        ):
            return i + 1
    return None


def convergence_report(
    progress: List[Dict[str, Any]], tolerance: float = 1e-3
) -> Dict[str, Any]:
    """Reconstruct a convergence report from ``progress`` records.

    Per coordinate: updates, first/final objective, objective share (the
    coordinate's fraction of the total objective drop, attributed to the
    update that realized it), iterations-to-tolerance, solver totals, and
    plateau detection (the last two updates each improved the objective by
    less than ``tolerance`` relative).
    """
    coord_rows = [r for r in progress if r.get("kind") == "coordinate"]
    val_rows = [r for r in progress if r.get("kind") == "validation"]
    block_rows = [r for r in progress if r.get("kind") == "block"]
    anomalies = [r for r in progress if r.get("kind") == "anomaly"]
    residency_rows = [r for r in progress if r.get("kind") == "residency"]

    report: Dict[str, Any] = {
        "num_updates": len(coord_rows),
        "coordinates": {},
        "objective_trace": [
            [r["outer"], r["coordinate"], r["objective"]] for r in coord_rows
        ],
        "validation_trace": [
            [r["outer"], r["coordinate"], r["metric"]] for r in val_rows
        ],
        "anomalies": anomalies,
        "blocks": {},
        "tolerance": tolerance,
    }
    if not coord_rows:
        return report

    first_obj = coord_rows[0]["objective"]
    final_obj = coord_rows[-1]["objective"]
    total_drop = first_obj - final_obj
    report["first_objective"] = first_obj
    report["final_objective"] = final_obj
    report["objective_drop"] = total_drop
    full_trace = [(r["outer"], r["objective"]) for r in coord_rows]
    report["iterations_to_tolerance"] = _iters_to_tolerance(
        full_trace, final_obj, tolerance
    )
    if val_rows:
        report["final_validation_metric"] = val_rows[-1]["metric"]

    prev_obj = None
    per_coord: Dict[str, Dict[str, Any]] = {}
    for rec in coord_rows:
        cid = rec["coordinate"]
        c = per_coord.setdefault(cid, {
            "updates": 0,
            "first_objective": rec["objective"],
            "objective_share": 0.0,
            "solver_iterations": 0,
            "line_search_trials": 0,
            "trace": [],
        })
        c["updates"] += 1
        c["final_objective"] = rec["objective"]
        c["trace"].append((rec["outer"], rec["objective"]))
        if prev_obj is not None:
            c["objective_share"] += prev_obj - rec["objective"]
        c["solver_iterations"] += int(rec.get("solver_iterations") or 0)
        c["line_search_trials"] += int(rec.get("line_search_trials") or 0)
        if rec.get("grad_norm") is not None:
            c["final_grad_norm"] = rec["grad_norm"]
        prev_obj = rec["objective"]

    for cid, c in per_coord.items():
        trace = c.pop("trace")
        c["objective_share"] = (
            c["objective_share"] / total_drop if total_drop > 0 else 0.0
        )
        c["iterations_to_tolerance"] = _iters_to_tolerance(
            trace, c["final_objective"], tolerance
        )
        deltas = [
            a[1] - b[1] for a, b in zip(trace, trace[1:])
        ]
        scale = max(1.0, abs(c["final_objective"]))
        c["stalled"] = len(deltas) >= 2 and all(
            d <= tolerance * scale for d in deltas[-2:]
        )
    report["coordinates"] = per_coord

    if block_rows:
        per_blocks: Dict[str, Dict[str, Any]] = {}
        for rec in block_rows:
            cid = rec["coordinate"]
            b = per_blocks.setdefault(cid, {"final_pass": {}})
            # later records overwrite earlier ones per block index, so
            # final_pass ends as the LAST recorded pass per coordinate
            b.setdefault("_latest_outer", rec["outer"])
            if rec["outer"] >= b["_latest_outer"]:
                if rec["outer"] > b["_latest_outer"]:
                    b["final_pass"] = {}
                    b["_latest_outer"] = rec["outer"]
                b["final_pass"][int(rec["block"])] = {
                    "partial_loss": rec["partial_loss"],
                    "partial_grad_norm": rec["partial_grad_norm"],
                    "gap_estimate": rec["gap_estimate"],
                }
        for cid, b in per_blocks.items():
            b.pop("_latest_outer", None)
            gaps = [v["gap_estimate"] for v in b["final_pass"].values()]
            if gaps:
                b["gap_max"] = max(gaps)
                b["gap_sum"] = sum(gaps)
        report["blocks"] = per_blocks

    if residency_rows:
        per_res: Dict[str, Dict[str, Any]] = {}
        for rec in residency_rows:
            cid = rec["coordinate"]
            r = per_res.setdefault(cid, {
                "pins": 0, "evictions": 0, "resident_blocks": 0,
                "resident_bytes": 0, "saved_bytes_per_pass": 0,
            })
            if rec["action"] == "pin":
                r["pins"] += 1
            elif rec["action"] == "evict":
                r["evictions"] += 1
            # records are chronological: the last one carries the final
            # resident footprint; the byte deltas telescope to the same
            r["resident_blocks"] = int(rec.get("resident_blocks", 0))
            r["resident_bytes"] = int(rec.get("resident_bytes", 0))
            r["saved_bytes_per_pass"] = r["resident_bytes"]
        report["residency"] = per_res
    return report


def format_progress_report(report: Dict[str, Any]) -> str:
    """Human-readable convergence report (``analyze_run --progress``)."""
    lines: List[str] = []
    lines.append("== convergence report ==")
    lines.append(f"coordinate updates : {report.get('num_updates', 0)}")
    if "first_objective" in report:
        lines.append(
            f"objective          : {report['first_objective']:.6g} -> "
            f"{report['final_objective']:.6g} "
            f"(drop {report['objective_drop']:.6g})"
        )
        itt = report.get("iterations_to_tolerance")
        lines.append(
            f"iters-to-tolerance : "
            f"{itt if itt is not None else 'not reached'} "
            f"(tol {report['tolerance']:g} relative)"
        )
    if "final_validation_metric" in report:
        lines.append(
            f"final held-out     : {report['final_validation_metric']:.6g}"
        )
    coords = report.get("coordinates", {})
    if coords:
        lines.append("")
        lines.append(
            f"{'coordinate':<16} {'updates':>7} {'final obj':>12} "
            f"{'share':>7} {'to-tol':>6} {'slv-it':>6} {'stalled':>7}"
        )
        for cid, c in coords.items():
            itt = c.get("iterations_to_tolerance")
            lines.append(
                f"{cid:<16} {c['updates']:>7d} "
                f"{c['final_objective']:>12.6g} "
                f"{c['objective_share']:>6.1%} "
                f"{str(itt) if itt is not None else '-':>6} "
                f"{c['solver_iterations']:>6d} "
                f"{'yes' if c.get('stalled') else 'no':>7}"
            )
    blocks = report.get("blocks", {})
    for cid, b in blocks.items():
        final = b.get("final_pass", {})
        if final:
            lines.append("")
            lines.append(
                f"streamed blocks [{cid}]: {len(final)} blocks, "
                f"gap_sum={b.get('gap_sum', 0.0):.6g}, "
                f"gap_max={b.get('gap_max', 0.0):.6g}"
            )
    residency = report.get("residency", {})
    for cid, r in residency.items():
        lines.append("")
        lines.append(
            f"hbm residency [{cid}]: {r['resident_blocks']} blocks pinned "
            f"({r['resident_bytes'] / 1e6:.1f} MB), "
            f"{r['pins']} pins / {r['evictions']} evictions, "
            f"~{r['saved_bytes_per_pass'] / 1e6:.1f} MB H2D saved per pass"
        )
    anomalies = report.get("anomalies", [])
    if anomalies:
        lines.append("")
        for a in anomalies:
            lines.append(
                f"ANOMALY: {a.get('anomaly_kind')} at outer {a.get('outer')} "
                f"coordinate {a.get('coordinate')!r} "
                f"objective={a.get('objective')!r}"
            )
    return "\n".join(lines)
