"""Run-scoped telemetry session: tracer + registry + sinks, one handle.

A CLI (or bench lane) calls :func:`start_run` once, optionally
:meth:`TelemetryRun.attach`\\ es the driver's emitter so existing events
land in the ledger, and calls :meth:`TelemetryRun.finish` in its
``finally`` block. ``finish`` drains the tracer into the ledger and Chrome
trace files, records memory watermarks, logs the terminal summary table,
and returns the summary dict (which bench embeds in its artifacts).
"""
from __future__ import annotations

import logging
from typing import Any, Dict, List, Optional

import photon_ml_tpu.telemetry.metrics as _metrics
import photon_ml_tpu.telemetry.sinks as _sinks

# NB: imported per-name — the package __init__ re-exports a *function*
# named ``span`` that shadows the submodule on the package object.
from photon_ml_tpu.telemetry.span import Tracer, enable_tracing, get_tracer

_log = logging.getLogger("photon_ml_tpu.telemetry")

__all__ = ["TelemetryRun", "start_run"]


class TelemetryRun:
    """Owns the sinks for one run and (optionally) the global tracer."""

    def __init__(
        self,
        label: str,
        ledger: Optional[_sinks.RunLedger] = None,
        trace_path: Optional[str] = None,
        tracer: Optional[Tracer] = None,
        registry: Optional[_metrics.MetricsRegistry] = None,
    ):
        self.label = label
        self.ledger = ledger
        self.trace_path = trace_path
        self.tracer = tracer if tracer is not None else get_tracer()
        self.registry = (
            registry if registry is not None else _metrics.get_registry()
        )
        self._emitters: List[Any] = []
        self._finished = False
        self._spans_flushed = 0
        self._extra_trace_events: List[Dict[str, Any]] = []
        if self.ledger is not None:
            self.ledger.write("meta", phase="start", label=label)

    def add_trace_events(self, events) -> None:
        """Queue pre-built Chrome trace events (e.g. the per-host cluster
        lanes from :func:`~photon_ml_tpu.telemetry.sinks.cluster_lane_events`)
        for the trace file ``finish`` writes. No-op without a trace path."""
        self._extra_trace_events.extend(events)

    def attach(self, emitter) -> _sinks.TelemetryEventListener:
        """Register the event bridge on ``emitter`` and track it so
        ``finish`` can report its swallowed listener-error count."""
        listener = _sinks.TelemetryEventListener(
            ledger=self.ledger, registry=self.registry
        )
        emitter.register_listener(listener)
        self._emitters.append(emitter)
        return listener

    def listener_errors(self) -> int:
        return sum(
            int(getattr(emitter, "listener_errors", 0))
            for emitter in self._emitters
        )

    def checkpoint(self, label: str = "") -> None:
        """Span-tree checkpoint: drain spans finished so far into the
        ledger and fsync it, so a later crash still leaves an analyzable
        prefix. Spans are written once — a checkpoint remembers how many it
        has flushed and ``finish`` continues from there."""
        if self.ledger is None or self._finished:
            return
        spans = self.tracer.spans()
        for rec in spans[self._spans_flushed:]:
            self.ledger.write_span(rec, self.tracer.origin_unix)
        self._spans_flushed = len(spans)
        self.ledger.write(
            "meta", phase="checkpoint", label=label or self.label,
            num_spans=self._spans_flushed,
        )
        self.ledger.flush()

    def finish(self, extra: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
        """Drain spans into the sinks; returns the summary dict. Safe to
        call once per run (subsequent calls return the cached summary)."""
        if self._finished:
            return self._summary
        self._finished = True
        _metrics.record_memory_watermarks(self.registry)
        spans = self.tracer.spans()
        metrics_snapshot = self.registry.snapshot()
        listener_errors = self.listener_errors()
        summary: Dict[str, Any] = {
            "label": self.label,
            "num_spans": len(spans),
            "failed_spans": sum(1 for s in spans if s.failed),
            "listener_errors": listener_errors,
            "span_tree": _sinks.span_tree_summary(spans, max_depth=2),
            "jit_trace_counts": _metrics.jit_trace_counts(),
            "metrics": metrics_snapshot,
        }
        if extra:
            summary.update(extra)
        if self.trace_path:
            n = _sinks.write_chrome_trace(
                self.trace_path,
                spans,
                metadata={"label": self.label, "num_spans": len(spans)},
                extra_events=self._extra_trace_events or None,
                pid_key="host",
            )
            _log.info("wrote chrome trace (%d events) to %s", n, self.trace_path)
        if self.ledger is not None:
            for rec in spans[self._spans_flushed:]:
                self.ledger.write_span(rec, self.tracer.origin_unix)
            self._spans_flushed = len(spans)
            self.ledger.write("metrics", snapshot=metrics_snapshot)
            self.ledger.write(
                "meta",
                phase="finish",
                label=self.label,
                num_spans=len(spans),
                listener_errors=listener_errors,
            )
            self.ledger.close()
            _log.info(
                "wrote run ledger (%d records) to %s",
                self.ledger.num_records,
                self.ledger.path,
            )
        _log.info(
            "%s", _sinks.format_summary_table(spans, metrics_snapshot, self.label)
        )
        self._summary = summary
        return summary


def start_run(
    label: str,
    ledger_path: Optional[str] = None,
    trace_path: Optional[str] = None,
    enable_tracer: bool = True,
    device_sync: bool = True,
) -> TelemetryRun:
    """Open sinks and (by default) enable + clear the global tracer."""
    ledger = _sinks.RunLedger(ledger_path) if ledger_path else None
    tracer = get_tracer()
    if enable_tracer:
        enable_tracing(device_sync=device_sync, clear=True)
    return TelemetryRun(
        label=label, ledger=ledger, trace_path=trace_path, tracer=tracer
    )
