"""Hierarchical span tracer.

One process-global :class:`Tracer` collects :class:`SpanRecord`\\ s from
``with span("cd/outer_iter", outer=3):`` blocks.  Nesting is tracked with a
:mod:`contextvars` variable, so spans opened on different threads (or in
different asyncio tasks) chain to the right parent without any locking on
the hot path — the only lock is taken once per span, on close, to append
the finished record.

The disabled path is near-free: :func:`span` returns a singleton no-op
context manager (no allocation, no clock read), so instrumentation can stay
on hot loops unconditionally.  Spans that exit via an exception are kept
and tagged ``failed=True`` with the exception type name.

Optionally a span can request *device-sync* timing: the enter/exit clock
reads are preceded by a barrier that drains the async XLA dispatch queue,
so the measured wall time covers device work issued inside the block
instead of just the Python time spent enqueueing it.
"""
from __future__ import annotations

import contextvars
import dataclasses
import itertools
import threading
import time
from typing import Any, Dict, List, Optional

__all__ = [
    "SpanRecord",
    "Tracer",
    "get_tracer",
    "span",
    "timed_span",
    "enable_tracing",
    "disable_tracing",
]


@dataclasses.dataclass
class SpanRecord:
    """A finished span. ``start_s`` is seconds since the tracer's origin
    (``Tracer.origin_unix`` converts it to wall-clock time)."""

    span_id: int
    parent_id: Optional[int]
    name: str
    path: str
    depth: int
    start_s: float
    duration_s: float
    thread_id: int
    thread_name: str
    failed: bool = False
    error: Optional[str] = None
    attrs: Dict[str, Any] = dataclasses.field(default_factory=dict)


class _NoopSpan:
    """Singleton returned when tracing is disabled. Accepts the same calls
    as a live span so call sites never branch."""

    __slots__ = ()

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, *exc) -> bool:
        return False

    def set_attrs(self, **attrs) -> "_NoopSpan":
        return self


NOOP_SPAN = _NoopSpan()

# The innermost live span for the current thread/task (None at top level).
_CURRENT: contextvars.ContextVar[Optional["_LiveSpan"]] = contextvars.ContextVar(
    "photon_ml_tpu_current_span", default=None
)


def _device_barrier() -> None:
    """Best-effort barrier: block until previously dispatched device work
    (on the default backend) has retired. Used for device-sync spans."""
    try:  # pragma: no cover - exercised only with jax present (always here)
        import jax

        jax.block_until_ready(jax.device_put(0.0))
    except Exception:
        pass


class _LiveSpan:
    __slots__ = (
        "_tracer",
        "name",
        "attrs",
        "span_id",
        "parent_id",
        "path",
        "depth",
        "duration_s",
        "failed",
        "error",
        "_token",
        "_start",
        "_sync",
    )

    def __init__(self, tracer: "Tracer", name: str, sync: bool, attrs: Dict[str, Any]):
        self._tracer = tracer
        self.name = name
        self.attrs = attrs
        self._sync = sync
        self.span_id = next(tracer._ids)
        self.parent_id: Optional[int] = None
        self.path = name
        self.depth = 1
        self.duration_s = 0.0
        self.failed = False
        self.error: Optional[str] = None
        self._token: Optional[contextvars.Token] = None
        self._start = 0.0

    def set_attrs(self, **attrs) -> "_LiveSpan":
        self.attrs.update(attrs)
        return self

    def __enter__(self) -> "_LiveSpan":
        parent = _CURRENT.get()
        if parent is not None:
            self.parent_id = parent.span_id
            self.path = f"{parent.path}/{self.name}"
            self.depth = parent.depth + 1
        self._token = _CURRENT.set(self)
        if self._sync:
            _device_barrier()
        self._start = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        if self._sync:
            _device_barrier()
        end = time.perf_counter()
        if self._token is not None:
            _CURRENT.reset(self._token)
        self.duration_s = end - self._start
        self.failed = exc_type is not None
        self.error = exc_type.__name__ if exc_type is not None else None
        # timed_span measures even with tracing off; only record when on
        if self._tracer.enabled:
            thread = threading.current_thread()
            self._tracer._record(
                SpanRecord(
                    span_id=self.span_id,
                    parent_id=self.parent_id,
                    name=self.name,
                    path=self.path,
                    depth=self.depth,
                    start_s=self._start - self._tracer.origin_perf,
                    duration_s=self.duration_s,
                    thread_id=thread.ident or 0,
                    thread_name=thread.name,
                    failed=self.failed,
                    error=self.error,
                    attrs=self.attrs,
                )
            )
        return False  # never swallow exceptions


class Tracer:
    """Thread-safe collector of finished spans.

    ``enabled`` gates collection: when False, :meth:`span` hands back the
    shared no-op singleton. ``device_sync`` master-switches per-span barrier
    requests (so a run can ask for wall-only timing even at instrumented
    call sites that request a sync).
    """

    def __init__(self, enabled: bool = False, device_sync: bool = True):
        self.enabled = enabled
        self.device_sync = device_sync
        self._lock = threading.Lock()
        self._spans: List[SpanRecord] = []
        self._ids = itertools.count(1)
        # Anchor for converting perf_counter readings to wall-clock time.
        self.origin_perf = time.perf_counter()
        self.origin_unix = time.time()

    # ------------------------------------------------------------- control
    def span(self, name: str, device_sync: bool = False, **attrs):
        if not self.enabled:
            return NOOP_SPAN
        return _LiveSpan(self, name, device_sync and self.device_sync, attrs)

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()

    # ------------------------------------------------------------- access
    def _record(self, record: SpanRecord) -> None:
        with self._lock:
            self._spans.append(record)

    def spans(self) -> List[SpanRecord]:
        with self._lock:
            return list(self._spans)

    def __len__(self) -> int:
        with self._lock:
            return len(self._spans)


_TRACER = Tracer(enabled=False)


def get_tracer() -> Tracer:
    """The process-global tracer used by :func:`span`."""
    return _TRACER


def span(name: str, device_sync: bool = False, **attrs):
    """Open a span on the global tracer. Near-free when tracing is off:
    a single attribute check then the shared no-op context manager."""
    t = _TRACER
    if not t.enabled:
        return NOOP_SPAN
    return _LiveSpan(t, name, device_sync and t.device_sync, attrs)


def timed_span(name: str, **attrs) -> _LiveSpan:
    """An ALWAYS-measuring span: times the block whether or not tracing is
    on, exposing ``duration_s``/``failed``/``error`` afterwards, and lands
    in the tracer only when it is enabled. This is the single timing path
    behind ``utils.timer.Timer``/``Timed``."""
    return _LiveSpan(_TRACER, name, False, attrs)


def enable_tracing(device_sync: bool = True, clear: bool = True) -> Tracer:
    """Turn on the global tracer (optionally clearing prior spans)."""
    if clear:
        _TRACER.clear()
    _TRACER.device_sync = device_sync
    _TRACER.enabled = True
    return _TRACER


def disable_tracing() -> None:
    _TRACER.enabled = False
