"""Telemetry sinks: JSONL run ledger, Chrome trace export, summary table.

Three complementary views of one run:

* :class:`RunLedger` — an append-only JSONL file. Every line is one typed
  record (``{"type": ..., "ts": <unix seconds>, ...}``): ``meta`` for run
  boundaries, ``event`` for bridged :class:`~photon_ml_tpu.event.Event`\\ s,
  ``span`` for finished spans, ``metrics`` for registry snapshots.
* :func:`write_chrome_trace` — the span list as Chrome trace-event JSON
  (``ph: "X"`` complete events, microsecond timestamps), loadable in
  Perfetto / ``chrome://tracing``.
* :func:`format_summary_table` — an end-of-run terminal table aggregating
  spans by path with the headline counters.

:class:`TelemetryEventListener` bridges the existing pub/sub events into
the ledger (and folds the stats-bearing ones into the metrics registry),
so a run with ``--telemetry-out`` captures every ``Event`` without any of
the emit sites knowing telemetry exists.
"""
from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
from typing import Any, Dict, Iterable, List, Optional

from photon_ml_tpu.event import (
    AnomalyEvent,
    Event,
    EventListener,
    ModelSwapEvent,
    ScoringFinishEvent,
    SolverStatsEvent,
    TransferStatsEvent,
)
from photon_ml_tpu.telemetry.span import SpanRecord

__all__ = [
    "RunLedger",
    "TelemetryEventListener",
    "chrome_trace_events",
    "write_chrome_trace",
    "span_tree_summary",
    "format_summary_table",
]


def _jsonable(value: Any) -> Any:
    """Best-effort conversion to something json.dumps accepts."""
    if isinstance(value, (str, int, float, bool)) or value is None:
        return value
    if isinstance(value, dict):
        return {str(k): _jsonable(v) for k, v in value.items()}
    if isinstance(value, (list, tuple, set)):
        return [_jsonable(v) for v in value]
    if dataclasses.is_dataclass(value) and not isinstance(value, type):
        return _jsonable(dataclasses.asdict(value))
    item = getattr(value, "item", None)  # numpy scalars
    if callable(item):
        try:
            return item()
        except Exception:
            pass
    return repr(value)


class RunLedger:
    """Streaming JSONL writer. Thread-safe; every record is flushed so a
    crashed run still leaves a readable ledger prefix."""

    def __init__(self, path: str):
        self.path = str(path)
        parent = os.path.dirname(os.path.abspath(self.path))
        os.makedirs(parent, exist_ok=True)
        self._lock = threading.Lock()
        self._f = open(self.path, "w", encoding="utf-8")
        self.num_records = 0

    def write(self, record_type: str, **fields: Any) -> None:
        record = {"type": record_type, "ts": time.time()}
        record.update({k: _jsonable(v) for k, v in fields.items()})
        line = json.dumps(record, sort_keys=True)
        with self._lock:
            if self._f.closed:
                return
            self._f.write(line + "\n")
            self._f.flush()
            self.num_records += 1

    def write_span(self, rec: SpanRecord, origin_unix: float) -> None:
        self.write(
            "span",
            name=rec.name,
            path=rec.path,
            span_id=rec.span_id,
            parent_id=rec.parent_id,
            start_unix=origin_unix + rec.start_s,
            duration_s=rec.duration_s,
            thread=rec.thread_name,
            failed=rec.failed,
            error=rec.error,
            attrs=rec.attrs,
        )

    def flush(self) -> None:
        """Push buffered records to stable storage (flush + best-effort
        fsync). ``write`` already flushes to the OS after every record; this
        additionally asks the kernel to persist, so span-tree checkpoints
        survive a machine-level crash, not just a process kill."""
        with self._lock:
            if self._f.closed:
                return
            self._f.flush()
            try:
                os.fsync(self._f.fileno())
            except OSError:
                pass

    def close(self) -> None:
        with self._lock:
            if not self._f.closed:
                self._f.flush()
                self._f.close()


class TelemetryEventListener(EventListener):
    """Bridge: every emitted ``Event`` becomes a ledger ``event`` record,
    and the stats-bearing events are folded into the metrics registry."""

    def __init__(self, ledger: Optional[RunLedger] = None, registry=None):
        self.ledger = ledger
        if registry is None:
            from photon_ml_tpu.telemetry.metrics import get_registry

            registry = get_registry()
        self.registry = registry
        self.num_events = 0

    def on_event(self, event: Event) -> None:
        self.num_events += 1
        if self.ledger is not None:
            self.ledger.write(
                "event",
                event=type(event).__name__,
                fields=dataclasses.asdict(event),
            )
        reg = self.registry
        reg.count(f"events.{type(event).__name__}")
        if isinstance(event, SolverStatsEvent):
            reg.record_solver_stats(event, coordinate=event.coordinate_id)
        elif isinstance(event, TransferStatsEvent):
            reg.count("transfer.row_bytes_h2d", event.row_bytes_h2d)
            reg.count("transfer.row_bytes_d2h", event.row_bytes_d2h)
            reg.count("transfer.row_transfers_h2d", event.row_transfers_h2d)
            reg.count("transfer.row_transfers_d2h", event.row_transfers_d2h)
            reg.count("transfer.host_score_sums", event.host_score_sums)
            reg.count("transfer.device_plane_updates", event.device_plane_updates)
        elif isinstance(event, ScoringFinishEvent):
            reg.record_serving_snapshot(event.metrics or {})
        elif isinstance(event, ModelSwapEvent):
            reg.observe("serving.swap_blackout_s", event.blackout_s)
            if event.rolled_back:
                reg.count("serving.swap_rollbacks")
            else:
                reg.count("serving.swaps")
        elif isinstance(event, AnomalyEvent):
            reg.count("progress.anomaly_events")
            reg.count(f"progress.anomaly.{event.kind}")

    def close(self) -> None:
        if self.ledger is not None:
            self.ledger.write("meta", phase="listener_close", events=self.num_events)


# ---------------------------------------------------------------- chrome

def chrome_trace_events(
    spans: Iterable[SpanRecord], pid: int = 0, pid_key: Optional[str] = None
) -> List[Dict[str, Any]]:
    """Spans as Chrome trace-event dicts (``ph: "X"`` complete events).
    Timestamps/durations are microseconds relative to the tracer origin.
    ``pid_key`` names a span attribute whose integer value becomes the
    event's pid — per-host lanes for cluster-plane spans (a worker's
    ``cluster/fragment`` spans carry ``host=N``); spans without the
    attribute keep the default ``pid``."""
    events: List[Dict[str, Any]] = []
    for rec in spans:
        args = {str(k): _jsonable(v) for k, v in rec.attrs.items()}
        if rec.failed:
            args["error"] = rec.error
        event_pid = pid
        if pid_key is not None and pid_key in rec.attrs:
            try:
                event_pid = 1 + int(rec.attrs[pid_key])
            except (TypeError, ValueError):
                pass
        events.append(
            {
                "name": rec.name,
                "cat": rec.path.split("/", 1)[0],
                "ph": "X",
                "ts": rec.start_s * 1e6,
                "dur": rec.duration_s * 1e6,
                "pid": event_pid,
                "tid": rec.thread_id,
                "args": args,
            }
        )
    return events


def cluster_lane_events(
    cluster_passes: Iterable[Dict[str, Any]], origin_unix: float = 0.0
) -> List[Dict[str, Any]]:
    """Per-host Chrome trace lanes from the coordinator's skew profiles
    (``ClusterCoordinator.drain_pass_profiles()`` /
    ``ConvergenceTracker.cluster_passes``): one ``X`` event per dispatched
    fragment spanning dispatch→arrival, on ``pid = 1 + host`` so each
    worker host gets its own track while the coordinator's spans stay on
    pid 0. ``origin_unix`` is the tracer origin the coordinator's own
    spans are relative to, so the lanes line up with them."""
    events: List[Dict[str, Any]] = []
    for p in cluster_passes:
        base = float(p.get("start_unix", 0.0)) - float(origin_unix)
        for f in p.get("fragments", ()):
            dispatch = float(f.get("dispatch_s", 0.0))
            arrival = float(f.get("arrival_s", dispatch))
            events.append(
                {
                    "name": f"pass {p.get('pass_id')} frag {f.get('frag')}",
                    "cat": "cluster",
                    "ph": "X",
                    "ts": max(0.0, base + dispatch) * 1e6,
                    "dur": max(0.0, arrival - dispatch) * 1e6,
                    "pid": 1 + int(f.get("host", 0)),
                    "tid": 0,
                    "args": {str(k): _jsonable(v) for k, v in f.items()},
                }
            )
    return events


def write_chrome_trace(
    path: str,
    spans: Iterable[SpanRecord],
    metadata: Optional[Dict[str, Any]] = None,
    extra_events: Optional[Iterable[Dict[str, Any]]] = None,
    pid_key: Optional[str] = None,
) -> int:
    """Write a Perfetto-loadable trace file; returns the event count.
    ``extra_events`` (already trace-event dicts, e.g. from
    :func:`cluster_lane_events`) are appended verbatim."""
    events = chrome_trace_events(spans, pid_key=pid_key)
    if extra_events is not None:
        events.extend(extra_events)
    doc: Dict[str, Any] = {
        "traceEvents": events,
        "displayTimeUnit": "ms",
    }
    if metadata:
        doc["otherData"] = {str(k): _jsonable(v) for k, v in metadata.items()}
    parent = os.path.dirname(os.path.abspath(path))
    os.makedirs(parent, exist_ok=True)
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f)
    return len(events)


# --------------------------------------------------------------- summary

def span_tree_summary(
    spans: Iterable[SpanRecord], max_depth: Optional[int] = None
) -> Dict[str, Dict[str, Any]]:
    """Aggregate spans by path: count, total/mean/max seconds, failures.
    ``max_depth`` keeps only spans nested at most that deep (depth 1 =
    top-level spans only); parents already include child wall time, so
    dropped children are not re-rolled-up."""
    out: Dict[str, Dict[str, Any]] = {}
    for rec in spans:
        if max_depth is not None and rec.depth > max_depth:
            continue
        path = rec.path
        entry = out.get(path)
        if entry is None:
            entry = out[path] = {
                "count": 0,
                "total_s": 0.0,
                "mean_s": 0.0,
                "max_s": 0.0,
                "failed": 0,
            }
        entry["count"] += 1
        entry["total_s"] += rec.duration_s
        entry["max_s"] = max(entry["max_s"], rec.duration_s)
        entry["failed"] += int(rec.failed)
    for entry in out.values():
        entry["mean_s"] = entry["total_s"] / entry["count"]
    return dict(sorted(out.items()))


def format_summary_table(
    spans: Iterable[SpanRecord],
    metrics_snapshot: Optional[Dict[str, Any]] = None,
    label: str = "run",
) -> str:
    """End-of-run terminal summary: span table + headline counters."""
    summary = span_tree_summary(spans)
    lines = [f"telemetry summary [{label}]"]
    if summary:
        name_w = max(len("span"), *(len(p) for p in summary))
        header = f"  {'span'.ljust(name_w)}  {'count':>7}  {'total_s':>10}  {'mean_s':>10}  {'max_s':>10}  fail"
        lines.append(header)
        for path, entry in summary.items():
            lines.append(
                f"  {path.ljust(name_w)}  {entry['count']:>7d}  "
                f"{entry['total_s']:>10.4f}  {entry['mean_s']:>10.4f}  "
                f"{entry['max_s']:>10.4f}  {entry['failed']:>4d}"
            )
    else:
        lines.append("  (no spans recorded)")
    if metrics_snapshot:
        counters = metrics_snapshot.get("counters", {})
        jit = {k: v for k, v in counters.items() if k.startswith("jit.traces.")}
        if jit:
            lines.append("  jit traces:")
            for name, value in sorted(jit.items()):
                lines.append(f"    {name[len('jit.traces.'):]}: {int(value)}")
        transfer = {
            k: v for k, v in counters.items() if k.startswith("transfer.")
        }
        if transfer:
            lines.append("  transfers:")
            for name, value in sorted(transfer.items()):
                lines.append(f"    {name[len('transfer.'):]}: {int(value)}")
    return "\n".join(lines)
