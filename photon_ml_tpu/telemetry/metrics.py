"""Process-global metrics registry: counters, gauges, histograms.

One :class:`MetricsRegistry` per process (via :func:`get_registry`)
absorbs every numeric signal the codebase already produces piecemeal —
``SolverStats``/``TransferStats`` from the optimizers, serving
latency/hit-rate snapshots, hot-swap blackouts — plus two new ones:

* **jit compile/retrace counting** — :func:`note_jit_trace` generalizes the
  per-module ``solver_trace_counts()`` counter: any jitted program whose
  Python body calls it at trace time shows up under ``jit.traces.*``.
  Python side effects inside a traced function only run when XLA actually
  (re)traces, so the counters move exactly on compile-cache misses.
* **memory watermarks** — :func:`record_memory_watermarks` records the host
  peak RSS and, where the backend reports it, per-device peak bytes.

Histograms reuse the seeded bounded reservoir from
``serving/metrics.py`` (Vitter's Algorithm R), so percentile snapshots are
deterministic and memory stays fixed no matter how many observations land.
All mutators are thread-safe and cheap (one lock + dict update), so the
registry stays on even when span tracing is off.
"""
from __future__ import annotations

import threading
from typing import Any, Dict, Optional

__all__ = [
    "MetricsRegistry",
    "ScopedMetrics",
    "get_registry",
    "note_jit_trace",
    "jit_trace_counts",
    "record_memory_watermarks",
]


def _parse_labels(labels) -> Dict[str, str]:
    """Label spec → dict: accepts a mapping or a ``"k=v"`` /
    ``"k=v,k2=v2"`` string (the ``scoped("tenant=a")`` shorthand)."""
    if isinstance(labels, str):
        out: Dict[str, str] = {}
        for part in labels.split(","):
            part = part.strip()
            if not part:
                continue
            if "=" not in part:
                raise ValueError(
                    f"label spec {labels!r}: expected 'key=value' parts"
                )
            k, v = part.split("=", 1)
            out[k.strip()] = v.strip()
        if not out:
            raise ValueError("label spec must name at least one label")
        return out
    return {str(k): str(v) for k, v in dict(labels).items()}


def _format_labels(labels: Dict[str, str]) -> str:
    """Canonical Prometheus label suffix ``{k="v",...}``: keys sorted so
    the same label set always produces the same metric name, values
    escaped per the exposition format."""
    parts = []
    for k in sorted(labels):
        v = (
            str(labels[k])
            .replace("\\", "\\\\")
            .replace('"', '\\"')
            .replace("\n", "\\n")
        )
        parts.append(f'{k}="{v}"')
    return "{" + ",".join(parts) + "}"


class ScopedMetrics:
    """Label-scoped view onto a :class:`MetricsRegistry`: every metric
    name written through it carries a fixed Prometheus label set
    (``serving.requests{tenant="a"}``). The underlying storage is the
    parent registry — scoped names land in the same counters/gauges/
    histograms dicts, render as proper labeled samples in ``/metrics``
    (see ``serving/introspect.py``), and never collide with the unlabeled
    base names. Views are cheap and stateless; build one per tenant."""

    def __init__(self, parent: "MetricsRegistry", labels):
        self._parent = parent
        self.labels = _parse_labels(labels)
        if not self.labels:
            raise ValueError("ScopedMetrics needs at least one label")
        self._suffix = _format_labels(self.labels)

    def scoped_name(self, name: str) -> str:
        """The labeled storage name a metric renders under."""
        return name + self._suffix

    def scoped(self, labels) -> "ScopedMetrics":
        """A further-scoped view (merged labels; new keys win)."""
        merged = dict(self.labels)
        merged.update(_parse_labels(labels))
        return ScopedMetrics(self._parent, merged)

    def count(self, name: str, value: float = 1.0) -> None:
        self._parent.count(self.scoped_name(name), value)

    def gauge(self, name: str, value: float) -> None:
        self._parent.gauge(self.scoped_name(name), value)

    def observe(self, name: str, value: float) -> None:
        self._parent.observe(self.scoped_name(name), value)

    def counter_value(self, name: str) -> float:
        return self._parent.counter_value(self.scoped_name(name))


def _new_reservoir(seed: int):
    # Imported lazily: ``photon_ml_tpu.serving`` imports modules that
    # themselves import telemetry, so a module-level import here would be
    # circular during package init.
    from photon_ml_tpu.serving.metrics import _Reservoir

    return _Reservoir(seed=seed)


class MetricsRegistry:
    """Thread-safe named counters, gauges (last value + peak watermark),
    and reservoir-backed histograms."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[str, float] = {}
        self._gauges: Dict[str, float] = {}
        self._gauge_peaks: Dict[str, float] = {}
        self._hists: Dict[str, Any] = {}
        self._next_seed = 0

    # ----------------------------------------------------------- mutators
    def count(self, name: str, value: float = 1.0) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0.0) + value

    def gauge(self, name: str, value: float) -> None:
        with self._lock:
            self._gauges[name] = float(value)
            peak = self._gauge_peaks.get(name)
            if peak is None or value > peak:
                self._gauge_peaks[name] = float(value)

    def observe(self, name: str, value: float) -> None:
        with self._lock:
            hist = self._hists.get(name)
            if hist is None:
                hist = _new_reservoir(seed=self._next_seed)
                self._next_seed += 1
                self._hists[name] = hist
            hist.add(value)

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._gauge_peaks.clear()
            self._hists.clear()
            self._next_seed = 0

    def scoped(self, labels) -> ScopedMetrics:
        """A label-scoped view of this registry: ``scoped("tenant=a")``
        (or a mapping) returns a :class:`ScopedMetrics` whose writes land
        under Prometheus-labeled names. Existing unlabeled names are
        untouched."""
        return ScopedMetrics(self, labels)

    # ------------------------------------------------------------ readers
    def counter_value(self, name: str) -> float:
        with self._lock:
            return self._counters.get(name, 0.0)

    def snapshot(self) -> Dict[str, Any]:
        """Everything as one plain JSON-serializable dict."""
        with self._lock:
            counters = dict(self._counters)
            gauges = {
                name: {"last": value, "peak": self._gauge_peaks[name]}
                for name, value in self._gauges.items()
            }
            hists = {}
            for name, res in self._hists.items():
                entry = {
                    "count": int(res.count),
                    "mean": float(res.mean),
                    "max": float(res.maximum),
                }
                if len(res):
                    p50, p95, p99 = (
                        float(x) for x in res.percentile([50, 95, 99])
                    )
                    entry.update(p50=p50, p95=p95, p99=p99)
                hists[name] = entry
        return {"counters": counters, "gauges": gauges, "histograms": hists}

    # --------------------------------------------------------- absorbers
    def record_solver_stats(self, stats, coordinate: Optional[str] = None) -> None:
        """Fold a ``SolverStats`` (duck-typed; see opt/tracking.py) into
        solver.* counters/histograms."""
        prefix = f"solver.{coordinate}" if coordinate else "solver"
        self.count(f"{prefix}.buckets")
        self.count(f"{prefix}.entities", getattr(stats, "num_entities", 0))
        self.count(f"{prefix}.rounds", getattr(stats, "rounds", 0))
        self.count(
            f"{prefix}.executed_lane_iterations",
            getattr(stats, "executed_lane_iterations", 0),
        )
        self.count(
            f"{prefix}.lockstep_lane_iterations",
            getattr(stats, "lockstep_lane_iterations", 0),
        )
        self.count(f"{prefix}.chunk_retraces", getattr(stats, "chunk_retraces", 0))
        self.observe(f"{prefix}.iterations_p99", getattr(stats, "iterations_p99", 0))
        if not getattr(stats, "converged", True):
            self.count(f"{prefix}.unconverged_buckets")

    def record_transfer_stats(self, transfers) -> None:
        """Fold a full ``TransferStats`` (duck-typed; opt/tracking.py) into
        transfer.* counters (one CD run's totals)."""
        for field in (
            "row_transfers_h2d",
            "row_transfers_d2h",
            "row_bytes_h2d",
            "row_bytes_d2h",
            "host_score_sums",
            "device_plane_updates",
            "coordinate_updates",
            "outer_iterations",
        ):
            self.count(f"transfer.{field}", getattr(transfers, field, 0))

    def record_cluster_pass(self, profile: Dict[str, Any]) -> None:
        """Fold one distributed-pass skew profile (the coordinator's
        telemetry, parallel/cluster) into cluster.* metrics: pass-level
        wall/wait histograms plus host-scoped busy/blocks via the same
        ``scoped`` mechanism the tenancy plane uses."""
        self.count("cluster.passes")
        self.observe("cluster.pass.wall_s", float(profile.get("wall_s", 0.0)))
        self.observe(
            "cluster.pass.allreduce_wait_s",
            float(profile.get("allreduce_wait_s", 0.0)),
        )
        self.gauge(
            "cluster.pass.bubble_s", float(profile.get("bubble_s", 0.0))
        )
        self.gauge(
            "cluster.straggler_index",
            float(profile.get("straggler_index", 1.0)),
        )
        for host, h in (profile.get("hosts") or {}).items():
            scoped = self.scoped({"host": str(host)})
            scoped.gauge("cluster.host.busy_s", float(h.get("busy_s", 0.0)))
            scoped.gauge("cluster.host.wall_s", float(h.get("wall_s", 0.0)))
            scoped.count("cluster.host.blocks", float(h.get("blocks", 0)))
            scoped.count(
                "cluster.host.h2d_bytes", float(h.get("h2d_bytes", 0))
            )

    def record_serving_snapshot(self, snap: Dict[str, Any]) -> None:
        """Fold a serving metrics snapshot dict into serving.* gauges.

        Accepts both the event-shaped keys (``latency_p99_ms``,
        ``batch_fill``, ``compile_count``) and the keys
        ``ServingMetrics.snapshot()`` actually emits (``latency_p99_s``,
        ``batch_fill_ratio``, ``xla_compiles``), normalizing everything to
        the canonical serving.* gauge names documented in
        docs/OBSERVABILITY.md — the ``--auto-tune`` judge and the /metrics
        endpoint both read the canonical names."""
        norm: Dict[str, float] = {}
        for key in (
            "num_requests",
            "num_batches",
            "latency_p50_ms",
            "latency_p99_ms",
            "batch_fill",
            "cache_hit_rate",
            "compile_count",
            "num_swaps",
            "swap_blackout_max_ms",
            "requests_per_s",
            "device_resident_rate",
            "deferred_rate",
            "deferred_lookups",
        ):
            value = snap.get(key)
            if isinstance(value, (int, float)):
                norm[key] = float(value)
        for sec_key, ms_key in (
            ("latency_p50_s", "latency_p50_ms"),
            ("latency_p99_s", "latency_p99_ms"),
        ):
            value = snap.get(sec_key)
            if isinstance(value, (int, float)) and ms_key not in norm:
                norm[ms_key] = float(value) * 1e3
        fill = snap.get("batch_fill_ratio")
        if isinstance(fill, (int, float)) and "batch_fill" not in norm:
            norm["batch_fill"] = float(fill)
        compiles = snap.get("xla_compiles")
        if isinstance(compiles, (int, float)) and "compile_count" not in norm:
            norm["compile_count"] = float(compiles)
        residency = snap.get("residency")
        if isinstance(residency, dict):
            # nested per-coordinate ({cid: {...}}) or one flat stats dict
            coords = [
                v for v in residency.values() if isinstance(v, dict)
            ] or [residency]
            for key, agg in (
                ("resident_rows", sum),
                ("device_rows", sum),
                ("num_shards", max),
            ):
                values = [
                    c[key] for c in coords
                    if isinstance(c.get(key), (int, float))
                ]
                if values:
                    norm[f"residency_{key}"] = float(agg(values))
            # eviction-policy plane (CoordinateRouting.stats): per-policy
            # victim counters and the admitted set's importance spread
            for key, agg, out in (
                ("evicted_oldest", sum, "eviction.oldest"),
                ("evicted_importance", sum, "eviction.importance"),
                ("importance_mean", max, "importance.mean"),
                ("importance_max", max, "importance.max"),
            ):
                values = [
                    c[key] for c in coords
                    if isinstance(c.get(key), (int, float))
                ]
                if values:
                    norm[out] = float(agg(values))
        admission = snap.get("admission")
        if isinstance(admission, dict):
            for key in (
                "admitted_total",
                "evicted_total",
                "dropped_total",
                "queue_depth",
                "deferred_total",
            ):
                value = admission.get(key)
                if isinstance(value, (int, float)):
                    norm[f"admission_{key}"] = float(value)
            by_policy = admission.get("evicted_by_policy")
            if isinstance(by_policy, dict):
                for policy, value in by_policy.items():
                    if isinstance(value, (int, float)):
                        norm[f"eviction.{policy}"] = max(
                            norm.get(f"eviction.{policy}", 0.0), float(value)
                        )
        swaps = snap.get("swaps")
        if isinstance(swaps, dict):
            if isinstance(swaps.get("num_swaps"), (int, float)):
                norm.setdefault("num_swaps", float(swaps["num_swaps"]))
            if isinstance(swaps.get("max_blackout_s"), (int, float)):
                norm.setdefault(
                    "swap_blackout_max_ms",
                    float(swaps["max_blackout_s"]) * 1e3,
                )
        for key, value in norm.items():
            self.gauge(f"serving.{key}", value)


_REGISTRY = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    """The process-global registry."""
    return _REGISTRY


def note_jit_trace(program: str, kind: str = "") -> None:
    """Global jit compile/retrace hook. Call from *inside* a traced
    function body: the Python side effect fires only on a compile-cache
    miss, so ``jit.traces.<program>[/<kind>]`` counts actual (re)traces."""
    key = f"{program}/{kind}" if kind else program
    _REGISTRY.count("jit.traces")
    _REGISTRY.count(f"jit.traces.{key}")


def jit_trace_counts() -> Dict[str, int]:
    """Per-program trace counts recorded via :func:`note_jit_trace`."""
    snap = _REGISTRY.snapshot()["counters"]
    prefix = "jit.traces."
    return {
        name[len(prefix):]: int(value)
        for name, value in snap.items()
        if name.startswith(prefix)
    }


def record_memory_watermarks(registry: Optional[MetricsRegistry] = None) -> Dict[str, float]:
    """Record host peak RSS and per-device peak bytes as mem.* gauges.
    Best-effort: backends without memory_stats (CPU) just skip devices."""
    reg = registry if registry is not None else _REGISTRY
    out: Dict[str, float] = {}
    try:
        import resource

        peak_kib = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        out["mem.host_peak_rss_bytes"] = float(peak_kib) * 1024.0  # Linux: KiB
    except Exception:
        pass
    try:
        import jax

        for dev in jax.local_devices():
            stats = getattr(dev, "memory_stats", None)
            stats = stats() if callable(stats) else None
            if not stats:
                continue
            peak = stats.get("peak_bytes_in_use") or stats.get("bytes_in_use")
            if peak:
                out[f"mem.device{dev.id}_peak_bytes"] = float(peak)
    except Exception:
        pass
    for name, value in out.items():
        reg.gauge(name, value)
    return out
