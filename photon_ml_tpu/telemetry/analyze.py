"""Ledger-replay performance analyzer: RunLedger → occupancy report.

PR 5 made every hot path write telemetry; this module reads it back. From
one JSONL :class:`~photon_ml_tpu.telemetry.sinks.RunLedger` it

* reconstructs the span tree (``span_id``/``parent_id`` chains),
* computes per-phase occupancy — wall-clock attributed to FE solves, RE
  chunked rounds, CD driver algebra, serving, incremental updates, I/O —
  from per-span **exclusive self-intervals** (a span's own interval minus
  the union of its direct children's intervals). Concurrent spans — the
  async CD schedule runs FE and RE solves on overlapping wall-clock — are
  shared via a sweep-line: a segment where k spans are simultaneously open
  contributes 1/k of its length to each span's phase, so phase ``seconds``
  always sum to wall-clock actually covered (coverage stays <= ~1), while
  the full per-phase busy time and the concurrency win are reported
  separately as ``busy_s`` and ``overlap_s = busy_s - seconds``,
* accounts the **bubbles**: driver-thread gaps where no span was open are
  attributed explicitly as host driver time, so the report sums to the
  measured wall-clock instead of silently dropping it,
* joins in the SolverStats / TransferStats events, jit retrace counters
  and the metrics-registry snapshot, and
* emits a structured :class:`RunReport` (JSON-ready) plus a human-readable
  table via :func:`format_report`.

The occupancy accounting is the Snap-ML-style per-level breakdown (arxiv
1803.06333) that the offline tuner (:mod:`photon_ml_tpu.tuning`) consumes
to propose configs over the declared knob space. CLI:
``python -m photon_ml_tpu.cli.analyze_run LEDGER.jsonl``.
"""
from __future__ import annotations

import dataclasses
import json
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from photon_ml_tpu.telemetry.progress import convergence_report
from photon_ml_tpu.telemetry.validate import _REQUEST_STAGES, validate_ledger

__all__ = [
    "RunReport",
    "analyze_ledger",
    "analyze_records",
    "classify_span",
    "format_report",
    "format_request_report",
    "request_report",
    "PHASES",
]

# Canonical phase buckets, in report order. Span NAMES (not paths — paths
# concatenate parent names) map onto these; see classify_span.
PHASES = (
    "fe_solve",      # fixed-effect GLM solves (fe/*)
    "re_solve",      # random-effect chunked rounds / bucket solves (re/*)
    "cd_driver",     # coordinate-descent driver algebra (cd/*)
    "serving",       # online scoring path (serve/*)
    "incremental",   # nearline update path (incremental/*)
    "transfers",     # explicit host<->device transfer spans
    "io",            # data read / model save / artifact pack phases
    "host_driver",   # everything else: Python glue, setup, graph build
)

_IO_WORDS = (
    "read", "load", "save", "write", "export", "pack",
    "prepare feature maps", "build requests", "feature stats", "check data",
)


def classify_span(name: str) -> str:
    """Span name → phase bucket. Uses the name (the span's own identity),
    not the path, so nesting never reclassifies a child."""
    head = name.split("/", 1)[0]
    if head == "fe":
        return "fe_solve"
    if head == "re":
        return "re_solve"
    if head == "cd":
        return "cd_driver"
    if head == "serve":
        return "serving"
    if head == "incremental":
        return "incremental"
    low = name.lower()
    if "transfer" in low or "h2d" in low or "d2h" in low:
        return "transfers"
    if any(low.startswith(w) or f" {w}" in low for w in _IO_WORDS):
        return "io"
    return "host_driver"


@dataclasses.dataclass
class RunReport:
    """Structured result of replaying one run ledger.

    ``phases`` maps each phase bucket to ``{"seconds", "spans",
    "fraction", "busy_s", "overlap_s"}``. ``seconds`` is exclusive span
    time with concurrent segments SHARED across the open spans (a segment
    where k spans are open contributes 1/k to each), so phase seconds sum
    to covered wall-clock even under the async schedule's overlapped span
    trees. ``busy_s`` is the phase's full (unshared) exclusive time and
    ``overlap_s = busy_s - seconds`` is the wall-clock the phase spent
    running concurrently with other spans — the async schedule's win shows
    up here. ``bubble_s`` is wall-clock inside the run window covered by
    NO span (host driver gaps between instrumented regions) — it is
    attributed, not dropped, so ``attributed_s = Σ phases + bubble_s``
    and ``coverage = attributed_s / wall_clock_s`` should sit near 1.0
    regardless of concurrency; much below 1 means uninstrumented time.
    ``overlap_s`` (report level) totals the per-phase overlap.
    """

    label: str
    source_path: Optional[str]
    wall_clock_s: float
    span_extent_s: float
    phases: Dict[str, Dict[str, float]]
    bubble_s: float
    attributed_s: float
    coverage: float
    num_spans: int
    failed_spans: int
    top_spans: Dict[str, Dict[str, Any]]
    solver: Dict[str, Any]
    transfers: Dict[str, float]
    jit_traces: Dict[str, int]
    events: Dict[str, int]
    metrics: Dict[str, Any]
    warnings: List[str] = dataclasses.field(default_factory=list)
    overlap_s: float = 0.0
    # convergence-plane reconstruction (telemetry.progress.convergence_report)
    # when the ledger carries "progress" records; None for perf-only ledgers
    progress: Optional[Dict[str, Any]] = None
    # request-plane tail attribution (request_report) when the ledger
    # carries sampled "request" lifecycle records; None otherwise
    requests: Optional[Dict[str, Any]] = None
    # cluster-plane skew attribution (cluster_report) when the ledger
    # carries cluster_pass/host_pass progress records; None otherwise
    cluster: Optional[Dict[str, Any]] = None

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "RunReport":
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in known})

    # convenience readers used by the tuner ------------------------------
    def phase_seconds(self, phase: str) -> float:
        return float(self.phases.get(phase, {}).get("seconds", 0.0))

    def phase_fraction(self, phase: str) -> float:
        return float(self.phases.get(phase, {}).get("fraction", 0.0))

    def phase_overlap(self, phase: str) -> float:
        """Wall-clock this phase spent overlapped with other open spans
        (0.0 for sequential runs and for reports from older ledgers)."""
        return float(self.phases.get(phase, {}).get("overlap_s", 0.0))

    def metric(self, name: str) -> Optional[float]:
        """Look a flat metric name up across the snapshot's counters,
        gauges (last value) and histograms (mean), in that order."""
        snap = self.metrics or {}
        counters = snap.get("counters") or {}
        if name in counters:
            return float(counters[name])
        gauges = snap.get("gauges") or {}
        if name in gauges:
            return float(gauges[name]["last"])
        hists = snap.get("histograms") or {}
        if name in hists:
            return float(hists[name].get("mean", 0.0))
        return None


def _merge_intervals(
    intervals: Sequence[Tuple[float, float]]
) -> List[Tuple[float, float]]:
    """The union of [start, end) intervals as a sorted, disjoint list."""
    out: List[Tuple[float, float]] = []
    for start, end in sorted(intervals):
        if end <= start:
            continue
        if out and start <= out[-1][1]:
            if end > out[-1][1]:
                out[-1] = (out[-1][0], end)
        else:
            out.append((start, end))
    return out


def _merged_coverage(intervals: Sequence[Tuple[float, float]]) -> float:
    """Total length of the union of [start, end) intervals."""
    return sum(end - start for start, end in _merge_intervals(intervals))


def _subtract_intervals(
    own: Tuple[float, float], children: Sequence[Tuple[float, float]]
) -> List[Tuple[float, float]]:
    """``own`` minus the union of ``children`` (clipped to ``own``): a
    span's exclusive SELF time as intervals rather than a scalar, so a
    parent whose concurrent children together outlast it still nets out at
    zero instead of going negative or double-counting."""
    s, e = own
    out: List[Tuple[float, float]] = []
    cursor = s
    for cs, ce in _merge_intervals(children):
        if ce <= cursor:
            continue
        if cs >= e:
            break
        if cs > cursor:
            out.append((cursor, min(cs, e)))
        cursor = max(cursor, ce)
        if cursor >= e:
            break
    if cursor < e:
        out.append((cursor, e))
    return out


def _span_tree_summary(spans: List[dict], max_depth: int = 2) -> Dict[str, dict]:
    """span_tree_summary over ledger span dicts (depth reconstructed from
    the path, which encodes the ancestor chain)."""
    out: Dict[str, dict] = {}
    for rec in spans:
        path = rec.get("path", rec["name"])
        # depth = nesting level in the span tree: count ancestors via
        # parent links is not possible per-path, so approximate from how
        # many recorded spans prefix this one; cheap proxy: parent chain
        if rec.get("_depth", 1) > max_depth:
            continue
        entry = out.setdefault(
            path,
            {"count": 0, "total_s": 0.0, "mean_s": 0.0, "max_s": 0.0, "failed": 0},
        )
        entry["count"] += 1
        entry["total_s"] += float(rec.get("duration_s", 0.0))
        entry["max_s"] = max(entry["max_s"], float(rec.get("duration_s", 0.0)))
        entry["failed"] += int(bool(rec.get("failed")))
    for entry in out.values():
        entry["mean_s"] = entry["total_s"] / entry["count"]
    return dict(sorted(out.items()))


def request_report(
    records: Sequence[Dict[str, Any]], tail_q: float = 99.0
) -> Optional[Dict[str, Any]]:
    """Tail-latency attribution over sampled ``request`` lifecycle records.

    Joins the request-plane's per-request stage durations into per-stage
    p50/p99 distributions, then isolates the tail (requests at or above
    the ``tail_q`` end-to-end percentile) and breaks its latency down by
    stage — because the stage boundaries telescope, the per-stage tail
    breakdown sums to the tail's end-to-end time (``coverage`` ~1.0), so
    "where did the p99 go" has a complete answer. Interference overlap
    (``swap_pause``, ``admission`` seconds inside request windows) is
    aggregated alongside, and the worst bucket carries exemplar request
    ids for flight-recorder-style drill-down. Returns None when the
    records carry no request entries.
    """
    reqs = [
        r
        for r in records
        if r.get("type") == "request" and isinstance(r.get("stages"), dict)
    ]
    if not reqs:
        return None
    totals = np.array([float(r.get("total_s", 0.0)) for r in reqs])
    per_stage = {
        s: np.array([float(r["stages"].get(s, 0.0)) for r in reqs])
        for s in _REQUEST_STAGES
    }

    def _dist(a: np.ndarray) -> Dict[str, float]:
        return {
            "p50_s": round(float(np.percentile(a, 50)), 9),
            "p99_s": round(float(np.percentile(a, 99)), 9),
            "mean_s": round(float(a.mean()), 9),
            "max_s": round(float(a.max()), 9),
        }

    stages = {s: _dist(a) for s, a in per_stage.items()}
    e2e = _dist(totals)

    # ---- the tail: requests at/above the e2e tail_q percentile ----------
    threshold = float(np.percentile(totals, tail_q))
    tail_idx = np.nonzero(totals >= threshold)[0]
    tail_total = float(totals[tail_idx].mean())
    breakdown = {
        s: round(float(per_stage[s][tail_idx].mean()), 9)
        for s in _REQUEST_STAGES
    }
    covered = sum(breakdown.values())
    worst_stage = max(breakdown, key=lambda s: breakdown[s])

    # worst bucket among tail requests, with exemplar ids for drill-down
    by_bucket: Dict[int, List[int]] = {}
    for i in tail_idx:
        by_bucket.setdefault(int(reqs[int(i)].get("bucket", -1)), []).append(
            int(i)
        )
    worst_bucket, worst_members = max(
        by_bucket.items(), key=lambda kv: float(totals[kv[1]].mean())
    )
    exemplar_idx = sorted(worst_members, key=lambda i: -totals[i])[:3]

    # ---- interference join ----------------------------------------------
    interference: Dict[str, Dict[str, float]] = {}
    for i, r in enumerate(reqs):
        for key, v in (r.get("interference") or {}).items():
            kind = key[:-2] if key.endswith("_s") else key
            entry = interference.setdefault(
                kind, {"requests": 0, "total_s": 0.0, "tail_s": 0.0}
            )
            entry["requests"] += 1
            entry["total_s"] += float(v)
            if totals[i] >= threshold:
                entry["tail_s"] += float(v)
    for entry in interference.values():
        entry["total_s"] = round(entry["total_s"], 9)
        entry["tail_s"] = round(entry["tail_s"], 9)

    by_batcher: Dict[str, int] = {}
    for r in reqs:
        name = str(r.get("batcher", "?"))
        by_batcher[name] = by_batcher.get(name, 0) + 1

    return {
        "num_records": len(reqs),
        "stages": stages,
        "e2e": e2e,
        "tail": {
            "quantile": tail_q / 100.0,
            "threshold_s": round(threshold, 9),
            "num_requests": int(tail_idx.size),
            "mean_total_s": round(tail_total, 9),
            "breakdown_s": breakdown,
            "attribution_coverage": (
                round(covered / tail_total, 6) if tail_total > 0 else 1.0
            ),
            "worst_stage": worst_stage,
            "worst_bucket": worst_bucket,
            "exemplars": [reqs[i].get("request_id") for i in exemplar_idx],
        },
        "interference": interference,
        "by_batcher": by_batcher,
    }


def format_request_report(report: Dict[str, Any]) -> str:
    """Human-readable tail-attribution table (``analyze_run --requests``
    and the live ``/requests`` route's text form)."""
    lines = [
        f"request plane: {report['num_records']} sampled lifecycle record(s)"
    ]
    e2e = report.get("e2e") or {}
    if e2e:
        lines.append(
            f"  end-to-end   p50 {e2e['p50_s'] * 1e3:9.3f}ms   "
            f"p99 {e2e['p99_s'] * 1e3:9.3f}ms   "
            f"max {e2e['max_s'] * 1e3:9.3f}ms"
        )
    lines.append(f"  {'stage':<12} {'p50 ms':>10} {'p99 ms':>10} {'tail ms':>10}")
    tail = report.get("tail") or {}
    breakdown = tail.get("breakdown_s") or {}
    for stage, dist in (report.get("stages") or {}).items():
        lines.append(
            f"  {stage:<12} {dist['p50_s'] * 1e3:>10.3f} "
            f"{dist['p99_s'] * 1e3:>10.3f} "
            f"{breakdown.get(stage, 0.0) * 1e3:>10.3f}"
        )
    if tail:
        lines.append(
            f"  tail (>= p{tail['quantile'] * 100:.0f}): "
            f"{tail['num_requests']} request(s) >= "
            f"{tail['threshold_s'] * 1e3:.3f}ms, worst stage "
            f"'{tail['worst_stage']}', attribution coverage "
            f"{tail['attribution_coverage'] * 100:.2f}%"
        )
        lines.append(
            f"  worst bucket {tail['worst_bucket']}: exemplar ids "
            + ", ".join(str(x) for x in tail.get("exemplars") or [])
        )
    interference = report.get("interference") or {}
    for kind, entry in sorted(interference.items()):
        lines.append(
            f"  interference '{kind}': {entry['requests']} request(s), "
            f"{entry['total_s'] * 1e3:.3f}ms overlap "
            f"({entry['tail_s'] * 1e3:.3f}ms on the tail)"
        )
    by_batcher = report.get("by_batcher") or {}
    if by_batcher:
        lines.append(
            "  by batcher: "
            + ", ".join(f"{k}={v}" for k, v in sorted(by_batcher.items()))
        )
    return "\n".join(lines)


def cluster_report(
    records: Sequence[Dict[str, Any]]
) -> Optional[Dict[str, Any]]:
    """Cluster-plane skew attribution over ``cluster_pass``/``host_pass``
    progress records (the coordinator's per-pass profiles).

    Per pass the coordinator's decomposition is exact — busy (start →
    first arrival) + allreduce wait (first → last arrival) + coordinator
    bubble (last arrival → end) == wall — so ``attribution_coverage``
    should sit at ~1.0; much below 1 means malformed records. Per host
    it joins measured busy seconds and blocks against the assigner's
    LPT-predicted gap shares (``share_error`` is the mean |predicted −
    actual|, the assignment-quality signal a skew-aware assigner would
    actuate on), ranks stragglers by how often each host was the last
    arrival, and tracks the straggler-index trend across passes. Joins
    kind="cluster" event records (rebalances, host losses) when present.
    Returns None when the records carry no ``cluster_pass`` entries.
    """
    progress = [r for r in records if r.get("kind")]
    passes = [r for r in progress if r.get("kind") == "cluster_pass"]
    if not passes:
        return None
    host_rows = [r for r in progress if r.get("kind") == "host_pass"]

    pass_rows: List[Dict[str, Any]] = []
    tot_wall = tot_busy = tot_wait = tot_bubble = 0.0
    straggler_counts: Dict[int, int] = {}
    trend: List[float] = []
    for r in passes:
        wall = float(r.get("wall_s", 0.0))
        busy = float(r.get("busy_s", 0.0))
        wait = float(r.get("allreduce_wait_s", 0.0))
        bubble = float(r.get("bubble_s", 0.0))
        cov = (busy + wait + bubble) / wall if wall > 0 else 1.0
        idx = float(r.get("straggler_index", 1.0))
        trend.append(round(idx, 4))
        sh = int(r.get("straggler_host", -1))
        if sh >= 0:
            straggler_counts[sh] = straggler_counts.get(sh, 0) + 1
        pass_rows.append({
            "outer": r.get("outer"),
            "pass_id": r.get("pass_id"),
            "hosts": int(r.get("hosts", 0)),
            "blocks": int(r.get("blocks", 0)),
            "wall_s": round(wall, 6),
            "busy_s": round(busy, 6),
            "allreduce_wait_s": round(wait, 6),
            "bubble_s": round(bubble, 6),
            "straggler_index": round(idx, 4),
            "straggler_host": sh,
            "attribution_coverage": round(cov, 6),
            "stray_partials": int(r.get("stray_partials", 0)),
            "requeued_blocks": int(r.get("requeued_blocks", 0)),
        })
        tot_wall += wall
        tot_busy += busy
        tot_wait += wait
        tot_bubble += bubble

    hosts: Dict[str, Dict[str, Any]] = {}
    for r in host_rows:
        h = hosts.setdefault(
            str(r.get("host")),
            {
                "passes": 0,
                "busy_s": 0.0,
                "wall_s": 0.0,
                "blocks": 0,
                "h2d_bytes": 0,
                "share_error": 0.0,
                "_share_samples": 0,
            },
        )
        h["passes"] += 1
        h["busy_s"] = round(h["busy_s"] + float(r.get("busy_s", 0.0)), 9)
        h["wall_s"] = round(h["wall_s"] + float(r.get("wall_s", 0.0)), 9)
        h["blocks"] += int(r.get("blocks", 0))
        h["h2d_bytes"] += int(r.get("h2d_bytes", 0))
        if "predicted_share" in r and "actual_share" in r:
            h["share_error"] += abs(
                float(r["predicted_share"]) - float(r["actual_share"])
            )
            h["_share_samples"] += 1
    for h in hosts.values():
        n = h.pop("_share_samples")
        h["share_error"] = round(h["share_error"] / n, 6) if n else None
        h["times_straggler"] = 0
    for sh, n in straggler_counts.items():
        if str(sh) in hosts:
            hosts[str(sh)]["times_straggler"] = n
    ranking = sorted(
        hosts,
        key=lambda k: (-hosts[k]["times_straggler"], -hosts[k]["wall_s"]),
    )

    events: Dict[str, int] = {}
    for r in progress:
        if r.get("kind") == "cluster":
            ev = str(r.get("event", "unknown"))
            events[ev] = events.get(ev, 0) + 1

    return {
        "num_passes": len(pass_rows),
        "num_hosts": len(hosts),
        "wall_s": round(tot_wall, 6),
        "busy_s": round(tot_busy, 6),
        "allreduce_wait_s": round(tot_wait, 6),
        "bubble_s": round(tot_bubble, 6),
        "busy_frac": round(tot_busy / tot_wall, 6) if tot_wall else 1.0,
        "comm_wait_frac": round(tot_wait / tot_wall, 6) if tot_wall else 0.0,
        "bubble_frac": round(tot_bubble / tot_wall, 6) if tot_wall else 0.0,
        "attribution_coverage": (
            round((tot_busy + tot_wait + tot_bubble) / tot_wall, 6)
            if tot_wall
            else 1.0
        ),
        "straggler_index_mean": round(sum(trend) / len(trend), 4),
        "imbalance_trend": trend,
        "straggler_ranking": ranking,
        "hosts": hosts,
        "passes": pass_rows,
        "events": events,
        "stray_partials": sum(p["stray_partials"] for p in pass_rows),
        "requeued_blocks": sum(p["requeued_blocks"] for p in pass_rows),
    }


def format_cluster_report(report: Dict[str, Any]) -> str:
    """Human-readable cluster skew tables (``analyze_run --cluster`` and
    the live ``/cluster`` route's text form)."""
    lines = [
        f"cluster plane: {report['num_passes']} distributed pass(es) over "
        f"{report['num_hosts']} host(s)"
    ]
    lines.append(
        f"  wall {report['wall_s']:.4f}s = busy {report['busy_s']:.4f}s "
        f"({report['busy_frac'] * 100:.1f}%) + allreduce wait "
        f"{report['allreduce_wait_s']:.4f}s "
        f"({report['comm_wait_frac'] * 100:.1f}%) + coordinator bubble "
        f"{report['bubble_s']:.4f}s ({report['bubble_frac'] * 100:.1f}%) — "
        f"coverage {report['attribution_coverage'] * 100:.2f}%"
    )
    lines.append(
        f"  {'pass':>5} {'hosts':>5} {'blocks':>6} {'wall s':>9} "
        f"{'busy s':>9} {'wait s':>9} {'skew':>6} {'requeue':>7}"
    )
    for p in report.get("passes") or []:
        lines.append(
            f"  {p['pass_id']:>5} {p['hosts']:>5} {p['blocks']:>6} "
            f"{p['wall_s']:>9.4f} {p['busy_s']:>9.4f} "
            f"{p['allreduce_wait_s']:>9.4f} {p['straggler_index']:>6.2f} "
            f"{p['requeued_blocks']:>7}"
        )
    hosts = report.get("hosts") or {}
    if hosts:
        lines.append(
            f"  {'host':>5} {'busy s':>9} {'blocks':>6} {'h2d MB':>8} "
            f"{'straggler':>9} {'share err':>9}"
        )
        for host in sorted(hosts, key=lambda k: int(k) if k.isdigit() else 0):
            h = hosts[host]
            err = h.get("share_error")
            lines.append(
                f"  {host:>5} {h['busy_s']:>9.4f} {h['blocks']:>6} "
                f"{h['h2d_bytes'] / 1e6:>8.2f} {h['times_straggler']:>9} "
                + (f"{err:>9.4f}" if err is not None else f"{'—':>9}")
            )
    ranking = report.get("straggler_ranking") or []
    if ranking:
        lines.append("  straggler ranking (worst first): " + ", ".join(
            f"host {h}" for h in ranking
        ))
    trend = report.get("imbalance_trend") or []
    if trend:
        lines.append(
            "  imbalance trend (straggler index per pass): "
            + " ".join(f"{x:.2f}" for x in trend)
            + f"   mean {report['straggler_index_mean']:.2f}"
        )
    if report.get("stray_partials"):
        lines.append(
            f"  stray partials dropped: {report['stray_partials']}"
        )
    events = report.get("events") or {}
    if events:
        lines.append("  events: " + ", ".join(
            f"{k}={v}" for k, v in sorted(events.items())
        ))
    return "\n".join(lines)


def analyze_records(
    records: Sequence[Dict[str, Any]],
    source_path: Optional[str] = None,
) -> RunReport:
    """Build a :class:`RunReport` from parsed ledger records (the output of
    :func:`photon_ml_tpu.telemetry.validate.validate_ledger`)."""
    warnings: List[str] = []
    spans = [r for r in records if r.get("type") == "span"]
    metas = [r for r in records if r.get("type") == "meta"]
    events = [r for r in records if r.get("type") == "event"]
    metric_recs = [r for r in records if r.get("type") == "metrics"]
    progress_recs = [r for r in records if r.get("type") == "progress"]
    request_recs = [r for r in records if r.get("type") == "request"]

    label = next(
        (m.get("label", "run") for m in metas if m.get("phase") == "start"),
        "run",
    )
    start_ts = next(
        (float(m["ts"]) for m in metas if m.get("phase") == "start"), None
    )
    finish_ts = next(
        (float(m["ts"]) for m in metas if m.get("phase") == "finish"), None
    )

    # ---- span tree reconstruction --------------------------------------
    by_id: Dict[int, dict] = {}
    children_dur: Dict[int, float] = {}
    for rec in spans:
        sid = rec.get("span_id")
        if sid is not None:
            by_id[int(sid)] = rec
    for rec in spans:
        pid = rec.get("parent_id")
        if pid is not None:
            children_dur[int(pid)] = children_dur.get(int(pid), 0.0) + float(
                rec.get("duration_s", 0.0)
            )
    # depth for the top-span table: walk parent links
    for rec in spans:
        depth, pid = 1, rec.get("parent_id")
        while pid is not None and int(pid) in by_id and depth < 64:
            depth += 1
            pid = by_id[int(pid)].get("parent_id")
        rec["_depth"] = depth

    # ---- window and wall-clock -----------------------------------------
    starts = [float(r["start_unix"]) for r in spans if "start_unix" in r]
    ends = [
        float(r["start_unix"]) + float(r.get("duration_s", 0.0))
        for r in spans
        if "start_unix" in r
    ]
    span_extent = (max(ends) - min(starts)) if starts else 0.0
    if start_ts is not None and finish_ts is not None:
        wall = max(0.0, finish_ts - start_ts)
    elif start_ts is not None and ends:
        wall = max(0.0, max(ends) - start_ts)
        warnings.append(
            "no finish record (crash-truncated run?); wall-clock measured "
            "to the last span end"
        )
    else:
        wall = span_extent
        if start_ts is None:
            warnings.append("no start record; wall-clock is the span extent")

    # ---- per-phase exclusive occupancy ---------------------------------
    # Self-intervals (own interval minus the union of direct children),
    # then a sweep-line: a segment where k self-intervals are open gives
    # each phase its full length as busy_s but only length/k as seconds —
    # so concurrent span trees (the async CD schedule) never double-count
    # against wall-clock, and the concurrency win is explicit overlap_s.
    phases: Dict[str, Dict[str, float]] = {
        p: {
            "seconds": 0.0, "spans": 0, "fraction": 0.0,
            "busy_s": 0.0, "overlap_s": 0.0,
        }
        for p in PHASES
    }
    failed = 0
    have_starts = all("start_unix" in r for r in spans)
    for rec in spans:
        phases[classify_span(str(rec.get("name", "")))]["spans"] += 1
        failed += int(bool(rec.get("failed")))

    if have_starts and spans:
        children_iv: Dict[int, List[Tuple[float, float]]] = {}
        for rec in spans:
            pid = rec.get("parent_id")
            if pid is not None:
                s = float(rec["start_unix"])
                children_iv.setdefault(int(pid), []).append(
                    (s, s + float(rec.get("duration_s", 0.0)))
                )
        # boundary events over every span's self-intervals
        edges: List[Tuple[float, int, str]] = []
        for rec in spans:
            s = float(rec["start_unix"])
            own = (s, s + float(rec.get("duration_s", 0.0)))
            sid = rec.get("span_id")
            kids = children_iv.get(int(sid), []) if sid is not None else []
            phase = classify_span(str(rec.get("name", "")))
            for a, b in _subtract_intervals(own, kids):
                edges.append((a, 1, phase))
                edges.append((b, -1, phase))
        edges.sort(key=lambda e: (e[0], e[1]))
        active: Dict[str, int] = {}
        k = 0
        prev_t: Optional[float] = None
        for t, delta, phase in edges:
            if prev_t is not None and k > 0 and t > prev_t:
                seg = t - prev_t
                for ph, cnt in active.items():
                    if cnt:
                        phases[ph]["busy_s"] += seg * cnt
                        phases[ph]["seconds"] += seg * cnt / k
            active[phase] = active.get(phase, 0) + delta
            k += delta
            prev_t = t
        for p in phases.values():
            p["overlap_s"] = max(0.0, p["busy_s"] - p["seconds"])
    else:
        # legacy ledgers without start_unix: scalar exclusive time (no
        # interval data to share concurrency with)
        if spans and not have_starts:
            warnings.append(
                "span records lack start_unix; exclusive time computed "
                "per-span (concurrent spans may double-count)"
            )
        for rec in spans:
            dur = float(rec.get("duration_s", 0.0))
            sid = rec.get("span_id")
            child = children_dur.get(int(sid), 0.0) if sid is not None else 0.0
            exclusive = max(0.0, dur - child)
            bucket = phases[classify_span(str(rec.get("name", "")))]
            bucket["seconds"] += exclusive
            bucket["busy_s"] += exclusive

    # ---- bubble accounting ---------------------------------------------
    # gaps inside the run window covered by NO root span = host driver
    # time between instrumented regions (plus pre-first-span setup)
    root_intervals = []
    window_start = start_ts if start_ts is not None else (min(starts) if starts else 0.0)
    window_end = window_start + wall
    for rec in spans:
        if rec.get("parent_id") is None and "start_unix" in rec:
            s = max(window_start, float(rec["start_unix"]))
            e = min(window_end, float(rec["start_unix"]) + float(rec.get("duration_s", 0.0)))
            if e > s:
                root_intervals.append((s, e))
    covered = _merged_coverage(root_intervals)
    bubble = max(0.0, wall - covered)

    span_total = sum(p["seconds"] for p in phases.values())
    overlap_total = sum(p["overlap_s"] for p in phases.values())
    attributed = span_total + bubble
    coverage = attributed / wall if wall > 0 else 0.0
    for p in phases.values():
        p["fraction"] = (p["seconds"] / wall) if wall > 0 else 0.0
        p["seconds"] = round(p["seconds"], 6)
        p["fraction"] = round(p["fraction"], 6)
        p["busy_s"] = round(p["busy_s"], 6)
        p["overlap_s"] = round(p["overlap_s"], 6)

    # ---- joins ----------------------------------------------------------
    event_counts: Dict[str, int] = {}
    solver_events = []
    transfer_events = []
    for rec in events:
        name = str(rec.get("event", "?"))
        event_counts[name] = event_counts.get(name, 0) + 1
        if name == "SolverStatsEvent":
            solver_events.append(rec.get("fields") or {})
        elif name == "TransferStatsEvent":
            transfer_events.append(rec.get("fields") or {})

    solver: Dict[str, Any] = {}
    if solver_events:
        def _sum(key):
            return sum(float(f.get(key, 0) or 0) for f in solver_events)

        executed = _sum("executed_lane_iterations")
        lockstep = _sum("lockstep_lane_iterations")
        solver = {
            "buckets": len(solver_events),
            "entities": int(_sum("num_entities")),
            "rounds": int(_sum("rounds")),
            "executed_lane_iterations": int(executed),
            "lockstep_lane_iterations": int(lockstep),
            "lane_iteration_savings": (
                round(lockstep / executed, 4) if executed else None
            ),
            "chunk_retraces": int(_sum("chunk_retraces")),
            "unconverged_buckets": sum(
                1 for f in solver_events if not f.get("converged", True)
            ),
        }

    snapshot = dict(metric_recs[-1].get("snapshot") or {}) if metric_recs else {}
    counters = snapshot.get("counters") or {}
    transfers = {
        k[len("transfer."):]: v
        for k, v in counters.items()
        if k.startswith("transfer.")
    }
    if not transfers and transfer_events:
        for f in transfer_events:
            for k, v in f.items():
                if isinstance(v, (int, float)):
                    transfers[k] = transfers.get(k, 0) + v
    jit = {
        k[len("jit.traces."):]: int(v)
        for k, v in counters.items()
        if k.startswith("jit.traces.")
    }

    return RunReport(
        label=str(label),
        source_path=source_path,
        wall_clock_s=round(wall, 6),
        span_extent_s=round(span_extent, 6),
        phases=phases,
        bubble_s=round(bubble, 6),
        attributed_s=round(attributed, 6),
        coverage=round(coverage, 6),
        num_spans=len(spans),
        failed_spans=failed,
        top_spans=_span_tree_summary(spans, max_depth=2),
        solver=solver,
        transfers=transfers,
        jit_traces=jit,
        events=event_counts,
        metrics=snapshot,
        warnings=warnings,
        overlap_s=round(overlap_total, 6),
        progress=(
            convergence_report(progress_recs) if progress_recs else None
        ),
        requests=request_report(request_recs) if request_recs else None,
        cluster=cluster_report(progress_recs) if progress_recs else None,
    )


def analyze_ledger(path: str) -> RunReport:
    """Validate + replay one run-ledger file into a :class:`RunReport`.
    Crash-truncated ledgers analyze their valid prefix (with a report
    warning) rather than failing."""
    import warnings as _w

    with _w.catch_warnings(record=True) as caught:
        _w.simplefilter("always")
        records = validate_ledger(path)
    report = analyze_records(records, source_path=path)
    for w in caught:
        report.warnings.append(str(w.message))
    return report


def format_report(report: RunReport) -> str:
    """Human-readable occupancy report (the analyze_run CLI output)."""
    lines = [
        f"run report [{report.label}]"
        + (f" — {report.source_path}" if report.source_path else ""),
        f"  wall clock      {report.wall_clock_s:10.4f}s"
        f"   spans {report.num_spans}"
        + (f"   FAILED {report.failed_spans}" if report.failed_spans else ""),
        f"  attributed      {report.attributed_s:10.4f}s"
        f"   coverage {report.coverage * 100:6.2f}%",
        "",
        f"  {'phase':<12} {'seconds':>10} {'share':>8} {'spans':>7}",
    ]
    rows = sorted(
        ((p, v) for p, v in report.phases.items() if v["spans"] or v["seconds"]),
        key=lambda kv: -kv[1]["seconds"],
    )
    for phase, v in rows:
        overlap = float(v.get("overlap_s", 0.0) or 0.0)
        lines.append(
            f"  {phase:<12} {v['seconds']:>10.4f} {v['fraction'] * 100:>7.2f}% "
            f"{int(v['spans']):>7d}"
            + (f"   overlap {overlap:.4f}s" if overlap > 0 else "")
        )
    lines.append(
        f"  {'(bubbles)':<12} {report.bubble_s:>10.4f} "
        f"{(report.bubble_s / report.wall_clock_s * 100 if report.wall_clock_s else 0):>7.2f}%"
        f" {'—':>7}"
    )
    if report.overlap_s > 0:
        lines.append(
            f"  overlapped      {report.overlap_s:10.4f}s of concurrent span "
            "time shared across phases (busy − attributed)"
        )
    if report.solver:
        s = report.solver
        lines += [
            "",
            "  solver join: "
            f"{s['buckets']} bucket(s), {s['entities']} entities, "
            f"{s['rounds']} adaptive round(s)",
            f"    lane iterations executed/lockstep: "
            f"{s['executed_lane_iterations']}/{s['lockstep_lane_iterations']}"
            + (
                f" (savings {s['lane_iteration_savings']}x)"
                if s.get("lane_iteration_savings")
                else ""
            ),
        ]
    if report.transfers:
        lines.append("  transfer join: " + ", ".join(
            f"{k}={int(v)}" for k, v in sorted(report.transfers.items())
        ))
    if report.jit_traces:
        lines.append("  jit traces: " + ", ".join(
            f"{k}={v}" for k, v in sorted(report.jit_traces.items())
        ))
    if report.progress:
        prog = report.progress
        anomalies = prog.get("anomalies") or []
        lines.append(
            f"  convergence plane: {prog.get('num_updates', 0)} coordinate "
            f"update(s) over {len(prog.get('coordinates') or {})} "
            "coordinate(s)"
            + (f", {len(anomalies)} ANOMALY record(s)" if anomalies else "")
            + " — full report via analyze_run --progress"
        )
    if report.requests:
        req = report.requests
        tail = req.get("tail") or {}
        lines.append(
            f"  request plane: {req.get('num_records', 0)} sampled "
            f"lifecycle record(s), tail worst stage "
            f"'{tail.get('worst_stage', '?')}' — full attribution via "
            "analyze_run --requests"
        )
    if report.cluster:
        clu = report.cluster
        lines.append(
            f"  cluster plane: {clu.get('num_passes', 0)} distributed "
            f"pass(es) over {clu.get('num_hosts', 0)} host(s), comm wait "
            f"{clu.get('comm_wait_frac', 0.0) * 100:.1f}% of pass wall — "
            "full skew attribution via analyze_run --cluster"
        )
    if report.warnings:
        lines.append("")
        for w in report.warnings:
            lines.append(f"  warning: {w}")
    return "\n".join(lines)
