"""Unified telemetry: span tracing, metrics registry, exportable ledgers.

The training and serving stacks grew their timing/counting signals
piecemeal (``utils/timer.py``, ``opt/tracking.py``, ``serving/metrics.py``,
the ``Event`` pub/sub). This package is the single place they all land:

* :func:`span` — hierarchical, contextvar-scoped timing spans. Near-free
  when disabled (the default); see :mod:`photon_ml_tpu.telemetry.span`.
* :func:`get_registry` — process-global counters/gauges/histograms,
  including the :func:`note_jit_trace` compile/retrace counter and
  :func:`record_memory_watermarks`.
* sinks — JSONL run ledger, Chrome trace-event (Perfetto) export, terminal
  summary table, and the :class:`TelemetryEventListener` bridge.
* :func:`start_run` — one handle tying the above together for a CLI/bench
  run (``--telemetry-out`` / ``--trace-out``).

See docs/OBSERVABILITY.md for the span model, metric names, and schemas.
"""
from photon_ml_tpu.telemetry.span import (
    NOOP_SPAN,
    SpanRecord,
    Tracer,
    disable_tracing,
    enable_tracing,
    get_tracer,
    span,
)
from photon_ml_tpu.telemetry.metrics import (
    MetricsRegistry,
    ScopedMetrics,
    get_registry,
    jit_trace_counts,
    note_jit_trace,
    record_memory_watermarks,
)
from photon_ml_tpu.telemetry.sinks import (
    RunLedger,
    TelemetryEventListener,
    chrome_trace_events,
    cluster_lane_events,
    format_summary_table,
    span_tree_summary,
    write_chrome_trace,
)
from photon_ml_tpu.telemetry.progress import (
    ConvergenceTracker,
    DivergenceError,
    convergence_report,
    extract_progress_records,
    format_progress_report,
    iterations_to_target_metric,
)
from photon_ml_tpu.telemetry.session import TelemetryRun, start_run
from photon_ml_tpu.telemetry.validate import (
    TruncatedLedgerWarning,
    validate_chrome_trace,
    validate_ledger,
)
from photon_ml_tpu.telemetry.analyze import (
    RunReport,
    analyze_ledger,
    analyze_records,
    classify_span,
    cluster_report,
    format_cluster_report,
    format_report,
)

__all__ = [
    "NOOP_SPAN",
    "SpanRecord",
    "Tracer",
    "disable_tracing",
    "enable_tracing",
    "get_tracer",
    "span",
    "MetricsRegistry",
    "ScopedMetrics",
    "get_registry",
    "jit_trace_counts",
    "note_jit_trace",
    "record_memory_watermarks",
    "RunLedger",
    "TelemetryEventListener",
    "chrome_trace_events",
    "cluster_lane_events",
    "format_summary_table",
    "span_tree_summary",
    "write_chrome_trace",
    "ConvergenceTracker",
    "DivergenceError",
    "convergence_report",
    "extract_progress_records",
    "format_progress_report",
    "iterations_to_target_metric",
    "TelemetryRun",
    "start_run",
    "TruncatedLedgerWarning",
    "validate_chrome_trace",
    "validate_ledger",
    "RunReport",
    "analyze_ledger",
    "analyze_records",
    "classify_span",
    "cluster_report",
    "format_cluster_report",
    "format_report",
]
