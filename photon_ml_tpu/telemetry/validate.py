"""Schema checks for telemetry artifacts (used by tests and the CI gate).

These are deliberately dependency-free structural validators — no
jsonschema in the image — that raise ``ValueError`` with a precise message
on the first violation and return the parsed payload on success.
"""
from __future__ import annotations

import json
import warnings as _warnings
from typing import Any, Dict, List

__all__ = ["validate_chrome_trace", "validate_ledger", "TruncatedLedgerWarning"]


class TruncatedLedgerWarning(UserWarning):
    """The ledger's final line was cut mid-write (crash-truncated); the
    valid prefix was still validated and returned."""

_REQUIRED_TRACE_KEYS = ("name", "cat", "ph", "ts", "dur", "pid", "tid")

# ledger record type -> required extra fields ("type" and "ts" are
# required on every record)
_LEDGER_SCHEMAS: Dict[str, tuple] = {
    "meta": ("phase",),
    "event": ("event", "fields"),
    "span": ("name", "path", "span_id", "duration_s", "failed"),
    "metrics": ("snapshot",),
    # convergence plane (telemetry/progress.py): one record per coordinate
    # update / validation probe / streamed block / watchdog anomaly
    "progress": ("kind",),
    # request plane (serving/requestplane.py): one record per SAMPLED
    # serving request — per-stage exclusive seconds + end-to-end latency
    "request": ("request_id", "bucket", "stages", "total_s"),
}

# per-stage exclusive durations every request record's "stages" dict must
# carry (they telescope: their sum IS total_s)
_REQUEST_STAGES = ("queue", "featurize", "route", "dispatch", "device", "reply")

# progress record kind -> required extra fields beyond "kind"
_PROGRESS_SCHEMAS: Dict[str, tuple] = {
    "coordinate": ("outer", "coordinate", "objective"),
    "validation": ("outer", "coordinate", "metric"),
    "block": ("outer", "coordinate", "block", "partial_loss",
              "partial_grad_norm", "gap_estimate"),
    # gap scheduler: one record per stochastic epoch's visit decision
    "schedule": ("outer", "coordinate", "epoch", "visited", "explored",
                 "num_blocks"),
    "anomaly": ("anomaly_kind", "objective"),
    # failure plane (resilience/): recovered or degraded events — retry
    # exhaustion, skipped blocks, supervised-thread crashes
    "resilience": ("failure_kind", "site"),
    # cluster plane (parallel/cluster): block rebalance / host-loss /
    # reassignment events of a distributed solve
    "cluster": ("outer", "coordinate", "event"),
    # cluster plane skew profiles: one record per distributed pass with
    # the exact wall-clock decomposition (busy + allreduce wait +
    # coordinator bubble == wall) ...
    "cluster_pass": ("outer", "coordinate", "pass_id", "wall_s", "busy_s",
                     "allreduce_wait_s", "bubble_s", "straggler_index",
                     "hosts"),
    # ... plus one record per (pass, host) with that host's measured
    # busy/wall and blocks visited (and, when present, the assigner's
    # predicted_share vs the measured actual_share)
    "host_pass": ("outer", "coordinate", "pass_id", "host", "busy_s",
                  "wall_s", "blocks"),
    # HBM residency plane (streaming/residency.py): one record per
    # pin/evict decision — which block, on what gap score, byte delta
    "residency": ("outer", "coordinate", "epoch", "action", "block",
                  "gap_score", "byte_delta"),
}


def validate_chrome_trace(path: str) -> Dict[str, Any]:
    """Check ``path`` is valid Chrome trace-event JSON (object form with a
    ``traceEvents`` list of complete events). Returns the parsed doc."""
    with open(path, "r", encoding="utf-8") as f:
        doc = json.load(f)
    if not isinstance(doc, dict):
        raise ValueError(f"{path}: trace must be a JSON object, got {type(doc).__name__}")
    events = doc.get("traceEvents")
    if not isinstance(events, list):
        raise ValueError(f"{path}: missing traceEvents list")
    for i, ev in enumerate(events):
        if not isinstance(ev, dict):
            raise ValueError(f"{path}: traceEvents[{i}] is not an object")
        for key in _REQUIRED_TRACE_KEYS:
            if key not in ev:
                raise ValueError(f"{path}: traceEvents[{i}] missing {key!r}")
        if ev["ph"] != "X":
            raise ValueError(
                f"{path}: traceEvents[{i}] has phase {ev['ph']!r}, expected 'X'"
            )
        for key in ("ts", "dur"):
            if not isinstance(ev[key], (int, float)) or ev[key] < 0:
                raise ValueError(
                    f"{path}: traceEvents[{i}][{key!r}] must be a non-negative "
                    f"number, got {ev[key]!r}"
                )
    return doc


def validate_ledger(
    path: str, allow_truncated_tail: bool = True
) -> List[Dict[str, Any]]:
    """Check every line of ``path`` is a typed JSONL record matching the
    ledger schema. Returns the parsed records.

    A crash-truncated ledger — the process was killed mid-``write``, so the
    FINAL line is a partial record with no trailing newline — is tolerated
    by default: the partial tail raises a :class:`TruncatedLedgerWarning`
    and the valid prefix is still validated and returned. A malformed line
    anywhere else (or any bad line with ``allow_truncated_tail=False``)
    remains a hard ``ValueError``: that is corruption, not a crash
    artifact.
    """
    with open(path, "r", encoding="utf-8", newline="") as f:
        raw = f.read()
    lines = raw.split("\n")
    # A well-formed ledger ends with "\n", leaving a trailing "" element; a
    # non-empty final element means the last write was cut short.
    truncated_tail = bool(lines) and lines[-1] != ""
    records: List[Dict[str, Any]] = []
    for lineno, line in enumerate(lines, start=1):
        line = line.strip()
        if not line:
            continue
        is_tail = lineno == len(lines) and truncated_tail
        try:
            rec = json.loads(line)
        except json.JSONDecodeError as e:
            if is_tail and allow_truncated_tail:
                _warnings.warn(
                    f"{path}:{lineno}: final line is a partial record "
                    f"(crash-truncated ledger); validated the "
                    f"{len(records)}-record prefix",
                    TruncatedLedgerWarning,
                    stacklevel=2,
                )
                break
            raise ValueError(f"{path}:{lineno}: invalid JSON ({e})") from e
        if not isinstance(rec, dict):
            raise ValueError(f"{path}:{lineno}: record is not an object")
        rec_type = rec.get("type")
        if rec_type not in _LEDGER_SCHEMAS:
            raise ValueError(
                f"{path}:{lineno}: unknown record type {rec_type!r} "
                f"(expected one of {sorted(_LEDGER_SCHEMAS)})"
            )
        if not isinstance(rec.get("ts"), (int, float)):
            raise ValueError(f"{path}:{lineno}: missing numeric 'ts'")
        for field in _LEDGER_SCHEMAS[rec_type]:
            if field not in rec:
                raise ValueError(
                    f"{path}:{lineno}: {rec_type} record missing {field!r}"
                )
        if rec_type == "progress":
            kind = rec.get("kind")
            if kind not in _PROGRESS_SCHEMAS:
                raise ValueError(
                    f"{path}:{lineno}: unknown progress kind {kind!r} "
                    f"(expected one of {sorted(_PROGRESS_SCHEMAS)})"
                )
            for field in _PROGRESS_SCHEMAS[kind]:
                if field not in rec:
                    raise ValueError(
                        f"{path}:{lineno}: progress/{kind} record missing "
                        f"{field!r}"
                    )
        if rec_type == "request":
            stages = rec.get("stages")
            if not isinstance(stages, dict):
                raise ValueError(
                    f"{path}:{lineno}: request record 'stages' must be an "
                    f"object, got {type(stages).__name__}"
                )
            for stage in _REQUEST_STAGES:
                if not isinstance(stages.get(stage), (int, float)):
                    raise ValueError(
                        f"{path}:{lineno}: request record missing numeric "
                        f"stage {stage!r}"
                    )
            if not isinstance(rec.get("total_s"), (int, float)):
                raise ValueError(
                    f"{path}:{lineno}: request record 'total_s' must be a "
                    f"number"
                )
        records.append(rec)
    if not records:
        raise ValueError(f"{path}: ledger is empty")
    return records
