"""GAME nearline update driver: fold a batch of new events into a trained
model and publish a delta artifact.

The offline driver (``train_game``) runs full block coordinate descent from
scratch; this driver is the nearline half of the loop — it warm-starts from
an already-trained model (model dir or training checkpoint), re-solves ONLY
the per-entity random-effect rows touched by the new events (optionally
refreshing the fixed effects first with the random effects frozen), and
writes the result as a versioned *delta* directory that chains to the base
serving artifact by content fingerprint. A live server picks deltas up with
``serve_game --watch-deltas`` (or ``HotSwapManager.poll_directory``) and
applies them between requests without restarting or re-jitting.

Usage:
    # publish one delta from a batch of fresh events
    python -m photon_ml_tpu.cli.update_game \
        --base-artifact-dir out/artifact --model-dir out/best \
        --coordinate-config game.json --events-data-dirs data/new \
        --output-dir out/deltas

    # periodically: fold the accumulated chain back into a full artifact
    python -m photon_ml_tpu.cli.update_game \
        --base-artifact-dir out/artifact --model-dir out/best \
        --coordinate-config game.json --events-data-dirs data/new \
        --output-dir out/deltas --compact-into out/artifact.v2
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import List, Optional

from photon_ml_tpu.cli.common import (
    add_telemetry_args,
    finish_telemetry,
    id_tags_needed,
    load_game_config,
    parse_input_columns,
    setup_logger,
    start_telemetry,
)
from photon_ml_tpu.utils.timer import Timer


def parse_args(argv: Optional[List[str]] = None) -> argparse.Namespace:
    p = argparse.ArgumentParser(
        prog="photon-ml-tpu update-game", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    p.add_argument("--base-artifact-dir", required=True,
                   help="serving artifact the delta chains to (feature "
                        "index maps are reused so featurization matches)")
    src = p.add_mutually_exclusive_group(required=True)
    src.add_argument("--model-dir",
                     help="trained GAME model directory to warm-start from")
    src.add_argument("--checkpoint-dir",
                     help="training checkpoint directory to warm-start from")
    p.add_argument("--coordinate-config", required=True,
                   help="typed JSON coordinate-config file (same file used "
                        "to train the base model)")
    p.add_argument("--events-data-dirs", nargs="+", required=True,
                   help="Avro dirs holding the new-events batch")
    p.add_argument("--output-dir", required=True,
                   help="deltas root; the new delta lands at "
                        "<output-dir>/delta-NNNNNN")
    p.add_argument("--refresh-fixed-iterations", type=int, default=0,
                   help="fixed-effect refresh passes (random effects "
                        "frozen) before the per-entity re-solves")
    p.add_argument("--generation", type=int, default=None,
                   help="delta generation number (default: one past the "
                        "last delta already in --output-dir)")
    p.add_argument("--compact-into", default=None,
                   help="also fold base + full delta chain into a fresh "
                        "artifact at this directory")
    p.add_argument("--event-listeners", nargs="*", default=[],
                   help="dotted class paths registered on the event emitter")
    p.add_argument("--input-columns-names", default=None,
                   help="JSON map overriding input field names")
    p.add_argument("--log-file", default=None)
    add_telemetry_args(p)
    return p.parse_args(argv)


def _chain_head(output_dir: str, base_artifact_dir: str):
    """(generation, base_fingerprint) for the next delta: chain to the last
    delta already published in ``output_dir``, else root at the base
    artifact's content fingerprint."""
    from photon_ml_tpu.incremental import (
        discover_deltas,
        fingerprint_dir,
        load_delta,
    )

    existing = discover_deltas(output_dir)
    if existing:
        last = load_delta(existing[-1])
        return last.generation + 1, last.fingerprint
    return 1, fingerprint_dir(base_artifact_dir)


def run(args: argparse.Namespace) -> dict:
    from photon_ml_tpu.event import EventEmitter, PhotonSetupEvent

    logger = setup_logger(args.log_file)
    timer = Timer()
    emitter = EventEmitter()
    for name in args.event_listeners:
        emitter.register_listener_class(name)
    telemetry = start_telemetry(args, "update_game", emitter=emitter)
    emitter.send_event(PhotonSetupEvent(params=vars(args)))
    t_start = time.perf_counter()
    try:
        return _run_update(args, logger, timer, emitter, t_start)
    finally:
        # listeners must flush/close even when the run fails; telemetry
        # finishes after them so every bridged event is in the ledger
        emitter.clear_listeners()
        finish_telemetry(telemetry, phases=dict(timer.durations))


def _run_update(args, logger, timer, emitter, t_start) -> dict:
    from photon_ml_tpu.estimators.game import GameEstimator
    from photon_ml_tpu.event import TrainingFinishEvent, TrainingStartEvent
    from photon_ml_tpu.incremental import (
        build_delta,
        compact,
        delta_dir_name,
        discover_deltas,
        incremental_update,
        save_delta,
    )
    from photon_ml_tpu.io.data_reader import read_game_data
    from photon_ml_tpu.serving import load_artifact

    shard_configs, coordinates, update_order, _ = load_game_config(
        args.coordinate_config
    )

    with timer.time("load artifact"):
        artifact = load_artifact(args.base_artifact_dir)
    index_maps = dict(artifact.feature_index) or None
    if index_maps is None:
        logger.warning(
            "base artifact carries no feature index maps; indexes will be "
            "rebuilt from the events and may not match the model"
        )

    col_names = parse_input_columns(args.input_columns_names)
    with timer.time("read events"):
        events, _, _ = read_game_data(
            args.events_data_dirs,
            shard_configs,
            index_maps,
            id_tags=id_tags_needed(coordinates),
            **col_names,
        )
    logger.info("read %d new events", events.num_rows)

    estimator = GameEstimator(
        task=artifact.task,
        coordinates=coordinates,
        update_order=update_order,
        num_outer_iterations=1,
        emitter=emitter,
    )

    if args.model_dir:
        from photon_ml_tpu.io.model_io import load_game_model

        with timer.time("load model"):
            model, _ = load_game_model(args.model_dir)
    else:
        model = args.checkpoint_dir  # incremental_update loads checkpoints

    emitter.send_event(TrainingStartEvent(task=artifact.task.name))
    with timer.time("incremental update"):
        update = incremental_update(
            estimator, model, events,
            refresh_fixed_iterations=args.refresh_fixed_iterations,
            merge=False,
        )

    generation, base_fp = _chain_head(args.output_dir, args.base_artifact_dir)
    if args.generation is not None:
        generation = args.generation
    delta_dir = os.path.join(args.output_dir, delta_dir_name(generation))
    with timer.time("publish delta"):
        delta = build_delta(
            update.re_updates, artifact,
            fe_updates=update.fe_updates or None,
            base_fingerprint=base_fp,
            generation=generation,
            created_at_unix=time.time(),
        )
        delta = save_delta(delta, delta_dir)
    logger.info(
        "published delta generation %d (%d rows) at %s",
        generation, delta.num_rows_updated, delta_dir,
    )

    compacted_fp = None
    if args.compact_into:
        with timer.time("compact"):
            compacted_fp = compact(
                args.base_artifact_dir,
                discover_deltas(args.output_dir),
                args.compact_into,
            )
        logger.info(
            "compacted chain into %s (fingerprint %s)",
            args.compact_into, compacted_fp,
        )

    emitter.send_event(TrainingFinishEvent(
        task=artifact.task.name,
        wall_seconds=time.perf_counter() - t_start,
    ))

    summary = {
        "delta_dir": delta_dir,
        "generation": generation,
        "fingerprint": delta.fingerprint,
        "base_fingerprint": base_fp,
        "rows_updated": delta.num_rows_updated,
        "num_events": update.num_events,
        "touched_entities": {
            cid: len(eids) for cid, eids in update.touched_entities.items()
        },
        "new_entities": {
            cid: len(eids) for cid, eids in update.new_entities.items()
        },
        "fixed_effects_refreshed": sorted(update.fe_updates),
    }
    if compacted_fp is not None:
        summary["compacted_into"] = args.compact_into
        summary["compacted_fingerprint"] = compacted_fp
    print(json.dumps(summary))

    for name, seconds in timer.durations.items():
        logger.info("timing %-20s %.3fs", name, seconds)
    return summary


def main(argv: Optional[List[str]] = None) -> int:
    run(parse_args(argv))
    return 0


if __name__ == "__main__":
    sys.exit(main())
