"""Replay a telemetry run ledger into a performance report.

Reads the JSONL RunLedger a ``--telemetry-out`` run (or ``bench.py``
telemetry mode) wrote, reconstructs the span tree, and prints per-phase
occupancy/bubble accounting with the SolverStats / TransferStats /
jit-retrace joins. Optionally emits the structured ``RunReport`` as JSON,
gates on wall-clock attribution coverage (the CI analyze smoke gate), and
runs the offline tuner over the report to propose a config.

Usage:
    # human-readable occupancy report
    python -m photon_ml_tpu.cli.analyze_run out/run-ledger.jsonl

    # CI gate: fail unless >=95% of wall-clock is attributed
    python -m photon_ml_tpu.cli.analyze_run out/run-ledger.jsonl \
        --check-coverage 0.95

    # structured report + tuner proposal over the registered knob space
    python -m photon_ml_tpu.cli.analyze_run out/run-ledger.jsonl \
        --json report.json --propose --propose-json proposal.json
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional

from photon_ml_tpu.telemetry.analyze import analyze_ledger, format_report


def parse_args(argv: Optional[List[str]] = None) -> argparse.Namespace:
    parser = argparse.ArgumentParser(
        prog="analyze_run",
        description="Replay a telemetry run ledger into a performance report.",
    )
    parser.add_argument("ledger", help="Path to a run-ledger JSONL file.")
    parser.add_argument(
        "--json",
        default=None,
        metavar="PATH",
        help="Also write the structured RunReport as JSON to PATH ('-' for stdout).",
    )
    parser.add_argument(
        "--check-coverage",
        type=float,
        default=None,
        metavar="FRACTION",
        help=(
            "Exit nonzero unless attributed time covers at least FRACTION of "
            "wall-clock AND does not exceed it by the same margin (catches "
            "both unattributed time and cross-thread double-counting)."
        ),
    )
    parser.add_argument(
        "--propose",
        action="store_true",
        help="Run the offline tuner over the report and print its proposal.",
    )
    parser.add_argument(
        "--propose-json",
        default=None,
        metavar="PATH",
        help="Write the tuner proposal as JSON to PATH (implies --propose).",
    )
    parser.add_argument(
        "--progress",
        action="store_true",
        help=(
            "Render the convergence report (iterations-to-tolerance per "
            "coordinate, objective shares, per-block gap estimates, "
            "anomalies) from the ledger's progress records; exits nonzero "
            "when the ledger carries none."
        ),
    )
    parser.add_argument(
        "--requests",
        action="store_true",
        help=(
            "Render the request-plane tail-latency attribution (per-stage "
            "p50/p99, tail breakdown with exemplar request ids, "
            "interference overlap) from the ledger's sampled request "
            "records; exits nonzero when the ledger carries none."
        ),
    )
    parser.add_argument(
        "--cluster",
        action="store_true",
        help=(
            "Render the cluster-plane skew attribution (per-pass busy/"
            "allreduce-wait/bubble decomposition, per-host work vs the "
            "assigner's predicted shares, straggler ranking, imbalance "
            "trend) from the ledger's cluster_pass/host_pass records; "
            "exits nonzero when the ledger carries none."
        ),
    )
    parser.add_argument(
        "--quiet",
        action="store_true",
        help="Suppress the human-readable report (JSON outputs still written).",
    )
    return parser.parse_args(argv)


def run(args: argparse.Namespace) -> int:
    report = analyze_ledger(args.ledger)
    if args.progress:
        from photon_ml_tpu.telemetry.progress import format_progress_report

        if not report.progress:
            print(
                "analyze_run: ledger carries no progress records (train with "
                "--progress-out to record the convergence plane)",
                file=sys.stderr,
            )
            return 1
        if not args.quiet:
            print(format_progress_report(report.progress))
    if args.requests:
        from photon_ml_tpu.telemetry.analyze import format_request_report

        if not report.requests:
            print(
                "analyze_run: ledger carries no request records (serve with "
                "a RequestPlane attached — serve_game --request-sample-rate "
                "— to record sampled lifecycles)",
                file=sys.stderr,
            )
            return 1
        if not args.quiet:
            print(format_request_report(report.requests))
    if args.cluster:
        from photon_ml_tpu.telemetry.analyze import format_cluster_report

        if not report.cluster:
            print(
                "analyze_run: ledger carries no cluster_pass records (run "
                "the cluster plane with telemetry — train_game --hosts "
                "N --telemetry-out, or bench.py --multihost with "
                "BENCH_TELEMETRY_DIR — to record skew profiles)",
                file=sys.stderr,
            )
            return 1
        if not args.quiet:
            print(format_cluster_report(report.cluster))
    if not args.quiet:
        print(format_report(report))
    if args.json:
        payload = json.dumps(report.to_dict(), indent=2, sort_keys=True)
        if args.json == "-":
            print(payload)
        else:
            with open(args.json, "w", encoding="utf-8") as f:
                f.write(payload + "\n")

    if args.propose or args.propose_json:
        from photon_ml_tpu.tuning import propose

        proposal = propose(report)
        if not args.quiet:
            print()
            print(f"tuner proposal over {len(proposal.knobs)} registered knob(s):")
            for name, knob in sorted(proposal.knobs.items()):
                marker = "->" if knob.changed else "  "
                print(
                    f"  {marker} {name}: {knob.value!r}"
                    + (f" (default {knob.default!r})" if knob.changed else "")
                )
                print(f"       {knob.rationale}")
        if args.propose_json:
            with open(args.propose_json, "w", encoding="utf-8") as f:
                f.write(
                    json.dumps(proposal.to_dict(), indent=2, sort_keys=True) + "\n"
                )

    if args.check_coverage is not None:
        lo, hi = args.check_coverage, 2.0 - args.check_coverage
        if not (lo <= report.coverage <= hi):
            print(
                f"analyze_run: coverage {report.coverage:.4f} outside "
                f"[{lo:.2f}, {hi:.2f}] — "
                + (
                    "unattributed wall-clock time"
                    if report.coverage < lo
                    else "attributed more than wall-clock (double-counting?)"
                ),
                file=sys.stderr,
            )
            return 1
        print(
            f"analyze_run: coverage {report.coverage:.4f} within "
            f"[{lo:.2f}, {hi:.2f}]"
        )
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    return run(parse_args(argv))


if __name__ == "__main__":
    sys.exit(main())
