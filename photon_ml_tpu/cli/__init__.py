"""Command-line drivers.

Reference parity: photon-client's four entry points —
cli/game/training/Driver.scala:448 (GAME training),
cli/game/scoring/Driver.scala:266 (GAME scoring),
Driver.scala:71 (legacy single-GLM pipeline),
FeatureIndexingJob.scala:214 (off-heap index-map build) —
launched with ``python -m photon_ml_tpu.cli.<driver>`` instead of
spark-submit. The reference's string mini-languages
(GLMOptimizationConfiguration et al.) are replaced by a typed JSON
coordinate-config file with the same knobs (SURVEY.md §5 rebuild note).
"""
