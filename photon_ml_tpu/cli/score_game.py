"""GAME scoring driver.

Reference parity: cli/game/scoring/Driver.scala:37 — run() (:176-209):
prepareFeatureMaps → read data (response optional) → loadGameModelFromHDFS →
gameModel.score → saveScoresToHDFS (ScoringResultAvro) → optional evaluation.

Usage:
    python -m photon_ml_tpu.cli.score_game \
        --data-dirs data/test --model-dir out/best \
        --output-dir scores/ --evaluator AUC
"""

from __future__ import annotations

import argparse
import sys
from typing import List, Optional

import numpy as np

from photon_ml_tpu.cli.common import (
    add_telemetry_args,
    delete_dirs_if_exist,
    finish_telemetry,
    parse_input_columns,
    setup_logger,
    start_telemetry,
)
from photon_ml_tpu.cli.train_game import _make_evaluator
from photon_ml_tpu.io.data_reader import (
    FeatureShardConfiguration,
    read_game_data,
)
from photon_ml_tpu.io.model_io import load_game_model, load_game_model_metadata
from photon_ml_tpu.io.scores_io import ScoredItem, save_scores
from photon_ml_tpu.types import TaskType
from photon_ml_tpu.utils.timer import Timer


def parse_args(argv: Optional[List[str]] = None) -> argparse.Namespace:
    p = argparse.ArgumentParser(
        prog="photon-ml-tpu score-game", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    from photon_ml_tpu.parallel.multihost import add_distributed_args

    add_distributed_args(p)
    p.add_argument("--data-dirs", nargs="+", required=True)
    p.add_argument("--model-dir", required=True)
    p.add_argument("--output-dir", required=True)
    p.add_argument("--date-range", default=None,
                   help="yyyyMMdd-yyyyMMdd; expands each data dir to its "
                        "daily yyyy/MM/dd subdirs (reference --date-range)")
    p.add_argument("--date-days-ago", default=None,
                   help="start-end days ago, e.g. 90-1 (reference "
                        "--date-range-days-ago)")
    p.add_argument("--model-id", default=None,
                   help="modelId stamped on ScoringResultAvro records "
                        "(defaults to the saved model name)")
    p.add_argument("--offheap-indexmap-dir", default=None,
                   help="score through prebuilt off-heap index stores "
                        "instead of the maps reconstructed from the model "
                        "(reference --offheap-indexmap-dir)")
    def _positive_int(v: str) -> int:
        n = int(v)
        if n < 1:
            raise argparse.ArgumentTypeError(f"must be >= 1, got {n}")
        return n

    p.add_argument("--num-output-files", type=_positive_int, default=None,
                   help="partition the score output into this many part "
                        "files (reference --num-files)")
    p.add_argument("--evaluator", default=None,
                   help="optional metric over scored data, e.g. AUC, "
                        "'RMSE:userId', or 'PRECISION@5:userId'")
    p.add_argument("--delete-output-dir-if-exists", action="store_true",
                   help="remove an existing --output-dir before writing")
    p.add_argument("--random-effect-id-set", default=None,
                   help="comma-separated random effect types to read from "
                        "the records, overriding the set derived from the "
                        "model (reference --random-effect-id-set)")
    p.add_argument("--input-columns-names", default=None,
                   help="JSON map overriding input field names; keys: "
                        "response, offset, weight, uid (reference "
                        "InputColumnsNames)")
    p.add_argument("--missing-entity-policy", choices=("fe-only", "error"),
                   default="fe-only",
                   help="rows naming entities absent from the model: "
                        "'fe-only' (default) scores them with the fixed "
                        "effects only (RE contribution 0, the reference "
                        "left-join semantics, same fallback the serving "
                        "path uses); 'error' fails fast instead")
    p.add_argument("--log-data-and-model-stats", action="store_true",
                   help="log dataset stats (rows, per-id-tag entity counts "
                        "and samples-per-entity) and per-coordinate model "
                        "sizes (reference --log-game-dataset-and-model-stats)")
    p.add_argument("--event-listeners", nargs="*", default=[],
                   metavar="module.Class",
                   help="EventListener classes to register")
    p.add_argument("--log-file", default=None)
    add_telemetry_args(p)
    return p.parse_args(argv)


def _log_data_and_model_stats(logger, data, model, id_tags) -> None:
    """Reference logGameDataSet/logGameModel (scoring Driver.scala:88-103):
    debug-level dataset summary (samples per id-tag entity) + model sizes."""
    logger.info("dataset stats: numSamples: %d", data.num_rows)
    for tag in id_tags:
        ids = data.id_tags.get(tag)
        if ids is None:
            continue
        _, counts = np.unique(np.asarray(ids), return_counts=True)
        logger.info(
            "dataset stats: samples per %s: entities=%d mean=%.2f "
            "stdev=%.2f min=%d max=%d",
            tag, counts.size, counts.mean(),
            counts.std(), counts.min(), counts.max(),
        )
    for cid, sub in model.models.items():
        coef = getattr(sub, "coefficients", None)
        if coef is not None and hasattr(coef, "means"):
            logger.info(
                "model stats [%s]: fixed effect, %d coefficients",
                cid, int(np.asarray(coef.means).shape[0]),
            )
        elif hasattr(sub, "num_entities"):
            logger.info(
                "model stats [%s]: random effect '%s', %d entities",
                cid, getattr(sub, "random_effect_type", "?"), sub.num_entities,
            )
        else:
            logger.info("model stats [%s]: %s", cid, type(sub).__name__)


def _check_missing_entities(model, data) -> None:
    """--missing-entity-policy=error: fail fast when the dataset names
    random-effect entities the model has never seen (the default scores
    those rows FE-only, exactly like the online serving fallback)."""
    from photon_ml_tpu.algorithm.factored_random_effect import (
        FactoredRandomEffectModel,
    )

    problems = []
    for cid, sub in model.models.items():
        re_type = model.meta[cid].random_effect_type
        if not re_type:
            continue
        loc = (
            sub.latent.entity_to_loc
            if isinstance(sub, FactoredRandomEffectModel)
            else sub.entity_to_loc
        )
        ids = data.id_tags.get(re_type)
        if ids is None:
            continue
        missing = sorted({str(e) for e in ids if str(e) not in loc})
        if missing:
            problems.append(
                f"[{cid}] {len(missing)} unknown {re_type!r} entities "
                f"(e.g. {missing[:5]})"
            )
    if problems:
        raise ValueError(
            "--missing-entity-policy=error: the dataset references "
            "entities absent from the model: " + "; ".join(problems)
        )


def run(args: argparse.Namespace) -> Optional[float]:
    import time

    from photon_ml_tpu.event import EventEmitter

    logger = setup_logger(args.log_file)
    timer = Timer()
    emitter = EventEmitter()
    for name in args.event_listeners:
        emitter.register_listener_class(name)
    telemetry = start_telemetry(args, "score_game", emitter=emitter)
    t_start = time.perf_counter()
    try:
        return _run_scoring(args, logger, timer, emitter, t_start)
    finally:
        # listeners must flush/close even when the run fails; telemetry
        # finishes after them so every bridged event is in the ledger
        emitter.clear_listeners()
        finish_telemetry(telemetry, phases=dict(timer.durations))


def _run_scoring(args, logger, timer, emitter, t_start) -> Optional[float]:
    import time

    from photon_ml_tpu.event import ScoringFinishEvent, ScoringStartEvent

    # a bad date spec must fail before the (possibly huge) model load
    from photon_ml_tpu.cli.common import expand_data_dirs

    data_dirs = expand_data_dirs(
        args.data_dirs, args.date_range, args.date_days_ago
    )

    metadata = load_game_model_metadata(args.model_dir)
    model_id = args.model_id or metadata.get("modelName", "game-model")

    # The saved config names the shard → feature bags mapping; without it,
    # each shard reads the record field of the same name.
    shard_bags = {}
    cfg = metadata.get("configurations") or {}
    for sid, s in (cfg.get("feature_shards") or {}).items():
        shard_bags[sid] = FeatureShardConfiguration(
            feature_bags=s["feature_bags"],
            add_intercept=bool(s.get("add_intercept", True)),
        )

    preloaded_maps = None
    if args.offheap_indexmap_dir:
        from photon_ml_tpu.cli.common import load_index_maps

        if not shard_bags:
            raise ValueError(
                "--offheap-indexmap-dir needs the model metadata to name "
                "its feature shards (configurations.feature_shards); this "
                "model carries none, so the off-heap stores cannot be "
                "bound to shards"
            )
        with timer.time("load index maps"):
            preloaded_maps = load_index_maps(
                args.offheap_indexmap_dir, shard_bags
            )
        logger.info(
            "scoring through off-heap index stores for shards: %s",
            sorted(preloaded_maps),
        )

    with timer.time("load model"):
        model, index_maps = load_game_model(
            args.model_dir, index_maps=preloaded_maps
        )
    for sid in index_maps:
        shard_bags.setdefault(
            sid, FeatureShardConfiguration(feature_bags=[sid])
        )

    if args.random_effect_id_set:
        id_tags = sorted(
            t.strip() for t in args.random_effect_id_set.split(",") if t.strip()
        )
    else:
        id_tags = sorted(
            {
                m.random_effect_type
                for m in model.meta.values()
                if m.random_effect_type
            }
        )
    # a sharded evaluator tag must be read even if no sub-model uses it
    if args.evaluator and ":" in args.evaluator:
        tag = args.evaluator.partition(":")[2].strip()
        if tag and tag not in id_tags:
            id_tags.append(tag)

    col_names = parse_input_columns(args.input_columns_names)

    with timer.time("read data"):
        data, _, uids = read_game_data(
            data_dirs, shard_bags, index_maps,
            id_tags=id_tags, is_response_required=False, **col_names,
        )
    logger.info("scoring rows: %d", data.num_rows)
    emitter.send_event(
        ScoringStartEvent(model_id=model_id, num_requests=data.num_rows)
    )

    if args.log_data_and_model_stats:
        _log_data_and_model_stats(logger, data, model, id_tags)

    if args.missing_entity_policy == "error":
        _check_missing_entities(model, data)

    with timer.time("score"):
        scores = model.score(data) + data.offsets

    import jax

    if args.delete_output_dir_if_exists:
        delete_dirs_if_exist(args.output_dir)

    with timer.time("save scores"):
        if jax.process_index() != 0:
            n = 0  # single writer on shared filesystems
        else:
            file_sizes = None
            if args.num_output_files:
                # exactly N part files (reference --num-files), the first
                # rows % N of them one record larger
                nf = args.num_output_files
                base, rem = divmod(data.num_rows, nf)
                file_sizes = [base + (1 if i < rem else 0) for i in range(nf)]
            n = save_scores(
                args.output_dir,
                (
                    ScoredItem(
                        prediction_score=float(s),
                        label=None if np.isnan(l) else float(l),
                        weight=float(w),
                        uid=uid,
                        id_tags={t: str(data.id_tags[t][i]) for t in id_tags},
                    )
                    for i, (s, l, w, uid) in enumerate(
                        zip(scores, data.labels, data.weights, uids)
                    )
                ),
                model_id=model_id,
                file_sizes=file_sizes,
            )
    logger.info("saved %d scores to %s", n, args.output_dir)

    metric = None
    if args.evaluator:
        have_labels = ~np.isnan(data.labels)
        if have_labels.any():
            # group ids must align with the labeled subset being evaluated
            sub = data.slice_rows(have_labels) if not have_labels.all() else data
            ev = _make_evaluator(args.evaluator, model.task, sub)
            metric = ev.evaluate(
                scores[have_labels],
                data.labels[have_labels],
                data.weights[have_labels],
            )
            logger.info("%s: %.6f", ev.name, metric)
    emitter.send_event(ScoringFinishEvent(
        model_id=model_id,
        num_requests=data.num_rows,
        wall_seconds=time.perf_counter() - t_start,
        metrics={} if metric is None else {"evaluator_metric": metric},
    ))
    for name, seconds in timer.durations.items():
        logger.info("timing %-20s %.3fs", name, seconds)
    return metric


def main(argv: Optional[List[str]] = None) -> int:
    from photon_ml_tpu.parallel.multihost import initialize_from_args

    args = parse_args(argv)
    # cluster join (or single-process no-op) must precede any jax device use
    initialize_from_args(args)
    run(args)
    return 0


if __name__ == "__main__":
    sys.exit(main())
