"""Feature indexing job: build partitioned off-heap index maps from Avro.

Reference parity: FeatureIndexingJob.scala:56 — scan Avro input dirs for
distinct (name, term) features per feature shard, hash-partition, and write
an off-heap store (:92-179; PalDB there, the native PHIX mmap store here)
that training/scoring jobs open without loading into heap.

Usage:
    python -m photon_ml_tpu.cli.build_index \
        --data-dirs data/train --output-dir indexes/ \
        --feature-shard global=features,userFeatures --feature-shard user=userFeatures
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import Dict, List, Optional

from photon_ml_tpu.cli.common import setup_logger
from photon_ml_tpu.indexmap import INTERCEPT_KEY, feature_key
from photon_ml_tpu.indexmap.offheap import build_offheap_index_map
from photon_ml_tpu.io.avro import read_avro_dir
from photon_ml_tpu.utils.timer import Timer


def parse_shard_spec(specs: List[str]) -> Dict[str, List[str]]:
    """'shard=bagA,bagB' flags → {shard: [bags]}."""
    out: Dict[str, List[str]] = {}
    for spec in specs:
        shard, _, bags = spec.partition("=")
        if not bags:
            raise ValueError(f"bad --feature-shard spec: {spec!r}")
        out[shard.strip()] = [b.strip() for b in bags.split(",") if b.strip()]
    return out


def parse_args(argv: Optional[List[str]] = None) -> argparse.Namespace:
    p = argparse.ArgumentParser(
        prog="photon-ml-tpu build-index", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    p.add_argument("--data-dirs", nargs="+", required=True)
    p.add_argument("--date-range", default=None,
                   help="yyyyMMdd-yyyyMMdd; expands each data dir to its "
                        "daily yyyy/MM/dd subdirs (reference --date-range)")
    p.add_argument("--date-days-ago", default=None,
                   help="start-end days ago, e.g. 90-1 (reference "
                        "--date-range-days-ago)")
    p.add_argument("--output-dir", required=True)
    p.add_argument("--feature-shard", action="append", required=True,
                   dest="feature_shards", metavar="SHARD=BAG[,BAG...]")
    p.add_argument("--num-partitions", type=int, default=1)
    p.add_argument("--add-intercept", dest="add_intercept",
                   action="store_true", default=True)
    p.add_argument("--no-intercept", dest="add_intercept", action="store_false")
    p.add_argument("--log-file", default=None)
    return p.parse_args(argv)


def run(args: argparse.Namespace) -> Dict[str, int]:
    logger = setup_logger(args.log_file)
    timer = Timer()
    shards = parse_shard_spec(args.feature_shards)
    from photon_ml_tpu.cli.common import expand_data_dirs

    data_dirs = expand_data_dirs(
        args.data_dirs, args.date_range, args.date_days_ago
    )
    names: Dict[str, set] = {sid: set() for sid in shards}
    with timer.time("scan"):
        for path in data_dirs:
            for record in read_avro_dir(path):
                for sid, bags in shards.items():
                    bucket = names[sid]
                    for bag in bags:
                        for f in record.get(bag) or ():
                            bucket.add(feature_key(f["name"], f["term"]))
    sizes = {}
    for sid, keys in names.items():
        if args.add_intercept:
            keys.add(INTERCEPT_KEY)
        out = os.path.join(args.output_dir, sid)
        with timer.time(f"build [{sid}]"):
            m = build_offheap_index_map(
                keys, out, num_partitions=args.num_partitions
            )
            sizes[sid] = len(m)
            m.close()
        logger.info("shard %s: %d features -> %s", sid, sizes[sid], out)
    for name, seconds in timer.durations.items():
        logger.info("timing %-16s %.3fs", name, seconds)
    return sizes


def main(argv: Optional[List[str]] = None) -> int:
    run(parse_args(argv))
    return 0


if __name__ == "__main__":
    sys.exit(main())
