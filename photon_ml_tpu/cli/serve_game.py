"""GAME online serving driver: export a serving artifact and replay a
request stream against it.

The offline driver (``score_game``) reloads the Avro model and scores a
static dataset in one pass; this driver exercises the *online* path: the
model is packed into a serving artifact (dense FE coefficients +
contiguous per-entity RE tables behind off-heap entity indexes), requests
are drawn row-by-row from a scoring dataset, coalesced by the microbatcher
into fixed-bucket jit'd batches, and scored through the hot-entity cache.
Prints a one-line JSON metrics report (latency percentiles, sustained
request rate, batch fill, cache hit rate, XLA compile count).

Usage:
    # pack a trained model and serve a replayed stream
    python -m photon_ml_tpu.cli.serve_game \
        --model-dir out/best --data-dirs data/test \
        --export-artifact-dir out/artifact --max-requests 10000

    # serve from a previously exported artifact
    python -m photon_ml_tpu.cli.serve_game \
        --artifact-dir out/artifact --data-dirs data/test

    # additionally hot-swap nearline deltas (update_game output) into the
    # live scorer between request chunks — no restart, no re-jit
    python -m photon_ml_tpu.cli.serve_game \
        --artifact-dir out/artifact --data-dirs data/test \
        --watch-deltas out/deltas
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional

from photon_ml_tpu.cli.common import (
    add_telemetry_args,
    finish_telemetry,
    parse_input_columns,
    setup_logger,
    start_telemetry,
)
from photon_ml_tpu.utils.timer import Timer

DEFAULT_BUCKETS = "1,2,4,8,16,32"


def parse_args(argv: Optional[List[str]] = None) -> argparse.Namespace:
    p = argparse.ArgumentParser(
        prog="photon-ml-tpu serve-game", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    src = p.add_mutually_exclusive_group(required=True)
    src.add_argument("--model-dir",
                     help="trained GAME model directory to pack on the fly")
    src.add_argument("--artifact-dir",
                     help="previously exported serving artifact directory")
    p.add_argument("--data-dirs", nargs="+", default=None,
                   help="scoring dataset dirs replayed as the request stream")
    p.add_argument("--export-artifact-dir", default=None,
                   help="write the packed serving artifact here "
                        "(with --model-dir; train → export → serve)")
    p.add_argument("--bucket-sizes", default=DEFAULT_BUCKETS,
                   help="comma-separated microbatch bucket sizes "
                        f"(default {DEFAULT_BUCKETS}); XLA compiles once "
                        "per bucket")
    p.add_argument("--cache-capacity", type=int, default=None,
                   help="hot-entity cache rows per RE coordinate (default: "
                        "full tables device-resident, no cache)")
    p.add_argument("--max-requests", type=int, default=None,
                   help="replay at most this many rows")
    p.add_argument("--watch-deltas", default=None,
                   help="directory of nearline delta artifacts "
                        "(update_game output); polled between request "
                        "chunks and hot-swapped into the live scorer")
    p.add_argument("--watch-chunk", type=int, default=256,
                   help="requests replayed between delta polls "
                        "(with --watch-deltas; default 256)")
    p.add_argument("--max-nnz", type=int, default=None,
                   help="padded nonzeros per shard (default: tight "
                        "power-of-two fit to the request stream)")
    p.add_argument("--metrics-output", default=None,
                   help="also write the metrics snapshot JSON to this file")
    p.add_argument("--model-id", default=None,
                   help="model id stamped on scoring events")
    p.add_argument("--event-listeners", nargs="*", default=[],
                   help="dotted class paths registered on the event emitter")
    p.add_argument("--input-columns-names", default=None,
                   help="JSON map overriding input field names")
    p.add_argument("--log-file", default=None)
    add_telemetry_args(p)
    return p.parse_args(argv)


def _load_or_pack(args, logger, timer):
    from photon_ml_tpu.serving import load_artifact, pack_game_model

    if args.artifact_dir:
        with timer.time("load artifact"):
            artifact = load_artifact(args.artifact_dir)
        logger.info(
            "loaded artifact: %d coordinates, %s entities",
            len(artifact.tables),
            sum(t.n_entities for t in artifact.tables.values()),
        )
        return artifact

    from photon_ml_tpu.io.model_io import (
        load_game_model,
        load_game_model_metadata,
    )

    metadata = load_game_model_metadata(args.model_dir)
    with timer.time("load model"):
        model, index_maps = load_game_model(args.model_dir)
    with timer.time("pack artifact"):
        artifact = pack_game_model(
            model,
            index_maps=index_maps,
            model_name=metadata.get("modelName", "game-model"),
            configurations=metadata.get("configurations") or {},
        )
    return artifact


def run(args: argparse.Namespace) -> Optional[dict]:
    from photon_ml_tpu.event import EventEmitter

    logger = setup_logger(args.log_file)
    timer = Timer()
    emitter = EventEmitter()
    for name in args.event_listeners:
        emitter.register_listener_class(name)
    telemetry = start_telemetry(args, "serve_game", emitter=emitter)
    try:
        return _run_serving(args, logger, timer, emitter)
    finally:
        # listeners must flush/close even when the run fails; telemetry
        # finishes after them so every bridged event is in the ledger
        emitter.clear_listeners()
        finish_telemetry(telemetry, phases=dict(timer.durations))


def _run_serving(args, logger, timer, emitter) -> Optional[dict]:
    bucket_sizes = tuple(
        int(b) for b in str(args.bucket_sizes).split(",") if b.strip()
    )

    artifact = _load_or_pack(args, logger, timer)
    model_id = args.model_id or artifact.model_name

    if args.export_artifact_dir:
        from photon_ml_tpu.serving import save_artifact

        with timer.time("export artifact"):
            save_artifact(artifact, args.export_artifact_dir)
        logger.info("exported serving artifact to %s", args.export_artifact_dir)

    snapshot: Optional[dict] = None
    if args.data_dirs:
        from photon_ml_tpu.io.data_reader import (
            FeatureShardConfiguration,
            read_game_data,
        )
        from photon_ml_tpu.serving import GameScorer, replay_requests
        from photon_ml_tpu.serving.replay import (
            max_nnz_of,
            requests_from_game_data,
        )

        shard_bags = {}
        for sid, s in (
            (artifact.configurations.get("feature_shards") or {}).items()
        ):
            shard_bags[sid] = FeatureShardConfiguration(
                feature_bags=s["feature_bags"],
                add_intercept=bool(s.get("add_intercept", True)),
            )
        for sid in artifact.shard_dims():
            shard_bags.setdefault(
                sid, FeatureShardConfiguration(feature_bags=[sid])
            )
        index_maps = dict(artifact.feature_index) or None
        if index_maps is None:
            logger.warning(
                "artifact carries no feature index maps; indexes will be "
                "rebuilt from the request data and may not match the model"
            )
        col_names = parse_input_columns(args.input_columns_names)
        with timer.time("read data"):
            data, _, uids = read_game_data(
                args.data_dirs,
                {
                    sid: cfg for sid, cfg in shard_bags.items()
                    if sid in artifact.shard_dims()
                },
                index_maps,
                id_tags=artifact.random_effect_types(),
                is_response_required=False,
                **col_names,
            )
        with timer.time("build requests"):
            requests = requests_from_game_data(
                data, artifact, uids=uids, max_requests=args.max_requests
            )
        logger.info("replaying %d requests", len(requests))

        scorer = GameScorer(
            artifact,
            max_nnz=args.max_nnz if args.max_nnz else max_nnz_of(requests),
            cache_capacity=args.cache_capacity,
            growth_headroom=bool(args.watch_deltas),
        )
        from photon_ml_tpu.serving import ServingMetrics

        metrics = ServingMetrics()
        manager = None
        if args.watch_deltas:
            from photon_ml_tpu.incremental import fingerprint_dir
            from photon_ml_tpu.serving import HotSwapManager

            manager = HotSwapManager(
                scorer,
                fingerprint=(
                    fingerprint_dir(args.artifact_dir)
                    if args.artifact_dir else None
                ),
                metrics=metrics,
                emitter=emitter,
                model_id=model_id,
            )
            logger.info(
                "watching %s for delta artifacts (poll every %d requests)",
                args.watch_deltas, args.watch_chunk,
            )
        with timer.time("replay"):
            results, snapshot = replay_requests(
                scorer, requests,
                bucket_sizes=bucket_sizes,
                metrics=metrics,
                emitter=emitter,
                model_id=model_id,
                swap_manager=manager,
                watch_dir=args.watch_deltas,
                poll_every=args.watch_chunk,
            )
        if manager is not None:
            logger.info(
                "served through generation %d (%d swap(s))",
                manager.generation,
                len(snapshot.get("swap_reports", [])),
            )

        snapshot["model_id"] = model_id
        snapshot["bucket_sizes"] = list(bucket_sizes)
        if args.metrics_output:
            with open(args.metrics_output, "w") as f:
                json.dump(snapshot, f, indent=2)
        print(json.dumps(snapshot))

    for name, seconds in timer.durations.items():
        logger.info("timing %-20s %.3fs", name, seconds)
    return snapshot


def main(argv: Optional[List[str]] = None) -> int:
    args = parse_args(argv)
    if not args.data_dirs and not args.export_artifact_dir:
        print(
            "nothing to do: pass --data-dirs to serve and/or "
            "--export-artifact-dir to export",
            file=sys.stderr,
        )
        return 2
    run(args)
    return 0


if __name__ == "__main__":
    sys.exit(main())
