"""GAME online serving driver: export a serving artifact and replay a
request stream against it.

The offline driver (``score_game``) reloads the Avro model and scores a
static dataset in one pass; this driver exercises the *online* path: the
model is packed into a serving artifact (dense FE coefficients +
contiguous per-entity RE tables behind off-heap entity indexes), requests
are drawn row-by-row from a scoring dataset, coalesced by the continuous
microbatcher into fixed-bucket jit'd batches, and scored against sharded
device-resident RE tables (entity→(shard, slot) routing, async admission
of the cold tail, optionally one scorer replica per device). Passing
``--cache-capacity`` instead selects the legacy sealed path: a single
``GameScorer`` behind an LRU hot-entity row cache. Prints a one-line JSON
metrics report (latency percentiles, sustained request rate, batch fill,
device residency, XLA compile count).

Usage:
    # pack a trained model and serve a replayed stream
    python -m photon_ml_tpu.cli.serve_game \
        --model-dir out/best --data-dirs data/test \
        --export-artifact-dir out/artifact --max-requests 10000

    # serve from a previously exported artifact
    python -m photon_ml_tpu.cli.serve_game \
        --artifact-dir out/artifact --data-dirs data/test

    # additionally hot-swap nearline deltas (update_game output) into the
    # live scorer between request chunks — no restart, no re-jit
    python -m photon_ml_tpu.cli.serve_game \
        --artifact-dir out/artifact --data-dirs data/test \
        --watch-deltas out/deltas
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional

from photon_ml_tpu.cli.common import (
    add_telemetry_args,
    finish_telemetry,
    parse_input_columns,
    setup_logger,
    start_telemetry,
)
from photon_ml_tpu.utils.timer import Timer

DEFAULT_BUCKETS = "1,2,4,8,16,32"


def parse_args(argv: Optional[List[str]] = None) -> argparse.Namespace:
    p = argparse.ArgumentParser(
        prog="photon-ml-tpu serve-game", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    src = p.add_mutually_exclusive_group(required=True)
    src.add_argument("--model-dir",
                     help="trained GAME model directory to pack on the fly")
    src.add_argument("--artifact-dir",
                     help="previously exported serving artifact directory")
    p.add_argument("--data-dirs", nargs="+", default=None,
                   help="scoring dataset dirs replayed as the request stream")
    p.add_argument("--export-artifact-dir", default=None,
                   help="write the packed serving artifact here "
                        "(with --model-dir; train → export → serve)")
    p.add_argument("--bucket-sizes", default=DEFAULT_BUCKETS,
                   help="comma-separated microbatch bucket sizes "
                        f"(default {DEFAULT_BUCKETS}); XLA compiles once "
                        "per bucket")
    p.add_argument("--cache-capacity", type=int, default=None,
                   help="legacy mode: hot-entity LRU cache rows per RE "
                        "coordinate behind a single sealed scorer (default: "
                        "sharded device-resident serving)")
    p.add_argument("--scorers", type=int, default=1,
                   help="scorer replicas, one per serving device; replicas "
                        "share one routing index and round-robin drained "
                        "buckets (default 1)")
    p.add_argument("--shards", type=int, default=None,
                   help="device shards per RE table in sharded mode "
                        "(default 4)")
    p.add_argument("--device-budget-rows", type=int, default=None,
                   help="cap device-resident RE rows per coordinate; rows "
                        "beyond it serve FE-only until admitted (default: "
                        "full residency plus hot-swap headroom)")
    p.add_argument("--admit-batch", type=int, default=None,
                   help="rows per async admission step in sharded mode "
                        "(default 64); one fixed-shape scatter per step")
    p.add_argument("--eviction-policy", choices=("oldest", "importance"),
                   default="oldest",
                   help="victim selection when admission needs headroom: "
                        "'oldest' evicts FIFO (default); 'importance' evicts "
                        "the lowest request-frequency x coefficient-norm "
                        "score (docs/SERVING.md)")
    p.add_argument("--batch-deadline-ms", type=float, default=None,
                   help="continuous-batching deadline: a forming bucket is "
                        "scored once its oldest request has waited this "
                        "long (default 2.0)")
    p.add_argument("--max-queue", type=int, default=None,
                   help="backpressure cap on pending requests in continuous "
                        "mode (default: 2x the largest bucket)")
    p.add_argument("--sealed", action="store_true",
                   help="drive the sealed single-thread MicroBatcher loop "
                        "instead of continuous batching (single scorer)")
    p.add_argument("--max-requests", type=int, default=None,
                   help="replay at most this many rows")
    p.add_argument("--watch-deltas", default=None,
                   help="directory of nearline delta artifacts "
                        "(update_game output); polled between request "
                        "chunks and hot-swapped into the live scorer")
    p.add_argument("--watch-chunk", type=int, default=256,
                   help="requests replayed between delta polls "
                        "(with --watch-deltas; default 256)")
    p.add_argument("--max-nnz", type=int, default=None,
                   help="padded nonzeros per shard (default: tight "
                        "power-of-two fit to the request stream)")
    p.add_argument("--metrics-output", default=None,
                   help="also write the metrics snapshot JSON to this file")
    p.add_argument("--model-id", default=None,
                   help="model id stamped on scoring events")
    p.add_argument("--event-listeners", nargs="*", default=[],
                   help="dotted class paths registered on the event emitter")
    p.add_argument("--input-columns-names", default=None,
                   help="JSON map overriding input field names")
    p.add_argument("--log-file", default=None)
    p.add_argument("--auto-tune", action="store_true",
                   help="A/B candidate serving configs on warmup replay "
                        "traffic (judged by the metrics registry), serve "
                        "with the winner, and persist it as the artifact's "
                        "tuned config")
    p.add_argument("--auto-tune-warmup", type=int, default=256,
                   help="requests replayed per auto-tune trial (default 256)")
    p.add_argument("--auto-tune-judge", default="serving.latency_p99_ms",
                   help="registry metric that judges auto-tune trials, "
                        "minimized (default serving.latency_p99_ms)")
    p.add_argument("--introspect-port", type=int, default=None,
                   help="serve /metrics, /healthz, /varz on this local port "
                        "while replaying (0 = ephemeral)")
    p.add_argument("--introspect-port-file", default=None,
                   help="write the bound introspection port to this file "
                        "(useful with --introspect-port 0)")
    p.add_argument("--introspect-hold", type=float, default=0.0,
                   help="after the replay, keep the introspection endpoints "
                        "up for this many seconds (or until "
                        "/quitquitquit is hit)")
    p.add_argument("--request-sample-rate", type=int, default=0,
                   help="request-plane lifecycle sampling: trace ~1/N "
                        "requests' per-stage timings (0 = off, the default; "
                        "1 = every request). Sampled records land in the "
                        "--telemetry-out ledger (analyze_run --requests) "
                        "and the live /requests introspection route")
    p.add_argument("--request-sample-seed", type=int, default=0,
                   help="seed for the request-plane sampler hash "
                        "(default 0); the same (id, seed) always samples "
                        "identically")
    p.add_argument("--slo-latency-ms", type=float, default=None,
                   help="enable SLO tracking with this per-request latency "
                        "threshold in ms: rolling availability + latency "
                        "objectives with error-budget burn accounting; "
                        "budget exhaustion flips /healthz degraded and the "
                        "serving.slo.* gauges")
    p.add_argument("--slo-latency-objective", type=float, default=0.99,
                   help="fraction of requests that must beat the latency "
                        "threshold (default 0.99)")
    p.add_argument("--slo-availability-objective", type=float, default=0.999,
                   help="fraction of requests that must not error "
                        "(default 0.999)")
    p.add_argument("--overload-control", action="store_true",
                   help="closed-loop overload control (needs "
                        "--slo-latency-ms): when the error-budget burn "
                        "rate crosses --overload-burn-high, batch "
                        "deadlines shrink by --overload-shrink and "
                        "requests scoreable FE-only (all RE entities "
                        "absent/non-resident) are answered on the host "
                        "without queueing; recovers below "
                        "--overload-burn-low (serving.overload.* gauges, "
                        "/varz overload doc)")
    p.add_argument("--overload-burn-high", type=float, default=1.0,
                   help="burn rate at/above which overload actuation "
                        "engages (default 1.0 = budget burning faster "
                        "than it accrues)")
    p.add_argument("--overload-burn-low", type=float, default=0.5,
                   help="burn rate at/below which overload actuation "
                        "releases (default 0.5; the gap to "
                        "--overload-burn-high is the hysteresis band)")
    p.add_argument("--overload-shrink", type=float, default=0.5,
                   help="batch-deadline multiplier while overloaded, in "
                        "(0, 1] (default 0.5)")
    p.add_argument("--tenants", default=None,
                   help="comma-separated tenant names: the replayed stream "
                        "is tagged round-robin across them and, with "
                        "--slo-latency-ms, each tenant gets an INDEPENDENT "
                        "SLO error budget (tenant-labeled serving.slo.* "
                        "series in /metrics, per-tenant burn in /healthz "
                        "and /varz)")
    p.add_argument("--variants", default=None,
                   help="comma-separated candidate variant names: serve "
                        "through the full tenancy plane (quota -> seeded "
                        "router -> one per-variant batcher over the shared "
                        "sharded scorer) instead of the plain replay path; "
                        "each variant starts undiverged from the base "
                        "(sharded mode only)")
    p.add_argument("--variant-ramp", type=float, default=None,
                   help="percent of traffic routed to EACH --variants "
                        "entry (default: an even split with the base, "
                        "100/(n+1)); ramps must sum to <= 100")
    p.add_argument("--variant-seed", type=int, default=0,
                   help="router hash seed: the same (tenant, request id, "
                        "seed) always routes identically (default 0)")
    p.add_argument("--tenant-rate", type=float, default=None,
                   help="with --tenants and --variants: per-tenant token "
                        "refill rate (requests/s) for quota admission; "
                        "over-budget tenants shed alone")
    p.add_argument("--tenant-burst", type=float, default=None,
                   help="per-tenant token bucket burst capacity (with "
                        "--tenant-rate; default: the rate)")
    add_telemetry_args(p)
    return p.parse_args(argv)


def _load_or_pack(args, logger, timer):
    from photon_ml_tpu.serving import load_artifact, pack_game_model

    if args.artifact_dir:
        with timer.time("load artifact"):
            artifact = load_artifact(args.artifact_dir)
        logger.info(
            "loaded artifact: %d coordinates, %s entities",
            len(artifact.tables),
            sum(t.n_entities for t in artifact.tables.values()),
        )
        return artifact

    from photon_ml_tpu.io.model_io import (
        load_game_model,
        load_game_model_metadata,
    )

    metadata = load_game_model_metadata(args.model_dir)
    with timer.time("load model"):
        model, index_maps = load_game_model(args.model_dir)
    with timer.time("pack artifact"):
        artifact = pack_game_model(
            model,
            index_maps=index_maps,
            model_name=metadata.get("modelName", "game-model"),
            configurations=metadata.get("configurations") or {},
        )
    return artifact


def _effective_config(args, artifact, logger) -> dict:
    """Resolve the serving config the replay will actually use.

    Explicit CLI flags always win; flags left at their defaults fall back
    to the artifact's ``tuned_config`` (a previous --auto-tune winner) and
    finally to the built-in defaults — the "boots tuned" path. Returns the
    /varz-ready dict of active values."""
    tuned = dict(artifact.tuned_config or {})
    bucket_sizes = tuple(
        int(b) for b in str(args.bucket_sizes).split(",") if b.strip()
    )
    cache_capacity = args.cache_capacity
    max_nnz = args.max_nnz
    shards = args.shards
    admit_batch = args.admit_batch
    deadline_ms = args.batch_deadline_ms
    applied = {}
    if tuned:
        if args.bucket_sizes == DEFAULT_BUCKETS and "serving.bucket_sizes" in tuned:
            bucket_sizes = tuple(int(b) for b in tuned["serving.bucket_sizes"])
            applied["serving.bucket_sizes"] = list(bucket_sizes)
        if cache_capacity is None and tuned.get("serving.cache_capacity"):
            # a tuned cache capacity only matters on the legacy cached
            # path; it must not silently flip the serving mode, so it is
            # recorded but applied only when --cache-capacity selected it
            pass
        if max_nnz is None and tuned.get("serving.max_nnz"):
            max_nnz = int(tuned["serving.max_nnz"])
            applied["serving.max_nnz"] = max_nnz
        if shards is None and tuned.get("serving.shards"):
            shards = int(tuned["serving.shards"])
            applied["serving.shards"] = shards
        if admit_batch is None and tuned.get("serving.admit_batch"):
            admit_batch = int(tuned["serving.admit_batch"])
            applied["serving.admit_batch"] = admit_batch
        if deadline_ms is None and tuned.get("serving.batch_deadline_ms"):
            deadline_ms = float(tuned["serving.batch_deadline_ms"])
            applied["serving.batch_deadline_ms"] = deadline_ms
        if applied:
            logger.info("booting with tuned config: %s", applied)
    mode = "cached" if cache_capacity is not None else "sharded"
    return {
        "mode": mode,
        "bucket_sizes": list(bucket_sizes),
        "cache_capacity": cache_capacity,
        "max_nnz": max_nnz,
        "scorers": max(1, int(args.scorers)),
        "shards": int(shards) if shards else 4,
        "device_budget_rows": args.device_budget_rows,
        "admit_batch": int(admit_batch) if admit_batch else 64,
        "eviction_policy": args.eviction_policy,
        "batch_deadline_ms": (
            float(deadline_ms) if deadline_ms is not None else 2.0
        ),
        "max_queue": args.max_queue,
        "sealed": bool(args.sealed or mode == "cached"),
        "tuned": bool(applied),
        "tuned_config": tuned or None,
        "tuned_applied": applied or None,
    }


def _auto_tune_serving(args, artifact, requests, active, logger):
    """Warmup-replay A/B over the serve-side knob space.

    A baseline warmup replay produces the evidence (its metrics snapshot,
    replayed through ``analyze_records`` into a RunReport); the tuner
    proposes candidates; each candidate replays the same warmup slice
    against a fresh scorer and a FRESH MetricsRegistry, judged by
    ``--auto-tune-judge``. Returns (winner_knob_values, ab_result_dict)."""
    import time as _time

    from photon_ml_tpu.serving import GameScorer, ServingMetrics, replay_requests
    from photon_ml_tpu.serving.replay import max_nnz_of
    from photon_ml_tpu.telemetry.analyze import analyze_records
    from photon_ml_tpu.tuning import ab_candidates, get_knob, propose, run_ab_trials

    warmup = requests[: max(1, min(args.auto_tune_warmup, len(requests)))]
    default_nnz = max_nnz_of(requests)

    def _replay_with(config, registry):
        buckets = get_knob("serving.bucket_sizes").parse(
            config.get("serving.bucket_sizes") or active["bucket_sizes"]
        )
        nnz = int(config.get("serving.max_nnz") or 0) or (
            active["max_nnz"] or default_nnz
        )
        cache = config.get("serving.cache_capacity") or active["cache_capacity"]
        scorer = GameScorer(
            artifact,
            max_nnz=nnz,
            cache_capacity=int(cache) if cache else None,
        )
        metrics = ServingMetrics()
        _, snap = replay_requests(
            scorer, warmup, bucket_sizes=buckets, metrics=metrics
        )
        registry.record_serving_snapshot(snap)

    # evidence pass: the control config IS the baseline trial; wrap its
    # snapshot in a minimal ledger so the tuner sees a real RunReport
    from photon_ml_tpu.telemetry.metrics import MetricsRegistry

    baseline_registry = MetricsRegistry()
    t0 = _time.time()
    _replay_with({}, baseline_registry)
    t1 = _time.time()
    report = analyze_records(
        [
            {"type": "meta", "ts": t0, "phase": "start", "label": "serve-warmup"},
            {"type": "metrics", "ts": t1, "snapshot": baseline_registry.snapshot()},
            {"type": "meta", "ts": t1, "phase": "finish"},
        ],
        source_path=None,
    )
    proposal = propose(report)
    candidates = ab_candidates(proposal, "serve")
    logger.info(
        "auto-tune: %d warmup requests, %d candidate config(s)",
        len(warmup), len(candidates),
    )
    result = run_ab_trials(
        candidates,
        _replay_with,
        judge_metric=args.auto_tune_judge,
        minimize=True,
        logger=logger,
    )
    winner = result.winner
    logger.info(
        "auto-tune winner: trial %d %s=%s config=%s",
        winner.index,
        args.auto_tune_judge,
        f"{winner.score:.6g}" if winner.score is not None else "n/a",
        winner.config,
    )
    return dict(winner.config), result.to_dict()


def _serve_tenancy(
    args, logger, active, tenants, scorers, admission, bucket_sizes,
    requests, metrics, plane,
) -> dict:
    """Replay through the full tenancy plane: per-tenant quota admission,
    seeded variant routing, and one sealed batcher per variant over the
    shared sharded scorer. Every ``--variants`` entry starts undiverged
    (bitwise the base) — this is the rollout topology; deltas diverge
    variants later via the registry. Returns the metrics snapshot with a
    ``tenancy`` status block (variants, router ramps, quota, tenant SLOs)."""
    import time as _time

    from photon_ml_tpu.serving import (
        TenancyPlane,
        TenantBudget,
        TenantQuota,
        VariantRegistry,
        VariantRouter,
    )
    from photon_ml_tpu.telemetry.metrics import get_registry

    registry = VariantRegistry(scorers[0])
    router = VariantRouter(seed=active["variant_seed"])
    names = active["variants"]
    ramp = (
        active["variant_ramp"]
        if active["variant_ramp"] is not None
        else 100.0 / (len(names) + 1)
    )
    for name in names:
        registry.add_variant(name)
        router.set_ramp(name, ramp)
    quota = None
    if tenants and args.tenant_rate is not None:
        burst = (
            args.tenant_burst
            if args.tenant_burst is not None
            else args.tenant_rate
        )
        quota = TenantQuota({
            t: TenantBudget(rate=args.tenant_rate, burst=burst)
            for t in tenants
        })
    tenancy = TenancyPlane(
        registry,
        router=router,
        plane=plane,
        quota=quota,
        metrics=metrics,
        bucket_sizes=tuple(bucket_sizes),
        max_wait_s=active["batch_deadline_ms"] / 1e3,
        metrics_registry=get_registry(),
    )
    logger.info(
        "tenancy plane: base + %d variant(s) at %.1f%% each%s",
        len(names), ramp, ", per-tenant quota" if quota is not None else "",
    )
    started_admission = False
    if admission is not None and admission._thread is None:
        admission.start()
        started_admission = True
    try:
        t0 = _time.perf_counter()
        results = tenancy.replay(requests, poll_every=64)
        wall = _time.perf_counter() - t0
    finally:
        if started_admission:
            admission.stop()
    lead = scorers[0]
    residency = None
    if hasattr(lead, "residency_stats"):
        residency = lead.residency_stats() or None
    snapshot = metrics.snapshot(
        cache_stats=lead.cache_stats() or None,
        compile_count=lead.compile_count,
        residency=residency,
        admission=admission.stats() if admission is not None else None,
    )
    snapshot["replay_wall_seconds"] = round(wall, 6)
    if wall > 0:
        snapshot["replay_requests_per_s"] = round(len(requests) / wall, 3)
    snapshot["num_results"] = len(results)
    if plane is not None:
        report = plane.live_report()
        slo_doc = report.pop("slo", None)
        snapshot["request_plane"] = report
        if slo_doc is not None:
            snapshot["slo"] = slo_doc
    snapshot["tenancy"] = tenancy.status()
    return snapshot


def run(args: argparse.Namespace) -> Optional[dict]:
    from photon_ml_tpu.event import EventEmitter

    logger = setup_logger(args.log_file)
    timer = Timer()
    emitter = EventEmitter()
    for name in args.event_listeners:
        emitter.register_listener_class(name)
    telemetry = start_telemetry(args, "serve_game", emitter=emitter)
    try:
        return _run_serving(args, logger, timer, emitter, telemetry)
    finally:
        # listeners must flush/close even when the run fails; telemetry
        # finishes after them so every bridged event is in the ledger
        emitter.clear_listeners()
        finish_telemetry(telemetry, phases=dict(timer.durations))


def _run_serving(args, logger, timer, emitter, telemetry=None) -> Optional[dict]:
    artifact = _load_or_pack(args, logger, timer)
    model_id = args.model_id or artifact.model_name
    active = _effective_config(args, artifact, logger)
    active["model_id"] = model_id
    bucket_sizes = tuple(active["bucket_sizes"])

    # request plane + SLO tracker (both off unless asked for)
    slo = None
    plane = None
    if args.slo_latency_ms is not None:
        from photon_ml_tpu.serving import SLOTracker
        from photon_ml_tpu.telemetry.metrics import get_registry

        slo = SLOTracker(
            latency_threshold_s=args.slo_latency_ms / 1e3,
            latency_objective=args.slo_latency_objective,
            availability_objective=args.slo_availability_objective,
            registry=get_registry(),
        )
    overload = None
    if args.overload_control:
        if slo is None:
            raise SystemExit(
                "--overload-control needs --slo-latency-ms: the controller "
                "actuates on the SLO burn rate"
            )
        from photon_ml_tpu.serving import OverloadController
        from photon_ml_tpu.telemetry.metrics import get_registry

        overload = OverloadController(
            slo,
            shrink_factor=args.overload_shrink,
            burn_high=args.overload_burn_high,
            burn_low=args.overload_burn_low,
            registry=get_registry(),
        )
        logger.info(
            "overload control on: burn >= %.2f shrinks deadlines x%.2f and "
            "sheds FE-only-able load; recovers at burn <= %.2f",
            args.overload_burn_high, args.overload_shrink,
            args.overload_burn_low,
        )
    tenants = [
        t.strip() for t in (args.tenants or "").split(",") if t.strip()
    ]
    tenant_slos = None
    if tenants:
        if args.slo_latency_ms is not None:
            from photon_ml_tpu.serving import build_tenant_slos
            from photon_ml_tpu.telemetry.metrics import get_registry

            tenant_slos = build_tenant_slos(
                tenants,
                registry=get_registry(),
                latency_threshold_s=args.slo_latency_ms / 1e3,
                latency_objective=args.slo_latency_objective,
                availability_objective=args.slo_availability_objective,
            )
            logger.info(
                "per-tenant SLO budgets for %s", ", ".join(tenants)
            )
        else:
            logger.warning(
                "--tenants without --slo-latency-ms: requests are tagged "
                "but no per-tenant SLO budgets are tracked"
            )
    if (
        args.request_sample_rate > 0
        or slo is not None
        or tenant_slos is not None
    ):
        from photon_ml_tpu.serving import RequestPlane

        plane = RequestPlane(
            sample_rate=max(0, args.request_sample_rate),
            seed=args.request_sample_seed,
            ledger=telemetry.ledger if telemetry is not None else None,
            slo=slo,
            tenant_slos=tenant_slos,
        )
        logger.info(
            "request plane: sampling ~1/%d requests (seed %d)%s",
            max(1, args.request_sample_rate), args.request_sample_seed,
            ", SLO tracking on" if slo is not None else "",
        )
    active["request_sample_rate"] = args.request_sample_rate
    active["slo_latency_ms"] = args.slo_latency_ms
    active["overload_control"] = overload is not None
    active["tenants"] = tenants or None

    variants = [
        v.strip() for v in (args.variants or "").split(",") if v.strip()
    ]
    if variants:
        if active["mode"] == "cached":
            raise SystemExit(
                "--variants needs variant views over the sharded scorer; "
                "drop --cache-capacity"
            )
        if args.watch_deltas or args.auto_tune:
            raise SystemExit(
                "--variants replaces the plain replay path; it is not "
                "combinable with --watch-deltas or --auto-tune (apply "
                "per-variant deltas through the variant registry instead)"
            )
    active["variants"] = variants or None
    active["variant_ramp"] = args.variant_ramp
    active["variant_seed"] = args.variant_seed

    if args.export_artifact_dir:
        from photon_ml_tpu.serving import save_artifact

        with timer.time("export artifact"):
            save_artifact(artifact, args.export_artifact_dir)
        logger.info("exported serving artifact to %s", args.export_artifact_dir)

    state = {"manager": None, "admission": None, "phase": "starting"}
    introspect = None
    if args.introspect_port is not None:
        from photon_ml_tpu.serving import IntrospectionServer

        def _health():
            manager = state["manager"]
            doc = {
                "healthy": True,
                "phase": state["phase"],
                "model_id": model_id,
                "watching_deltas": bool(args.watch_deltas),
            }
            if manager is not None:
                doc["swap_generation"] = manager.generation
            # degraded modes: a dead supervised daemon (admission past its
            # restart cap) flips /healthz to 503 with the reason, while
            # serving itself keeps answering (cold entities score FE-only)
            degraded = []
            admission = state["admission"]
            if admission is not None:
                adm = admission.health()
                doc["admission"] = adm
                if not adm.get("healthy", True):
                    degraded.append(adm.get("degraded", "admission dead"))
            # an exhausted error budget degrades health (still serving,
            # but the SLO says users are feeling it)
            if slo is not None:
                sh = slo.health()
                doc["slo"] = sh
                if not sh.get("healthy", True):
                    degraded.append(sh.get("degraded", "slo budget exhausted"))
            # per-tenant burn: ONE tenant's exhausted budget degrades
            # health with the tenant named, while the others stay readable
            if tenant_slos:
                tdoc = {}
                for t, tracker in sorted(tenant_slos.items()):
                    th = tracker.health()
                    tdoc[t] = th
                    if not th.get("healthy", True):
                        degraded.append(
                            f"tenant {t}: "
                            + th.get("degraded", "slo budget exhausted")
                        )
                doc["tenant_slo"] = tdoc
            if degraded:
                doc["healthy"] = False
                doc["degraded"] = "; ".join(degraded)
            return doc

        def _varz():
            doc = dict(active)
            if slo is not None:
                doc["slo"] = slo.status()
            if overload is not None:
                doc["overload"] = overload.status()
            if tenant_slos:
                doc["tenant_slo"] = {
                    t: tracker.status()
                    for t, tracker in sorted(tenant_slos.items())
                }
            return doc

        extra = {}
        if plane is not None:
            extra["/requests"] = plane.live_report
        introspect = IntrospectionServer(
            varz=_varz,
            health=_health,
            port=args.introspect_port,
            extra_json=extra or None,
        ).start()
        logger.info("introspection endpoints on 127.0.0.1:%d", introspect.port)
        if args.introspect_port_file:
            with open(args.introspect_port_file, "w") as f:
                f.write(str(introspect.port))
    try:
        snapshot = _serve_stream(
            args, logger, timer, emitter, artifact, model_id, active,
            bucket_sizes, state, plane, overload,
        )
        state["phase"] = "drained"
        if introspect is not None and args.introspect_hold > 0:
            logger.info(
                "holding introspection endpoints for %.1fs (POST "
                "/quitquitquit to release)", args.introspect_hold,
            )
            introspect.wait_quit(args.introspect_hold)
        return snapshot
    finally:
        if introspect is not None:
            introspect.stop()


def _serve_stream(
    args, logger, timer, emitter, artifact, model_id, active, bucket_sizes,
    state, plane=None, overload=None,
) -> Optional[dict]:
    snapshot: Optional[dict] = None
    if args.data_dirs:
        from photon_ml_tpu.io.data_reader import (
            FeatureShardConfiguration,
            read_game_data,
        )
        from photon_ml_tpu.serving import GameScorer, replay_requests
        from photon_ml_tpu.serving.replay import (
            max_nnz_of,
            requests_from_game_data,
        )

        shard_bags = {}
        for sid, s in (
            (artifact.configurations.get("feature_shards") or {}).items()
        ):
            shard_bags[sid] = FeatureShardConfiguration(
                feature_bags=s["feature_bags"],
                add_intercept=bool(s.get("add_intercept", True)),
            )
        for sid in artifact.shard_dims():
            shard_bags.setdefault(
                sid, FeatureShardConfiguration(feature_bags=[sid])
            )
        index_maps = dict(artifact.feature_index) or None
        if index_maps is None:
            logger.warning(
                "artifact carries no feature index maps; indexes will be "
                "rebuilt from the request data and may not match the model"
            )
        col_names = parse_input_columns(args.input_columns_names)
        with timer.time("read data"):
            data, _, uids = read_game_data(
                args.data_dirs,
                {
                    sid: cfg for sid, cfg in shard_bags.items()
                    if sid in artifact.shard_dims()
                },
                index_maps,
                id_tags=artifact.random_effect_types(),
                is_response_required=False,
                **col_names,
            )
        with timer.time("build requests"):
            requests = requests_from_game_data(
                data, artifact, uids=uids, max_requests=args.max_requests
            )
        tenants = active.get("tenants") or []
        if tenants:
            from photon_ml_tpu.serving.tenancy import tag_request

            requests = [
                tag_request(r, tenants[i % len(tenants)])
                for i, r in enumerate(requests)
            ]
            logger.info(
                "tagged requests round-robin across %d tenant(s): %s",
                len(tenants), ", ".join(tenants),
            )
        logger.info("replaying %d requests", len(requests))

        ab_result = None
        if args.auto_tune:
            state["phase"] = "auto-tune"
            with timer.time("auto-tune"):
                winner, ab_result = _auto_tune_serving(
                    args, artifact, requests, active, logger
                )
            tuned_now = {k: v for k, v in winner.items() if v}
            if "serving.bucket_sizes" in winner:
                bucket_sizes = tuple(int(b) for b in winner["serving.bucket_sizes"])
                active["bucket_sizes"] = list(bucket_sizes)
            if active["mode"] == "cached" and winner.get("serving.cache_capacity"):
                active["cache_capacity"] = int(winner["serving.cache_capacity"])
            if winner.get("serving.max_nnz"):
                active["max_nnz"] = int(winner["serving.max_nnz"])
            if winner.get("serving.shards"):
                active["shards"] = int(winner["serving.shards"])
            if winner.get("serving.admit_batch"):
                active["admit_batch"] = int(winner["serving.admit_batch"])
            if winner.get("serving.batch_deadline_ms"):
                active["batch_deadline_ms"] = float(
                    winner["serving.batch_deadline_ms"]
                )
            active["tuned"] = True
            active["tuned_config"] = {
                k: (list(v) if isinstance(v, tuple) else v)
                for k, v in tuned_now.items()
            }
            from photon_ml_tpu.serving import save_tuned_config

            provenance = {
                "source": "serve_game --auto-tune",
                "judge_metric": args.auto_tune_judge,
                "warmup_requests": int(args.auto_tune_warmup),
            }
            for target in (args.artifact_dir, args.export_artifact_dir):
                if target:
                    path = save_tuned_config(
                        target, active["tuned_config"], provenance=provenance
                    )
                    logger.info("persisted tuned config to %s", path)

        state["phase"] = "replaying"
        nnz = active["max_nnz"] if active["max_nnz"] else max_nnz_of(requests)
        admission = None
        if active["mode"] == "cached":
            scorers = [GameScorer(
                artifact,
                max_nnz=nnz,
                cache_capacity=active["cache_capacity"],
                growth_headroom=bool(args.watch_deltas),
            )]
        else:
            from photon_ml_tpu.serving import (
                AdmissionController,
                ShardedGameScorer,
            )

            routing = None
            scorers = []
            for _ in range(active["scorers"]):
                s = ShardedGameScorer(
                    artifact,
                    max_nnz=nnz,
                    num_shards=active["shards"],
                    device_budget_rows=active["device_budget_rows"],
                    eviction_policy=active["eviction_policy"],
                    routing=routing,
                )
                routing = s.routing
                scorers.append(s)
            admission = AdmissionController(
                scorers, admit_batch=active["admit_batch"]
            )
            for s in scorers:
                s.attach_admission(admission)
            # compile the fixed-shape admission scatter before traffic
            admission.warmup()
            state["admission"] = admission
        continuous = not active["sealed"]
        if active["sealed"] and len(scorers) > 1:
            logger.warning(
                "--sealed drives a single scorer; ignoring %d extra "
                "replica(s)", len(scorers) - 1,
            )
            scorers = scorers[:1]
        from photon_ml_tpu.serving import ServingMetrics

        metrics = ServingMetrics()
        manager = None
        if active.get("variants"):
            if len(scorers) > 1:
                logger.warning(
                    "--variants serves through ONE shared scorer; ignoring "
                    "%d extra replica(s)", len(scorers) - 1,
                )
                scorers = scorers[:1]
            active["mode"] = "sharded-tenancy"
            if overload is not None:
                logger.warning(
                    "--overload-control drives the plain replay batcher; "
                    "it is ignored on the tenancy path"
                )
            with timer.time("replay"):
                snapshot = _serve_tenancy(
                    args, logger, active, tenants, scorers, admission,
                    bucket_sizes, requests, metrics, plane,
                )
        else:
            if args.watch_deltas:
                from photon_ml_tpu.incremental import fingerprint_dir
                from photon_ml_tpu.serving import (
                    CoordinatedHotSwap,
                    HotSwapManager,
                )

                fingerprint = (
                    fingerprint_dir(args.artifact_dir)
                    if args.artifact_dir else None
                )
                managers = [
                    HotSwapManager(
                        s,
                        fingerprint=fingerprint,
                        # only the lead manager records swap metrics/events;
                        # replica swaps are the same delta fanned out
                        metrics=metrics if i == 0 else None,
                        emitter=emitter if i == 0 else None,
                        model_id=model_id,
                    )
                    for i, s in enumerate(scorers)
                ]
                manager = (
                    managers[0] if len(managers) == 1
                    else CoordinatedHotSwap(managers)
                )
                state["manager"] = manager
                logger.info(
                    "watching %s for delta artifacts (poll every %d "
                    "requests)", args.watch_deltas, args.watch_chunk,
                )
            with timer.time("replay"):
                results, snapshot = replay_requests(
                    scorers if continuous else scorers[0], requests,
                    bucket_sizes=bucket_sizes,
                    metrics=metrics,
                    emitter=emitter,
                    model_id=model_id,
                    swap_manager=manager,
                    watch_dir=args.watch_deltas,
                    poll_every=args.watch_chunk,
                    continuous=continuous,
                    max_wait_s=active["batch_deadline_ms"] / 1e3,
                    max_queue=active["max_queue"],
                    admission=admission,
                    plane=plane,
                    overload=overload,
                )
            if manager is not None:
                logger.info(
                    "served through generation %d (%d swap(s))",
                    manager.generation,
                    len(snapshot.get("swap_reports", [])),
                )

        snapshot["model_id"] = model_id
        snapshot["bucket_sizes"] = list(bucket_sizes)
        snapshot["serving_mode"] = active["mode"]
        snapshot["num_scorers"] = len(scorers)
        if ab_result is not None:
            snapshot["auto_tune"] = ab_result
        # fold the final serving snapshot into the process registry so the
        # /metrics endpoint reflects the replay even without --telemetry-out
        from photon_ml_tpu.telemetry.metrics import get_registry

        get_registry().record_serving_snapshot(snapshot)
        if args.metrics_output:
            with open(args.metrics_output, "w") as f:
                json.dump(snapshot, f, indent=2)
        print(json.dumps(snapshot))

    for name, seconds in timer.durations.items():
        logger.info("timing %-20s %.3fs", name, seconds)
    return snapshot


def main(argv: Optional[List[str]] = None) -> int:
    args = parse_args(argv)
    if not args.data_dirs and not args.export_artifact_dir:
        print(
            "nothing to do: pass --data-dirs to serve and/or "
            "--export-artifact-dir to export",
            file=sys.stderr,
        )
        return 2
    run(args)
    return 0


if __name__ == "__main__":
    sys.exit(main())
