"""Single-GLM training driver (the reference's "legacy" pipeline).

Reference parity: Driver.scala:71 — staged run() (:158-218):
preprocess (read + validate + stats/normalization) → train (λ sweep with
warm start, ModelTraining.scala:106) → validate (metric per λ,
ModelSelection.scala:29 best-model selection) → output (model text files +
best model Avro). Stage gating via DriverStage is replaced by a linear
pipeline; diagnostics live in photon_ml_tpu.diagnostics.

Usage:
    python -m photon_ml_tpu.cli.train_glm \
        --training-data-dirs data/train --validation-data-dirs data/test \
        --task LOGISTIC_REGRESSION --regularization-weights 0.1 1 10 100 \
        --output-dir out/
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List, Optional

import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.cli.common import (
    add_telemetry_args,
    finish_telemetry,
    load_index_maps,
    parse_optimizer_config,
    setup_logger,
    start_telemetry,
)
from photon_ml_tpu.data.validators import (
    DataValidationType,
    validate_labeled_data,
)
from photon_ml_tpu.estimators.model_training import train_glm
from photon_ml_tpu.evaluation.evaluators import default_evaluator
from photon_ml_tpu.indexmap import INTERCEPT_KEY, NAME_TERM_DELIMITER
from photon_ml_tpu.io import schemas
from photon_ml_tpu.io.avro import write_avro_file
from photon_ml_tpu.io.data_reader import (
    FeatureShardConfiguration,
    read_game_data,
)
from photon_ml_tpu.normalization import build_normalization_context
from photon_ml_tpu.ops.data import LabeledData
from photon_ml_tpu.stat.summary import summarize
from photon_ml_tpu.types import NormalizationType, TaskType
from photon_ml_tpu.utils.timer import Timer


def parse_args(argv: Optional[List[str]] = None) -> argparse.Namespace:
    p = argparse.ArgumentParser(
        prog="photon-ml-tpu train-glm", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    from photon_ml_tpu.parallel.multihost import add_distributed_args

    add_distributed_args(p)
    p.add_argument("--training-data-dirs", nargs="+", required=True)
    p.add_argument("--validation-data-dirs", nargs="*", default=[])
    p.add_argument("--task", required=True, choices=[t.name for t in TaskType])
    p.add_argument("--output-dir", required=True)
    p.add_argument("--input-format", default="AVRO",
                   choices=["AVRO", "LIBSVM"],
                   help="TRAINING_EXAMPLE avro or LibSVM text (reference "
                        "InputFormatFactory / LibSVMInputDataFormat)")
    p.add_argument("--feature-bags", nargs="+", default=["features"])
    p.add_argument("--add-intercept", dest="add_intercept",
                   action="store_true", default=True)
    p.add_argument("--no-intercept", dest="add_intercept", action="store_false")
    p.add_argument("--regularization-weights", nargs="+", type=float,
                   default=[0.0])
    p.add_argument("--optimizer", default="LBFGS", choices=["LBFGS", "TRON"])
    p.add_argument("--regularization", default="L2",
                   choices=["NONE", "L1", "L2", "ELASTIC_NET"])
    p.add_argument("--elastic-net-alpha", type=float, default=None)
    p.add_argument("--max-iterations", type=int, default=None)
    p.add_argument("--tolerance", type=float, default=None)
    p.add_argument("--normalization-type", default="NONE",
                   choices=[n.name for n in NormalizationType])
    p.add_argument("--coefficient-box-constraints", default=None,
                   help='JSON: global {"lower": -1.0, "upper": 1.0}, or the '
                        "reference's per-feature array "
                        '[{"name": "age", "term": "", "lowerBound": 0.0, '
                        '"upperBound": 1.0}, ...] with "*" wildcards '
                        "(GLMSuite constraint-map rules)")
    p.add_argument("--offheap-indexmap-dir", default=None,
                   help="read features through prebuilt off-heap index "
                        "stores (reference --offheap-indexmap-dir; AVRO "
                        "input only)")
    p.add_argument("--summarization-output-dir", default=None,
                   help="write per-feature summary stats as "
                        "FeatureSummarizationResultAvro (reference "
                        "--summarization-output-dir)")
    p.add_argument("--selected-features-file", default=None,
                   help="Avro file of name/term records; training uses "
                        "ONLY these features (reference "
                        "--selected-features-file)")
    p.add_argument("--data-validation", default="VALIDATE_FULL",
                   choices=[v.name for v in DataValidationType])
    p.add_argument("--compute-variances", action="store_true")
    p.add_argument("--delete-output-dirs-if-exist", action="store_true",
                   help="remove existing output (and summarization) dirs "
                        "before writing (reference DELETE_OUTPUT_DIRS_IF_EXIST)")
    p.add_argument("--use-warm-start", dest="use_warm_start",
                   action="store_true", default=True,
                   help="warm-start each lambda of the sweep from the "
                        "previous optimum (default on, reference "
                        "USE_WARM_START)")
    p.add_argument("--no-warm-start", dest="use_warm_start",
                   action="store_false")
    p.add_argument("--validate-per-iteration", action="store_true",
                   help="track per-iteration models (reference "
                        "ModelTracker/OPTIMIZATION_STATE_TRACKER) and log "
                        "the validation metric of every iteration's model "
                        "(reference VALIDATE_PER_ITERATION); requires "
                        "--validation-data-dirs")
    p.add_argument("--event-listeners", nargs="*", default=[],
                   metavar="module.Class",
                   help="EventListener classes to register (reference "
                        "--event-listeners, Params.scala:186)")
    p.add_argument("--diagnostic-mode", default="NONE",
                   choices=["NONE", "TRAIN", "VALIDATE", "ALL"],
                   help="writes model-diagnostic.html (reference Driver "
                        "diagnose stage, DiagnosticMode.scala): TRAIN runs "
                        "the training-data diagnostics (learning curves + "
                        "bootstrap), VALIDATE the held-out diagnostics "
                        "(Hosmer-Lemeshow, error independence, feature "
                        "importance), ALL both")
    p.add_argument("--log-file", default=None)
    add_telemetry_args(p)
    return p.parse_args(argv)


def _filter_selected_features(data, imap, path: str, logger):
    """Keep only features named in the Avro name/term file (+ intercept) —
    reference GLMSuite.getSelectedFeatureSetFromFile:139-146: entries of
    the COO shard whose feature key is not selected are dropped before
    training (the model dimension is unchanged; unselected coefficients
    simply never receive data)."""
    import dataclasses as _dc

    from photon_ml_tpu.indexmap import feature_key
    from photon_ml_tpu.io.avro import read_avro_dir

    selected = set()
    for rec in read_avro_dir(path):
        selected.add(feature_key(str(rec["name"]), str(rec.get("term") or "")))
    if not selected:
        raise ValueError(
            f"--selected-features-file {path!r} yielded no name/term "
            "records; refusing to silently train on ALL features"
        )
    # forward-lookup the (small) selected set, not a reverse scan of the
    # (possibly millions-large) index map
    keep_idx = np.array(
        [
            idx
            for idx in imap.get_indices(sorted(selected) + [INTERCEPT_KEY])
            if idx >= 0
        ],
        dtype=np.int64,
    )
    keep_mask = np.zeros(len(imap), dtype=bool)
    keep_mask[keep_idx] = True
    shard = data.feature_shards["features"]
    m = keep_mask[shard.cols]
    logger.info(
        "selected-features filter: %d/%d features kept, %d/%d entries",
        len(keep_idx), len(imap), int(m.sum()), len(shard.cols),
    )
    return _dc.replace(
        data,
        feature_shards={
            "features": _dc.replace(
                shard,
                rows=shard.rows[m], cols=shard.cols[m], vals=shard.vals[m],
            )
        },
    )


def _labeled_from_game(data, shard: str, norm=None) -> LabeledData:
    return LabeledData.create(
        data.sparse_features(shard, engine="auto"),
        jnp.asarray(data.labels),
        offsets=jnp.asarray(data.offsets),
        weights=jnp.asarray(data.weights),
        norm=norm,
    )


def _write_model_text(path: str, w, variances, index_map) -> None:
    """Per-feature text output 'name<TAB>term<TAB>value' (reference
    IOUtils.writeModelsInText, Driver.scala:213)."""
    w = np.asarray(w)
    with open(path, "w") as f:
        for i in np.flatnonzero(w):
            key = index_map.get_feature_name(int(i)) or str(i)
            name, _, term = key.partition(NAME_TERM_DELIMITER)
            line = f"{name}\t{term}\t{w[i]:.17g}"
            if variances is not None:
                line += f"\t{np.asarray(variances)[i]:.17g}"
            f.write(line + "\n")


def run(args: argparse.Namespace) -> dict:
    import time

    from photon_ml_tpu.event import (
        EventEmitter,
        PhotonOptimizationLogEvent,
        PhotonSetupEvent,
        TrainingFinishEvent,
        TrainingStartEvent,
    )

    logger = setup_logger(args.log_file)
    timer = Timer()
    task = TaskType[args.task]
    emitter = EventEmitter()
    for name in args.event_listeners:
        emitter.register_listener_class(name)
    telemetry = start_telemetry(args, "train_glm", emitter=emitter)
    emitter.send_event(PhotonSetupEvent(params=vars(args)))
    t_start = time.perf_counter()
    try:
        if args.validate_per_iteration and not args.validation_data_dirs:
            raise ValueError(
                "--validate-per-iteration requires --validation-data-dirs"
            )
        if args.delete_output_dirs_if_exist:
            from photon_ml_tpu.cli.common import delete_dirs_if_exist

            delete_dirs_if_exist(args.output_dir, args.summarization_output_dir)

        shard_cfg = {
            "features": FeatureShardConfiguration(
                feature_bags=args.feature_bags, add_intercept=args.add_intercept
            )
        }

        with timer.time("preprocess"):
            if args.input_format == "LIBSVM":
                from photon_ml_tpu.io.libsvm import read_libsvm

                if len(args.training_data_dirs) > 1:
                    raise ValueError("LIBSVM input takes a single path")
                for flag in ("offheap_indexmap_dir", "selected_features_file"):
                    if getattr(args, flag):
                        raise ValueError(
                            f"--{flag.replace('_', '-')} applies to AVRO "
                            "input (LIBSVM features are positional)"
                        )
                data, imap = read_libsvm(
                    args.training_data_dirs[0],
                    use_intercept=args.add_intercept,
                    binarize_labels=task.is_classification,
                )
                index_maps = {"features": imap}
            else:
                preloaded = load_index_maps(args.offheap_indexmap_dir, shard_cfg)
                data, index_maps, _ = read_game_data(
                    args.training_data_dirs, shard_cfg, preloaded
                )
                imap = index_maps["features"]
            if args.selected_features_file:
                data = _filter_selected_features(
                    data, imap, args.selected_features_file, logger
                )
            labeled = _labeled_from_game(data, "features")
            validate_labeled_data(
                labeled, task, DataValidationType[args.data_validation]
            )
            icpt = imap.get_index(INTERCEPT_KEY)
            intercept_index = icpt if icpt >= 0 else None
            norm = None
            norm_type = NormalizationType[args.normalization_type]
            if norm_type is not NormalizationType.NONE or args.summarization_output_dir:
                summary = summarize(labeled)
                if args.summarization_output_dir:
                    from photon_ml_tpu.cli.train_game import write_feature_stats

                    write_feature_stats(
                        args.summarization_output_dir, summary, imap
                    )
            if norm_type is not NormalizationType.NONE:
                norm = build_normalization_context(
                    norm_type,
                    mean=summary.mean,
                    variance=summary.variance,
                    max_magnitude=summary.max_abs,
                    intercept_index=intercept_index,
                )
                labeled = _labeled_from_game(data, "features", norm=norm)
        logger.info("rows: %d features: %d", data.num_rows, len(imap))

        opt_cfg = {
            "optimizer": args.optimizer,
            "regularization": args.regularization,
        }
        if args.elastic_net_alpha is not None:
            opt_cfg["alpha"] = args.elastic_net_alpha
        if args.max_iterations is not None:
            opt_cfg["max_iterations"] = args.max_iterations
        if args.tolerance is not None:
            opt_cfg["tolerance"] = args.tolerance
        from photon_ml_tpu.cli.common import parse_box_constraints

        scalar_lo, scalar_hi, box_constraints = parse_box_constraints(
            args.coefficient_box_constraints, imap, len(imap),
            intercept_index=intercept_index,
        )
        if scalar_lo is not None:
            opt_cfg["constraint_lower"] = scalar_lo
        if scalar_hi is not None:
            opt_cfg["constraint_upper"] = scalar_hi
        configuration = parse_optimizer_config(opt_cfg)

        emitter.send_event(TrainingStartEvent(task=task.name))
        with timer.time("train"):
            fits = train_glm(
                labeled,
                task,
                configuration,
                regularization_weights=args.regularization_weights,
                warm_start=args.use_warm_start,
                compute_variances=args.compute_variances,
                track_models=args.validate_per_iteration,
                intercept_index=intercept_index,
                box_constraints=box_constraints,
            )
        for fit in fits:
            emitter.send_event(PhotonOptimizationLogEvent(
                coordinate_id=None,
                regularization_weight=fit.regularization_weight,
                objective_value=float(fit.result.value),
                iterations=int(fit.result.iterations),
                convergence_reason=fit.result.reason_enum().name,
            ))

        # validate: metric per λ; best-λ selection by the task's default metric
        # (reference Driver.validate + ModelSelection.selectBestModel)
        evaluator = default_evaluator(task)
        metrics = {}
        per_iter_metrics: Dict[float, List[float]] = {}
        best_lambda = None
        if args.validation_data_dirs:
            with timer.time("validate"):
                if args.input_format == "LIBSVM":
                    from photon_ml_tpu.io.libsvm import read_libsvm

                    vdata, _ = read_libsvm(
                        args.validation_data_dirs[0],
                        feature_dimension=(
                            len(imap) - 1 if args.add_intercept else len(imap)
                        ),
                        use_intercept=args.add_intercept,
                        binarize_labels=task.is_classification,
                    )
                else:
                    vdata, _, _ = read_game_data(
                        args.validation_data_dirs, shard_cfg, index_maps
                    )
                vfeats = vdata.sparse_features("features", engine="auto")
                for fit in fits:
                    scores = np.asarray(
                        fit.model.compute_score(vfeats)
                    ) + vdata.offsets
                    m = evaluator.evaluate(scores, vdata.labels, vdata.weights)
                    metrics[fit.regularization_weight] = m
                    logger.info(
                        "lambda=%g %s=%.6f", fit.regularization_weight,
                        evaluator.name, m,
                    )
                    if args.validate_per_iteration and fit.tracked_models:
                        # metric-vs-iteration curve from the per-iteration
                        # tracked models (reference validatePerIteration)
                        curve = []
                        for i, tm in enumerate(fit.tracked_models):
                            s_i = np.asarray(
                                tm.compute_score(vfeats)
                            ) + vdata.offsets
                            m_i = evaluator.evaluate(
                                s_i, vdata.labels, vdata.weights
                            )
                            curve.append(float(m_i))
                            logger.info(
                                "lambda=%g iteration=%d %s=%.6f",
                                fit.regularization_weight, i,
                                evaluator.name, m_i,
                            )
                        per_iter_metrics[fit.regularization_weight] = curve
            best_lambda = None
            for lam, m in metrics.items():
                # nan-aware comparison (NaN never wins; reference
                # Evaluator.betterThan semantics)
                if best_lambda is None or evaluator.better_than(m, metrics[best_lambda]):
                    best_lambda = lam
            logger.info("best lambda: %g", best_lambda)
        else:
            best_lambda = fits[0].regularization_weight

        import jax

        write_outputs = jax.process_index() == 0  # single writer on shared FS
        with timer.time("output"):
            if write_outputs:
                os.makedirs(args.output_dir, exist_ok=True)
                for fit in fits:
                    _write_model_text(
                        os.path.join(
                            args.output_dir, f"model-lambda-{fit.regularization_weight:g}.txt"
                        ),
                        fit.model.coefficients.means,
                        fit.model.coefficients.variances,
                        imap,
                    )
                best = next(f for f in fits if f.regularization_weight == best_lambda)
                means = np.asarray(best.model.coefficients.means)
                ntv = []
                for i in np.flatnonzero(means):
                    key = imap.get_feature_name(int(i)) or str(i)
                    name, _, term = key.partition(NAME_TERM_DELIMITER)
                    ntv.append({"name": name, "term": term, "value": float(means[i])})
                record = {
                    "modelId": "best",
                    "modelClass": None,
                    "means": ntv,
                    "variances": None,
                    "lossFunction": None,
                }
                write_avro_file(
                    os.path.join(args.output_dir, "best-model.avro"),
                    schemas.bayesian_linear_model_schema(),
                    [record],
                )
                with open(os.path.join(args.output_dir, "selection.json"), "w") as f:
                    json.dump(
                        {
                            "best_lambda": best_lambda,
                            "metrics": {str(k): v for k, v in metrics.items()},
                            "evaluator": evaluator.name,
                        },
                        f, indent=2,
                    )
        if args.diagnostic_mode != "NONE" and write_outputs:
            with timer.time("diagnose"):
                _diagnose(
                    args, task, data, labeled, fits, best_lambda, imap,
                    intercept_index, configuration, logger,
                    val_data=vdata if args.validation_data_dirs else None,
                    metric_vs_iteration=per_iter_metrics or None,
                    metric_name=evaluator.name,
                    box_constraints=box_constraints,
                )

        emitter.send_event(TrainingFinishEvent(
            task=task.name, wall_seconds=time.perf_counter() - t_start
        ))
        for name, seconds in timer.durations.items():
            logger.info("timing %-12s %.3fs", name, seconds)
        return {"best_lambda": best_lambda, "metrics": metrics, "fits": fits}
    finally:
        # listeners must flush/close even when the run fails; telemetry
        # finishes after them so every bridged event is in the ledger
        emitter.clear_listeners()
        finish_telemetry(telemetry, phases=dict(timer.durations))


def _diagnose(
    args, task, data, labeled, fits, best_lambda, imap, intercept_index,
    configuration, logger, val_data=None, metric_vs_iteration=None,
    metric_name="metric", box_constraints=None,
) -> None:
    """Reference Driver diagnose() stage (Driver.scala:612-638): the mode
    splits the report — TRAIN|ALL runs the training-data diagnostics
    (FittingDiagnostic learning curves + BootstrapTrainingDiagnostic),
    VALIDATE|ALL the held-out diagnostics (Hosmer-Lemeshow, prediction-error
    independence, mean + variance feature importance). Held-out diagnostics
    score the validation set when one was given, else the training set."""
    from photon_ml_tpu.diagnostics import (
        bootstrap_training,
        evaluate_metrics,
        expected_magnitude_importance,
        fitting_diagnostic,
        hosmer_lemeshow_diagnostic,
        prediction_error_independence,
        variance_importance,
    )
    from photon_ml_tpu.diagnostics.report import (
        build_diagnostic_document,
        write_diagnostic_report,
    )

    do_train = args.diagnostic_mode in ("TRAIN", "ALL")
    do_validate = args.diagnostic_mode in ("VALIDATE", "ALL")
    lambdas = [f.regularization_weight for f in fits]
    best = next(f for f in fits if f.regularization_weight == best_lambda)

    # held-out diagnostics run on the validation set when available
    ddata = val_data if val_data is not None else data
    feats = ddata.sparse_features("features", engine="auto")
    scores = np.asarray(best.model.compute_score(feats)) + ddata.offsets
    metrics = evaluate_metrics(scores, ddata.labels, task, ddata.weights)

    def _sub_fits(idx, weights):
        sub = data.take_rows(idx)
        # same normalization as the diagnosed model — the regularizer acts
        # in normalized space, so dropping it would bootstrap a different
        # estimator
        sub_labeled = _labeled_from_game(sub, "features", norm=labeled.norm)
        return sub, train_glm(
            sub_labeled, task, configuration,
            regularization_weights=weights,
            intercept_index=intercept_index,
            box_constraints=box_constraints,
        )

    fitting = None
    bootstrap = None
    if do_train:
        def fit_portion(idx, warm):
            _, sub_fit = _sub_fits(idx, lambdas)
            return {f.regularization_weight: f.model for f in sub_fit}

        def eval_rows(model, idx):
            sub = data.take_rows(idx)
            s = np.asarray(
                model.compute_score(sub.sparse_features("features", engine="auto"))
            )
            return evaluate_metrics(s + sub.offsets, sub.labels, task, sub.weights)

        fitting = fitting_diagnostic(
            fit_portion, eval_rows, data.num_rows, len(imap), seed=0
        )

        def boot_train(idx):
            sub, sub_fit = _sub_fits(idx, [best_lambda])
            fit = sub_fit[0]
            s = np.asarray(
                fit.model.compute_score(sub.sparse_features("features", engine="auto"))
            )
            return (
                np.asarray(fit.model.coefficients.means),
                evaluate_metrics(s + sub.offsets, sub.labels, task, sub.weights),
            )

        bootstrap = bootstrap_training(
            boot_train, data.num_rows, num_samples=6, seed=0
        )

    hl = None
    independence = None
    importance = None
    importance_var = None
    if do_validate:
        if task is TaskType.LOGISTIC_REGRESSION:
            from photon_ml_tpu.diagnostics.evaluation import _sigmoid

            hl = hosmer_lemeshow_diagnostic(
                _sigmoid(scores), ddata.labels, len(imap)
            )
        independence = prediction_error_independence(
            scores, ddata.labels, max_items=2000
        )
        # importance scales (E|x|, Var x) come from the TRAINING summary,
        # like the reference's preprocessing-stage summary
        summary = summarize(labeled)
        importance = expected_magnitude_importance(
            best.model.coefficients.means,
            mean_abs=np.asarray(summary.mean_abs),
            index_map=imap,
        )
        importance_var = variance_importance(
            best.model.coefficients.means,
            variance=np.asarray(summary.variance),
            index_map=imap,
        )

    doc = build_diagnostic_document(
        f"Model diagnostics (lambda = {best_lambda:g})",
        metrics=metrics,
        fitting=fitting,
        bootstrap=bootstrap,
        hosmer_lemeshow=hl,
        independence=independence,
        importance=importance,
        importance_variance=importance_var,
        metric_vs_iteration=metric_vs_iteration,
        metric_name=metric_name,
    )
    out = write_diagnostic_report(args.output_dir, doc)
    logger.info("diagnostic report: %s", out)


def main(argv: Optional[List[str]] = None) -> int:
    from photon_ml_tpu.parallel.multihost import initialize_from_args

    args = parse_args(argv)
    # cluster join (or single-process no-op) must precede any jax device use
    initialize_from_args(args)
    run(args)
    return 0


if __name__ == "__main__":
    sys.exit(main())
