"""Shared CLI plumbing: JSON config → typed configs, index-map loading,
logger setup.

Reference parity: GameDriver.scala:32 (prepareFeatureMaps: default Avro scan
vs PalDB off-heap :46-85), GameTrainingParams.scala:269-610 (flag surface),
and the config mini-languages replaced by JSON (GLMOptimizationConfiguration
.scala:64-67, RandomEffectDataConfiguration.scala:78-143).
"""

from __future__ import annotations

import atexit
import json
import logging
import os
import sys
from typing import Dict, List, Optional, Tuple

from photon_ml_tpu.data.random_effect import RandomEffectDataConfiguration
from photon_ml_tpu.estimators.game import (
    CoordinateConfiguration,
    FactoredRandomEffectCoordinateConfiguration,
    FixedEffectCoordinateConfiguration,
    RandomEffectCoordinateConfiguration,
)
from photon_ml_tpu.algorithm.factored_random_effect import (
    MFOptimizationConfiguration,
)
from photon_ml_tpu.indexmap import IndexMap
from photon_ml_tpu.indexmap.offheap import OffHeapIndexMap
from photon_ml_tpu.io.data_reader import FeatureShardConfiguration
from photon_ml_tpu.opt.config import (
    AdaptiveSolveConfig,
    GlmOptimizationConfiguration,
    OptimizerConfig,
    OptimizerType,
    RegularizationContext,
)
from photon_ml_tpu.projector import ProjectorType
from photon_ml_tpu.types import RegularizationType


_logger_atexit_registered = False


def _close_logger_handlers() -> None:
    """Flush/close any handlers still attached at interpreter exit — a
    FileHandler left open otherwise loses its tail on shutdown."""
    logger = logging.getLogger("photon_ml_tpu")
    for h in list(logger.handlers):
        try:
            h.flush()
            if isinstance(h, logging.FileHandler):
                logger.removeHandler(h)
                h.close()
        except Exception:
            pass


def setup_logger(log_file: Optional[str] = None, level: str = "INFO") -> logging.Logger:
    """PhotonLogger-style driver logging: stderr + optional buffered file
    (reference util/PhotonLogger.scala:36 writes a per-job log file).

    The ``PHOTON_LOG_LEVEL`` environment variable overrides ``level``
    (handy for turning on DEBUG in a driver without a flag change)."""
    global _logger_atexit_registered
    logger = logging.getLogger("photon_ml_tpu")
    level = os.environ.get("PHOTON_LOG_LEVEL", level)
    resolved = getattr(logging, str(level).upper(), None)
    if not isinstance(resolved, int):
        logger.warning("unknown log level %r, falling back to INFO", level)
        resolved = logging.INFO
    logger.setLevel(resolved)
    # idempotent: a second driver run in the same process must not stack
    # handlers (duplicate lines, leaked file descriptors)
    for h in list(logger.handlers):
        logger.removeHandler(h)
        h.close()
    fmt = logging.Formatter("%(asctime)s %(levelname)s %(name)s %(message)s")
    handler = logging.StreamHandler(sys.stderr)
    handler.setFormatter(fmt)
    logger.addHandler(handler)
    if log_file:
        parent = os.path.dirname(log_file)
        if parent:
            os.makedirs(parent, exist_ok=True)
        fh = logging.FileHandler(log_file)
        fh.setFormatter(fmt)
        logger.addHandler(fh)
    if not _logger_atexit_registered:
        atexit.register(_close_logger_handlers)
        _logger_atexit_registered = True
    return logger


def add_telemetry_args(parser) -> None:
    """``--telemetry-out`` / ``--trace-out``: shared by all five drivers."""
    parser.add_argument(
        "--telemetry-out",
        default=None,
        metavar="LEDGER.jsonl",
        help="write a JSONL run ledger (spans, events, metrics snapshot) "
        "to this path; enables span tracing for the run",
    )
    parser.add_argument(
        "--trace-out",
        default=None,
        metavar="TRACE.json",
        help="write a Chrome trace-event file (load in Perfetto or "
        "chrome://tracing) to this path; enables span tracing for the run",
    )


def start_telemetry(args, label: str, emitter=None):
    """Start a telemetry run when the driver asked for one (either flag);
    returns None otherwise. ``emitter`` gets the event->ledger bridge."""
    ledger_path = getattr(args, "telemetry_out", None)
    trace_path = getattr(args, "trace_out", None)
    if not ledger_path and not trace_path:
        return None
    from photon_ml_tpu.telemetry import start_run

    run = start_run(label, ledger_path=ledger_path, trace_path=trace_path)
    if emitter is not None:
        run.attach(emitter)
    return run


def finish_telemetry(run, **extra):
    """Finish a run from ``start_telemetry`` (None-safe); disables the
    tracer again so later driver runs in-process start clean."""
    if run is None:
        return None
    from photon_ml_tpu.telemetry import disable_tracing

    try:
        return run.finish(extra=extra or None)
    finally:
        disable_tracing()


def parse_optimizer_config(cfg: dict) -> GlmOptimizationConfiguration:
    """JSON dict → GlmOptimizationConfiguration. Keys mirror the reference
    mini-language fields: optimizer, max_iterations, tolerance,
    regularization, alpha, regularization_weight, down_sampling_rate, plus
    box constraints."""
    opt_type = OptimizerType[cfg.get("optimizer", "LBFGS").upper()]
    kw = {}
    if "max_iterations" in cfg:
        kw["max_iterations"] = int(cfg["max_iterations"])
    if "tolerance" in cfg:
        kw["tolerance"] = float(cfg["tolerance"])
    if "constraint_lower" in cfg:
        kw["constraint_lower"] = cfg["constraint_lower"]
    if "constraint_upper" in cfg:
        kw["constraint_upper"] = cfg["constraint_upper"]
    if opt_type is OptimizerType.TRON:
        for key in ("history_length", "history_dtype"):
            if key in cfg:
                raise ValueError(f"{key} applies to LBFGS/OWL-QN, not TRON")
        opt = OptimizerConfig.tron(**kw)
    else:
        if "history_length" in cfg:
            kw["history_length"] = int(cfg["history_length"])
        if "history_dtype" in cfg:
            kw["history_dtype"] = cfg["history_dtype"]
        opt = OptimizerConfig.lbfgs(**kw)
    reg_type = RegularizationType[cfg.get("regularization", "NONE").upper()]
    reg = RegularizationContext(reg_type, alpha=cfg.get("alpha"))
    weight = cfg.get("regularization_weight")
    if weight is not None and cfg.get("regularization_weights"):
        raise ValueError(
            "give either regularization_weight or the sweep list "
            "regularization_weights, not both"
        )
    if weight is None:
        # plural form: the sweep list (cross-product across coordinates,
        # see coordinate_weight_sweeps); its first entry doubles as the
        # single-config default
        ws = cfg.get("regularization_weights")
        weight = ws[0] if ws else 0.0
    adaptive = AdaptiveSolveConfig()
    adaptive_cfg = cfg.get("adaptive")
    if adaptive_cfg is not None:
        # {"enabled": bool, "chunk_iters": int, "min_lanes": int} — knobs
        # for the convergence-adaptive random-effect driver
        akw = {}
        if "enabled" in adaptive_cfg:
            akw["enabled"] = bool(adaptive_cfg["enabled"])
        if "chunk_iters" in adaptive_cfg:
            akw["chunk_iters"] = int(adaptive_cfg["chunk_iters"])
        if "min_lanes" in adaptive_cfg:
            akw["min_lanes"] = int(adaptive_cfg["min_lanes"])
        adaptive = AdaptiveSolveConfig(**akw)
    return GlmOptimizationConfiguration(
        optimizer_config=opt,
        regularization=reg,
        regularization_weight=float(weight),
        down_sampling_rate=float(cfg.get("down_sampling_rate", 1.0)),
        adaptive=adaptive,
    )


def coordinate_weight_sweeps(raw: dict) -> Dict[str, List[float]]:
    """Per-coordinate λ sweep lists from the raw config JSON.

    A coordinate's optimizer block may declare
    ``"regularization_weights": [w1, w2, ...]`` (plural) instead of a single
    weight; the training driver then fits the CROSS-PRODUCT of all sweeping
    coordinates' weights, one GAME model per combination, and selects the
    best by the validation evaluator — the reference's per-coordinate
    config arrays expanded by getAllModelConfigs
    (cli/game/training/GameTrainingParams.scala:212-223).
    """
    out: Dict[str, List[float]] = {}
    for cid, c in (raw.get("coordinates") or {}).items():
        ws = (c.get("optimizer") or {}).get("regularization_weights")
        if ws:
            out[cid] = [float(w) for w in ws]
    return out


def parse_re_data_config(cfg: dict, re_type: str) -> RandomEffectDataConfiguration:
    return RandomEffectDataConfiguration(
        random_effect_type=re_type,
        active_data_upper_bound=cfg.get("active_data_upper_bound"),
        passive_data_lower_bound=cfg.get("passive_data_lower_bound"),
        features_to_samples_ratio=cfg.get("features_to_samples_ratio"),
        max_local_features=cfg.get("max_local_features"),
        num_buckets=int(cfg.get("num_buckets", 1)),
        projector=ProjectorType[cfg.get("projector", "INDEX_MAP").upper()],
        projected_dim=cfg.get("projected_dim"),
    )


def parse_coordinate_config(cfg: dict) -> CoordinateConfiguration:
    ctype = cfg.get("type", "fixed").lower()
    shard = cfg["feature_shard"]
    optimizer = parse_optimizer_config(cfg.get("optimizer", {}))
    if ctype == "fixed":
        return FixedEffectCoordinateConfiguration(
            feature_shard=shard,
            optimizer=optimizer,
            sparse_engine=cfg.get("sparse_engine", "auto"),
        )
    re_type = cfg["random_effect_type"]
    data = parse_re_data_config(cfg.get("data", {}), re_type)
    if ctype == "random":
        return RandomEffectCoordinateConfiguration(
            feature_shard=shard, data=data, optimizer=optimizer
        )
    if ctype == "factored_random":
        mf = cfg.get("mf", {})
        return FactoredRandomEffectCoordinateConfiguration(
            feature_shard=shard,
            data=data,
            mf=MFOptimizationConfiguration(
                num_latent_factors=int(mf.get("num_latent_factors", 8)),
                num_iterations=int(mf.get("num_iterations", 2)),
            ),
            optimizer=optimizer,
            matrix_optimizer=(
                parse_optimizer_config(cfg["matrix_optimizer"])
                if "matrix_optimizer" in cfg
                else None
            ),
        )
    raise ValueError(f"unknown coordinate type: {ctype}")


def load_game_config(path: str) -> Tuple[
    Dict[str, FeatureShardConfiguration],
    Dict[str, CoordinateConfiguration],
    List[str],
    dict,
]:
    """Load the typed JSON coordinate-config file. Returns (shard configs,
    coordinate configs, update order, the raw dict for metadata)."""
    with open(path) as f:
        raw = json.load(f)
    shards = {
        sid: FeatureShardConfiguration(
            feature_bags=s["feature_bags"],
            add_intercept=bool(s.get("add_intercept", True)),
        )
        for sid, s in raw["feature_shards"].items()
    }
    coordinates = {
        cid: parse_coordinate_config(c)
        for cid, c in raw["coordinates"].items()
    }
    update_order = raw.get("update_order", list(coordinates))
    return shards, coordinates, update_order, raw


def parse_box_constraints(
    spec: Optional[str], index_map, dim: int,
    intercept_index: Optional[int] = None,
):
    """``--coefficient-box-constraints`` → (scalar_lower, scalar_upper,
    per_feature_box_or_None).

    Two accepted payloads:
    - ``{"lower": s, "upper": s}`` — global scalar bounds (shorthand).
    - the reference's JSON array of ``{"name", "term", "lowerBound",
      "upperBound"}`` maps (GLMSuite.createConstraintFeatureMap:206-282):
      every map names both name and term; at least one bound must be finite
      and strictly lower < upper; ``name='*', term='*'`` bounds every
      feature except the intercept and may not combine with any other
      entry; ``term='*'`` alone bounds every feature whose key name-part
      equals ``name`` (all terms) and combines with non-overlapping
      entries; a wildcard name requires a wildcard term; bounds reaching
      the same feature twice are rejected.
    """
    if not spec:
        return None, None, None
    import numpy as np

    payload = json.loads(spec)
    if isinstance(payload, dict):
        return payload.get("lower"), payload.get("upper"), None
    if not isinstance(payload, list):
        raise ValueError(
            "--coefficient-box-constraints expects a JSON object with "
            "lower/upper or the reference's JSON array of per-feature maps"
        )
    from photon_ml_tpu.indexmap import NAME_TERM_DELIMITER, feature_key

    WILD = "*"
    lower = np.full(dim, -np.inf, dtype=np.float32)
    upper = np.full(dim, np.inf, dtype=np.float32)
    assigned = np.zeros(dim, dtype=bool)

    # One forward pass over the index builds name-part -> indices for all
    # term-wildcard entries at once (a per-entry reverse scan would be
    # O(dim) Python-level lookups per entry — pathological on off-heap maps)
    wild_names = {
        str(e["name"]) for e in payload
        if isinstance(e, dict)
        and e.get("term") == WILD and e.get("name") not in (None, WILD)
    }
    by_name: Dict[str, List[int]] = {nm: [] for nm in wild_names}
    if wild_names:
        items = (
            index_map.items() if hasattr(index_map, "items")
            else ((index_map.get_feature_name(i), i) for i in range(dim))
        )
        for key, idx in items:
            if key is None:
                continue
            # empty-term features carry the bare name as their key
            name_part = key.split(NAME_TERM_DELIMITER, 1)[0]
            if name_part in by_name:
                by_name[name_part].append(idx)

    def _set(idx: int, lo: float, hi: float, what: str) -> None:
        if assigned[idx]:
            raise ValueError(
                f"overlapping constraints for {what} (reference GLMSuite "
                "conflict rule: a feature may be bounded at most once)"
            )
        lower[idx] = lo
        upper[idx] = hi
        assigned[idx] = True

    for entry in payload:
        if "name" not in entry or "term" not in entry:
            raise ValueError(
                f"constraint map {entry!r} must name both 'name' and 'term'"
            )
        # JSON null == missing: unbounded on that side
        lo_raw = entry.get("lowerBound")
        hi_raw = entry.get("upperBound")
        lo = float(lo_raw) if lo_raw is not None else -np.inf
        hi = float(hi_raw) if hi_raw is not None else np.inf
        name, term = str(entry["name"]), str(entry["term"])
        if np.isnan(lo) or np.isnan(hi):
            raise ValueError(
                f"constraint for {name!r}/{term!r} has a NaN bound"
            )
        if not np.isfinite(lo) and not np.isfinite(hi):
            raise ValueError(
                f"constraint for {name!r}/{term!r} has -Inf and +Inf "
                "bounds: a no-op entry is an invalid specification "
                "(reference GLMSuite.scala:224)"
            )
        if lo >= hi:
            raise ValueError(
                f"constraint lower bound {lo} must be strictly below the "
                f"upper bound {hi} for {name!r}/{term!r} (reference "
                "GLMSuite.scala:228)"
            )
        if name == WILD and term != WILD:
            raise ValueError(
                "a wildcard name requires a wildcard term (reference "
                "GLMSuite.scala:245)"
            )
        if name == WILD:  # '*'/'*': every feature except the intercept
            if assigned.any():
                raise ValueError(
                    "potentially conflicting constraints: the all-wildcard "
                    "entry may not combine with any other constraint "
                    "(reference GLMSuite.scala:234)"
                )
            lower[:] = lo
            upper[:] = hi
            assigned[:] = True
            if intercept_index is not None:
                # the reference's wildcard bounds never pin the intercept
                # (it must stay free to absorb the base rate); since the
                # intercept is then absent from the constraint map, a LATER
                # explicit intercept entry may still bound it — exactly the
                # reference's containsKey-then-put order dependence
                lower[intercept_index] = -np.inf
                upper[intercept_index] = np.inf
                assigned[intercept_index] = False
            continue
        if term == WILD:
            # bounds every feature whose key name-part equals `name` (all
            # terms, including the empty term whose key is the bare name),
            # each conflict-checked (reference GLMSuite.scala:249)
            for idx in by_name.get(name, ()):
                _set(idx, lo, hi, f"{name!r} (term wildcard)")
            continue
        idx = index_map.get_index(feature_key(name, term))
        if idx < 0:
            continue  # feature absent from the training index
        _set(idx, lo, hi, f"{name!r}/{term!r}")
    if not assigned.any():
        return None, None, None
    return None, None, (lower, upper)


def delete_dirs_if_exist(*dirs: Optional[str]) -> None:
    """Single-writer removal of stale output dirs (reference
    DELETE_OUTPUT_DIR_IF_EXISTS). Process 0 only; None entries skipped."""
    import shutil

    import jax

    if jax.process_index() != 0:
        return
    for d in dirs:
        if d and os.path.isdir(d):
            shutil.rmtree(d)


def parse_input_columns(spec: Optional[str]) -> Dict[str, str]:
    """``--input-columns-names`` JSON → ``read_game_data`` field kwargs
    (reference InputColumnsNames: user-defined response/offset/weight/uid
    column names). Shared by the training and scoring drivers."""
    if not spec:
        return {}
    raw_cols = json.loads(spec)
    allowed = {"response", "offset", "weight", "uid"}
    bad = set(raw_cols) - allowed
    if bad:
        raise ValueError(
            f"--input-columns-names has unknown keys {sorted(bad)}; "
            f"allowed: {sorted(allowed)}"
        )
    return {f"{k}_field": v for k, v in raw_cols.items()}


def expand_data_dirs(
    dirs: List[str],
    date_range: Optional[str],
    days_ago: Optional[str],
) -> List[str]:
    """Date-range expansion shared by the drivers (reference
    --train-date-range / --date-range): each dir expands to its daily
    yyyy/MM/dd subdirs; an empty result fails fast."""
    from photon_ml_tpu.utils.date_range import paths_for_date_range

    out = paths_for_date_range(dirs, date_range, days_ago)
    if not out:
        raise FileNotFoundError(f"no input dirs in date range under {dirs}")
    return out


def load_index_maps(
    offheap_dir: Optional[str],
    shard_ids,
) -> Optional[Dict[str, IndexMap]]:
    """Off-heap (PHIX) maps when a directory is given — one subdir per
    feature shard — else None (callers fall back to the default Avro scan,
    reference GameDriver.prepareFeatureMaps)."""
    if not offheap_dir:
        return None
    import os

    out: Dict[str, IndexMap] = {}
    for sid in shard_ids:
        d = os.path.join(offheap_dir, sid)
        if not os.path.isdir(d):
            raise FileNotFoundError(f"no off-heap index map for shard {sid} at {d}")
        out[sid] = OffHeapIndexMap(d)
    return out


def id_tags_needed(coordinates: Dict[str, CoordinateConfiguration]) -> List[str]:
    tags = []
    for cfg in coordinates.values():
        re_type = getattr(getattr(cfg, "data", None), "random_effect_type", None)
        if re_type and re_type not in tags:
            tags.append(re_type)
    return tags
