"""GAME training driver.

Reference parity: cli/game/training/Driver.scala:50 — run() (:64-119):
prepareFeatureMaps → AvroDataReader.readMerged → feature stats /
normalization contexts → GameEstimator.fit per optimization configuration →
optional hyperparameter tuning (:318-348) → best-model selection →
model save (:389-433). Flags keep the reference's names where sensible
(GameTrainingParams.scala:274-319), with the per-coordinate mini-languages
replaced by the typed JSON config file (see cli/common.py).

Usage:
    python -m photon_ml_tpu.cli.train_game \
        --train-data-dirs data/train --validation-data-dirs data/test \
        --coordinate-config game.json --task LOGISTIC_REGRESSION \
        --output-dir out/
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
from typing import Dict, List, Optional

import numpy as np

from photon_ml_tpu.cli.common import (
    add_telemetry_args,
    coordinate_weight_sweeps,
    delete_dirs_if_exist,
    finish_telemetry,
    id_tags_needed,
    load_game_config,
    load_index_maps,
    parse_input_columns,
    setup_logger,
    start_telemetry,
)
from photon_ml_tpu.estimators.game import GameEstimator, GameFit
from photon_ml_tpu.estimators.tuning import run_hyperparameter_tuning
from photon_ml_tpu.evaluation.evaluators import (
    EvaluatorType,
    MultiEvaluator,
    evaluator_for,
)
from photon_ml_tpu.indexmap import DefaultIndexMap, INTERCEPT_KEY
from photon_ml_tpu.io import schemas
from photon_ml_tpu.io.avro import write_avro_file
from photon_ml_tpu.io.data_reader import read_game_data
from photon_ml_tpu.io.model_io import save_game_model
from photon_ml_tpu.normalization import build_normalization_context
from photon_ml_tpu.ops.data import LabeledData
from photon_ml_tpu.stat.summary import summarize
from photon_ml_tpu.types import NormalizationType, TaskType
from photon_ml_tpu.utils.timer import Timer


def parse_args(argv: Optional[List[str]] = None) -> argparse.Namespace:
    p = argparse.ArgumentParser(
        prog="photon-ml-tpu train-game", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    from photon_ml_tpu.parallel.multihost import add_distributed_args

    add_distributed_args(p)
    p.add_argument("--train-data-dirs", nargs="+", required=True)
    p.add_argument("--validation-data-dirs", nargs="*", default=[])
    p.add_argument("--train-date-range", default=None,
                   help="yyyyMMdd-yyyyMMdd; expands each data dir to its "
                        "daily yyyy/MM/dd subdirs (reference "
                        "--train-date-range)")
    p.add_argument("--train-date-days-ago", default=None,
                   help="start-end days ago, e.g. 90-1")
    p.add_argument("--validation-date-range", default=None,
                   help="yyyyMMdd-yyyyMMdd for the validation dirs "
                        "(reference --validation-date-range)")
    p.add_argument("--validation-date-days-ago", default=None,
                   help="start-end days ago for the validation dirs")
    p.add_argument("--coordinate-config", required=True,
                   help="typed JSON config: feature shards + coordinates")
    p.add_argument("--updating-sequence", nargs="+", default=None,
                   help="coordinate update order for coordinate descent; "
                        "overrides the config file's order (reference "
                        "--updating-sequence)")
    p.add_argument("--task", required=True,
                   choices=[t.name for t in TaskType])
    p.add_argument("--output-dir", required=True)
    p.add_argument("--num-outer-iterations", type=int, default=None,
                   help="overrides the config file's num_outer_iterations (default 1)")
    p.add_argument("--evaluator", nargs="+", default=None,
                   help="one or more of AUC, RMSE, PRECISION@k, or sharded "
                        "'AUC:userId' / 'PRECISION@5:userId' (reference "
                        "MultiEvaluatorType syntax). The FIRST selects the "
                        "best model; all are logged per coordinate per "
                        "iteration (CoordinateDescent.scala:283-293)")
    p.add_argument("--normalization-type", default="NONE",
                   choices=[n.name for n in NormalizationType])
    p.add_argument("--offheap-indexmap-dir", default=None)
    p.add_argument("--compute-variance", action="store_true",
                   help="attach per-coefficient variances ~ 1/(H_jj+eps) to "
                        "FE and RE models; saved in the BayesianLinearModel"
                        "Avro variances field (reference --compute-variance)")
    p.add_argument("--num-output-files-for-random-effect-model", type=int,
                   default=1, metavar="N",
                   help="partition each random-effect coordinate's "
                        "coefficients across N part files (reference "
                        "NUM_OUTPUT_FILES_FOR_RANDOM_EFFECT_MODEL)")
    p.add_argument("--model-output-mode", default="BEST",
                   choices=["ALL", "BEST", "NONE"],
                   help="BEST saves the selected model under <output>/best; "
                        "ALL additionally saves every swept configuration "
                        "under <output>/all/<i>; NONE saves nothing "
                        "(reference ModelOutputMode)")
    p.add_argument("--delete-output-dir-if-exists", action="store_true",
                   help="remove an existing --output-dir before writing")
    p.add_argument("--check-data", action="store_true",
                   help="run per-task input validation over every feature "
                        "shard before training (reference CHECK_DATA -> "
                        "DataValidators.sanityCheckData)")
    p.add_argument("--input-columns-names", default=None,
                   help="JSON map overriding input field names, e.g. "
                        '\'{"response": "y", "weight": "w"}\'; keys: '
                        "response, offset, weight, uid (reference "
                        "InputColumnsNames)")
    p.add_argument("--summarization-output-dir", default=None,
                   help="write per-shard feature stats here instead of "
                        "<output-dir>/feature-stats (implies stats are "
                        "computed for every shard)")
    p.add_argument("--hyperparameter-tuning", default="NONE",
                   choices=["NONE", "RANDOM", "BAYESIAN"])
    p.add_argument("--hyperparameter-tuning-iter", type=int, default=10)
    p.add_argument("--regularization-weight-range", default=None,
                   help="lower,upper bounds for tuned regularization "
                        "weights, e.g. 1e-4,1e4 (reference "
                        "--regularization-weight-range)")
    p.add_argument("--use-warm-start", dest="use_warm_start",
                   action="store_true", default=True,
                   help="warm-start tuning trials from the previous trial's "
                        "models (default on, reference USE_WARM_START)")
    p.add_argument("--no-warm-start", dest="use_warm_start",
                   action="store_false")
    p.add_argument("--model-name", default="photon-ml-tpu-game")
    p.add_argument("--checkpoint-dir", default=None,
                   help="atomic per-outer-iteration training checkpoints; "
                        "an existing checkpoint there is resumed")
    p.add_argument("--save-feature-stats", action="store_true",
                   help="write per-shard FeatureSummarizationResultAvro")
    p.add_argument("--event-listeners", nargs="*", default=[],
                   metavar="module.Class",
                   help="EventListener classes to register")
    p.add_argument("--parallel-data", type=int, default=0,
                   help="devices on the batch axis of the (data x feat) "
                        "training grid (0 = single device)")
    p.add_argument("--parallel-feat", type=int, default=1,
                   help="devices on the coefficient axis (shards w / grad / "
                        "optimizer history for huge feature spaces)")
    p.add_argument("--parallel-engine", default="benes",
                   choices=["benes", "ell", "fused"],
                   help="sparse engine per grid tile")
    p.add_argument("--profile-dir", default=None,
                   help="write a jax profiler trace of the fit phase here "
                        "(view with TensorBoard / xprof)")
    p.add_argument("--auto-tune", action="store_true",
                   help="A/B adaptive-RE solver configs on a 1-outer-"
                        "iteration trial fit before the real fit (judged by "
                        "the metrics registry); the winner trains the model "
                        "and is saved as the metadata's tuned_config")
    p.add_argument("--auto-tune-trials", type=int, default=2,
                   help="candidate configs trialed besides the incumbent "
                        "(default 2)")
    p.add_argument("--auto-tune-judge", default="autotune.wall_s",
                   help="registry metric that judges auto-tune trials, "
                        "minimized (default autotune.wall_s = trial "
                        "wall-clock)")
    p.add_argument("--auto-tune-report", default=None,
                   help="RunReport JSON from analyze_run; when given, trial "
                        "candidates come from the offline tuner's proposal "
                        "instead of ladder neighbors")
    p.add_argument("--schedule", default="sync", choices=("sync", "async"),
                   help="coordinate-descent schedule: 'sync' (sequential, "
                        "bitwise-reproducible default) or 'async' "
                        "(bounded-staleness pipelined FE/RE solves on the "
                        "device score plane; multi-controller runs fall "
                        "back to sync)")
    p.add_argument("--staleness", type=int, default=1,
                   help="async schedule only: max unreconciled coordinate "
                        "updates a dispatch may ignore (0 = serialize, "
                        "bitwise equal to sync)")
    p.add_argument("--streaming", action="store_true",
                   help="out-of-core training: stream the training set from "
                        "disk in fixed-shape blocks through a double-buffered "
                        "host->device prefetcher instead of materializing "
                        "fixed-effect design matrices in memory (validation "
                        "data is still read in-memory)")
    p.add_argument("--block-rows", type=int, default=65536,
                   help="streaming: rows per example block; every block has "
                        "this exact (padded) shape so nothing retraces "
                        "(default 65536)")
    p.add_argument("--prefetch-depth", type=int, default=2,
                   help="streaming: staged blocks the background decode "
                        "thread may buffer ahead (0 = synchronous decode; "
                        "default 2 = double buffering). Host staging memory "
                        "is bounded by prefetch-depth x block bytes")
    p.add_argument("--block-cache-dir", default=None,
                   help="streaming: directory for the decoded block cache "
                        "(default: a '_block_cache' directory next to the "
                        "input data). Epoch 1 decodes Avro once and spills "
                        "each padded block; later epochs (and later runs over "
                        "identical inputs) reload blocks zero-copy via mmap "
                        "with zero decode work. Entries are keyed by a "
                        "fingerprint of the input files (path, size, "
                        "mtime_ns), block-rows, shard geometry and the "
                        "feature index maps (incl. --offheap-indexmap-dir "
                        "contents), so any input, index-map or config "
                        "change invalidates automatically")
    p.add_argument("--no-block-cache", action="store_true",
                   help="streaming: disable the decoded block cache and "
                        "re-decode Avro every epoch")
    p.add_argument("--on-block-error", default="abort",
                   choices=("abort", "skip"),
                   help="streaming: what to do when a block permanently "
                        "fails to decode after IO retries — 'abort' (default) "
                        "fails the fit; 'skip' drops the block from the "
                        "epoch, records a resilience anomaly in the progress "
                        "ledger, and excludes it from gap scheduling")
    p.add_argument("--decode-workers", type=int, default=-1,
                   help="streaming: decode pool threads (-1 = auto: "
                        "cpu_count-1 capped at 16; 0 = synchronous decode in "
                        "the prefetch thread). Each worker decodes one part "
                        "file per GIL-released native call, so workers "
                        "genuinely overlap")
    p.add_argument("--stream-mode", default="full",
                   choices=("full", "stochastic"),
                   help="streaming solver: 'full' replays every block per "
                        "optimizer iteration (exact full-batch, default); "
                        "'stochastic' visits shuffled block groups per epoch "
                        "-- gate it on held-out metric parity first")
    p.add_argument("--gap-schedule", action="store_true",
                   help="stochastic streaming only: visit blocks by "
                        "staleness-decayed duality-gap importance (DuHL) "
                        "instead of a blind per-epoch shuffle. Epochs "
                        "concentrate on the blocks with the largest gap "
                        "estimates (with an exploration floor refreshing "
                        "stale blocks), typically reaching the target "
                        "held-out metric in far fewer block visits on "
                        "skewed data; off is bitwise-identical to the "
                        "historical shuffle order")
    p.add_argument("--resident-blocks", type=int, default=0, metavar="N",
                   help="streaming: pin up to N top-duality-gap blocks' "
                        "device uploads across passes (the HBM level of "
                        "the disk->RAM->HBM residency hierarchy, "
                        "docs/SCALING.md). Warm passes re-upload only the "
                        "non-resident remainder, cutting H2D bytes by "
                        "resident/total with an unchanged solve "
                        "trajectory; the set re-pins between passes as "
                        "gap mass shifts. 0 = off (bitwise-identical "
                        "streaming). Costs N x block upload bytes of "
                        "device memory")
    p.add_argument("--resident-bytes", type=int, default=None, metavar="B",
                   help="streaming: cap the resident set by device BYTES "
                        "instead of (or in addition to) --resident-blocks; "
                        "the tighter budget wins. The per-block unit is "
                        "the fixed block upload size, so B buys "
                        "B // block_upload_bytes pinned blocks")
    p.add_argument("--hosts", type=int, default=0, metavar="N",
                   help="cluster: run the streamed fixed-effect solve "
                        "data-parallel across N coordinated worker "
                        "processes (the emulated multi-host mesh; see "
                        "dev-scripts/run_multihost.py for real "
                        "multi-controller runs). Each full-batch pass "
                        "partitions the blocks across hosts by "
                        "gap-balanced assignment and allreduces the "
                        "partial (value, grad) sums; a killed host's "
                        "blocks are reassigned to survivors instead of "
                        "aborting. Requires --streaming with the default "
                        "--stream-mode full and exactly one fixed-effect "
                        "coordinate; random-effect coordinates still run "
                        "on this host (entity-partitioned)")
    p.add_argument("--cluster-block-latency-ms", type=float, default=0.0,
                   metavar="MS",
                   help="cluster: emulated per-block device latency in each "
                        "worker (benchmarking scaling on one box; 0 = off)")
    p.add_argument("--cluster-kill-host", default=None, metavar="HOST:BLOCKS",
                   help="cluster chaos drill: worker HOST kills itself after "
                        "streaming BLOCKS blocks; training must finish "
                        "anyway with its blocks reassigned (recovery lands "
                        "in the --progress-out ledger)")
    p.add_argument("--progress-out", default=None, metavar="PROGRESS.jsonl",
                   help="write the convergence-plane ledger here: one JSONL "
                        "record per coordinate update (objective, grad norm, "
                        "coefficient delta, solver iterations), per held-out "
                        "evaluation, and — under --streaming — per block "
                        "(partial loss / grad norm / duality-gap estimate). "
                        "Replay with analyze_run --progress. Also arms the "
                        "divergence watchdog: NaN/Inf or increasing "
                        "objectives abort the run instead of saving garbage")
    p.add_argument("--introspect-port", type=int, default=None,
                   metavar="PORT",
                   help="serve live training introspection on "
                        "127.0.0.1:PORT (0 = ephemeral): /progress (JSON "
                        "convergence trace), /metrics (Prometheus), /healthz "
                        "(503 once the divergence watchdog trips), /varz. "
                        "Implies the convergence tracker even without "
                        "--progress-out")
    p.add_argument("--introspect-port-file", default=None,
                   help="write the bound introspection port here (for "
                        "--introspect-port 0)")
    p.add_argument("--introspect-hold", type=float, default=0.0,
                   metavar="SECONDS",
                   help="keep the introspection server up for at most this "
                        "long after training, until /quitquitquit")
    p.add_argument("--log-file", default=None)
    add_telemetry_args(p)
    args = p.parse_args(argv)
    if args.introspect_port is not None and args.introspect_port < 0:
        p.error("--introspect-port must be >= 0 (0 = ephemeral)")
    if args.block_rows < 1:
        p.error("--block-rows must be >= 1")
    if args.prefetch_depth < 0:
        p.error("--prefetch-depth must be >= 0")
    if args.decode_workers < -1:
        p.error("--decode-workers must be >= -1 (-1 = auto)")
    if args.gap_schedule and not (
        args.streaming and args.stream_mode == "stochastic"
    ):
        p.error(
            "--gap-schedule requires --streaming with "
            "--stream-mode stochastic (full-batch mode must visit every "
            "block per pass to stay exact)"
        )
    if args.resident_blocks < 0:
        p.error("--resident-blocks must be >= 0")
    if args.resident_bytes is not None and args.resident_bytes < 1:
        p.error("--resident-bytes must be >= 1")
    residency_on = args.resident_blocks > 0 or args.resident_bytes is not None
    if residency_on and not args.streaming:
        p.error("--resident-blocks/--resident-bytes require --streaming "
                "(they pin streamed block uploads)")
    if residency_on and args.stream_mode == "stochastic" and not args.gap_schedule:
        p.error("--resident-blocks/--resident-bytes with --stream-mode "
                "stochastic require --gap-schedule (the scheduler's gap "
                "feedback picks the resident set)")
    if residency_on and args.hosts > 0:
        p.error("--resident-blocks/--resident-bytes do not compose with "
                "--hosts (cluster workers own their blocks' device "
                "placement)")
    if args.hosts < 0:
        p.error("--hosts must be >= 0")
    if args.hosts > 0 and (not args.streaming or args.stream_mode != "full"):
        p.error(
            "--hosts requires --streaming with --stream-mode full (the "
            "distributed pass sums exact per-host partials)"
        )
    if args.cluster_kill_host is not None:
        if args.hosts < 2:
            p.error("--cluster-kill-host needs --hosts >= 2 (someone must "
                    "survive to take over the blocks)")
        try:
            h, n = args.cluster_kill_host.split(":")
            int(h), int(n)
        except ValueError:
            p.error("--cluster-kill-host must be HOST:BLOCKS, e.g. 1:4")
    if args.staleness < 0:
        p.error("--staleness must be >= 0")
    if args.parallel_data < 0 or args.parallel_feat < 1:
        p.error("--parallel-data must be >= 0 and --parallel-feat >= 1")
    if args.parallel_data == 0 and args.parallel_feat != 1:
        p.error(
            "--parallel-feat requires --parallel-data >= 1 (the grid always "
            "has a data axis; use --parallel-data 1 for pure coefficient-"
            "axis sharding)"
        )
    return args


def _default_block_cache_dir(train_dirs) -> str:
    """Default decoded-block cache location: a ``_block_cache`` directory
    next to the input part files (inside the first data directory, or beside
    the first file when inputs are listed as files). Keeping it with the
    data means the cache travels with — and is cleaned up with — the
    dataset, and the fingerprint keying makes sharing one directory across
    configs safe."""
    first = str(train_dirs[0])
    base = first if os.path.isdir(first) else os.path.dirname(first)
    return os.path.join(base, "_block_cache")


def _check_streaming_compatible(args: argparse.Namespace) -> None:
    """--streaming replaces the in-memory training read; every flag whose
    implementation needs the materialized training GameData (or a second
    full-data pass) fails fast here rather than deep in the fit."""
    conflicts = [
        (args.parallel_data > 0, "--parallel-data (device-grid layout)"),
        (args.compute_variance, "--compute-variance (Hessian-diagonal pass)"),
        (args.check_data, "--check-data (validates in-memory shards)"),
        (args.auto_tune, "--auto-tune (trial fits need in-memory data)"),
        (args.hyperparameter_tuning != "NONE", "--hyperparameter-tuning"),
        (args.normalization_type != "NONE",
         "--normalization-type (needs a streamed feature-stats pass)"),
        (bool(args.summarization_output_dir) or args.save_feature_stats,
         "feature-stats output (summarizes in-memory shards)"),
    ]
    bad = [name for flag, name in conflicts if flag]
    if bad:
        raise ValueError(
            "--streaming is incompatible with: " + "; ".join(bad)
            + ". Drop those flags or train in-memory."
        )


def _sweep_model_configs(sweeps, coordinates):
    """Cross-product of per-coordinate λ lists → fit_multiple config maps
    (reference getAllModelConfigs)."""
    import itertools

    if not sweeps:
        return [{}]
    ids = sorted(sweeps)
    return [
        {
            cid: dataclasses.replace(
                coordinates[cid].optimizer, regularization_weight=w
            )
            for cid, w in zip(ids, combo)
        }
        for combo in itertools.product(*(sweeps[cid] for cid in ids))
    ]


def _apply_adaptive_knobs(coordinates: dict, knobs: dict) -> dict:
    """Return ``coordinates`` with the adaptive-RE knob values folded into
    every optimizer that carries an AdaptiveSolveConfig (frozen dataclasses
    throughout, so this is replace(), never mutation — the originals stay
    usable as the A/B control)."""
    out = {}
    for cid, cfg in coordinates.items():
        opt = getattr(cfg, "optimizer", None)
        adaptive = getattr(opt, "adaptive", None) if opt is not None else None
        if adaptive is None:
            out[cid] = cfg
            continue
        new_adaptive = dataclasses.replace(
            adaptive,
            chunk_iters=int(
                knobs.get("adaptive.chunk_iters", adaptive.chunk_iters)
            ),
            min_lanes=int(knobs.get("adaptive.min_lanes", adaptive.min_lanes)),
        )
        out[cid] = dataclasses.replace(
            cfg, optimizer=dataclasses.replace(opt, adaptive=new_adaptive)
        )
    return out


def _auto_tune_training(args, logger, estimator_kwargs, coordinates, data):
    """Iteration-0 A/B over the adaptive-RE knob space.

    Each candidate runs a 1-outer-iteration fit with its knob values and a
    FRESH MetricsRegistry fed by a trial-local emitter (trial A's solver
    counters cannot leak into trial B's judgment, and none of it pollutes
    the surrounding run's telemetry). Judged by ``--auto-tune-judge``
    (default: trial wall-clock). Returns (winner_knobs, ab_result_dict) —
    winner_knobs is {} when the incumbent wins."""
    from photon_ml_tpu.event import EventEmitter
    from photon_ml_tpu.telemetry.sinks import TelemetryEventListener
    from photon_ml_tpu.tuning import get_knob, run_ab_trials

    spec = get_knob("adaptive.chunk_iters")
    incumbent = None
    for cfg in coordinates.values():
        adaptive = getattr(getattr(cfg, "optimizer", None), "adaptive", None)
        if adaptive is not None:
            incumbent = {
                "adaptive.chunk_iters": adaptive.chunk_iters,
                "adaptive.min_lanes": adaptive.min_lanes,
            }
            break
    if incumbent is None:
        logger.info("auto-tune: no adaptive-RE coordinate; nothing to tune")
        return {}, None

    candidates = [dict(incumbent)]
    if args.auto_tune_report:
        from photon_ml_tpu.telemetry.analyze import RunReport
        from photon_ml_tpu.tuning import ab_candidates, propose

        with open(args.auto_tune_report, "r", encoding="utf-8") as f:
            report = RunReport.from_dict(json.load(f))
        for cand in ab_candidates(propose(report), "train")[1:]:
            knobs = {
                k: v for k, v in cand.items() if k.startswith("adaptive.")
            }
            if knobs and knobs != incumbent:
                candidates.append({**incumbent, **knobs})
    else:
        ladder = list(spec.candidates)
        cur = incumbent["adaptive.chunk_iters"]
        for alt in sorted(ladder, key=lambda v: abs(v - cur)):
            if alt != cur:
                candidates.append(
                    {**incumbent, "adaptive.chunk_iters": alt}
                )
    candidates = candidates[: 1 + max(0, args.auto_tune_trials)]

    def _trial(knobs, registry):
        trial_emitter = EventEmitter()
        trial_emitter.register_listener(
            TelemetryEventListener(ledger=None, registry=registry)
        )
        try:
            trial = GameEstimator(
                coordinates=_apply_adaptive_knobs(coordinates, knobs),
                emitter=trial_emitter,
                **{**estimator_kwargs, "num_outer_iterations": 1},
            )
            trial.fit(data, validation_data=None)
        finally:
            trial_emitter.clear_listeners()

    logger.info(
        "auto-tune: %d candidate config(s) over 1-outer-iteration trials",
        len(candidates),
    )
    result = run_ab_trials(
        candidates,
        _trial,
        judge_metric=args.auto_tune_judge,
        minimize=True,
        logger=logger,
    )
    winner = result.winner
    logger.info(
        "auto-tune winner: trial %d %s=%s config=%s",
        winner.index,
        args.auto_tune_judge,
        f"{winner.score:.6g}" if winner.score is not None else "n/a",
        winner.config,
    )
    if winner.index == 0:
        return {}, result.to_dict()
    return dict(winner.config), result.to_dict()


def _make_evaluator(spec: Optional[str], task: TaskType, data):
    """'AUC', 'AUC:idTag', or 'PRECISION@k[:idTag]' → Evaluator /
    MultiEvaluator bound to the validation id tag (reference
    MultiEvaluatorType.scala:46-60 parses exactly these spellings)."""
    if not spec:
        return None
    name, _, tag = spec.partition(":")
    name = name.strip().upper()
    if name.startswith("PRECISION@"):
        from photon_ml_tpu.evaluation.evaluators import PrecisionAtK

        try:
            k = int(name[len("PRECISION@"):])
        except ValueError:
            raise ValueError(
                f"bad precision@k spelling {name!r}; expected PRECISION@<int>"
            )
        if k <= 0:
            raise ValueError(f"precision@k needs k >= 1, got {k}")
        base = PrecisionAtK(k)
    else:
        base = evaluator_for(EvaluatorType[name])
    if not tag:
        return base
    tag = tag.strip()
    ids = data.id_tags.get(tag)
    if ids is None:
        raise ValueError(f"validation data has no id tag '{tag}'")
    return MultiEvaluator(base=base, group_ids=tuple(ids), tag=tag)


def _save_feature_stats(stats_base, shard, summary, index_map) -> None:
    """Per-shard stats under <stats_base>/<shard>."""
    write_feature_stats(os.path.join(stats_base, shard), summary, index_map)


def write_feature_stats(stats_dir, summary, index_map) -> None:
    """writeBasicStatistics parity (ModelProcessingUtils.scala:560):
    FeatureSummarizationResultAvro part files into ``stats_dir``."""
    import jax

    if jax.process_index() != 0:
        return  # single writer on shared filesystems
    os.makedirs(stats_dir, exist_ok=True)
    mean = np.asarray(summary.mean)
    var = np.asarray(summary.variance)
    mx = np.asarray(summary.max_val)
    mn = np.asarray(summary.min_val)
    nnz = np.asarray(summary.num_nonzeros)
    from photon_ml_tpu.indexmap import NAME_TERM_DELIMITER

    def records():
        for i in range(len(mean)):
            key = index_map.get_feature_name(i)
            if key is None:
                continue
            name, _, term = key.partition(NAME_TERM_DELIMITER)
            yield {
                "featureName": name,
                "featureTerm": term,
                "metrics": {
                    "mean": float(mean[i]),
                    "variance": float(var[i]),
                    "min": float(mn[i]),
                    "max": float(mx[i]),
                    "numNonzeros": float(nnz[i]),
                },
            }

    write_avro_file(
        os.path.join(stats_dir, "part-00000.avro"),
        schemas.feature_summarization_schema(),
        records(),
    )


def run(args: argparse.Namespace) -> GameFit:
    import contextlib
    import time

    from photon_ml_tpu.event import (
        EventEmitter,
        PhotonOptimizationLogEvent,
        PhotonSetupEvent,
        TrainingFinishEvent,
        TrainingStartEvent,
    )

    logger = setup_logger(args.log_file)
    timer = Timer()
    task = TaskType[args.task]
    emitter = EventEmitter()
    for name in args.event_listeners:
        emitter.register_listener_class(name)
    telemetry = start_telemetry(args, "train_game", emitter=emitter)
    emitter.send_event(PhotonSetupEvent(params=vars(args)))
    t_start = time.perf_counter()
    progress = None
    introspect = None
    cluster = None
    try:
        if args.progress_out or args.introspect_port is not None:
            from photon_ml_tpu.telemetry import ConvergenceTracker

            progress = ConvergenceTracker(
                ledger_path=args.progress_out,
                emitter=emitter,
                label="train_game",
            )
            # mirror resilience failures (retry exhaustion, skipped blocks,
            # thread crashes) into the convergence ledger as they happen
            progress.attach_failure_sink()
        if args.introspect_port is not None:
            from photon_ml_tpu.serving.introspect import IntrospectionServer

            introspect = IntrospectionServer(
                varz=lambda: vars(args),
                health=progress.health,
                port=args.introspect_port,
                extra_json={
                    "/progress": progress.progress_json,
                    "/cluster": progress.cluster_json,
                },
            ).start()
            logger.info(
                "introspection on http://%s:%d "
                "(/progress /cluster /metrics /healthz)",
                introspect.host, introspect.port,
            )
            if args.introspect_port_file:
                with open(args.introspect_port_file, "w") as f:
                    f.write(str(introspect.port))
        shard_configs, coordinates, update_order, raw_config = load_game_config(
            args.coordinate_config
        )
        if args.updating_sequence:
            unknown = [c for c in args.updating_sequence if c not in coordinates]
            if unknown:
                raise ValueError(
                    f"--updating-sequence names unknown coordinates {unknown}; "
                    f"config has {sorted(coordinates)}"
                )
            update_order = list(args.updating_sequence)

        col_names = parse_input_columns(args.input_columns_names)

        if args.delete_output_dir_if_exists:
            delete_dirs_if_exist(args.output_dir)

        with timer.time("prepare feature maps"):
            index_maps = load_index_maps(args.offheap_indexmap_dir, shard_configs)

        from photon_ml_tpu.cli.common import expand_data_dirs

        train_dirs = expand_data_dirs(
            args.train_data_dirs, args.train_date_range, args.train_date_days_ago
        )

        id_tags = id_tags_needed(coordinates)
        source = None
        if args.streaming:
            _check_streaming_compatible(args)
            from photon_ml_tpu.streaming import StreamingSource

            cache_dir = None
            if not args.no_block_cache:
                cache_dir = args.block_cache_dir or _default_block_cache_dir(
                    train_dirs
                )
            with timer.time("open streaming source"):
                source = StreamingSource.open(
                    train_dirs, shard_configs, index_maps=index_maps,
                    block_rows=args.block_rows, id_tags=id_tags,
                    decode_workers=(
                        None if args.decode_workers < 0 else args.decode_workers
                    ),
                    cache_dir=cache_dir,
                    **col_names,
                )
            source.on_block_error = args.on_block_error
            index_maps = source.index_maps
            data = None
            logger.info(
                "training rows (streamed): %d in %d blocks of %d "
                "(block cache: %s, decode workers: %d)",
                source.plan.total_rows, source.plan.num_blocks,
                args.block_rows, cache_dir or "off", source.decode_workers,
            )
            if args.hosts > 0:
                from photon_ml_tpu.estimators.game import (
                    FixedEffectCoordinateConfiguration as _FECfg,
                )
                from photon_ml_tpu.parallel.cluster import ClusterPlane

                fe_shards = [
                    cfg.feature_shard
                    for cfg in coordinates.values()
                    if isinstance(cfg, _FECfg)
                ]
                if len(fe_shards) != 1:
                    raise ValueError(
                        "--hosts requires exactly one fixed-effect "
                        f"coordinate, config has {len(fe_shards)}"
                    )
                kill_host = None
                if args.cluster_kill_host is not None:
                    h, n = args.cluster_kill_host.split(":")
                    kill_host = (int(h), int(n))
                # federate observability across the mesh: worker ledgers
                # land beside the coordinator's --telemetry-out ledger
                cluster_telemetry_dir = None
                if args.telemetry_out:
                    cluster_telemetry_dir = os.path.join(
                        os.path.dirname(os.path.abspath(args.telemetry_out)),
                        "cluster-workers",
                    )
                with timer.time("launch cluster"):
                    cluster = ClusterPlane.launch(
                        num_hosts=args.hosts,
                        num_blocks=source.plan.num_blocks,
                        train_dirs=train_dirs,
                        coordinate_config=args.coordinate_config,
                        task=args.task,
                        feature_shard=fe_shards[0],
                        block_rows=args.block_rows,
                        input_columns_names=args.input_columns_names,
                        on_block_error=args.on_block_error,
                        prefetch_depth=args.prefetch_depth,
                        block_cache_dir=(
                            os.path.join(cache_dir, "cluster")
                            if cache_dir
                            else None
                        ),
                        block_latency_s=(
                            args.cluster_block_latency_ms / 1000.0
                            if args.cluster_block_latency_ms > 0
                            else None
                        ),
                        kill_host=kill_host,
                        telemetry_dir=cluster_telemetry_dir,
                    )
                if progress is not None or telemetry is not None:
                    # skew profiles feed the progress ledger's
                    # cluster_pass/host_pass records and the /cluster route
                    cluster.coordinator.enable_telemetry()
                logger.info(
                    "cluster: %d worker host(s) connected on %s:%d",
                    args.hosts, *cluster.coordinator.address,
                )
        else:
            with timer.time("read training data"):
                data, index_maps, _ = read_game_data(
                    train_dirs, shard_configs, index_maps, id_tags=id_tags,
                    **col_names,
                )
            logger.info("training rows: %d", data.num_rows)

        def _check_shards(game_data, phase: str) -> None:
            """--check-data gate over every feature shard (reference CHECK_DATA
            -> readAndCheckGameDataSet wraps BOTH the train and validation
            reads, Driver.scala:74-75). engine="auto" reuses the same cached
            layout training/stats will use."""
            from photon_ml_tpu.data.validators import validate_labeled_data

            with timer.time(f"check data [{phase}]"):
                import jax.numpy as jnp

                for sid in shard_configs:
                    validate_labeled_data(
                        LabeledData.create(
                            game_data.sparse_features(sid, engine="auto"),
                            jnp.asarray(game_data.labels),
                            offsets=jnp.asarray(game_data.offsets),
                            weights=jnp.asarray(game_data.weights),
                        ),
                        task,
                    )

        if args.check_data:
            _check_shards(data, "train")

        # a sharded evaluator ('AUC:tag') needs its tag in the validation read
        # even when no coordinate uses it
        val_tags = list(id_tags)
        for spec in args.evaluator or []:
            tag = spec.partition(":")[2].strip()
            if tag and tag not in val_tags:
                val_tags.append(tag)

        validation_data = None
        if args.validation_data_dirs:
            validation_dirs = expand_data_dirs(
                args.validation_data_dirs,
                args.validation_date_range,
                args.validation_date_days_ago,
            )
            with timer.time("read validation data"):
                validation_data, _, _ = read_game_data(
                    validation_dirs, shard_configs, index_maps,
                    id_tags=val_tags, **col_names,
                )
            logger.info("validation rows: %d", validation_data.num_rows)
            if args.check_data:
                _check_shards(validation_data, "validation")

        norm_type = NormalizationType[args.normalization_type]
        normalization = {}
        intercept_indices = {}
        # normalization applies to fixed-effect coordinates (see GameEstimator);
        # stats are computed/saved for every shard
        from photon_ml_tpu.estimators.game import FixedEffectCoordinateConfiguration

        fe_shards = {
            c.feature_shard
            for c in coordinates.values()
            if isinstance(c, FixedEffectCoordinateConfiguration)
        }
        # summarize only what's needed: fe shards for normalization, every shard
        # when stats output was requested
        stats_base = args.summarization_output_dir or (
            os.path.join(args.output_dir, "feature-stats")
            if args.save_feature_stats else None
        )
        stat_shards = (
            list(shard_configs) if stats_base else sorted(fe_shards)
        )
        if norm_type is not NormalizationType.NONE or stats_base:
            for sid in stat_shards:
                with timer.time(f"feature stats [{sid}]"):
                    import jax.numpy as jnp

                    labeled = LabeledData.create(
                        data.sparse_features(sid, engine="auto"), jnp.asarray(data.labels),
                        weights=jnp.asarray(data.weights),
                    )
                    summary = summarize(labeled)
                if stats_base:
                    _save_feature_stats(stats_base, sid, summary, index_maps[sid])
                icpt = index_maps[sid].get_index(INTERCEPT_KEY)
                intercept_indices[sid] = icpt if icpt >= 0 else None
                if norm_type is not NormalizationType.NONE and sid in fe_shards:
                    normalization[sid] = build_normalization_context(
                        norm_type,
                        mean=summary.mean,
                        variance=summary.variance,
                        max_magnitude=summary.max_abs,
                        intercept_index=intercept_indices[sid],
                    )

        if args.evaluator and not all(s.strip() for s in args.evaluator):
            raise ValueError(
                "--evaluator got an empty spec (check shell quoting); "
                f"specs were {args.evaluator!r}"
            )
        evaluator = None
        extra_evaluators = []
        if validation_data is not None and args.evaluator:
            evaluator = _make_evaluator(args.evaluator[0], task, validation_data)
            extra_evaluators = [
                _make_evaluator(s, task, validation_data)
                for s in args.evaluator[1:]
            ]
        parallel = None
        if args.parallel_data > 0:
            from photon_ml_tpu.estimators.game import ParallelConfiguration

            parallel = ParallelConfiguration(
                n_data=args.parallel_data,
                n_feat=args.parallel_feat,
                engine=args.parallel_engine,
            )
        estimator_kwargs = dict(
            task=task,
            update_order=update_order,
            num_outer_iterations=(
                args.num_outer_iterations
                if args.num_outer_iterations is not None
                else int(raw_config.get("num_outer_iterations", 1))
            ),
            normalization=normalization,
            intercept_indices={k: v for k, v in intercept_indices.items() if v is not None},
            parallel=parallel,
            compute_variance=False,  # trials skip variances; the real fit below opts in
            schedule=args.schedule,
            staleness=args.staleness,
        )

        tuned_config: Dict[str, object] = {}
        if args.auto_tune:
            with timer.time("auto-tune"):
                tuned_config, ab_result = _auto_tune_training(
                    args, logger, estimator_kwargs, coordinates, data
                )
            if tuned_config:
                coordinates = _apply_adaptive_knobs(coordinates, tuned_config)
            if ab_result is not None:
                os.makedirs(args.output_dir, exist_ok=True)
                with open(
                    os.path.join(args.output_dir, "auto-tune.json"), "w"
                ) as f:
                    json.dump(ab_result, f, indent=2, sort_keys=True)

        estimator = GameEstimator(
            coordinates=coordinates,
            evaluator=evaluator,
            extra_evaluators=extra_evaluators,
            emitter=emitter,
            **{**estimator_kwargs, "compute_variance": args.compute_variance},
        )

        emitter.send_event(TrainingStartEvent(task=task.name))
        profile_ctx = contextlib.nullcontext()
        if args.profile_dir:
            import jax

            profile_ctx = jax.profiler.trace(args.profile_dir)
        sweep_configs = _sweep_model_configs(
            coordinate_weight_sweeps(raw_config), coordinates
        )
        if len(sweep_configs) > 1 and validation_data is None:
            raise ValueError(
                "regularization_weights sweeps need --validation-data-dirs: "
                "without a validation evaluator there is no way to select "
                "the best of the swept models"
            )
        def _config_with_overrides(overrides) -> dict:
            """raw_config with one sweep point's (or tuning trial's) λ folded
            in, so each saved model's metadata names the configuration that
            trained IT (reference writes per-model modelConfig,
            Driver.scala:419-427). ``overrides`` values may be
            GlmOptimizationConfiguration (sweep) or full
            CoordinateConfiguration (tuning trials, incl. factored matrix λ)."""
            if not overrides:
                return raw_config
            cfg = json.loads(json.dumps(raw_config))
            for cid, o in overrides.items():
                opt = getattr(o, "optimizer", o)
                opt_cfg = cfg["coordinates"][cid].setdefault("optimizer", {})
                opt_cfg.pop("regularization_weights", None)
                opt_cfg["regularization_weight"] = opt.regularization_weight
                matrix = getattr(o, "matrix_optimizer", None)
                if matrix is not None:
                    m_cfg = cfg["coordinates"][cid].setdefault(
                        "matrix_optimizer", {}
                    )
                    m_cfg.pop("regularization_weights", None)
                    m_cfg["regularization_weight"] = matrix.regularization_weight
            return cfg

        def _final_config(overrides) -> dict:
            """_config_with_overrides plus the --auto-tune winner, so the
            saved metadata records exactly what trained the model and the
            pack flow carries the tuned config into the serving artifact."""
            cfg = _config_with_overrides(overrides)
            if tuned_config:
                cfg = dict(cfg)
                cfg["tuned_config"] = dict(tuned_config)
            return cfg

        fit_overrides: Dict[str, object] = {}  # the winning config's map
        all_fits: List[GameFit] = []  # every swept fit, for --model-output-mode ALL
        all_fit_overrides: List[Dict[str, object]] = []  # aligned with all_fits
        if args.streaming and len(sweep_configs) > 1:
            raise ValueError(
                "--streaming does not compose with regularization_weights "
                "sweeps (each swept fit would re-stream the dataset); pick "
                "one weight per coordinate or train in-memory"
            )
        if progress is not None and len(sweep_configs) > 1:
            raise ValueError(
                "--progress-out/--introspect-port track ONE fit's trajectory; "
                "they do not compose with regularization_weights sweeps"
            )
        with profile_ctx, timer.time("fit"):
            if args.streaming:
                fit = estimator.fit_streaming(
                    source,
                    validation_data=validation_data,
                    checkpoint_dir=args.checkpoint_dir,
                    prefetch_depth=args.prefetch_depth,
                    mode=args.stream_mode,
                    gap_schedule=args.gap_schedule,
                    resident_blocks=args.resident_blocks,
                    resident_bytes=args.resident_bytes,
                    progress=progress,
                    cluster=cluster,
                )
                all_fits = [fit]
                all_fit_overrides = [{}]
            elif len(sweep_configs) > 1:
                # one fit per swept configuration, best by the validation
                # evaluator (reference Driver.scala:112 selectBestModel over
                # getAllModelConfigs)
                fits = estimator.fit_multiple(
                    data,
                    validation_data=validation_data,
                    configs=sweep_configs,
                    checkpoint_dir=args.checkpoint_dir,
                )
                for cfg_map, f in zip(sweep_configs, fits):
                    logger.info(
                        "config %s -> metric %s",
                        {c: v.regularization_weight for c, v in cfg_map.items()},
                        "n/a" if f.validation_metric is None else
                        "%.6f" % f.validation_metric,
                    )
                best_i = estimator.select_best_fit(fits)
                if best_i is None:
                    raise ValueError(
                        "no swept fit produced a validation metric; cannot "
                        "select a best model"
                    )
                fit = fits[best_i]
                fit_overrides = sweep_configs[best_i]
                all_fits = list(fits)
                all_fit_overrides = list(sweep_configs)
            else:
                fit = estimator.fit(
                    data,
                    validation_data=validation_data,
                    checkpoint_dir=args.checkpoint_dir,
                    progress=progress,
                )
                all_fits = [fit]
                all_fit_overrides = [{}]
        for cid, value in fit.objective_history:
            cfg = estimator.coordinate_configs.get(cid)
            opt_cfg = fit_overrides.get(cid) or (cfg.optimizer if cfg else None)
            emitter.send_event(PhotonOptimizationLogEvent(
                coordinate_id=cid,
                regularization_weight=(
                    opt_cfg.regularization_weight if opt_cfg else 0.0
                ),
                objective_value=value,
                iterations=-1,  # per-coordinate iteration counts live in trackers
                convergence_reason="",
            ))
            logger.info("objective [%s]: %.6f", cid, value)
        if fit.validation_metric is not None:
            logger.info("validation metric: %.6f", fit.validation_metric)
        logger.info("%s", fit.model.to_summary_string())

        best = fit
        best_overrides: Dict[str, object] = fit_overrides
        if (
            args.hyperparameter_tuning != "NONE"
            and validation_data is not None
            and args.hyperparameter_tuning_iter > 0
        ):
            tuning_kwargs = {}
            if args.regularization_weight_range:
                parts = args.regularization_weight_range.split(",")
                if len(parts) != 2:
                    raise ValueError(
                        "--regularization-weight-range expects lower,upper "
                        f"(e.g. 1e-4,1e4), got {args.regularization_weight_range!r}"
                    )
                lo, hi = float(parts[0]), float(parts[1])
                if not (0 < lo < hi):
                    raise ValueError(
                        f"need 0 < lower < upper, got {lo}, {hi}"
                    )
                tuning_kwargs["log10_range"] = (np.log10(lo), np.log10(hi))
            with timer.time("hyperparameter tuning"):
                trials = run_hyperparameter_tuning(
                    estimator, data, validation_data,
                    mode=args.hyperparameter_tuning,
                    num_iterations=args.hyperparameter_tuning_iter,
                    prior_fits=[fit],
                    warm_start=args.use_warm_start,
                    **tuning_kwargs,
                )
            for t in trials:
                logger.info(
                    "trial lambda=%s metric=%.6f",
                    ["%.4g" % (10.0 ** v) for v in t.hyperparameters], t.value,
                )
            # trial hyperparameters → per-coordinate configs so the winning
            # trial's λ lands in the saved metadata too
            from photon_ml_tpu.estimators.tuning import (
                GameEstimatorEvaluationFunction,
            )

            to_configs = GameEstimatorEvaluationFunction(
                estimator, None, None
            ).vector_to_configuration
            candidates = [(fit, fit_overrides)] + [
                (t.fit, to_configs(t.hyperparameters)) for t in trials
            ]
            better = estimator.evaluator.better_than
            for c, ovr in candidates:
                if c.validation_metric is not None and (
                    best.validation_metric is None
                    or better(c.validation_metric, best.validation_metric)
                ):
                    best = c
                    best_overrides = ovr

        if args.model_output_mode != "NONE":
            with timer.time("save model"):
                save_game_model(
                    best.model,
                    os.path.join(args.output_dir, "best"),
                    index_maps=index_maps,
                    model_name=args.model_name,
                    configurations=_final_config(best_overrides),
                    num_output_files_per_random_effect=(
                        args.num_output_files_for_random_effect_model
                    ),
                )
                if args.model_output_mode == "ALL":
                    # reference Driver.scala:416-433: every swept
                    # configuration's model under <output>/all/<i>, each with
                    # the metadata of its own configuration
                    for i, (f, ovr) in enumerate(
                        zip(all_fits, all_fit_overrides)
                    ):
                        save_game_model(
                            f.model,
                            os.path.join(args.output_dir, "all", str(i)),
                            index_maps=index_maps,
                            model_name=args.model_name,
                            configurations=_final_config(ovr),
                            num_output_files_per_random_effect=(
                                args.num_output_files_for_random_effect_model
                            ),
                        )
            logger.info("model saved to %s", os.path.join(args.output_dir, "best"))
        emitter.send_event(TrainingFinishEvent(
            task=task.name, wall_seconds=time.perf_counter() - t_start
        ))
        for name, seconds in timer.durations.items():
            logger.info("timing %-28s %.3fs", name, seconds)
        return best
    finally:
        if cluster is not None:
            cluster.close()
        # the introspection hold runs first, so an operator can still read
        # /healthz (503 after a divergence abort) and /progress before the
        # plane tears down
        if introspect is not None:
            if args.introspect_hold > 0:
                introspect.wait_quit(args.introspect_hold)
            introspect.stop()
        if progress is not None:
            progress.finish()
        # listeners must flush/close even when the run fails; telemetry
        # finishes after them so every bridged event is in the ledger
        emitter.clear_listeners()
        if (
            telemetry is not None
            and progress is not None
            and progress.cluster_passes
        ):
            from photon_ml_tpu.telemetry import cluster_lane_events

            # per-host lanes (pid = 1 + host) alongside the coordinator's
            # own spans in the Chrome trace
            telemetry.add_trace_events(
                cluster_lane_events(
                    progress.cluster_passes,
                    origin_unix=telemetry.tracer.origin_unix,
                )
            )
        finish_telemetry(telemetry, phases=dict(timer.durations))


def main(argv: Optional[List[str]] = None) -> int:
    from photon_ml_tpu.parallel.multihost import initialize_from_args
    from photon_ml_tpu.telemetry import DivergenceError

    args = parse_args(argv)
    # cluster join (or single-process no-op) must precede any jax device use
    initialize_from_args(args)
    try:
        run(args)
    except DivergenceError as e:
        # the watchdog already wrote the anomaly record and flipped
        # /healthz; abort without a model artifact rather than save garbage
        print(f"training aborted by divergence watchdog: {e}", file=sys.stderr)
        return 2
    return 0


if __name__ == "__main__":
    sys.exit(main())
