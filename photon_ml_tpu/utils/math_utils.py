"""Numerically-stable scalar math helpers.

Reference parity: photon-lib util/MathUtils.scala (log1pExp) plus small
helpers used throughout the objective/optimizer stack.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def log1p_exp(x: jax.Array) -> jax.Array:
    """log(1 + exp(x)) without overflow (reference MathUtils.log1pExp).

    Implemented via ``jax.nn.softplus`` which is the numerically-stable
    formulation ``max(x, 0) + log1p(exp(-|x|))``.
    """
    return jax.nn.softplus(x)


def is_almost_zero(x: jax.Array, eps: float = 1e-15) -> jax.Array:
    """|x| < eps (reference MathUtils.isAlmostZero)."""
    return jnp.abs(x) < eps


def safe_div(num: jax.Array, den: jax.Array, eps: float = 1e-15) -> jax.Array:
    """num / den, returning 0 where |den| < eps (used for masked means)."""
    safe_den = jnp.where(jnp.abs(den) < eps, 1.0, den)
    return jnp.where(jnp.abs(den) < eps, 0.0, num / safe_den)
