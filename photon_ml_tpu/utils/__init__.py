from photon_ml_tpu.utils import math_utils
from photon_ml_tpu.utils.timer import Timed, Timer

__all__ = ["math_utils", "Timed", "Timer"]
