"""Phase timing utilities (reference util/Timed.scala:33, Timer.scala:182).

The reference wraps every driver phase in ``Timed { }`` blocks writing to a
driver-side logger; here the same pattern is a context manager that logs
wall-clock per phase and can be queried afterwards (bench/driver code uses it).

Both ``Timer`` and ``Timed`` are thin shims over the telemetry span API
(:mod:`photon_ml_tpu.telemetry.span`) so there is exactly ONE timing path:
when span tracing is enabled each phase also lands in the trace/ledger as a
span; when disabled the span still measures but records nowhere but here.
``Timer`` is thread-safe and keeps phases that raise (accumulated in
``durations`` as before, flagged in ``failures``).
"""

from __future__ import annotations

import logging
import threading
from contextlib import contextmanager
from typing import Dict, Iterator

from photon_ml_tpu.telemetry.span import timed_span

logger = logging.getLogger("photon_ml_tpu")


class Timer:
    """Accumulates named phase durations (thread-safe). Phases that raise
    are still accumulated and additionally counted in ``failures``."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.durations: Dict[str, float] = {}
        self.failures: Dict[str, int] = {}

    @contextmanager
    def time(self, name: str) -> Iterator[None]:
        sp = timed_span(name)
        try:
            with sp:
                yield
        finally:
            with self._lock:
                self.durations[name] = (
                    self.durations.get(name, 0.0) + sp.duration_s
                )
                if sp.failed:
                    self.failures[name] = self.failures.get(name, 0) + 1
            if sp.failed:
                logger.info(
                    "phase %s FAILED (%s) after %.3fs",
                    name, sp.error, sp.duration_s,
                )
            else:
                logger.info("phase %s took %.3fs", name, sp.duration_s)

    def failed(self, name: str) -> bool:
        """True when at least one run of ``name`` raised."""
        with self._lock:
            return self.failures.get(name, 0) > 0


@contextmanager
def Timed(name: str) -> Iterator[None]:
    """Standalone timed block, logging at INFO."""
    sp = timed_span(name)
    try:
        with sp:
            yield
    finally:
        if sp.failed:
            logger.info(
                "phase %s FAILED (%s) after %.3fs", name, sp.error, sp.duration_s
            )
        else:
            logger.info("phase %s took %.3fs", name, sp.duration_s)
