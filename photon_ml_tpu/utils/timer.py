"""Phase timing utilities (reference util/Timed.scala:33, Timer.scala:182).

The reference wraps every driver phase in ``Timed { }`` blocks writing to a
driver-side logger; here the same pattern is a context manager that logs
wall-clock per phase and can be queried afterwards (bench/driver code uses it).
"""

from __future__ import annotations

import logging
import time
from contextlib import contextmanager
from typing import Dict, Iterator

logger = logging.getLogger("photon_ml_tpu")


class Timer:
    """Accumulates named phase durations."""

    def __init__(self) -> None:
        self.durations: Dict[str, float] = {}

    @contextmanager
    def time(self, name: str) -> Iterator[None]:
        start = time.perf_counter()
        try:
            yield
        finally:
            elapsed = time.perf_counter() - start
            self.durations[name] = self.durations.get(name, 0.0) + elapsed
            logger.info("phase %s took %.3fs", name, elapsed)


@contextmanager
def Timed(name: str) -> Iterator[None]:
    """Standalone timed block, logging at INFO."""
    start = time.perf_counter()
    try:
        yield
    finally:
        logger.info("phase %s took %.3fs", name, time.perf_counter() - start)
