"""Date ranges as dataset coordinates.

Reference parity: util/DateRange.scala (range specs ``yyyyMMdd-yyyyMMdd``
and days-ago ``start-end``), IOUtils.getInputPathsWithinDateRange (daily
``<base>/yyyy/MM/dd`` subdirectories), and GameDriver.pathsForDateRange
(GameDriver.scala:103: date-range XOR days-ago, else the base dirs as-is;
missing daily dirs tolerated).
"""

from __future__ import annotations

import dataclasses
import datetime
import os
from typing import List, Optional, Sequence


@dataclasses.dataclass(frozen=True)
class DateRange:
    start_date: datetime.date
    end_date: datetime.date

    def __post_init__(self) -> None:
        if self.start_date > self.end_date:
            raise ValueError(
                f"invalid range: start date {self.start_date} comes after "
                f"end date {self.end_date}"
            )

    def __str__(self) -> str:
        return f"{self.start_date}-{self.end_date}"

    def days(self):
        d = self.start_date
        while d <= self.end_date:
            yield d
            d += datetime.timedelta(days=1)

    @classmethod
    def from_dates(cls, spec: str) -> "DateRange":
        """``yyyyMMdd-yyyyMMdd``."""
        try:
            start, end = spec.split("-", 1)
            fmt = "%Y%m%d"
            return cls(
                datetime.datetime.strptime(start.strip(), fmt).date(),
                datetime.datetime.strptime(end.strip(), fmt).date(),
            )
        except (ValueError, AttributeError) as e:
            raise ValueError(f"couldn't parse the date range: {spec}") from e

    @classmethod
    def from_days_ago(
        cls, spec: str, today: Optional[datetime.date] = None
    ) -> "DateRange":
        """``startDaysAgo-endDaysAgo`` (e.g. ``90-1``)."""
        today = today or datetime.date.today()
        try:
            start_ago, end_ago = (int(x) for x in spec.split("-", 1))
        except ValueError as e:
            raise ValueError(f"couldn't parse days ago: {spec}") from e
        if start_ago < 0 or end_ago < 0:
            raise ValueError("days ago cannot be negative")
        return cls(
            today - datetime.timedelta(days=start_ago),
            today - datetime.timedelta(days=end_ago),
        )


def input_paths_within_date_range(
    base_dirs: Sequence[str],
    date_range: DateRange,
    error_on_missing: bool = False,
) -> List[str]:
    """``<base>/yyyy/MM/dd`` per day in range; missing days skipped unless
    ``error_on_missing``."""
    out: List[str] = []
    for base in base_dirs:
        for day in date_range.days():
            p = os.path.join(
                base, f"{day.year:04d}", f"{day.month:02d}", f"{day.day:02d}"
            )
            if os.path.isdir(p):
                out.append(p)
            elif error_on_missing:
                raise FileNotFoundError(p)
    return out


def paths_for_date_range(
    base_dirs: Sequence[str],
    date_range_spec: Optional[str] = None,
    days_ago_spec: Optional[str] = None,
    today: Optional[datetime.date] = None,
) -> List[str]:
    """GameDriver.pathsForDateRange: range XOR days-ago, else base dirs."""
    if date_range_spec and days_ago_spec:
        raise ValueError(
            "both date range and days ago given; specify only one format"
        )
    if date_range_spec:
        rng = DateRange.from_dates(date_range_spec)
    elif days_ago_spec:
        rng = DateRange.from_days_ago(days_ago_spec, today=today)
    else:
        return list(base_dirs)
    return input_paths_within_date_range(base_dirs, rng)
