"""Threaded native argsort for the big host-side prep sorts.

``lexsort_pairs(major, minor)`` == ``np.lexsort((minor, major))`` (sort by
major, ties by minor, stable) but runs the threaded C++ radix sort in
``native/sortperm.cpp`` when it can be built and the keys are non-negative
int64 — the routing/tiling prep's dominant cost at 1e7+ nnz. Falls back to
``np.lexsort`` transparently (negative keys, no toolchain, tiny inputs).
"""

from __future__ import annotations

import ctypes
import logging
from pathlib import Path
from typing import Optional

import numpy as np

from photon_ml_tpu.utils.nativelib import build_and_load

logger = logging.getLogger(__name__)

_NATIVE_DIR = Path(__file__).resolve().parent.parent / "native"
_SRC = _NATIVE_DIR / "sortperm.cpp"
_LIB = _NATIVE_DIR / "_sortperm.so"

_lib: Optional[ctypes.CDLL] = None
_lib_tried = False

# below this the fallback's constant factors win and threading is noise
_MIN_NATIVE = 1 << 16


def _load_native():
    global _lib, _lib_tried
    if _lib_tried:
        return _lib
    _lib_tried = True
    lib = build_and_load(_SRC, _LIB)
    if lib is not None:
        lib.argsort_pairs.restype = ctypes.c_int
        lib.argsort_pairs.argtypes = [
            ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int,
        ]
    _lib = lib
    return _lib


def lexsort_pairs(major: np.ndarray, minor: Optional[np.ndarray] = None) -> np.ndarray:
    """Stable argsort by (major, minor); equivalent to
    ``np.lexsort((minor, major))`` / ``np.argsort(major, kind="stable")``."""
    major = np.ascontiguousarray(major, dtype=np.int64)
    n = major.shape[0]
    use_native = n >= _MIN_NATIVE and (n == 0 or major.min() >= 0)
    if minor is not None:
        minor = np.ascontiguousarray(minor, dtype=np.int64)
        if minor.shape[0] != n:
            raise ValueError(
                f"minor key length {minor.shape[0]} != major length {n}"
            )
        use_native = use_native and (n == 0 or minor.min() >= 0)
    if use_native:
        lib = _load_native()
        if lib is not None:
            import os

            out = np.empty(n, dtype=np.int64)
            i64p = ctypes.POINTER(ctypes.c_int64)
            rc = lib.argsort_pairs(
                ctypes.c_int64(n),
                major.ctypes.data_as(i64p),
                minor.ctypes.data_as(i64p) if minor is not None else None,
                out.ctypes.data_as(i64p),
                ctypes.c_int(max(1, min(os.cpu_count() or 1, 16))),
            )
            if rc == 0:
                return out
            logger.warning("native argsort_pairs rc=%d; numpy fallback", rc)
    if minor is None:
        return np.argsort(major, kind="stable")
    return np.lexsort((minor, major))
