"""Per-uid on-disk cache directories (routing plans, compiled executables).

Shared safety rules: directories live under the system tempdir with the uid
in the name, are created 0700, and are refused if owned by someone else or
writable by group/other (a pre-planted directory in the sticky shared
tempdir must never be trusted).
"""

from __future__ import annotations

import os
import stat
import tempfile
from typing import Optional


def per_uid_cache_dir(name: str) -> Optional[str]:
    """``$TMPDIR/<name>_<uid>`` created 0700, or None when unavailable."""
    uid = os.getuid() if hasattr(os, "getuid") else 0
    path = os.path.join(tempfile.gettempdir(), f"{name}_{uid}")
    try:
        os.makedirs(path, mode=0o700, exist_ok=True)
        st = os.stat(path)
        if st.st_uid != uid or (st.st_mode & (stat.S_IWGRP | stat.S_IWOTH)):
            return None
    except OSError:
        return None
    return path


def enable_compilation_cache() -> Optional[str]:
    """Point JAX's persistent compilation cache at a per-uid directory so
    repeat CLI runs skip the 20-40s first-compile cost on TPU.

    $PHOTON_ML_TPU_COMPILE_CACHE overrides the location ("" disables).
    Returns the directory in use, or None when disabled/unavailable.
    """
    env = os.environ.get("PHOTON_ML_TPU_COMPILE_CACHE")
    if env is not None:
        path = env or None
    else:
        path = per_uid_cache_dir("photon_ml_tpu_compile_cache")
    if not path:
        return None
    try:
        import jax

        jax.config.update("jax_compilation_cache_dir", path)
        # cache every compilation that takes meaningful time, not only the
        # very slow ones (the default min time is 1s; GLM solves compile in
        # the 2-40s range and all benefit)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    except Exception:  # pragma: no cover - very old jax
        return None
    return path
