"""One hardened build-and-load path for the in-tree C++ components.

Every native module (Euler-coloring router, off-heap index store, columnar
Avro decoder, radix argsort) needs the same thing: compile ``<name>.cpp``
next to it into ``_<name>.so`` when missing or stale, then ``CDLL`` it.
Doing that safely requires building to a temp file and atomically renaming
— concurrent builders (multihost launches, pytest workers) must never CDLL
or cache a half-written .so. This helper is that pattern, once.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import tempfile
from pathlib import Path
from typing import Optional, Sequence

logger = logging.getLogger(__name__)

_DEFAULT_FLAGS = ("-O3", "-std=c++17", "-shared", "-fPIC", "-pthread")


def build_and_load(
    src: Path,
    lib_path: Path,
    flags: Sequence[str] = _DEFAULT_FLAGS,
    ldflags: Sequence[str] = (),
) -> Optional[ctypes.CDLL]:
    """Compile ``src`` to ``lib_path`` (if missing/stale) and CDLL it.

    ``ldflags`` (e.g. ``("-lz",)``) are placed AFTER the source on the
    command line — with ``--as-needed`` linkers a library named before the
    objects that use it is silently dropped.

    Returns None when the toolchain is unavailable or the build fails —
    callers keep a pure-Python fallback. Never leaves a half-written .so
    visible at ``lib_path``.
    """
    try:
        if not lib_path.exists() or lib_path.stat().st_mtime < src.stat().st_mtime:
            fd, tmp = tempfile.mkstemp(
                suffix=".so", dir=str(lib_path.parent),
                prefix=f"._{src.stem}_",
            )
            os.close(fd)
            try:
                subprocess.run(
                    ["g++", *flags, "-o", tmp, str(src), *ldflags],
                    check=True,
                    capture_output=True,
                )
                os.replace(tmp, str(lib_path))
            finally:
                if os.path.exists(tmp):
                    os.unlink(tmp)
        return ctypes.CDLL(str(lib_path))
    except Exception as e:  # pragma: no cover - toolchain-dependent
        logger.info("native build of %s unavailable (%s)", src.name, e)
        return None
