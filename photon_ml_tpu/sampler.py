"""Down-samplers: pre-optimization data reduction.

Reference parity: sampler/DownSampler.scala:27 (interface),
DefaultDownSampler.scala:27 (uniform sampling with weight re-scale) and
BinaryClassificationDownSampler.scala:32 (samples only negatives, keeps every
positive, and re-scales surviving negative weights so the objective stays an
unbiased estimate).

TPU-first design: shapes under jit must stay static, and every objective in
this framework treats weight-0 rows as algebraic no-ops (ops/data.py). So a
"down-sampled dataset" here is the SAME batch with dropped rows' weights set
to 0 and survivors' weights re-scaled — no compaction, no recompile. The
reference instead materializes a smaller RDD; the weight algebra is identical
(DownSampler re-scales by 1/rate in both designs).
"""

from __future__ import annotations

import abc
import dataclasses

import numpy as np

from photon_ml_tpu.types import POSITIVE_RESPONSE_THRESHOLD, TaskType


class DownSampler(abc.ABC):
    """Weight-masking down-sampler (reference DownSampler.scala:27)."""

    @abc.abstractmethod
    def sample_weights(
        self, labels: np.ndarray, weights: np.ndarray, seed: int
    ) -> np.ndarray:
        """Return new per-row weights: 0 for dropped rows, re-scaled for
        survivors, untouched for rows outside the sampled class."""


@dataclasses.dataclass(frozen=True)
class DefaultDownSampler(DownSampler):
    """Uniform row sampling at ``rate`` with 1/rate weight re-scale
    (reference DefaultDownSampler.scala:27)."""

    down_sampling_rate: float

    def __post_init__(self) -> None:
        if not 0.0 < self.down_sampling_rate < 1.0:
            raise ValueError(
                f"down_sampling_rate must be in (0, 1), got {self.down_sampling_rate}"
            )

    def sample_weights(
        self, labels: np.ndarray, weights: np.ndarray, seed: int
    ) -> np.ndarray:
        rng = np.random.default_rng(seed)
        keep = rng.random(labels.shape[0]) < self.down_sampling_rate
        return np.where(keep, weights / self.down_sampling_rate, 0.0).astype(
            np.float32
        )


@dataclasses.dataclass(frozen=True)
class BinaryClassificationDownSampler(DownSampler):
    """Negatives-only sampling for class-imbalanced binary tasks (reference
    BinaryClassificationDownSampler.scala:32): positives always survive with
    unchanged weight; negatives survive with probability ``rate`` and weight
    scaled by 1/rate."""

    down_sampling_rate: float

    def __post_init__(self) -> None:
        if not 0.0 < self.down_sampling_rate < 1.0:
            raise ValueError(
                f"down_sampling_rate must be in (0, 1), got {self.down_sampling_rate}"
            )

    def sample_weights(
        self, labels: np.ndarray, weights: np.ndarray, seed: int
    ) -> np.ndarray:
        rng = np.random.default_rng(seed)
        negative = labels < POSITIVE_RESPONSE_THRESHOLD
        keep_negative = rng.random(labels.shape[0]) < self.down_sampling_rate
        out = np.where(
            negative,
            np.where(keep_negative, weights / self.down_sampling_rate, 0.0),
            weights,
        )
        return out.astype(np.float32)


def down_sampler_for(task: TaskType, rate: float) -> DownSampler:
    """Pick the sampler the reference picks (DistributedOptimizationProblem
    factory :172-197: binary-classification sampler for classification tasks,
    default otherwise)."""
    if task.is_classification:
        return BinaryClassificationDownSampler(rate)
    return DefaultDownSampler(rate)
