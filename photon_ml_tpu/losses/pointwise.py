"""Pointwise GLM losses: l(z, y) and its first/second derivatives w.r.t. the
margin z.

Reference parity: function/glm/PointwiseLossFunction.scala:36 (`lossAndDzLoss`,
`DzzLoss`) with implementations LogisticLossFunction.scala:45 (numerically
stable via log1pExp), SquaredLossFunction.scala:32, PoissonLossFunction.scala:31
and the Rennie smoothed hinge (svm/SmoothedHingeLossFunction.scala:30).

Each loss is a plain class of static vectorized functions so it can be closed
over in jit as a static argument. Labels follow reference conventions:
logistic/hinge labels are {0, 1} (hinge converts to ±1 internally).
"""

from __future__ import annotations

from typing import Tuple, Type

import jax
import jax.numpy as jnp

from photon_ml_tpu.types import TaskType
from photon_ml_tpu.utils.math_utils import log1p_exp


class PointwiseLoss:
    """Interface: value(z, y), d1(z, y), d2(z, y) — all elementwise."""

    #: whether d2 is available (hinge is DiffFunction-only in the reference,
    #: so TRON must be rejected for it: OptimizerFactory.scala)
    has_hessian: bool = True

    @staticmethod
    def value(z: jax.Array, y: jax.Array) -> jax.Array:
        raise NotImplementedError

    @staticmethod
    def d1(z: jax.Array, y: jax.Array) -> jax.Array:
        raise NotImplementedError

    @staticmethod
    def d2(z: jax.Array, y: jax.Array) -> jax.Array:
        raise NotImplementedError


class LogisticLoss(PointwiseLoss):
    """Negative log-likelihood of the Bernoulli/logit model, y in {0, 1}.

    l(z, y) = log(1 + e^z) - y*z  (stable form; reference
    LogisticLossFunction.scala:70-77 branches on the label sign to avoid
    overflow — softplus does the equivalent internally).
    """

    @staticmethod
    def value(z: jax.Array, y: jax.Array) -> jax.Array:
        # y=1: log1pExp(-z); y=0: log1pExp(z). Both equal softplus(z) - y*z.
        return log1p_exp(z) - y * z

    @staticmethod
    def d1(z: jax.Array, y: jax.Array) -> jax.Array:
        return jax.nn.sigmoid(z) - y

    @staticmethod
    def d2(z: jax.Array, y: jax.Array) -> jax.Array:
        s = jax.nn.sigmoid(z)
        return s * (1.0 - s)


class SquaredLoss(PointwiseLoss):
    """l(z, y) = (z - y)^2 / 2 (reference SquaredLossFunction.scala:32)."""

    @staticmethod
    def value(z: jax.Array, y: jax.Array) -> jax.Array:
        d = z - y
        return 0.5 * d * d

    @staticmethod
    def d1(z: jax.Array, y: jax.Array) -> jax.Array:
        return z - y

    @staticmethod
    def d2(z: jax.Array, y: jax.Array) -> jax.Array:
        return jnp.ones_like(z)


class PoissonLoss(PointwiseLoss):
    """l(z, y) = e^z - y*z (reference PoissonLossFunction.scala:31)."""

    @staticmethod
    def value(z: jax.Array, y: jax.Array) -> jax.Array:
        return jnp.exp(z) - y * z

    @staticmethod
    def d1(z: jax.Array, y: jax.Array) -> jax.Array:
        return jnp.exp(z) - y

    @staticmethod
    def d2(z: jax.Array, y: jax.Array) -> jax.Array:
        return jnp.exp(z)


class SmoothedHingeLoss(PointwiseLoss):
    """Rennie smoothed hinge, labels {0,1} mapped to t=±1 (reference
    svm/SmoothedHingeLossFunction.scala:30). With u = t*z:

        l = 0          if u >= 1
        l = (1-u)^2/2  if 0 < u < 1
        l = 1/2 - u    if u <= 0

    First-derivative only (no Hessian in the reference either).
    """

    has_hessian = False

    @staticmethod
    def _t(y: jax.Array) -> jax.Array:
        return jnp.where(y > 0.5, 1.0, -1.0)

    @staticmethod
    def value(z: jax.Array, y: jax.Array) -> jax.Array:
        u = SmoothedHingeLoss._t(y) * z
        quad = 0.5 * (1.0 - u) * (1.0 - u)
        return jnp.where(u >= 1.0, 0.0, jnp.where(u <= 0.0, 0.5 - u, quad))

    @staticmethod
    def d1(z: jax.Array, y: jax.Array) -> jax.Array:
        t = SmoothedHingeLoss._t(y)
        u = t * z
        dz_du = jnp.where(u >= 1.0, 0.0, jnp.where(u <= 0.0, -1.0, u - 1.0))
        return dz_du * t

    @staticmethod
    def d2(z: jax.Array, y: jax.Array) -> jax.Array:
        # Not used by LBFGS/OWLQN; provided for completeness (piecewise 2nd
        # derivative of the quadratic region).
        u = SmoothedHingeLoss._t(y) * z
        return jnp.where((u > 0.0) & (u < 1.0), 1.0, 0.0)


def loss_for_task(task: TaskType) -> Type[PointwiseLoss]:
    """TaskType -> loss class (reference ModelTraining.scala:127-149)."""
    return {
        TaskType.LOGISTIC_REGRESSION: LogisticLoss,
        TaskType.LINEAR_REGRESSION: SquaredLoss,
        TaskType.POISSON_REGRESSION: PoissonLoss,
        TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM: SmoothedHingeLoss,
    }[task]


def mean_function(task: TaskType, z: jax.Array) -> jax.Array:
    """Link-inverse posterior mean (reference GeneralizedLinearModel.scala:68-117).

    logistic -> sigmoid, poisson -> exp, linear/SVM -> identity margin.
    """
    if task is TaskType.LOGISTIC_REGRESSION:
        return jax.nn.sigmoid(z)
    if task is TaskType.POISSON_REGRESSION:
        return jnp.exp(z)
    return z
