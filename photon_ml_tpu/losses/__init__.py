from photon_ml_tpu.losses.pointwise import (
    LogisticLoss,
    PointwiseLoss,
    PoissonLoss,
    SmoothedHingeLoss,
    SquaredLoss,
    loss_for_task,
)
from photon_ml_tpu.normalization import NormalizationContext
from photon_ml_tpu.losses.objective import GlmObjective, make_glm_objective

__all__ = [
    "LogisticLoss",
    "PointwiseLoss",
    "PoissonLoss",
    "SmoothedHingeLoss",
    "SquaredLoss",
    "loss_for_task",
    "NormalizationContext",
    "GlmObjective",
    "make_glm_objective",
]
