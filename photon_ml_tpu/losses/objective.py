"""The GLM objective: value / gradient / Hessian-vector / Hessian-diagonal.

Reference parity: this is the fusion of function/glm/{ValueAndGradient,
HessianVector,HessianDiagonal}Aggregator.scala (the per-partition compute
kernels) with function/L2Regularization.scala (stackable L2 term) and
DistributedGLMLossFunction.scala / SingleNodeGLMLossFunction.scala (the
distributed/local bindings). On TPU there is no distributed/local split at
this layer: the same jit-compiled functions run on one chip, inside ``vmap``
for per-entity solves, or inside ``shard_map`` with a ``psum`` over the batch
axis for the sharded fixed effect (dist/sharded_objective.py).

Semantics (matching the reference exactly):
- objective(w) = sum_i weight_i * l(z_i, y_i) + 0.5 * l2 * ||w||^2
- z_i = x_i . (factor .* w) - shift . (factor .* w) + offset_i
- L1 is NOT part of the smooth objective; OWL-QN handles it at the optimizer
  level (reference OWLQN.scala:40).

``l2_weight`` is a traced scalar argument so λ sweeps reuse one compiled
program (reference updateRegularizationWeight,
DistributedOptimizationProblem.scala:60-71).
"""

from __future__ import annotations

from typing import NamedTuple, Tuple, Type

import jax
import jax.numpy as jnp

from photon_ml_tpu.normalization import NormalizationContext
from photon_ml_tpu.losses.pointwise import PointwiseLoss
from photon_ml_tpu.ops.data import LabeledData
from photon_ml_tpu.ops.features import DenseFeatures

_IDENTITY_NORM = NormalizationContext()


def _norm_of(data: LabeledData) -> NormalizationContext:
    return data.norm if data.norm is not None else _IDENTITY_NORM


class GlmObjective(NamedTuple):
    """Bundle of pure functions; pass as a static closure into optimizers.

    The NormalizationContext is read from ``data.norm`` so that factor/shift
    arrays are traced jit arguments, not compile-time constants.
    """

    value: "callable"          # (w, data, l2) -> scalar
    value_and_grad: "callable"  # (w, data, l2) -> (scalar, [d])
    hessian_vec: "callable"    # (w, v, data, l2) -> [d]
    hessian_diag: "callable"   # (w, data, l2) -> [d]
    has_hessian: bool


def make_glm_objective(
    loss: Type[PointwiseLoss], use_pallas: bool = None
) -> GlmObjective:
    """``use_pallas``: route eligible dense problems through the fused
    pallas kernel; None (default) defers to the PHOTON_ML_TPU_PALLAS flag
    (ops/pallas_kernels.enabled), read once at objective construction."""
    def margins(w: jax.Array, data: LabeledData) -> jax.Array:
        norm = _norm_of(data)
        ew = norm.effective_coefficients(w)
        return data.features.matvec(ew) - norm.margin_shift(ew) + data.offsets

    def _wmask(weights: jax.Array, terms: jax.Array) -> jax.Array:
        # weight-0 padding rows must be exact no-ops even when the unweighted
        # term overflows to inf (0 * inf = NaN would poison the sum)
        return jnp.where(weights > 0, weights * terms, 0.0)

    def value(w: jax.Array, data: LabeledData, l2: jax.Array) -> jax.Array:
        z = margins(w, data)
        loss_sum = jnp.sum(_wmask(data.weights, loss.value(z, data.labels)))
        return loss_sum + 0.5 * l2 * jnp.dot(w, w)

    if use_pallas is None:
        from photon_ml_tpu.ops import pallas_kernels

        use_pallas = pallas_kernels.enabled()

    def value_and_grad(
        w: jax.Array, data: LabeledData, l2: jax.Array
    ) -> Tuple[jax.Array, jax.Array]:
        norm = _norm_of(data)
        if (
            use_pallas
            and isinstance(data.features, DenseFeatures)
            and data.features.matrix.ndim == 2
            and norm.is_identity
        ):
            # fused MXU kernel: one HBM pass over X for value + gradient
            # (None => problem too large for the chip-local kernel; use XLA)
            from photon_ml_tpu.ops.pallas_kernels import fused_value_grad_auto

            fused = fused_value_grad_auto(
                data.features.matrix, data.labels, data.offsets,
                data.weights, w, kind=loss,
            )
            if fused is not None:
                loss_sum, raw, _ = fused
                return loss_sum + 0.5 * l2 * jnp.dot(w, w), raw + l2 * w
        z = margins(w, data)
        loss_sum = jnp.sum(_wmask(data.weights, loss.value(z, data.labels)))
        c = _wmask(data.weights, loss.d1(z, data.labels))
        raw = data.features.rmatvec(c)
        grad = norm.apply_to_gradient(raw, jnp.sum(c))
        return loss_sum + 0.5 * l2 * jnp.dot(w, w), grad + l2 * w

    def hessian_vec(
        w: jax.Array, v: jax.Array, data: LabeledData, l2: jax.Array
    ) -> jax.Array:
        """Gauss-Newton/true Hessian-vector product via the analytic d2z form
        (reference HessianVectorAggregator.scala:36): Hv = J^T diag(w_i d2_i) J v
        where J is the normalized feature map."""
        norm = _norm_of(data)
        z = margins(w, data)
        ev = norm.effective_coefficients(v)
        zv = data.features.matvec(ev) - norm.margin_shift(ev)
        c2 = _wmask(data.weights, loss.d2(z, data.labels) * zv)
        raw = data.features.rmatvec(c2)
        return norm.apply_to_gradient(raw, jnp.sum(c2)) + l2 * v

    def hessian_diag(w: jax.Array, data: LabeledData, l2: jax.Array) -> jax.Array:
        """diag(H)_j = sum_i a_i * ((x_ij - s_j) f_j)^2 + l2, a_i = weight_i*d2_i
        (reference HessianDiagonalAggregator.scala:33; used for coefficient
        variances, DistributedOptimizationProblem.scala:80-94).

        Expanded so sparse layouts never densify:
        sum a (x-s)^2 = (X*X)^T a - 2 s * (X^T a) + s^2 * sum(a).
        """
        norm = _norm_of(data)
        z = margins(w, data)
        a = _wmask(data.weights, loss.d2(z, data.labels))
        sq = data.features.rmatvec_sq(a)
        if norm.shift is not None:
            lin = data.features.rmatvec(a)
            sq = sq - 2.0 * norm.shift * lin + norm.shift * norm.shift * jnp.sum(a)
        if norm.factor is not None:
            sq = sq * norm.factor * norm.factor
        return sq + l2

    return GlmObjective(
        value=value,
        value_and_grad=value_and_grad,
        hessian_vec=hessian_vec,
        hessian_diag=hessian_diag,
        has_hessian=loss.has_hessian,
    )
