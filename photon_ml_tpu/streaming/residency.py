"""Hierarchical device residency for streamed training (DuHL / Snap ML).

Streamed solves re-upload every block to the device on every pass even
though the per-block duality-gap probe says exactly which blocks still
carry objective mass. "Large-Scale Stochastic Learning using GPUs"
(arXiv 1702.07005) keeps only the largest-gap working set device-resident;
Snap ML (arXiv 1803.06333) frames the system as a hierarchy of data
partitions — disk, host RAM, device HBM — with the next level's transfer
pipelined under the current level's solve. This module is the HBM level
plus the interface that unifies all three:

* :class:`ResidencyManager` owns a bounded set of device-resident
  ``DeviceBlock`` uploads (capped by a block and/or byte budget). Resident
  blocks keep their BASE offsets — the CD residual is re-fused per pass by
  the existing fixed-shape program, so persistence never staleness-poisons
  the objective — and are served straight from HBM, skipping their
  ``device_put`` entirely. The non-resident remainder streams through the
  ordinary double-buffered prefetcher, whose H2D overlaps the resident
  blocks' solve work.
* The resident set is picked from staleness-decayed per-block gap
  estimates (the same ``score · decay^age`` bookkeeping as the stochastic
  :class:`~photon_ml_tpu.streaming.gapsched.GapScheduler`); re-pinning
  happens only between epochs (``repin``), never mid-pass, so a pass's
  arithmetic visit order — and therefore the accumulation trajectory — is
  untouched by eviction.
* :func:`residency_hierarchy` reports per-level hit/byte accounting for
  the three levels that already exist separately: the mmap ``BlockCache``
  (disk), the decode-pool file LRU (RAM), and the resident set (HBM).

Everything here is host-side numpy/dict bookkeeping: no jitted program is
added, so the zero-retrace contract is unaffected, and with no manager
attached the streamed coordinate's code path is bitwise identical to
before (the CI residency parity gate pins this).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

import numpy as np

from photon_ml_tpu.telemetry import get_registry


@dataclasses.dataclass
class ResidencyStats:
    """Host-side accounting of one manager's lifetime."""

    hbm_hit_blocks: int = 0    # block serves that skipped device_put
    hbm_hit_bytes: int = 0     # H2D bytes those serves avoided
    stored_blocks: int = 0     # uploads retained as resident (pins)
    evicted_blocks: int = 0    # residents dropped (gap decay or failure)
    repins: int = 0            # between-epoch re-pin rounds


class ResidencyManager:
    """Gap-pinned bounded set of device-resident blocks.

    Parameters
    ----------
    num_blocks:
        Blocks in the streamed plan (fixed for the manager's lifetime).
    block_bytes:
        H2D bytes of ONE uploaded block for the shard(s) this manager
        serves. Block shapes are fixed by the plan, so the per-block cost
        is uniform and the byte budget reduces to a block budget.
    max_blocks:
        Resident-block cap; 0 means "bytes only".
    max_bytes:
        Resident-byte cap; ``None`` means "blocks only". The effective
        capacity is the tighter of the two, and must admit at least one
        block — a residency plane that can pin nothing is a
        misconfiguration, not a silent no-op.
    decay:
        Per-epoch staleness discount on a block's last measured gap
        (``score · decay^age``), mirroring the GapScheduler: a once-hot
        block cannot stay pinned forever on stale evidence.
    """

    def __init__(
        self,
        num_blocks: int,
        block_bytes: int,
        max_blocks: int = 0,
        max_bytes: Optional[int] = None,
        decay: float = 0.6,
    ) -> None:
        if num_blocks < 1:
            raise ValueError(f"num_blocks must be >= 1, got {num_blocks}")
        if block_bytes < 1:
            raise ValueError(f"block_bytes must be >= 1, got {block_bytes}")
        if not 0.0 < decay <= 1.0:
            raise ValueError(f"decay must be in (0, 1], got {decay}")
        if max_blocks < 0:
            raise ValueError(f"max_blocks must be >= 0, got {max_blocks}")
        capacity = int(max_blocks) if max_blocks else int(num_blocks)
        if max_bytes is not None:
            capacity = min(capacity, int(max_bytes) // int(block_bytes))
        if capacity < 1:
            raise ValueError(
                f"residency budget admits no blocks (max_blocks={max_blocks},"
                f" max_bytes={max_bytes}, block_bytes={block_bytes})"
            )
        # pinning EVERYTHING is allowed (tiny datasets) but the budget is
        # still honored: capacity never exceeds the plan
        self.num_blocks = int(num_blocks)
        self.block_bytes = int(block_bytes)
        self.capacity = min(capacity, self.num_blocks)
        self.decay = float(decay)
        # -1.0 sentinel = never measured. Unlike the scheduler's +inf
        # bootstrap (which must VISIT unmeasured blocks first), residency
        # must never pin on no evidence once measurements exist — the
        # bootstrap resident set is simply first-come up to capacity.
        self.scores = np.full(self.num_blocks, -1.0, dtype=np.float64)
        self.age = np.zeros(self.num_blocks, dtype=np.int64)
        self.excluded = np.zeros(self.num_blocks, dtype=bool)
        self.epoch = 0
        self.stats = ResidencyStats()
        self.decisions: List[dict] = []
        self._entries: Dict[int, object] = {}  # block -> DeviceBlock
        # None = bootstrap (admit first-come); set after the first repin
        self._target: Optional[set] = None

    # -- inspection -------------------------------------------------------

    @property
    def resident_blocks(self) -> int:
        return len(self._entries)

    @property
    def resident_bytes(self) -> int:
        return len(self._entries) * self.block_bytes

    def resident_indices(self) -> List[int]:
        return sorted(self._entries)

    def is_resident(self, block: int) -> bool:
        return int(block) in self._entries

    def effective_scores(self) -> np.ndarray:
        """Staleness-discounted gap scores; unmeasured and excluded blocks
        sink to ``-inf`` so they can never displace measured evidence."""
        eff = self.scores * np.power(self.decay, self.age)
        eff[self.scores < 0.0] = -np.inf
        eff[self.excluded] = -np.inf
        return eff

    # -- serving ----------------------------------------------------------

    def get(self, block: int):
        """The resident DeviceBlock for ``block`` or ``None``. A hit is an
        upload that never happened — accounted in blocks and bytes."""
        entry = self._entries.get(int(block))
        if entry is not None:
            self.stats.hbm_hit_blocks += 1
            self.stats.hbm_hit_bytes += self.block_bytes
            reg = get_registry()
            reg.count("stream.residency.hbm_hit_blocks")
            reg.count("stream.residency.h2d_saved_bytes", self.block_bytes)
        return entry

    def offer(self, block: int, entry) -> bool:
        """Offer a freshly uploaded DeviceBlock for pinning. Admitted when
        the block is wanted (in the repin target, or first-come during
        bootstrap) and the budget has room. The entry MUST carry base
        (unfused) offsets — the caller fuses the CD residual per pass."""
        b = int(block)
        if b in self._entries or self.excluded[b]:
            return False
        if len(self._entries) >= self.capacity:
            return False
        if self._target is not None and b not in self._target:
            return False
        self._entries[b] = entry
        self.stats.stored_blocks += 1
        self._decide("pin", b, byte_delta=self.block_bytes)
        return True

    # -- feedback / re-pinning -------------------------------------------

    def update_gaps(self, gaps: Dict[int, float]) -> None:
        """Fold measured per-block gap estimates in (epoch end): every
        block ages one epoch, measured blocks reset to the new magnitude."""
        self.age += 1
        for block, gap in gaps.items():
            b = int(block)
            if not 0 <= b < self.num_blocks:
                raise IndexError(
                    f"gap update for block {b} outside [0, {self.num_blocks})"
                )
            self.scores[b] = abs(float(gap))
            self.age[b] = 0

    def repin(self) -> List[int]:
        """Recompute the target resident set from effective scores and
        evict residents that fell out (gap decay). Called ONLY between
        epochs — mid-pass the resident set is frozen so the pass's
        arithmetic order is deterministic. Returns the new target.

        Deterministic under a fixed gap trajectory: the stable argsort on
        ``-eff`` breaks exact ties by block index, so two managers fed the
        same measurements pin the same sets.
        """
        eff = self.effective_scores()
        ranked = np.argsort(-eff, kind="stable")
        target = [int(b) for b in ranked[: self.capacity] if eff[b] > -np.inf]
        self._target = set(target)
        for b in sorted(self._entries):
            if b not in self._target:
                self._evict(b)
        self.epoch += 1
        self.stats.repins += 1
        reg = get_registry()
        reg.gauge("stream.residency.resident_blocks", float(len(self._entries)))
        reg.gauge("stream.residency.resident_bytes", float(self.resident_bytes))
        reg.gauge("stream.residency.target_blocks", float(len(self._target)))
        reg.gauge("stream.residency.capacity_blocks", float(self.capacity))
        return target

    def mark_failed(self, blocks) -> None:
        """Permanently failed blocks (on_block_error=skip) leave the
        residency plane entirely: evicted if resident, never pinned again.
        The GapScheduler forwards its own ``mark_failed`` here when a
        residency plane is attached (stochastic mode)."""
        for b in blocks:
            bi = int(b)
            if not 0 <= bi < self.num_blocks:
                continue
            self.excluded[bi] = True
            if self._target is not None:
                self._target.discard(bi)
            if bi in self._entries:
                self._evict(bi)

    def _evict(self, block: int) -> None:
        del self._entries[block]
        self.stats.evicted_blocks += 1
        self._decide("evict", block, byte_delta=-self.block_bytes)

    def _decide(self, action: str, block: int, byte_delta: int) -> None:
        eff = self.effective_scores()[block]
        self.decisions.append({
            "epoch": int(self.epoch),
            "action": action,
            "block": int(block),
            # -1.0 = pinned on bootstrap (no measurement yet)
            "gap_score": float(eff) if np.isfinite(eff) else -1.0,
            "byte_delta": int(byte_delta),
            "resident_blocks": int(len(self._entries)),
            "resident_bytes": int(self.resident_bytes),
        })

    def drain_decisions(self) -> List[dict]:
        """Pin/evict records accumulated since the last drain (consumed by
        the streamed coordinate into the progress ledger)."""
        out = self.decisions
        self.decisions = []
        return out

    def snapshot(self) -> dict:
        """Point-in-time summary for bench/telemetry reports."""
        return {
            "capacity_blocks": int(self.capacity),
            "block_bytes": int(self.block_bytes),
            "resident_blocks": int(self.resident_blocks),
            "resident_bytes": int(self.resident_bytes),
            "resident_set": self.resident_indices(),
            "repins": int(self.stats.repins),
            "pins": int(self.stats.stored_blocks),
            "evictions": int(self.stats.evicted_blocks),
            "hbm_hit_blocks": int(self.stats.hbm_hit_blocks),
            "hbm_hit_bytes": int(self.stats.hbm_hit_bytes),
        }


def residency_hierarchy(source, manager: Optional[ResidencyManager] = None) -> dict:
    """Per-level hit/byte accounting of the disk → RAM → HBM hierarchy.

    * ``disk``  — the mmap :class:`~photon_ml_tpu.streaming.blockcache.BlockCache`:
      decoded blocks spilled once and re-served as zero-copy memmap views.
    * ``ram``   — the decode pool's part-file LRU: a hit skips an Avro
      decode entirely.
    * ``hbm``   — the resident set: a hit skips the ``device_put`` upload.

    Levels a run does not use report zeros, so the dict shape is stable
    for the bench contract.
    """
    cache = getattr(source, "cache", None)
    disk = {
        "hit_blocks": int(cache.stats.hits) if cache is not None else 0,
        "load_s": float(cache.stats.load_s) if cache is not None else 0.0,
    }
    ram = {
        "file_cache_hits": int(getattr(source, "file_cache_hits", 0)),
        "files_decoded": int(getattr(source, "files_decoded", 0)),
    }
    hbm = (
        {
            "hit_blocks": int(manager.stats.hbm_hit_blocks),
            "saved_bytes": int(manager.stats.hbm_hit_bytes),
            "resident_blocks": int(manager.resident_blocks),
            "resident_bytes": int(manager.resident_bytes),
        }
        if manager is not None
        else {
            "hit_blocks": 0, "saved_bytes": 0,
            "resident_blocks": 0, "resident_bytes": 0,
        }
    )
    return {"disk": disk, "ram": ram, "hbm": hbm}
