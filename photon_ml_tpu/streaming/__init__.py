"""Out-of-core training: disk-resident datasets streamed as fixed-shape
example blocks through a double-buffered host→device prefetcher into
block-sharded solvers. See docs/SCALING.md ("Streaming out-of-core").
"""

from photon_ml_tpu.streaming.blockcache import (
    BlockCache,
    CacheStats,
    plan_fingerprint,
)
from photon_ml_tpu.streaming.blocks import (
    BlockPlan,
    HostBlock,
    RowPlanes,
    StreamingSource,
    auto_decode_workers,
    group_by_part_file,
    readahead_file_budget,
)
from photon_ml_tpu.streaming.coordinate import StreamingFixedEffectCoordinate
from photon_ml_tpu.streaming.gapsched import GapScheduler
from photon_ml_tpu.streaming.prefetch import (
    BlockPrefetcher,
    DeviceBlock,
    PrefetchStats,
)
from photon_ml_tpu.streaming.residency import (
    ResidencyManager,
    ResidencyStats,
    residency_hierarchy,
)
from photon_ml_tpu.streaming.solver import (
    BlockStatsProbe,
    StreamSolveInfo,
    reset_stream_trace_counts,
    solve_streaming,
    solve_streaming_stochastic,
    stream_trace_counts,
    streamed_objective_value,
)

__all__ = [
    "BlockCache",
    "CacheStats",
    "plan_fingerprint",
    "auto_decode_workers",
    "group_by_part_file",
    "readahead_file_budget",
    "GapScheduler",
    "BlockPlan",
    "HostBlock",
    "RowPlanes",
    "StreamingSource",
    "StreamingFixedEffectCoordinate",
    "BlockPrefetcher",
    "DeviceBlock",
    "PrefetchStats",
    "ResidencyManager",
    "ResidencyStats",
    "residency_hierarchy",
    "BlockStatsProbe",
    "StreamSolveInfo",
    "reset_stream_trace_counts",
    "solve_streaming",
    "solve_streaming_stochastic",
    "stream_trace_counts",
    "streamed_objective_value",
]
