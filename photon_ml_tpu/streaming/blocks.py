"""Out-of-core example blocks: fixed-shape slices of a disk-resident dataset.

The in-memory trainers materialize one ``GameData`` for the whole dataset.
This module instead lays the dataset out as a sequence of ``block_rows``-row
blocks over the part files (``io/data_reader.py`` provides the file-granular
iterator), where every block has IDENTICAL shapes:

* row planes (labels / offsets / weights) are padded ``[block_rows]`` arrays
  with weight 0 in padding rows — an algebraic no-op in every objective term
  (see ops/data.py), so padded blocks are exact;
* each feature shard is packed into a padded ELL pair ``[block_rows, k]``
  where ``k`` is the GLOBAL max nnz/row recorded by the planning pass, so one
  compiled per-block program serves every block and nothing retraces.

A stable feature index (the off-heap/prebuilt index maps) is mandatory: all
blocks must live in one column space. The planning pass decodes each part
file once to record per-shard ELL widths and exact per-file row counts; the
streaming pass then re-decodes files on demand with a tiny LRU so peak host
memory is O(decoded files in cache) + O(prefetch_depth × block bytes), never
O(dataset).
"""

from __future__ import annotations

import dataclasses
import os
import threading
import time
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from photon_ml_tpu.io.data_reader import (
    FeatureShardConfiguration,
    build_index_maps,
    file_row_counts,
    read_game_data,
)
from photon_ml_tpu.ops.features import pack_ell_into
from photon_ml_tpu.resilience.failures import record_failure
from photon_ml_tpu.resilience.faultpoints import fault_point, register_fault_site
from photon_ml_tpu.resilience.retry import DEFAULT_IO_RETRY
from photon_ml_tpu.streaming.blockcache import BlockCache, plan_fingerprint
from photon_ml_tpu.telemetry import span

FAULT_READ = register_fault_site(
    "stream.read_part_file",
    "part-file read + columnar decode (retried; pool failures fall back"
    " to a synchronous decode on the consumer thread)",
)
FAULT_BUILD = register_fault_site(
    "stream.build_block",
    "block assembly after decode; a permanent failure here is what"
    " on_block_error=abort|skip governs",
)


def auto_decode_workers() -> int:
    """Measured auto default for the decode pool width.

    inflate + the columnar decode run with the GIL released (one native
    call per file — see io/native_reader.py), so file decodes scale
    near-linearly with threads until memory bandwidth; the cap is one
    thread per core minus one (reserved for the consumer/solver), bounded
    at 16 where the packed decoder's gains flatten. On a single-CPU host
    this is 0 — synchronous decode, since extra threads only add
    contention there. Override with ``PHOTON_STREAM_DECODE_WORKERS``.
    """
    env = os.environ.get("PHOTON_STREAM_DECODE_WORKERS")
    if env is not None:
        try:
            return max(0, int(env))
        except ValueError:
            pass
    return max(0, min((os.cpu_count() or 1) - 1, 16))


def readahead_file_budget() -> int:
    """Max decoded part files the readahead may hold AHEAD of the consumer.

    Decoded-file residency is the peak-RSS term of streaming, and it must
    be bounded independently of the pool width: with the worker cap at 16,
    scheduling ``workers + depth`` files ahead would let a many-core host
    keep ~17 decoded files resident — the out-of-core bound the bench
    guarantees assumes a handful. The default (4) matches the residency of
    the original ``min(4, cpus-1)`` pool; override with
    ``PHOTON_STREAM_READAHEAD_FILES`` when files are small relative to
    RAM and deeper readahead measurably helps the hide ratio.
    """
    env = os.environ.get("PHOTON_STREAM_READAHEAD_FILES")
    if env is not None:
        try:
            return max(1, int(env))
        except ValueError:
            pass
    return 4


@dataclasses.dataclass(frozen=True)
class BlockPlan:
    """Static layout of a streamed dataset: file boundaries + block shapes.

    Produced once by the planning pass; every block of the run obeys it, so
    block shapes are a function of the plan alone (the zero-retrace
    contract)."""

    block_rows: int
    total_rows: int
    files: Tuple[str, ...]
    file_rows: Tuple[int, ...]
    shard_widths: Dict[str, int]   # shard -> ELL k (global max nnz/row)
    shard_dims: Dict[str, int]     # shard -> feature dimension d

    @property
    def num_blocks(self) -> int:
        return max(1, -(-self.total_rows // self.block_rows))

    @property
    def padded_rows(self) -> int:
        """Total rows including final-block padding (num_blocks*block_rows)."""
        return self.num_blocks * self.block_rows

    def block_bounds(self, index: int) -> Tuple[int, int]:
        """[start, stop) global row range of real rows in block ``index``."""
        if not 0 <= index < self.num_blocks:
            raise IndexError(f"block {index} out of range [0, {self.num_blocks})")
        start = index * self.block_rows
        return start, min(start + self.block_rows, self.total_rows)

    def spans(self, index: int) -> List[Tuple[int, int, int]]:
        """Per-file pieces of block ``index`` as (file_idx, lo, hi) with
        lo/hi local to that file — a block freely spans file boundaries."""
        start, stop = self.block_bounds(index)
        out: List[Tuple[int, int, int]] = []
        base = 0
        for fi, rows in enumerate(self.file_rows):
            file_end = base + rows
            lo = max(start, base)
            hi = min(stop, file_end)
            if lo < hi:
                out.append((fi, lo - base, hi - base))
            base = file_end
            if base >= stop:
                break
        return out


def group_by_part_file(
    indices: Sequence[int], plan: BlockPlan
) -> List[int]:
    """Reorder ``indices`` so blocks that START in the same part file are
    adjacent, without changing the set of blocks visited.

    Shuffled or importance-ordered visits are the stochastic mode's
    re-decode hazard: two blocks of the same file scheduled far apart make
    the decode LRU decode that file twice. Grouping fixes it — part files
    appear in order of their highest-priority block (the first appearance
    in ``indices``), and within a file blocks run in ascending index so
    the decode walk is monotone across each file's spans. With the default
    ``file_cache_size`` (2 — current + next for boundary-spanning blocks),
    each part file is decoded once per pass over the result, plus at most
    one extra decode per file-boundary-straddling block whose neighbor
    group lands much later — O(num_files) total instead of the O(visits)
    worst case of an ungrouped shuffle.
    """
    by_file: Dict[int, List[int]] = {}
    file_order: List[int] = []
    for i in indices:
        b = int(i)
        fi = plan.spans(b)[0][0]
        bucket = by_file.get(fi)
        if bucket is None:
            bucket = by_file[fi] = []
            file_order.append(fi)
        bucket.append(b)
    out: List[int] = []
    for fi in file_order:
        out.extend(sorted(by_file[fi]))
    return out


@dataclasses.dataclass
class HostBlock:
    """One decoded, padded, host-staged block (numpy only — built in the
    prefetcher's background thread; the consumer does the device_put).

    ALL arrays are read-only by contract: cache hits are views over a
    ``mode='r'`` memmap, and the decode path freezes its arrays to match,
    so an in-place mutation fails uniformly on cold and warm epochs
    instead of only once the cache warms. Consumers copy if they must
    write (none currently do — blocks are device_put and dropped)."""

    index: int
    start: int        # global row of the first real row
    num_real: int     # real rows (rest is weight-0 padding)
    labels: np.ndarray    # [block_rows] f32
    offsets: np.ndarray   # [block_rows] f32 (base offsets from the files)
    weights: np.ndarray   # [block_rows] f32, 0.0 in padding rows
    shards: Dict[str, Tuple[np.ndarray, np.ndarray]]  # sid -> (vals, idx) ELL
    id_tags: Dict[str, np.ndarray]  # re_type -> [num_real] entity ids


@dataclasses.dataclass
class RowPlanes:
    """Whole-dataset per-row scalar planes accumulated by one setup pass.

    These are O(n) scalars + id strings (not features); the random-effect
    coordinates and the CD driver's objective need them resident. The
    feature payload of the streamed (fixed-effect) shard is what stays
    out-of-core."""

    labels: np.ndarray
    offsets: np.ndarray
    weights: np.ndarray
    id_tags: Dict[str, np.ndarray]
    shard_coo: Dict[str, Tuple[np.ndarray, np.ndarray, np.ndarray, int]]


class StreamingSource:
    """A disk-resident GAME dataset exposed as fixed-shape example blocks.

    Open once per run (the planning pass decodes every part file once to
    fix ELL widths); then ``iter_blocks`` streams HostBlocks in any block
    order, re-decoding part files on demand through a small LRU cache.
    """

    def __init__(
        self,
        files: Sequence[str],
        file_rows: Sequence[int],
        shard_configs: Dict[str, FeatureShardConfiguration],
        index_maps,
        plan: BlockPlan,
        id_tags: Sequence[str] = (),
        read_kwargs: Optional[dict] = None,
        file_cache_size: int = 2,
        decode_workers: Optional[int] = None,
    ):
        self.files = list(files)
        self.file_rows = list(file_rows)
        self.shard_configs = shard_configs
        self.index_maps = index_maps
        self.plan = plan
        self.id_tags = tuple(id_tags)
        self.read_kwargs = dict(read_kwargs or {})
        self.file_cache_size = max(1, int(file_cache_size))
        if decode_workers is None:
            decode_workers = auto_decode_workers()
        self.decode_workers = max(0, int(decode_workers))
        self.cache: Optional[BlockCache] = None  # see attach_cache
        self._file_cache: Dict[int, object] = {}  # fi -> GameData (LRU)
        self._cache_limit = self.file_cache_size
        self._lock = threading.RLock()
        self._pending: Dict[int, Future] = {}  # fi -> in-flight decode
        self._pool: Optional[ThreadPoolExecutor] = None
        self._row_planes: Optional[RowPlanes] = None
        # degraded mode for permanent block failures: "abort" (default —
        # exactness over availability) or "skip" (train on the blocks that
        # decode; each skip is recorded and excluded from gap scheduling)
        self.on_block_error = "abort"
        self.failed_blocks: set = set()
        self._skipped_log: List[dict] = []
        # decode accounting for the planning/setup passes (bench evidence)
        self.files_decoded = 0
        # RAM level of the residency hierarchy: part files served from the
        # decoded-file LRU instead of re-decoding (residency_hierarchy)
        self.file_cache_hits = 0
        self._work_s = 0.0  # host decode+pack seconds, whatever thread
        # wall-clock with >= 1 decode in flight (for the wall-based hide
        # ratio: parallel workers must not be double counted)
        self._wall_s = 0.0
        self._wall_active = 0
        self._wall_anchor = 0.0

    # -- construction ------------------------------------------------------

    @classmethod
    def open(
        cls,
        paths: Sequence[str] | str,
        shard_configs: Dict[str, FeatureShardConfiguration],
        index_maps=None,
        block_rows: int = 4096,
        id_tags: Sequence[str] = (),
        file_cache_size: int = 2,
        decode_workers: Optional[int] = None,
        cache_dir: Optional[str] = None,
        **read_kwargs,
    ) -> "StreamingSource":
        """Plan a streamed dataset: list part files, fix the feature index,
        and record global ELL widths with one decode pass per file.
        ``cache_dir`` attaches a decoded block cache (see blockcache.py)
        so later epochs reload spilled blocks instead of re-decoding."""
        if isinstance(paths, str):
            paths = [paths]
        if block_rows < 1:
            raise ValueError(f"block_rows must be >= 1, got {block_rows}")
        with span("read stream plan", files=0):
            counts = file_row_counts(paths)
        files = [p for p, _ in counts]
        rows = [n for _, n in counts]
        if not files or sum(rows) == 0:
            raise ValueError(f"no rows found under {paths}")
        if index_maps is None:
            index_maps = build_index_maps(paths, shard_configs)

        src = cls(
            files, rows, shard_configs, index_maps,
            plan=None,  # type: ignore[arg-type]  # set below
            id_tags=id_tags, read_kwargs=read_kwargs,
            file_cache_size=file_cache_size,
            decode_workers=decode_workers,
        )
        widths = {sid: 1 for sid in shard_configs}
        dims = {sid: len(index_maps[sid]) for sid in shard_configs}
        for fi in range(len(files)):
            data = src._decode_file(fi, cache=False)
            if data.num_rows != rows[fi]:
                raise ValueError(
                    f"{files[fi]}: framing scan counted {rows[fi]} rows but "
                    f"decode produced {data.num_rows}"
                )
            for sid, shard in data.feature_shards.items():
                if shard.rows.size:
                    per_row = np.bincount(shard.rows, minlength=data.num_rows)
                    widths[sid] = max(widths[sid], int(per_row.max()))
        src.plan = BlockPlan(
            block_rows=int(block_rows),
            total_rows=sum(rows),
            files=tuple(files),
            file_rows=tuple(rows),
            shard_widths=widths,
            shard_dims=dims,
        )
        if cache_dir:
            src.attach_cache(cache_dir)
        return src

    def attach_cache(self, cache_dir: str, sweep: bool = True) -> BlockCache:
        """Attach a decoded block cache rooted at ``cache_dir``. The cache
        key (plan fingerprint) commits to block_rows, the part files'
        (path, size, mtime_ns), the shard layout, a content digest of each
        feature index map (externally loaded maps change column ids without
        changing the input files), id tags and reader options — any change
        misses cleanly and ``sweep`` reclaims the orphaned entries of older
        plans."""
        fp = plan_fingerprint(
            self.plan.block_rows,
            self.plan.files,
            self.plan.shard_widths,
            self.plan.shard_dims,
            id_tags=self.id_tags,
            read_kwargs=self.read_kwargs,
            index_maps=self.index_maps,
        )
        self.cache = BlockCache(cache_dir, fp)
        if sweep:
            self.cache.sweep_stale()
        return self.cache

    # -- file decode + cache ----------------------------------------------

    @property
    def work_seconds(self) -> float:
        """Cumulative host decode+pack seconds across all threads — WORK,
        not exposed latency. Zero delta across a warm (fully cached) epoch
        is the 'zero Avro work' contract the tier-1 smoke test asserts."""
        with self._lock:
            return self._work_s

    @property
    def decode_wall_seconds(self) -> float:
        """Wall-clock seconds during which >= 1 decode/pack was in flight
        (overlapping workers counted once). The prefetcher differences
        this to compute the WALL-based hide ratio; cache loads are not
        decode and do not count."""
        with self._lock:
            w = self._wall_s
            if self._wall_active > 0:
                w += time.perf_counter() - self._wall_anchor
            return w

    def _add_work(self, dt: float) -> None:
        with self._lock:
            self._work_s += dt

    def _wall_enter(self) -> None:
        now = time.perf_counter()
        with self._lock:
            if self._wall_active == 0:
                self._wall_anchor = now
            self._wall_active += 1

    def _wall_exit(self) -> None:
        now = time.perf_counter()
        with self._lock:
            self._wall_active -= 1
            if self._wall_active == 0:
                self._wall_s += now - self._wall_anchor

    def _decode_now(self, fi: int):
        """The actual (uncached) file read — safe from any thread."""
        t0 = time.perf_counter()
        self._wall_enter()
        try:
            return self._decode_now_inner(fi, t0)
        finally:
            self._wall_exit()

    def _decode_now_inner(self, fi: int, t0: float):
        with span("read stream file", file=self.files[fi]):
            # the one seam where disk flakiness enters streaming: a
            # transient read/decode error retries with backoff instead of
            # aborting an hours-long fit (the Spark task-retry analogue)
            def _read():
                fault_point(FAULT_READ)
                return read_game_data(
                    [self.files[fi]],
                    self.shard_configs,
                    index_maps=self.index_maps,
                    id_tags=self.id_tags,
                    **self.read_kwargs,
                )

            data, _, _ = DEFAULT_IO_RETRY.run("stream.read_part_file", _read)
        # sort each shard's COO by (row, col) once here: block assembly
        # then slices row ranges by binary search instead of masking the
        # whole file, and ELL packing skips its per-block lexsort
        for shard in data.feature_shards.values():
            r, c = shard.rows, shard.cols
            if r.size and not bool(np.all(
                (r[1:] > r[:-1]) | ((r[1:] == r[:-1]) & (c[1:] >= c[:-1]))
            )):
                order = np.lexsort((c, r))
                shard.rows = r[order]
                shard.cols = c[order]
                shard.vals = shard.vals[order]
        with self._lock:
            self.files_decoded += 1
            self._work_s += time.perf_counter() - t0
        return data

    def _cache_insert(self, fi: int, data) -> None:
        with self._lock:
            self._file_cache[fi] = data
            while len(self._file_cache) > self._cache_limit:
                self._file_cache.pop(next(iter(self._file_cache)))

    def _decode_file(self, fi: int, cache: bool = True):
        with self._lock:
            cached = self._file_cache.pop(fi, None)
            if cached is not None:
                self._file_cache[fi] = cached  # re-insert: most recently used
                # RAM level of the residency hierarchy: a decoded-file LRU
                # hit is an Avro decode that never happened
                self.file_cache_hits += 1
                return cached
            fut = self._pending.get(fi)
        if fut is not None:
            try:
                return fut.result()  # the pool job inserts into the cache
            except Exception as exc:  # noqa: BLE001 - degraded mode below
                # pool decode failed even after its own retries: fall back
                # to a synchronous decode on this (consumer) thread — one
                # more independent attempt before the failure is permanent
                record_failure(
                    "prefetch_decode_failed",
                    "stream.read_part_file",
                    f"{type(exc).__name__}: {exc}; retrying synchronously",
                    file=self.files[fi],
                )
        data = self._decode_now(fi)
        if cache:
            self._cache_insert(fi, data)
        return data

    def prefetch_files(self, fis: Sequence[int]) -> None:
        """Schedule background decodes of the named part files on the decode
        pool (no-op when ``decode_workers`` is 0). The readahead window also
        widens the LRU so a prefetched file is not evicted before its blocks
        are consumed — decoded-file residency is the time/memory tradeoff of
        parallel decode."""
        if self.decode_workers <= 0:
            return
        with self._lock:
            self._cache_limit = max(self.file_cache_size, len(fis) + 1)
            todo = [
                fi for fi in fis
                if fi not in self._file_cache and fi not in self._pending
            ]
            if not todo:
                return
            if self._pool is None:
                self._pool = ThreadPoolExecutor(
                    max_workers=self.decode_workers,
                    thread_name_prefix="stream-decode",
                )
            for fi in todo:
                self._pending[fi] = self._pool.submit(self._prefetch_job, fi)

    def _prefetch_job(self, fi: int):
        try:
            data = self._decode_now(fi)
            self._cache_insert(fi, data)
            return data
        finally:
            with self._lock:
                self._pending.pop(fi, None)

    def prefetch_blocks(
        self, indices: Sequence[int], shards: Optional[Sequence[str]] = None
    ) -> None:
        """Cache-aware readahead: schedule file decodes for the named
        blocks, skipping any block the block cache already holds — the
        cache is consulted BEFORE the Avro decode pool, so a fully warm
        epoch never schedules a decode. The scheduled file list is capped
        at :func:`readahead_file_budget` + 1 regardless of how many blocks
        the caller names (blocks spanning many small files must not blow
        the decoded-file residency bound); dropped files simply decode on
        demand when their block is built."""
        want = tuple(shards) if shards is not None else tuple(self.shard_configs)
        budget = readahead_file_budget() + 1  # +1: the file being consumed
        fis: List[int] = []
        for b in indices:
            if self.cache is not None and self.cache.has(int(b), want):
                continue
            for fi, _, _ in self.plan.spans(int(b)):
                if fi not in fis:
                    fis.append(fi)
            if len(fis) >= budget:
                break
        if fis:
            self.prefetch_files(fis[:budget])

    # -- block assembly ----------------------------------------------------

    def build_block(
        self, index: int, shards: Optional[Sequence[str]] = None
    ) -> Optional[HostBlock]:
        """Assemble one padded HostBlock (host numpy only). ``shards``
        restricts ELL packing to the named feature shards (the streamed
        fixed-effect coordinate only needs its own). With a block cache
        attached, a valid cached entry is returned as zero-copy memmap
        views (no Avro work at all); otherwise the block is decoded and
        spilled so the NEXT visit hits.

        A permanently failing block (decode retries exhausted) either
        propagates (``on_block_error='abort'``, the default) or — under
        ``'skip'`` — is recorded, excluded from future gap scheduling,
        and returned as ``None`` (iteration drops it)."""
        want = tuple(shards) if shards is not None else tuple(self.shard_configs)
        try:
            fault_point(FAULT_BUILD)
            if self.cache is not None:
                blk = self.cache.load(index, want)
                if blk is not None:
                    return blk
            blk = self._build_block_decode(index, want)
        except Exception as exc:  # noqa: BLE001 - policy decides below
            if self.on_block_error != "skip":
                raise
            self._note_skipped(index, exc)
            return None
        if self.cache is not None:
            self.cache.store(blk, want)
        return blk

    def _note_skipped(self, index: int, exc: BaseException) -> None:
        with self._lock:
            self.failed_blocks.add(int(index))
            self._skipped_log.append(
                {
                    "block": int(index),
                    "error": f"{type(exc).__name__}: {exc}",
                }
            )
        record_failure(
            "block_skipped",
            "stream.build_block",
            f"block {int(index)}: {type(exc).__name__}: {exc}",
            block=int(index),
        )

    def drain_skipped_blocks(self) -> List[dict]:
        """Skip records accumulated since the last drain (the streamed
        coordinate forwards them to the progress ledger)."""
        with self._lock:
            out, self._skipped_log = self._skipped_log, []
        return out

    def _build_block_decode(
        self, index: int, want: Tuple[str, ...]
    ) -> HostBlock:
        """The decode path: pull file pieces through the LRU/pool and pack
        each piece's COO slice DIRECTLY into the block's preallocated ELL
        staging buffers (pieces are row-disjoint, so piecewise packing is
        exact and the per-block COO concatenation copy is gone)."""
        plan = self.plan
        start, stop = plan.block_bounds(index)
        num_real = stop - start
        b = plan.block_rows

        labels = np.zeros(b, dtype=np.float32)
        offsets = np.zeros(b, dtype=np.float32)
        weights = np.zeros(b, dtype=np.float32)  # padding stays weight 0
        tag_parts: Dict[str, List[np.ndarray]] = {t: [] for t in self.id_tags}
        packed: Dict[str, Tuple[np.ndarray, np.ndarray]] = {
            sid: (
                np.zeros((b, plan.shard_widths[sid]), dtype=np.float32),
                np.zeros((b, plan.shard_widths[sid]), dtype=np.int32),
            )
            for sid in want
        }

        out_row = 0
        t_build = 0.0
        self._wall_enter()
        t0 = time.perf_counter()
        try:
            for fi, lo, hi in plan.spans(index):
                t_build += time.perf_counter() - t0
                piece = self._decode_file(fi)
                t0 = time.perf_counter()
                n_piece = hi - lo
                sl = slice(lo, hi)
                labels[out_row:out_row + n_piece] = piece.labels[sl]
                offsets[out_row:out_row + n_piece] = piece.offsets[sl]
                weights[out_row:out_row + n_piece] = piece.weights[sl]
                for t in self.id_tags:
                    tag_parts[t].append(np.asarray(piece.id_tags[t])[sl])
                for sid in want:
                    shard = piece.feature_shards[sid]
                    r = shard.rows
                    if r.size and bool(np.all(r[1:] >= r[:-1])):
                        # decoder COO is row-major: slice by binary search
                        # instead of masking the whole file's triplets
                        i0, i1 = np.searchsorted(r, (lo, hi))
                        rr = r[i0:i1] - lo + out_row
                        cc, vv = shard.cols[i0:i1], shard.vals[i0:i1]
                    else:
                        keep = (r >= lo) & (r < hi)
                        rr = r[keep] - lo + out_row
                        cc, vv = shard.cols[keep], shard.vals[keep]
                    pack_ell_into(
                        rr, cc, vv, packed[sid][0], packed[sid][1],
                        num_cols=plan.shard_dims[sid],
                    )
                out_row += n_piece
            t_build += time.perf_counter() - t0
        finally:
            self._wall_exit()
        self._add_work(t_build)
        id_tags = {
            t: (np.concatenate(v) if v else np.zeros(0, dtype=object))
            for t, v in tag_parts.items()
        }
        # freeze: cache hits are read-only memmap views, so the decode path
        # must fail in-place writes identically (HostBlock contract)
        for arr in (labels, offsets, weights, *id_tags.values()):
            arr.flags.writeable = False
        for vals, idx in packed.values():
            vals.flags.writeable = False
            idx.flags.writeable = False
        return HostBlock(
            index=index,
            start=start,
            num_real=num_real,
            labels=labels,
            offsets=offsets,
            weights=weights,
            shards=packed,
            id_tags=id_tags,
        )

    def iter_blocks(
        self,
        order: Optional[Sequence[int]] = None,
        shards: Optional[Sequence[str]] = None,
    ) -> Iterator[HostBlock]:
        """Yield HostBlocks in ``order`` (default: sequential). Sequential
        order decodes each part file exactly once thanks to the LRU;
        arbitrary shuffled orders may re-decode. Callers that control the
        order (the gap scheduler, custom samplers) should pass it through
        :func:`group_by_part_file` first — same visit set, same-file
        blocks adjacent — so each part file is decoded at most once per
        pass; any residual re-decode cost stays visible in the io phase
        of the telemetry report."""
        indices = range(self.plan.num_blocks) if order is None else order
        for i in indices:
            with span("read stream block", block=int(i)):
                blk = self.build_block(int(i), shards=shards)
            if blk is not None:
                yield blk

    # -- whole-dataset row planes (setup pass) ----------------------------

    def row_planes(self, coo_shards: Sequence[str] = ()) -> RowPlanes:
        """One streamed setup pass accumulating the per-row scalar planes
        (labels/offsets/weights/id tags) and, optionally, the full COO of
        the named (small, per-entity) shards for random-effect grouping.
        Cached: a later call asking for shards the cache lacks re-runs the
        setup pass for the union."""
        if self._row_planes is not None:
            missing = set(coo_shards) - set(self._row_planes.shard_coo)
            if not missing:
                return self._row_planes
            coo_shards = sorted(set(coo_shards) | set(self._row_planes.shard_coo))
            self._row_planes = None
        labels, offsets, weights = [], [], []
        tags: Dict[str, List[np.ndarray]] = {t: [] for t in self.id_tags}
        coo: Dict[str, List[Tuple[np.ndarray, np.ndarray, np.ndarray]]] = {
            sid: [] for sid in coo_shards
        }
        base = 0
        with span("read stream row planes", shards=len(list(coo_shards))):
            for fi in range(len(self.files)):
                piece = self._decode_file(fi)
                labels.append(piece.labels)
                offsets.append(piece.offsets)
                weights.append(piece.weights)
                for t in self.id_tags:
                    tags[t].append(np.asarray(piece.id_tags[t]))
                for sid in coo_shards:
                    shard = piece.feature_shards[sid]
                    coo[sid].append((shard.rows + base, shard.cols, shard.vals))
                base += piece.num_rows
        self._row_planes = RowPlanes(
            labels=np.concatenate(labels),
            offsets=np.concatenate(offsets),
            weights=np.concatenate(weights),
            id_tags={t: np.concatenate(v) for t, v in tags.items()},
            shard_coo={
                sid: (
                    np.concatenate([p[0] for p in v]) if v else np.zeros(0, np.int64),
                    np.concatenate([p[1] for p in v]) if v else np.zeros(0, np.int64),
                    np.concatenate([p[2] for p in v]) if v else np.zeros(0, np.float32),
                    self.plan.shard_dims[sid],
                )
                for sid, v in coo.items()
            },
        )
        return self._row_planes

    def block_feature_bytes(self, shard: str) -> int:
        """Host bytes of ONE staged block of ``shard`` (f32 values + i32
        indices) — the unit the prefetch-depth RSS bound multiplies."""
        k = self.plan.shard_widths[shard]
        return self.plan.block_rows * k * 8

    def block_upload_bytes(self, shards: Optional[Sequence[str]] = None) -> int:
        """H2D bytes of ONE uploaded block restricted to ``shards``
        (default: all): the per-row scalar planes (labels/offsets/weights,
        f32 each) plus each shard's ELL payload as it crosses the link
        (f32 values + i32 indices). Block shapes are fixed by the plan, so
        this is uniform across blocks — the residency plane's byte budget
        divides by it exactly."""
        want = tuple(shards) if shards is not None else tuple(self.shard_configs)
        b = self.plan.block_rows
        total = 3 * b * 4
        for sid in want:
            total += b * self.plan.shard_widths[sid] * 8
        return total
