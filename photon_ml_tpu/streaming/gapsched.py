"""Gap-guided block scheduling for stochastic streaming (DuHL).

"Large-Scale Stochastic Learning using GPUs" (arXiv 1702.07005) keeps on
the accelerator only the working set with the largest duality-gap
contribution, swapping blocks in by importance instead of round-robin.
PR 12 landed the signal: ``BlockStatsProbe`` computes the per-block
first-order gap surrogate ``f_k + <w, g_k>`` on every progress-enabled
streamed solve. This module is the consumer — a scheduler that turns those
per-block scores into the visit order of the stochastic streaming mode.

The scheduler is deliberately simple and fully host-side (numpy only; it
never touches the jit plane, so the zero-retrace contract is unaffected):

* each block carries a **gap score** — the magnitude of its most recent
  gap estimate. Unvisited blocks hold an ``+inf`` sentinel so the first
  epoch (and any epoch where new blocks appear) is a full bootstrap pass;
* scores **decay exponentially with staleness**: a block last visited
  ``a`` epochs ago competes with ``score · decay^a``, so a once-important
  block cannot monopolize the schedule on stale evidence;
* an **ε-greedy exploration floor** always re-visits the stalest blocks
  regardless of score, so every block's estimate is refreshed within
  ``~1/explore`` epochs even if its last measured gap was tiny;
* the selected set is ordered by **part file** (``group_by_part_file``),
  not raw priority: same-file blocks stay adjacent so the decode LRU in
  ``streaming/blocks.py`` decodes each part file at most once per epoch —
  importance ordering must not thrash the file cache it is trying to
  out-run.

The solver feeds measured gaps back via :meth:`update` at each epoch end;
``epoch_order`` emits the next visit order. Decisions are recorded per
epoch (and exported as ``stream.gap_sched.*`` gauges) so the progress
ledger and the ``--auto-tune`` judge can see what the scheduler did.
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional

import numpy as np

from photon_ml_tpu.streaming.blocks import BlockPlan, group_by_part_file
from photon_ml_tpu.telemetry import get_registry


class GapScheduler:
    """Per-block gap-score bookkeeping + epoch visit-order emission.

    Parameters
    ----------
    num_blocks:
        Blocks in the streamed plan (fixed for the scheduler's lifetime).
    plan:
        Optional :class:`BlockPlan` for part-file-aware ordering of the
        selected set. Without a plan the selected blocks are visited in
        plain priority order.
    decay:
        Per-epoch staleness discount applied to a block's last measured
        score (``score · decay^age``). Smaller decays forget faster.
    explore:
        Exploration floor: every epoch at least
        ``max(1, round(explore · num_blocks))`` of the *stalest* blocks
        are visited regardless of score.
    visit_fraction:
        Share of blocks visited per scheduled epoch (the working set).
        The actual visit count is ``max(1, ceil(fraction · num_blocks))``
        plus any exploration picks not already selected.
    """

    def __init__(
        self,
        num_blocks: int,
        plan: Optional[BlockPlan] = None,
        decay: float = 0.6,
        explore: float = 0.1,
        visit_fraction: float = 0.5,
        seed: int = 0,
    ) -> None:
        if num_blocks < 1:
            raise ValueError(f"num_blocks must be >= 1, got {num_blocks}")
        if not 0.0 < decay <= 1.0:
            raise ValueError(f"decay must be in (0, 1], got {decay}")
        if not 0.0 <= explore <= 1.0:
            raise ValueError(f"explore must be in [0, 1], got {explore}")
        if not 0.0 < visit_fraction <= 1.0:
            raise ValueError(
                f"visit_fraction must be in (0, 1], got {visit_fraction}"
            )
        self.num_blocks = int(num_blocks)
        self.plan = plan
        self.decay = float(decay)
        self.explore = float(explore)
        self.visit_fraction = float(visit_fraction)
        # +inf sentinel = never measured: such a block outranks every
        # measured one, so bootstrap epochs visit everything first
        self.scores = np.full(self.num_blocks, np.inf, dtype=np.float64)
        self.age = np.zeros(self.num_blocks, dtype=np.int64)
        # failure plane: blocks that permanently failed to build under
        # on_block_error=skip — never scheduled again this run
        self.excluded = np.zeros(self.num_blocks, dtype=bool)
        self.epoch = 0
        self.decisions: List[dict] = []
        self._rng = np.random.default_rng(seed)
        # HBM residency plane (streaming/residency.py): when attached, the
        # scheduler's epoch-end gap feedback doubles as the residency
        # plane's repin signal, and permanently failed blocks are evicted
        # from the resident set the moment they are excluded here
        self._residency = None

    def attach_residency(self, manager) -> None:
        """Couple a :class:`~photon_ml_tpu.streaming.residency.ResidencyManager`
        to this scheduler's gap feedback: ``update`` forwards measurements
        and triggers the between-epoch repin; ``mark_failed`` evicts."""
        self._residency = manager

    # -- scheduling -------------------------------------------------------

    def effective_scores(self) -> np.ndarray:
        """Staleness-discounted scores (``+inf`` where never measured)."""
        eff = self.scores * np.power(self.decay, self.age)
        eff[~np.isfinite(self.scores)] = np.inf
        return eff

    def epoch_order(self) -> np.ndarray:
        """The next epoch's visit order (int64 block indices).

        Unmeasured blocks always rank first (bootstrap); afterwards the
        top-``visit_fraction`` by effective score are selected, plus the
        exploration picks — the stalest blocks not already selected.
        """
        eff = self.effective_scores()
        # excluded (permanently failed) blocks sink below every candidate
        # and never re-enter the schedule — not even as exploration picks
        available = int(self.num_blocks - np.sum(self.excluded))
        if available == 0:
            raise RuntimeError(
                "gap scheduler: every block is excluded (permanent"
                " failures) — nothing left to schedule"
            )
        eff[self.excluded] = -np.inf
        n_visit = max(1, math.ceil(self.visit_fraction * self.num_blocks))
        n_visit = max(
            n_visit,
            int(np.sum(~np.isfinite(self.scores) & ~self.excluded)),
        )
        n_visit = min(n_visit, available)
        # stable argsort on (-eff) keeps index order among exact ties —
        # deterministic schedules for a deterministic gap history
        ranked = np.argsort(-eff, kind="stable")
        selected = ranked[:n_visit]
        chosen = np.zeros(self.num_blocks, dtype=bool)
        chosen[selected] = True

        n_explore = max(1, int(round(self.explore * self.num_blocks)))
        rest = np.nonzero(~chosen & ~self.excluded)[0]
        explored = np.zeros(0, dtype=np.int64)
        if rest.size:
            # stalest first; ties broken uniformly so exploration does not
            # systematically favor low block indices
            tie = self._rng.random(rest.size)
            stale_rank = np.lexsort((tie, -self.age[rest]))
            explored = rest[stale_rank[: min(n_explore, rest.size)]]
            chosen[explored] = True

        priority = np.concatenate([selected, explored]).astype(np.int64)
        if self.plan is not None:
            order = np.asarray(
                group_by_part_file(priority, self.plan), dtype=np.int64
            )
        else:
            order = priority

        finite = self.scores[np.isfinite(self.scores)]
        decision = {
            "epoch": int(self.epoch),
            "visited": int(order.size),
            "explored": int(explored.size),
            "num_blocks": int(self.num_blocks),
            "unvisited": int(np.sum(~np.isfinite(self.scores) & ~self.excluded)),
            "excluded": int(np.sum(self.excluded)),
            "score_max": float(finite.max()) if finite.size else 0.0,
            "score_mean": float(finite.mean()) if finite.size else 0.0,
        }
        self.decisions.append(decision)
        reg = get_registry()
        reg.gauge("stream.gap_sched.visited_blocks", float(order.size))
        reg.gauge("stream.gap_sched.explored_blocks", float(explored.size))
        reg.gauge(
            "stream.gap_sched.visit_fraction",
            float(order.size) / float(self.num_blocks),
        )
        reg.gauge("stream.gap_sched.unvisited", decision["unvisited"])
        reg.gauge("stream.gap_sched.score_max", decision["score_max"])
        reg.gauge("stream.gap_sched.score_mean", decision["score_mean"])
        self.epoch += 1
        return order

    # -- feedback ---------------------------------------------------------

    def update(self, gaps: Dict[int, float]) -> None:
        """Fold measured per-block gap estimates back in (epoch end).

        Every block ages one epoch; the visited blocks' scores are reset
        to the new measurement (magnitude — the first-order surrogate can
        go slightly negative near the optimum) with age 0.
        """
        self.age += 1
        for block, gap in gaps.items():
            b = int(block)
            if not 0 <= b < self.num_blocks:
                raise IndexError(
                    f"gap update for block {b} outside [0, {self.num_blocks})"
                )
            self.scores[b] = abs(float(gap))
            self.age[b] = 0
        if self._residency is not None and gaps:
            # same signal, second consumer: the epoch boundary is the only
            # legal repin point (never mid-pass)
            self._residency.update_gaps(gaps)
            self._residency.repin()

    def mark_failed(self, blocks) -> None:
        """Exclude permanently failed blocks (on_block_error=skip) from
        all future schedules. Idempotent; feedback for an excluded block
        is simply never measured again."""
        for b in blocks:
            bi = int(b)
            if 0 <= bi < self.num_blocks:
                self.excluded[bi] = True
        if self._residency is not None:
            # a block that cannot build must not stay pinned in HBM
            self._residency.mark_failed(blocks)

    def drain_decisions(self) -> List[dict]:
        """Per-epoch decision records accumulated since the last drain
        (consumed by the coordinate into the progress ledger)."""
        out = self.decisions
        self.decisions = []
        return out
