"""Streaming fixed-effect coordinate: out-of-core CD participation.

The in-memory :class:`FixedEffectCoordinate` owns a device-resident
``LabeledData`` for the whole dataset. This coordinate instead owns a
:class:`StreamingSource` and re-streams fixed-shape blocks from disk
through a :class:`BlockPrefetcher` for every solve and every score:

* ``update_model_device`` fuses the CD residual into each block's base
  offsets with one fixed-shape ``dynamic_slice`` program (the residual is
  padded once per update to ``num_blocks × block_rows``), then runs the
  streamed full-batch (or stochastic) solver;
* ``score_device`` assembles the global ``[num_rows]`` score plane from
  per-block matvecs via donated ``dynamic_update_slice`` writes.

All jitted programs live in module-level caches keyed by static shapes, so
the per-(block, update, iteration) trace count is constant — the streaming
parity gate asserts this via ``stream_trace_counts``.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.algorithm.coordinate import Coordinate
from photon_ml_tpu.losses.objective import GlmObjective, make_glm_objective
from photon_ml_tpu.losses.pointwise import loss_for_task
from photon_ml_tpu.models.coefficients import Coefficients
from photon_ml_tpu.models.glm import GeneralizedLinearModel
from photon_ml_tpu.opt.config import GlmOptimizationConfiguration
from photon_ml_tpu.opt.tracking import (
    FixedEffectOptimizationTracker,
    OptimizationStatesTracker,
)
from photon_ml_tpu.streaming.blocks import StreamingSource
from photon_ml_tpu.streaming.gapsched import GapScheduler
from photon_ml_tpu.streaming.prefetch import (
    BlockPrefetcher,
    DeviceBlock,
    PrefetchStats,
)
from photon_ml_tpu.streaming.residency import ResidencyManager
from photon_ml_tpu.streaming.solver import (
    BlockStatsProbe,
    StreamPrograms,
    StreamSolveInfo,
    _note_trace,
    solve_streaming,
    solve_streaming_stochastic,
)
from photon_ml_tpu.telemetry import span
from photon_ml_tpu.types import TaskType


# make_glm_objective builds fresh closures per call; the streamed-solver
# program caches key on objective identity, so same-task coordinates must
# share one instance or every new estimator would retrace the solver suite
_OBJECTIVE_CACHE: Dict[TaskType, GlmObjective] = {}


def _objective_for_task(task: TaskType) -> GlmObjective:
    obj = _OBJECTIVE_CACHE.get(task)
    if obj is None:
        obj = make_glm_objective(loss_for_task(task))
        _OBJECTIVE_CACHE[task] = obj
    return obj


@partial(jax.jit, static_argnames=("padded",))
def _pad_residual(residual: jax.Array, padded: int) -> jax.Array:
    _note_trace("stream_pad_residual")
    return jnp.pad(residual, (0, padded - residual.shape[0]))


@jax.jit
def _fuse_block_offsets(
    base: jax.Array, residual_padded: jax.Array, start: jax.Array
) -> jax.Array:
    """base offsets + the block's residual slice; ``start`` is traced so one
    program serves every block."""
    _note_trace("stream_block_offsets")
    b = base.shape[0]
    return base + jax.lax.dynamic_slice(residual_padded, (start,), (b,))


@jax.jit
def _block_matvec(values, indices, w) -> jax.Array:
    _note_trace("stream_block_matvec")
    return jnp.sum(values * w[indices], axis=-1)


@partial(jax.jit, donate_argnums=(0,))
def _scatter_scores(out: jax.Array, block_scores: jax.Array, start: jax.Array):
    _note_trace("stream_scatter_scores")
    return jax.lax.dynamic_update_slice(out, block_scores, (start,))


@partial(jax.jit, static_argnames=("n",))
def _trim(out: jax.Array, n: int) -> jax.Array:
    _note_trace("stream_trim_scores")
    return out[:n]


@dataclasses.dataclass
class StreamingFixedEffectCoordinate(Coordinate):
    """Fixed-effect GLM trained out-of-core from a StreamingSource.

    Restrictions vs the in-memory coordinate (enforced by the estimator):
    no normalization context (a streamed-stats pass is future work), no
    per-coefficient variances, first-order solvers only in full-batch mode.
    """

    source: StreamingSource
    shard_id: str
    task: TaskType
    configuration: GlmOptimizationConfiguration
    prefetch_depth: int = 2
    mode: str = "full"            # "full" (exact) | "stochastic"
    epochs: int = 5               # stochastic: passes per update
    chunk_iters: int = 4          # stochastic: solver iters per block group
    blocks_per_update: int = 1    # stochastic: blocks concatenated per group
    seed: int = 0
    last_tracker: Optional[FixedEffectOptimizationTracker] = dataclasses.field(
        default=None, repr=False
    )
    last_solve_info: Optional[StreamSolveInfo] = dataclasses.field(
        default=None, repr=False
    )
    last_prefetch_stats: Optional[PrefetchStats] = dataclasses.field(
        default=None, repr=False
    )
    # convergence plane: when True, full-batch solves run the probe variant
    # of the accumulation program and leave each pass's per-block partial
    # loss / grad norm / gap estimate in ``last_block_stats`` (and on the
    # pass's PrefetchStats.block_gaps — the DuHL scheduler seam). Off by
    # default: the original programs run untouched (bitwise contract).
    collect_block_stats: bool = False
    last_block_stats: Optional[list] = dataclasses.field(
        default=None, repr=False
    )
    # DuHL: when True, stochastic epochs visit blocks by staleness-decayed
    # duality-gap importance (GapScheduler) instead of the blind per-epoch
    # permutation. Off by default — the off path is bitwise identical to
    # the historical trajectory (CI parity gate). The scheduler persists
    # across updates/outer iterations so gap scores survive between CD
    # rounds; each solve's per-epoch decisions land in
    # ``last_schedule_decisions`` for the progress ledger.
    gap_schedule: bool = False
    last_schedule_decisions: Optional[list] = dataclasses.field(
        default=None, repr=False
    )
    # failure plane: blocks skipped this update (on_block_error=skip),
    # drained by the CD driver into the progress ledger
    last_skipped_blocks: Optional[list] = dataclasses.field(
        default=None, repr=False
    )
    # cluster plane: when set (a ClusterPlane or ClusterCoordinator,
    # parallel/cluster), full-batch solves delegate every streamed pass to
    # the distributed allreduce — this host streams nothing itself; the
    # workers stream their assigned block shares and the solver consumes
    # the summed (f, g) through the pass_fn seam. Full-batch only: the
    # stochastic trajectory is order-dependent, so there is no cross-host
    # decomposition that preserves it.
    cluster: Optional[object] = dataclasses.field(default=None, repr=False)
    last_cluster_events: Optional[list] = dataclasses.field(
        default=None, repr=False
    )
    # per-pass skew profiles (coordinator telemetry, when enabled) drained
    # after each cluster solve for the progress ledger's
    # cluster_pass/host_pass records
    last_cluster_passes: Optional[list] = dataclasses.field(
        default=None, repr=False
    )
    # HBM residency plane (streaming/residency.py): a nonzero block budget
    # and/or a byte budget pins the top-gap blocks' device arrays across
    # passes, skipping their device_put entirely; the non-resident
    # remainder streams through the prefetcher as before. Off by default —
    # with both unset the streamed path is bitwise identical to today (the
    # CI residency parity gate pins this). The manager persists across CD
    # outer iterations, so pinned blocks survive between solves; re-pinning
    # happens only between passes (never mid-pass).
    resident_blocks: int = 0
    resident_bytes: Optional[int] = None
    last_residency_decisions: Optional[list] = dataclasses.field(
        default=None, repr=False
    )
    _residency: Optional[ResidencyManager] = dataclasses.field(
        default=None, repr=False
    )
    _gap_scheduler: Optional[GapScheduler] = dataclasses.field(
        default=None, repr=False
    )
    _objective: Optional[GlmObjective] = dataclasses.field(
        default=None, repr=False
    )

    supports_device_plane = True

    def __post_init__(self) -> None:
        if self.mode not in ("full", "stochastic"):
            raise ValueError(
                f"streaming mode must be 'full' or 'stochastic', got {self.mode!r}"
            )
        if self.shard_id not in self.source.plan.shard_dims:
            raise ValueError(
                f"shard {self.shard_id!r} not in streaming plan "
                f"{sorted(self.source.plan.shard_dims)}"
            )
        if self.gap_schedule and self.mode != "stochastic":
            raise ValueError(
                "gap_schedule requires stochastic streaming mode (full-batch"
                " mode must visit every block per pass to stay exact)"
            )
        if self.cluster is not None:
            if self.mode != "full":
                raise ValueError(
                    "cluster training requires full-batch streaming mode: "
                    "the distributed pass sums exact per-host partials"
                )
            if self.cluster.num_blocks != self.source.plan.num_blocks:
                raise ValueError(
                    f"cluster planned {self.cluster.num_blocks} blocks but "
                    f"this source streams {self.source.plan.num_blocks}"
                )
        if self.resident_blocks or self.resident_bytes is not None:
            if self.cluster is not None:
                raise ValueError(
                    "device residency requires local streaming: cluster "
                    "workers own their blocks' device placement"
                )
            if self.mode == "stochastic" and not self.gap_schedule:
                raise ValueError(
                    "stochastic residency requires gap_schedule — the "
                    "scheduler's gap feedback is what picks the resident set"
                )
            self._residency = ResidencyManager(
                self.source.plan.num_blocks,
                self.source.block_upload_bytes((self.shard_id,)),
                max_blocks=int(self.resident_blocks),
                max_bytes=self.resident_bytes,
            )

    # -- shapes -----------------------------------------------------------

    @property
    def dim(self) -> int:
        return self.source.plan.shard_dims[self.shard_id]

    @property
    def num_rows(self) -> int:
        return self.source.plan.total_rows

    def objective(self) -> GlmObjective:
        if self._objective is None:
            self._objective = _objective_for_task(self.task)
        return self._objective

    # -- streamed passes --------------------------------------------------

    def _blocks(self, residual_padded=None, order=None):
        """One streamed pass of DeviceBlocks for this shard; when a padded
        residual plane is given, each block's offsets get its slice fused
        in (fixed-shape program, traced once)."""
        prefetcher = BlockPrefetcher(
            self.source,
            shards=(self.shard_id,),
            depth=self.prefetch_depth,
            order=order,
        )
        self.last_prefetch_stats = prefetcher.stats
        for blk in prefetcher:
            data = blk.data[self.shard_id]
            if residual_padded is not None:
                start = jnp.int32(blk.start)
                data = data.replace(
                    offsets=_fuse_block_offsets(
                        data.offsets, residual_padded, start
                    )
                )
                blk.data[self.shard_id] = data
            yield blk

    def _pass_blocks(self, residual_padded=None, order=None, probe=None):
        """One streamed pass, residency-aware. With no residency plane this
        is exactly the historical ``_blocks`` pass (bitwise contract); with
        one it is the resident/streamed merge of ``_resident_pass``. Either
        way the probe (when given) is told each yielded block's true index
        so gap attribution survives skips and merges."""
        if self._residency is None:
            for blk in self._blocks(residual_padded, order=order):
                if probe is not None:
                    probe.note_visit(blk.index)
                yield blk
            return
        yield from self._resident_pass(residual_padded, order, probe)

    def _resident_pass(self, residual_padded, order, probe):
        """Merge device-resident blocks with the streamed remainder.

        The visit order is IDENTICAL to the non-resident pass — resident
        blocks are served in place, from HBM, while only the non-resident
        remainder flows through the prefetcher (whose H2D overlaps the
        resident blocks' solve work). Identical order means identical
        floating-point accumulation, so residency changes transfer volume
        only, never the trajectory.

        Resident entries keep their BASE offsets; the CD residual is fused
        into a per-pass copy by the same fixed-shape program as the
        streamed path (no mutation of the pinned arrays, no new traces).
        Re-pinning happens HERE, at pass start, from the probe's previous
        completed pass — between passes, never mid-pass.
        """
        mgr = self._residency
        if probe is not None and probe.has_measurements:
            mgr.update_gaps({
                s["block"]: s["gap_estimate"] for s in probe.last_pass
            })
            mgr.repin()
        visit = (
            list(range(self.source.plan.num_blocks))
            if order is None
            else [int(i) for i in order]
        )
        stream_order = [i for i in visit if not mgr.is_resident(i)]
        prefetcher = BlockPrefetcher(
            self.source,
            shards=(self.shard_id,),
            depth=self.prefetch_depth,
            order=stream_order,
        )
        self.last_prefetch_stats = prefetcher.stats
        streamed = iter(prefetcher)
        pending = next(streamed, None)
        for i in visit:
            blk = mgr.get(i)
            if blk is not None:
                prefetcher.stats.resident_hit_blocks += 1
                prefetcher.stats.resident_hit_bytes += mgr.block_bytes
            elif pending is not None and pending.index == i:
                blk = pending
                # store-on-visit: the upload we just paid for is retained
                # if the block is in the pin target and the budget has room
                mgr.offer(i, blk)
                pending = next(streamed, None)
            else:
                continue  # skipped upstream (on_block_error=skip)
            if probe is not None:
                probe.note_visit(blk.index)
            data = blk.data[self.shard_id]
            if residual_padded is not None:
                data = data.replace(
                    offsets=_fuse_block_offsets(
                        data.offsets, residual_padded, jnp.int32(blk.start)
                    )
                )
            yield DeviceBlock(
                index=blk.index, start=blk.start, num_real=blk.num_real,
                data={self.shard_id: data}, weight_sum=blk.weight_sum,
            )

    # -- Coordinate interface --------------------------------------------

    def update_model_device(
        self, model: Optional[GeneralizedLinearModel], residual_scores: jax.Array
    ) -> GeneralizedLinearModel:
        plan = self.source.plan
        residual_padded = _pad_residual(residual_scores, plan.padded_rows)
        w0 = (
            jnp.zeros((self.dim,), dtype=jnp.float32)
            if model is None
            else model.coefficients.means
        )
        info = StreamSolveInfo()
        probe = (
            BlockStatsProbe()
            if (
                # the residency plane NEEDS the gap probe: the resident set
                # is chosen from measured gaps, never statically
                (self.collect_block_stats or self._residency is not None)
                and self.mode == "full"
                and self.cluster is None  # workers report stats instead
            )
            else None
        )
        with span(
            "fe/solve",
            device_sync=True,
            optimizer=self.configuration.optimizer_config.optimizer.name,
            streaming=self.mode,
            blocks=plan.num_blocks,
        ):
            if self.cluster is not None:
                result = self._solve_cluster(w0, residual_scores, info)
            elif self.mode == "full":
                result = solve_streaming(
                    self.objective(),
                    w0,
                    make_blocks=lambda: (
                        blk.data[self.shard_id]
                        for blk in self._pass_blocks(
                            residual_padded, probe=probe
                        )
                    ),
                    configuration=self.configuration,
                    info=info,
                    probe=probe,
                )
            else:
                total_weight = float(np.sum(self.source.row_planes().weights))
                scheduler = None
                if self.gap_schedule:
                    if self._gap_scheduler is None:
                        self._gap_scheduler = GapScheduler(
                            plan.num_blocks, plan=plan, seed=self.seed
                        )
                        if self._residency is not None:
                            # stochastic repin rides the scheduler's own
                            # epoch-end gap feedback (one signal, two
                            # consumers); mark_failed evicts through the
                            # same attachment
                            self._gap_scheduler.attach_residency(
                                self._residency
                            )
                    scheduler = self._gap_scheduler
                result = solve_streaming_stochastic(
                    self.objective(),
                    w0,
                    make_blocks_ordered=lambda order: (
                        _OwnShardBlocks(self, residual_padded, order)
                    ),
                    configuration=self.configuration,
                    num_blocks=plan.num_blocks,
                    total_weight=total_weight,
                    epochs=self.epochs,
                    chunk_iters=self.chunk_iters,
                    blocks_per_update=self.blocks_per_update,
                    seed=self.seed,
                    info=info,
                    scheduler=scheduler,
                )
                if scheduler is not None:
                    self.last_schedule_decisions = (
                        scheduler.drain_decisions()
                    )
            jax.block_until_ready(result.w)
        skipped = self.source.drain_skipped_blocks()
        if skipped:
            self.last_skipped_blocks = skipped
            failed = [s["block"] for s in skipped]
            if self._gap_scheduler is not None:
                self._gap_scheduler.mark_failed(failed)
            if self._residency is not None:
                # idempotent with the scheduler's forwarding: a pinned
                # block that failed to rebuild must leave HBM either way
                self._residency.mark_failed(failed)
        self.last_solve_info = info
        self.last_tracker = FixedEffectOptimizationTracker(
            states=OptimizationStatesTracker.from_result(result)
        )
        if probe is not None:
            self.last_block_stats = probe.last_pass
            if self.last_prefetch_stats is not None:
                self.last_prefetch_stats.block_gaps = {
                    s["block"]: s["gap_estimate"] for s in probe.last_pass
                }
        if self._residency is not None:
            if probe is not None and probe.has_measurements:
                # fold the FINAL pass's gaps in so the next solve (or the
                # score passes between CD outer iterations) starts on the
                # freshest resident set — still a between-pass repin
                self._residency.update_gaps({
                    s["block"]: s["gap_estimate"] for s in probe.last_pass
                })
                self._residency.repin()
            decisions = self._residency.drain_decisions()
            if decisions:
                self.last_residency_decisions = (
                    self.last_residency_decisions or []
                ) + decisions
        return GeneralizedLinearModel(
            coefficients=Coefficients(means=result.w), task=self.task
        )

    def _solve_cluster(self, w0, residual_scores, info):
        """Full-batch solve with every streamed pass delegated to the
        cluster's distributed allreduce (parallel/cluster).

        The workers return UNregularized partial (f, g) sums; finalize runs
        here, on the coordinator, exactly as the single-host ``_full_pass``
        does — so the L-BFGS trajectory matches single-host up to
        floating-point reassociation of the per-host sums (parity is gated
        on held-out AUC, not bitwise). Per-pass worker block stats land in
        ``last_block_stats`` and reassignment/rebalance events in
        ``last_cluster_events`` for the progress ledger.
        """
        programs = StreamPrograms.for_objective(self.objective())
        self.cluster.set_residual(
            None if residual_scores is None else np.asarray(residual_scores)
        )
        last_stats: list = []

        def pass_fn(w_at, l2):
            f_sum, g_sum, _, block_stats = self.cluster.distributed_pass(
                np.asarray(w_at)
            )
            info.blocks += len(block_stats)
            last_stats[:] = block_stats
            return programs.finalize(
                jnp.asarray(f_sum, dtype=w_at.dtype),
                jnp.asarray(g_sum, dtype=w_at.dtype),
                w_at,
                l2,
            )

        result = solve_streaming(
            self.objective(),
            w0,
            make_blocks=None,
            configuration=self.configuration,
            info=info,
            pass_fn=pass_fn,
        )
        if last_stats:
            self.last_block_stats = [
                {
                    "block": st["block"],
                    "partial_loss": st["partial_loss"],
                    "partial_grad_norm": st["partial_grad_norm"],
                    "gap_estimate": st["gap"],
                    "host": st.get("host", -1),
                }
                for st in sorted(last_stats, key=lambda s: s["block"])
            ]
        events = self.cluster.drain_events()
        if events:
            self.last_cluster_events = events
        drain_profiles = getattr(self.cluster, "drain_pass_profiles", None)
        if drain_profiles is not None:
            profiles = drain_profiles()
            if profiles:
                self.last_cluster_passes = profiles
        return result

    def update_model(
        self, model: Optional[GeneralizedLinearModel], residual_scores: np.ndarray
    ) -> GeneralizedLinearModel:
        return self.update_model_device(
            model, jnp.asarray(residual_scores, dtype=jnp.float32)
        )

    def score_device(self, model: GeneralizedLinearModel) -> jax.Array:
        plan = self.source.plan
        w = model.coefficients.means
        out = jnp.zeros((plan.padded_rows,), dtype=jnp.float32)
        # residency-aware: score passes serve pinned blocks from HBM too
        for blk in self._pass_blocks():
            feats = blk.data[self.shard_id].features
            scores = _block_matvec(feats.values, feats.indices, w)
            out = _scatter_scores(out, scores, jnp.int32(blk.start))
        return _trim(out, plan.total_rows)

    def score(self, model: GeneralizedLinearModel) -> np.ndarray:
        return np.asarray(self.score_device(model))


class _OwnShardBlocks:
    """Iterable view of one streamed pass restricted to the coordinate's
    shard, with residual offsets fused (stochastic mode needs block-level
    weight sums, so it receives the DeviceBlock-shaped wrapper)."""

    def __init__(self, coord, residual_padded, order):
        self.coord = coord
        self.residual_padded = residual_padded
        self.order = None if order is None else [int(i) for i in order]

    def __iter__(self):
        for blk in self.coord._pass_blocks(
            self.residual_padded, order=self.order
        ):
            yield _ShardBlock(
                data=blk.data[self.coord.shard_id],
                weight_sum=blk.weight_sum,
                index=blk.index,
            )


@dataclasses.dataclass
class _ShardBlock:
    data: object
    weight_sum: float
    # real block index: keeps gap attribution correct when a degraded
    # pass (on_block_error=skip) yields fewer blocks than ordered
    index: int = -1
