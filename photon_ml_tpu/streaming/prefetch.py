"""Double-buffered host→device block prefetcher.

A background thread assembles and ELL-packs HostBlocks into a bounded queue
of depth ``prefetch_depth`` — the staging buffer. The expensive part-file
decodes are scheduled ahead of the assembly cursor on the source's decode
pool (``decode_workers`` threads; Avro inflate and the vectorized columnar
decode release the GIL), so several files decode concurrently while the
consumer pops a staged block, issues the (async) ``device_put``, and the
device solves block *k*. Host memory for staged feature payloads is bounded
by ``prefetch_depth × block bytes`` by the queue itself, plus the decoded
readahead files held by the source's LRU.

Telemetry: decode runs under ``read stream block`` spans (io phase in the
analyzer's bubble accounting), consumer stalls under ``read stream wait``
(io — a visible input-pipeline bubble), and uploads under
``stream h2d transfer`` (transfers phase). The registry gains
``stream.blocks`` / ``stream.decode_s`` / ``stream.stall_s`` /
``stream.prefetch_hide_ratio`` — the metric deps of the ``stream.*`` knobs.
"""

from __future__ import annotations

import dataclasses
import queue
import threading
import time
from typing import Dict, Iterator, Optional, Sequence

import jax
import jax.numpy as jnp

from photon_ml_tpu.ops.data import LabeledData
from photon_ml_tpu.ops.features import EllFeatures
from photon_ml_tpu.resilience.failures import record_failure
from photon_ml_tpu.streaming.blocks import (
    HostBlock,
    StreamingSource,
    readahead_file_budget,
)
from photon_ml_tpu.telemetry import get_registry, span

_DONE = object()


@dataclasses.dataclass
class DeviceBlock:
    """One device-resident block: fixed-shape LabeledData per shard plus
    the block's place in the global row space."""

    index: int
    start: int
    num_real: int
    data: Dict[str, LabeledData]   # shard -> [block_rows] LabeledData
    weight_sum: float              # Σ real weights (stochastic l2 scaling)


@dataclasses.dataclass
class PrefetchStats:
    """Wall-clock accounting of one streamed pass.

    ``decode_s`` is WALL time with at least one decode in flight;
    ``decode_work_s`` is the per-thread SUM — with N parallel workers the
    sum can be ~N× the wall, which is why the hide ratio is defined over
    wall (the PR 10 ratio divided stall by summed work and was distorted
    whenever the pool overlapped)."""

    blocks: int = 0
    decode_s: float = 0.0        # decode wall clock (>=1 decode in flight)
    decode_work_s: float = 0.0   # summed per-thread decode+pack seconds
    stall_s: float = 0.0         # consumer time blocked waiting for a block
    transfer_s: float = 0.0      # device_put dispatch time (all uploads)
    upload_hidden_s: float = 0.0  # uploads dispatched while solve in flight
    h2d_bytes: int = 0           # bytes actually crossing host->device
    cache_hit_blocks: int = 0    # blocks served from the block cache
    cache_load_s: float = 0.0    # wall seconds mapping+validating entries
    # HBM residency plane (streaming/residency.py): blocks this pass served
    # straight from the device-resident set — uploads that never happened.
    # Written by the streamed coordinate, which owns the resident/streamed
    # merge; the prefetcher itself only ever sees the non-resident order.
    resident_hit_blocks: int = 0
    resident_hit_bytes: int = 0  # H2D bytes those hits avoided
    # per-block duality-gap estimates of the most recent streamed solve's
    # final pass (block index -> gap), written by the streaming coordinate
    # when the convergence plane is on. The DuHL-style GapScheduler
    # (streaming/gapsched.py) consumes the same signal in stochastic mode
    # and drives BlockPrefetcher.order with it (ROADMAP item 3)
    block_gaps: Optional[Dict[int, float]] = None

    @property
    def hide_ratio(self) -> float:
        """WALL-based: fraction of decode wall clock that did NOT surface
        as a consumer stall. A fully cached pass has decode_s == 0 — all
        data movement hidden — and reads 1.0."""
        if self.decode_s <= 0:
            return 1.0
        return max(0.0, (self.decode_s - self.stall_s) / self.decode_s)

    @property
    def decode_parallelism(self) -> float:
        """Achieved decode-pool parallelism: summed per-thread decode work
        over decode wall clock. 1.0 means fully serial; ~N means N workers
        genuinely overlapped. 0.0 when no decode ran (fully cached pass)."""
        if self.decode_s <= 0:
            return 0.0
        return self.decode_work_s / self.decode_s


class BlockPrefetcher:
    """Iterate a StreamingSource's blocks with background decode.

    ``depth=0`` disables the thread (synchronous decode — the debugging /
    determinism baseline); ``depth>=1`` double-buffers with a staging queue
    of that size.
    """

    def __init__(
        self,
        source: StreamingSource,
        shards: Optional[Sequence[str]] = None,
        depth: int = 2,
        order: Optional[Sequence[int]] = None,
    ):
        self.source = source
        self.shards = tuple(shards) if shards is not None else None
        self.depth = int(depth)
        self.order = list(order) if order is not None else None
        self.stats = PrefetchStats()
        if self.depth < 0:
            raise ValueError(f"prefetch depth must be >= 0, got {depth}")

    # -- host->device -----------------------------------------------------

    def _to_device(self, blk: HostBlock) -> DeviceBlock:
        t0 = time.perf_counter()
        # indices upload as i32 regardless of the host dtype, so count the
        # converted size — these bytes feed the ≥2× residency gate and must
        # match what actually crosses the H2D link
        nbytes = blk.labels.nbytes + blk.offsets.nbytes + blk.weights.nbytes
        for vals, idx in blk.shards.values():
            nbytes += vals.nbytes + idx.size * 4
        with span("stream h2d transfer", block=blk.index, bytes=int(nbytes)):
            data: Dict[str, LabeledData] = {}
            labels = jax.device_put(blk.labels)
            offsets = jax.device_put(blk.offsets)
            weights = jax.device_put(blk.weights)
            for sid, (vals, idx) in blk.shards.items():
                feats = EllFeatures(
                    values=jax.device_put(vals),
                    indices=jax.device_put(jnp.asarray(idx, dtype=jnp.int32)),
                    num_cols=self.source.plan.shard_dims[sid],
                )
                data[sid] = LabeledData(
                    features=feats, labels=labels,
                    offsets=offsets, weights=weights,
                )
        dt = time.perf_counter() - t0
        self.stats.transfer_s += dt
        self.stats.h2d_bytes += int(nbytes)
        if self.stats.blocks > 1:
            # device_put is async-dispatched and acc_vg returns futures, so
            # every upload after the pass's first is issued while the
            # PREVIOUS block's solve is still in flight — that's the
            # H2D/compute overlap through the donated accumulator seam
            self.stats.upload_hidden_s += dt
        weight_sum = float(blk.weights.sum())
        return DeviceBlock(
            index=blk.index, start=blk.start, num_real=blk.num_real,
            data=data, weight_sum=weight_sum,
        )

    # -- iteration --------------------------------------------------------

    def __iter__(self) -> Iterator[DeviceBlock]:
        work0 = self.source.work_seconds
        wall0 = self.source.decode_wall_seconds
        cache = self.source.cache
        hits0 = cache.stats.hits if cache is not None else 0
        load0 = cache.stats.load_s if cache is not None else 0.0
        try:
            if self.depth == 0:
                yield from self._iter_sync()
            else:
                yield from self._iter_threaded()
        finally:
            # differencing the source's counters attributes exactly this
            # pass's decode, whichever thread ran it
            self.stats.decode_s += self.source.decode_wall_seconds - wall0
            self.stats.decode_work_s += self.source.work_seconds - work0
            if cache is not None:
                self.stats.cache_hit_blocks += cache.stats.hits - hits0
                self.stats.cache_load_s += cache.stats.load_s - load0
        reg = get_registry()
        reg.count("stream.blocks", self.stats.blocks)
        reg.count("stream.decode_s", self.stats.decode_s)
        reg.count("stream.decode_work_s", self.stats.decode_work_s)
        reg.count("stream.stall_s", self.stats.stall_s)
        reg.count("stream.transfer_s", self.stats.transfer_s)
        reg.count("stream.upload_hidden_s", self.stats.upload_hidden_s)
        reg.count("stream.h2d_bytes", self.stats.h2d_bytes)
        reg.count("stream.cache_hit_blocks", self.stats.cache_hit_blocks)
        reg.count("stream.cache_load_s", self.stats.cache_load_s)
        reg.gauge("stream.prefetch_hide_ratio", self.stats.hide_ratio)
        if self.stats.decode_s > 0:
            reg.gauge("stream.decode_parallelism", self.stats.decode_parallelism)

    def _block_order(self):
        if self.order is not None:
            return list(self.order)
        return list(range(self.source.plan.num_blocks))

    def _readahead(self, order, pos) -> None:
        """Schedule background decode of the files the next few blocks
        need; window = min(decode workers, readahead file budget) + queue
        depth, so the pool stays fed but decoded-file residency — the
        streaming peak-RSS term — stays bounded by the budget even on a
        many-core host where the pool is 16 wide (blocks.py enforces the
        same budget on the scheduled file list itself). Cache-aware:
        blocks the block cache already holds schedule nothing."""
        window = (
            min(self.source.decode_workers, readahead_file_budget())
            + max(1, self.depth)
        )
        self.source.prefetch_blocks(order[pos:pos + window], shards=self.shards)

    def _iter_sync(self) -> Iterator[DeviceBlock]:
        it = self.source.iter_blocks(order=self.order, shards=self.shards)
        while True:
            t0 = time.perf_counter()
            try:
                blk = next(it)
            except StopIteration:
                break
            dt = time.perf_counter() - t0
            # synchronous mode: decode time is fully exposed, count it as
            # a stall so hide_ratio reads 0 honestly
            self.stats.stall_s += dt
            self.stats.blocks += 1
            yield self._to_device(blk)

    def _iter_threaded(self) -> Iterator[DeviceBlock]:
        q: "queue.Queue" = queue.Queue(maxsize=self.depth)
        stop = threading.Event()

        order = self._block_order()

        def worker() -> None:
            pos = 0
            try:
                for pos, b in enumerate(order):
                    if stop.is_set():
                        break
                    self._readahead(order, pos)
                    with span("read stream block", block=int(b)):
                        blk = self.source.build_block(int(b), shards=self.shards)
                    if blk is not None:  # None = skipped (on_block_error)
                        q.put((pos, blk))
                q.put(_DONE)
            except BaseException as e:  # degraded mode: consumer takes over
                q.put((pos, e))

        t = threading.Thread(
            target=worker, name="stream-prefetch", daemon=True
        )
        t.start()
        try:
            while True:
                t0 = time.perf_counter()
                if q.empty():
                    with span("read stream wait"):
                        item = q.get()
                    self.stats.stall_s += time.perf_counter() - t0
                else:
                    item = q.get()
                if item is _DONE:
                    break
                pos, payload = item
                if isinstance(payload, BaseException):
                    # the prefetch thread died past build_block's own
                    # retries: finish the pass with synchronous decodes on
                    # this thread (one more independent attempt per block;
                    # a truly permanent failure still raises here, under
                    # whatever on_block_error policy the source carries)
                    record_failure(
                        "prefetch_worker_failed",
                        "stream.prefetch",
                        f"{type(payload).__name__}: {payload}; falling back"
                        f" to synchronous decode for {len(order) - pos}"
                        " remaining blocks",
                    )
                    for b in order[pos:]:
                        with span("read stream block", block=int(b)):
                            blk = self.source.build_block(
                                int(b), shards=self.shards
                            )
                        if blk is None:
                            continue
                        self.stats.blocks += 1
                        yield self._to_device(blk)
                    break
                self.stats.blocks += 1
                yield self._to_device(payload)
        finally:
            stop.set()
            # drain so a blocked worker can observe the stop flag and exit
            try:
                while True:
                    q.get_nowait()
            except queue.Empty:
                pass
            t.join(timeout=5.0)
