"""Decoded block cache: spill padded ELL blocks to an mmap-able on-disk
format so epochs after the first stream at disk/memory bandwidth with ZERO
Avro work.

PR 10's streaming trainer re-decoded every part file on every pass — 171.7
of 180.7 bench seconds stalled on decode. Snap ML's hierarchical pipeline
(arxiv 1803.06333) is the blueprint: pay the decode once, then every later
block visit is pure data movement. This module is that second level of the
hierarchy:

* after ``StreamingSource.build_block`` first materializes a fixed-shape
  padded :class:`HostBlock`, its arrays are spilled to ONE file per
  (block, shard-subset): an 8-byte magic, a JSON header (cache version,
  plan fingerprint, per-array dtype/shape/offset manifest, per-array
  crc32 checksums), then the raw little-endian array bytes at 64-byte
  alignment;
* reloading maps the file with ``np.memmap`` and returns dtype/shape
  views into the mapping — zero copy, paged in lazily by the kernel, so a
  warm epoch's host cost is one page-cache read per block;
* writers build the entry under a private ``.tmp`` name and ``os.replace``
  it into place — concurrent writers (two prefetch threads racing on one
  block) each produce a fully-valid entry and the last rename wins, so a
  reader never observes a torn file;
* every load validates the magic, version, plan fingerprint and (once per
  process per entry) the per-array checksums; ANY mismatch — truncation,
  corruption, a stale fingerprint after the input data changed — makes
  the cache miss, the caller re-decodes, and the entry is rewritten.

The plan fingerprint commits to the cache version, ``block_rows``, the
ordered part-file list with each file's size and mtime_ns, the feature
shard layout (ELL widths and dims), a content digest of each shard's
feature index map (decoded column ids depend on the name->index
assignment, so an externally loaded map with a different same-size
assignment must miss), the id tags and the reader column options — so
editing an input file, swapping the index maps, re-sharding features or
changing the block size all invalidate cleanly (see docs/SCALING.md).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import logging
import os
import tempfile
import threading
import zlib
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from photon_ml_tpu.resilience.failures import record_failure
from photon_ml_tpu.resilience.faultpoints import fault_point, register_fault_site
from photon_ml_tpu.resilience.retry import RetryExhausted, RetryPolicy

logger = logging.getLogger("photon_ml_tpu")

MAGIC = b"PHBLKC01"
CACHE_VERSION = 1
_ALIGN = 64

FAULT_CACHE_LOAD = register_fault_site(
    "stream.blockcache.load",
    "block-cache entry open/mmap (retried once; any persistent failure"
    " is a clean miss and the block re-decodes)",
)
FAULT_CACHE_STORE = register_fault_site(
    "stream.blockcache.store",
    "block-cache spill write+publish (retried once; a failing cache"
    " never fails training)",
)

# cache IO gets a tighter policy than the decode seam: the fallback
# (re-decode / skip the spill) is cheap, so one quick retry is enough
_CACHE_RETRY = RetryPolicy(max_attempts=2, base_delay_s=0.01)


def _index_map_digest(im) -> str:
    """Digest of one shard's feature name->index assignment."""
    fn = getattr(im, "content_digest", None)
    if callable(fn):
        return str(fn())
    # foreign map object: walk the dense index space (IndexMap contract)
    h = hashlib.sha256()
    for i in range(len(im)):
        h.update(f"{im.get_feature_name(i)}\x00{i}\x01".encode("utf-8"))
    return h.hexdigest()


def plan_fingerprint(
    block_rows: int,
    files: Sequence[str],
    shard_widths: Dict[str, int],
    shard_dims: Dict[str, int],
    id_tags: Sequence[str] = (),
    read_kwargs: Optional[dict] = None,
    index_maps: Optional[Dict[str, object]] = None,
) -> str:
    """Digest of everything the bytes of a decoded block depend on.

    File identity is (path, size, mtime_ns): touching or rewriting any
    part file changes the fingerprint and orphans the old entries (they
    are swept lazily by :meth:`BlockCache.sweep_stale`).

    ``index_maps`` (shard -> IndexMap) MUST be passed whenever the maps
    are loaded externally (--offheap-indexmap-dir): decoded column ids
    are a function of the name->index assignment, and two same-size maps
    with permuted assignments would otherwise produce identical
    fingerprints and silently serve blocks with wrong column indices.
    """
    stats = []
    for path in files:
        st = os.stat(path)
        stats.append([str(path), int(st.st_size), int(st.st_mtime_ns)])
    doc = {
        "version": CACHE_VERSION,
        "block_rows": int(block_rows),
        "files": stats,
        "shard_widths": {k: int(v) for k, v in sorted(shard_widths.items())},
        "shard_dims": {k: int(v) for k, v in sorted(shard_dims.items())},
        "id_tags": list(id_tags),
        "read_kwargs": sorted(
            (str(k), str(v)) for k, v in (read_kwargs or {}).items()
        ),
        "index_maps": {
            str(sid): _index_map_digest(im)
            for sid, im in sorted((index_maps or {}).items())
        },
    }
    blob = json.dumps(doc, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(blob.encode("utf-8")).hexdigest()


def _shard_sig(shards: Sequence[str]) -> str:
    blob = "\x00".join(sorted(shards))
    return hashlib.sha256(blob.encode("utf-8")).hexdigest()[:10]


def _align(n: int) -> int:
    return (n + _ALIGN - 1) // _ALIGN * _ALIGN


@dataclasses.dataclass
class CacheStats:
    """Host-side accounting of one BlockCache (cumulative per instance)."""

    hits: int = 0
    misses: int = 0
    writes: int = 0
    invalid: int = 0       # entries rejected (corrupt/stale) — re-decoded
    load_s: float = 0.0    # wall seconds spent mapping + validating
    write_s: float = 0.0   # wall seconds spent spilling entries


class BlockCache:
    """One fingerprint-keyed directory of spilled block files.

    Layout: ``<root>/<fingerprint[:20]>/block-<index>-<shardsig>.blk``.
    The fingerprint prefix keys the *directory*, so a changed input
    dataset naturally misses without any entry-by-entry checks; the full
    fingerprint is ALSO stored in every header and re-verified on load
    (a truncated hash collision must not resurrect stale data).
    """

    def __init__(self, root: str, fingerprint: str):
        self.root = str(root)
        self.fingerprint = str(fingerprint)
        self.dir = os.path.join(self.root, self.fingerprint[:20])
        self.stats = CacheStats()
        self._lock = threading.Lock()
        self._validated: set = set()  # entry paths whose checksums passed

    # -- paths -------------------------------------------------------------

    def entry_path(self, index: int, shards: Sequence[str]) -> str:
        return os.path.join(
            self.dir, f"block-{int(index):06d}-{_shard_sig(shards)}.blk"
        )

    # -- write -------------------------------------------------------------

    def store(self, block, shards: Sequence[str]) -> bool:
        """Spill one HostBlock. Returns False (and logs) on any IO error —
        a failing cache must never fail training."""
        import time as _time

        t0 = _time.perf_counter()
        try:
            os.makedirs(self.dir, exist_ok=True)
            arrays: List[Tuple[str, np.ndarray]] = [
                ("labels", np.ascontiguousarray(block.labels)),
                ("offsets", np.ascontiguousarray(block.offsets)),
                ("weights", np.ascontiguousarray(block.weights)),
            ]
            for sid in sorted(block.shards):
                vals, idx = block.shards[sid]
                arrays.append((f"shard:{sid}:vals", np.ascontiguousarray(vals)))
                arrays.append((f"shard:{sid}:idx", np.ascontiguousarray(idx)))
            tag_meta: Dict[str, str] = {}
            for tag in sorted(block.id_tags):
                arena, offs = _encode_strings(block.id_tags[tag])
                arrays.append((f"tag:{tag}:arena", arena))
                arrays.append((f"tag:{tag}:off", offs))
                tag_meta[tag] = str(block.id_tags[tag].dtype)

            manifest = []
            offset = 0
            for name, arr in arrays:
                offset = _align(offset)
                manifest.append({
                    "name": name,
                    "dtype": arr.dtype.str,      # little-endian '<f4' etc.
                    "shape": list(arr.shape),
                    "offset": offset,
                    "nbytes": int(arr.nbytes),
                    "crc32": zlib.crc32(arr.tobytes()) & 0xFFFFFFFF,
                })
                offset += arr.nbytes
            header = {
                "version": CACHE_VERSION,
                "fingerprint": self.fingerprint,
                "index": int(block.index),
                "start": int(block.start),
                "num_real": int(block.num_real),
                "shards": sorted(block.shards),
                "tag_dtypes": tag_meta,
                "arrays": manifest,
            }
            hdr = json.dumps(header, separators=(",", ":")).encode("utf-8")
            base = _align(len(MAGIC) + 4 + len(hdr))

            path = self.entry_path(block.index, shards)

            def _publish():
                # each attempt writes a fresh private tmp, so a retried
                # publish never reuses a half-written file
                fault_point(FAULT_CACHE_STORE)
                fd, tmp = tempfile.mkstemp(
                    dir=self.dir, prefix=f".tmp-{os.getpid()}-", suffix=".blk"
                )
                try:
                    with os.fdopen(fd, "wb") as f:
                        f.write(MAGIC)
                        f.write(len(hdr).to_bytes(4, "little"))
                        f.write(hdr)
                        f.write(b"\x00" * (base - len(MAGIC) - 4 - len(hdr)))
                        at = 0
                        for _, arr in arrays:
                            pad = _align(at) - at
                            if pad:
                                f.write(b"\x00" * pad)
                                at += pad
                            f.write(arr.tobytes())
                            at += arr.nbytes
                        f.flush()
                        os.fsync(f.fileno())
                    os.replace(tmp, path)  # atomic publish: readers never see torn files
                finally:
                    if os.path.exists(tmp):
                        os.unlink(tmp)

            _CACHE_RETRY.run("stream.blockcache.store", _publish)
            with self._lock:
                self.stats.writes += 1
                self._validated.add(path)  # we just wrote + checksummed it
            return True
        except Exception as e:
            # not just OSError: an odd id-tag dtype, a MemoryError on
            # tobytes() of a huge shard — none of it may abort training
            logger.warning("block cache store failed (%s); continuing", e)
            record_failure(
                "cache_store_failed",
                "stream.blockcache.store",
                f"{type(e).__name__}: {e}",
                block=int(block.index),
            )
            return False
        finally:
            with self._lock:
                self.stats.write_s += _time.perf_counter() - t0

    # -- read --------------------------------------------------------------

    def load(self, index: int, shards: Sequence[str]):
        """Return a HostBlock backed by memmap views, or None on miss or
        any validation failure (the caller then re-decodes and rewrites).
        Checksums are verified the first time each entry is loaded by this
        process; later loads of a validated entry skip the pass so warm
        epochs run at page-cache speed."""
        import time as _time

        from photon_ml_tpu.streaming.blocks import HostBlock

        t0 = _time.perf_counter()
        path = self.entry_path(index, shards)

        def _open():
            # map via an explicit fd so fstat pins the identity of the file
            # actually mapped: the invalidation unlink below must not delete
            # a FRESH entry a concurrent writer just os.replace'd over this
            # path after we opened the stale one
            fault_point(FAULT_CACHE_LOAD)
            with open(path, "rb") as f:
                st = os.fstat(f.fileno())
                m = np.memmap(f, dtype=np.uint8, mode="r")
            return m, (st.st_ino, st.st_size, st.st_mtime_ns)

        try:
            # FileNotFoundError is a normal miss (non-retryable); a flaky
            # open/mmap gets one quick retry before degrading to re-decode
            mm, mapped_key = _CACHE_RETRY.run("stream.blockcache.load", _open)
        except (RetryExhausted, OSError, ValueError):
            with self._lock:
                self.stats.misses += 1
                self.stats.load_s += _time.perf_counter() - t0
            return None
        try:
            header = self._parse_header(mm)
            if header is None or int(header["index"]) != int(index):
                raise ValueError("bad header")
            if header["fingerprint"] != self.fingerprint:
                raise ValueError("stale fingerprint")
            views: Dict[str, np.ndarray] = {}
            with self._lock:
                need_checksums = path not in self._validated
            # manifest offsets are relative to the aligned payload base
            # (the header length is not known until the manifest is final)
            hlen = int.from_bytes(
                mm[len(MAGIC):len(MAGIC) + 4].tobytes(), "little"
            )
            base = _align(len(MAGIC) + 4 + hlen)
            for spec in header["arrays"]:
                off = base + int(spec["offset"])
                nbytes = int(spec["nbytes"])
                if off + nbytes > mm.size:
                    raise ValueError("truncated entry")
                raw = mm[off:off + nbytes]
                if need_checksums:
                    if (zlib.crc32(raw.tobytes()) & 0xFFFFFFFF) != spec["crc32"]:
                        raise ValueError(f"checksum mismatch: {spec['name']}")
                views[spec["name"]] = (
                    raw.view(np.dtype(spec["dtype"]))
                    .reshape(tuple(spec["shape"]))
                )
            blk_shards = {}
            for sid in header["shards"]:
                blk_shards[sid] = (
                    views[f"shard:{sid}:vals"], views[f"shard:{sid}:idx"]
                )
            id_tags = {}
            for tag, dt in header.get("tag_dtypes", {}).items():
                arr = _decode_strings(
                    views[f"tag:{tag}:arena"], views[f"tag:{tag}:off"], dt
                )
                arr.flags.writeable = False  # HostBlock read-only contract
                id_tags[tag] = arr
            with self._lock:
                self.stats.hits += 1
                self._validated.add(path)
                self.stats.load_s += _time.perf_counter() - t0
            return HostBlock(
                index=int(header["index"]),
                start=int(header["start"]),
                num_real=int(header["num_real"]),
                labels=views["labels"],
                offsets=views["offsets"],
                weights=views["weights"],
                shards=blk_shards,
                id_tags=id_tags,
            )
        except (ValueError, KeyError, TypeError) as e:
            # corrupt/truncated/stale: drop the entry so the re-decode's
            # rewrite is the only copy, and miss
            logger.warning("block cache entry %s invalid (%s); re-decoding",
                           os.path.basename(path), e)
            del mm
            try:
                # unlink only while the path still holds the exact file that
                # failed validation — a concurrent writer may have replaced
                # it with a fresh valid entry since we mapped it (a remaining
                # inode-reuse window is theoretical and costs one re-decode,
                # never correctness)
                st_now = os.stat(path)
                if (st_now.st_ino, st_now.st_size,
                        st_now.st_mtime_ns) == mapped_key:
                    os.unlink(path)
            except OSError:
                pass
            with self._lock:
                self.stats.invalid += 1
                self.stats.misses += 1
                self._validated.discard(path)
                self.stats.load_s += _time.perf_counter() - t0
            return None

    def has(self, index: int, shards: Sequence[str]) -> bool:
        """Cheap existence probe (no validation) — used by the readahead
        window to skip scheduling Avro decodes for already-cached blocks."""
        return os.path.exists(self.entry_path(index, shards))

    # -- maintenance -------------------------------------------------------

    def sweep_stale(self) -> int:
        """Delete sibling fingerprint directories (entries of older plans).
        Returns the number of files removed. Safe to skip — stale dirs are
        only disk, never correctness."""
        removed = 0
        try:
            for name in os.listdir(self.root):
                sub = os.path.join(self.root, name)
                if name == self.fingerprint[:20] or not os.path.isdir(sub):
                    continue
                for f in os.listdir(sub):
                    try:
                        os.unlink(os.path.join(sub, f))
                        removed += 1
                    except OSError:
                        pass
                try:
                    os.rmdir(sub)
                except OSError:
                    pass
        except OSError:
            pass
        return removed

    # -- internals ---------------------------------------------------------

    @staticmethod
    def _parse_header(mm: np.ndarray) -> Optional[dict]:
        if mm.size < len(MAGIC) + 4:
            return None
        if mm[: len(MAGIC)].tobytes() != MAGIC:
            return None
        hlen = int.from_bytes(mm[len(MAGIC):len(MAGIC) + 4].tobytes(), "little")
        if hlen <= 0 or len(MAGIC) + 4 + hlen > mm.size:
            return None
        try:
            header = json.loads(mm[len(MAGIC) + 4:len(MAGIC) + 4 + hlen].tobytes())
        except (json.JSONDecodeError, UnicodeDecodeError):
            return None
        if not isinstance(header, dict) or header.get("version") != CACHE_VERSION:
            return None
        return header


def _encode_strings(arr: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """String/object array -> (uint8 arena, int64 offsets[len+1])."""
    parts = [str(s).encode("utf-8") for s in arr]
    offs = np.zeros(len(parts) + 1, dtype=np.int64)
    if parts:
        np.cumsum([len(p) for p in parts], out=offs[1:])
    arena = np.frombuffer(b"".join(parts), dtype=np.uint8).copy()
    return arena, offs


def _decode_strings(arena: np.ndarray, offs: np.ndarray, dtype: str) -> np.ndarray:
    blob = arena.tobytes()
    vals = [
        blob[offs[i]:offs[i + 1]].decode("utf-8")
        for i in range(len(offs) - 1)
    ]
    if dtype == "object":
        out = np.empty(len(vals), dtype=object)
        out[:] = vals
        return out
    return np.asarray(vals, dtype=np.dtype(dtype))
