"""Block-sharded GLM solving over streamed fixed-shape blocks.

Two modes, both built on the repo's existing optimizer primitives:

* ``solve_streaming`` — EXACT full-batch L-BFGS out of core. The GLM
  objective is a sum over rows plus an L2 term, and the normalization
  gradient map is linear, so accumulating per-block ``value_and_grad``
  (called with l2=0) across all blocks and adding ``0.5·λ·w·w / λ·w`` once
  reproduces the full-batch objective and gradient exactly (weight-0
  padding rows are algebraic no-ops). Directions and curvature updates
  reuse ``opt/lbfgs.py``'s ``two_loop_direction`` / ``update_history``;
  convergence uses ``opt/state.py``'s absolute-tolerance predicates. Each
  outer iteration costs one streamed accumulation pass per line-search
  trial.

* ``solve_streaming_stochastic`` — the resumable seam
  (``solve_init``/``solve_chunk``/``solve_finalize``, opt/solve.py) run as
  ONE jitted program per visited block group: shuffled block order per
  epoch, ``chunk_iters`` solver iterations per group, warm-started ``w``
  carried between groups, λ scaled by the group's weight fraction so the
  per-group optimum matches the full-batch regularization scale. Gated on
  held-out metric parity (tests/bench), per the convergence guidance of
  arxiv 1702.07005 / 1811.01564.

Every jitted program calls ``_note_trace`` inside its traced body, so
``stream_trace_counts()`` counts actual (re)compiles — the CI parity gate
asserts the count does not grow with the number of blocks.
"""

from __future__ import annotations

import dataclasses
from collections import Counter
from functools import partial
from typing import Callable, Dict, Iterable, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.losses.objective import GlmObjective
from photon_ml_tpu.opt.config import GlmOptimizationConfiguration, OptimizerType
from photon_ml_tpu.opt.lbfgs import (
    resolve_history_dtype,
    two_loop_direction,
    update_history,
)
from photon_ml_tpu.opt.solve import solve_chunk, solve_finalize, solve_init
from photon_ml_tpu.opt.state import (
    SolveResult,
    absolute_tolerances,
    function_values_converged,
    gradient_converged,
)
from photon_ml_tpu.telemetry import note_jit_trace
from photon_ml_tpu.types import ConvergenceReason

_TRACE_COUNTS: Counter = Counter()


def _note_trace(program: str, kind: str = "trace") -> None:
    """Python-side-effect compile counter: fires only on a jit cache miss
    (same pattern as estimators/random_effect.py)."""
    _TRACE_COUNTS[(program, kind)] += 1
    note_jit_trace(program, kind)


def stream_trace_counts() -> Dict[Tuple[str, str], int]:
    """(program, kind) -> number of actual jit traces in streaming solvers."""
    return dict(_TRACE_COUNTS)


def reset_stream_trace_counts() -> None:
    _TRACE_COUNTS.clear()


# BlockFn: fresh iterable of per-block LabeledData (offsets already fused
# with the CD residual). Each call streams one full pass from disk.
BlockFn = Callable[[], Iterable]


class BlockStatsProbe:
    """Per-block convergence-plane collector for one streamed solve.

    When a probe is passed to ``solve_streaming`` the accumulation pass runs
    ``acc_vg_probe`` instead of ``acc_vg``: same donated-accumulator math
    plus three extra scalar reductions per block — the block's partial loss,
    partial gradient norm, and a first-order Fenchel duality-gap surrogate
    ``f_k + <w, g_k>`` (the DuHL-style block importance score of
    arxiv 1702.07005, with dual variables implicitly refreshed at the
    current iterate). ``last_pass`` holds the scalars of the most recent
    completed pass — for a converged solve that is the final streamed
    epoch. With no probe the original programs run untouched, so the
    disabled path stays bitwise identical.
    """

    def __init__(self) -> None:
        self._pending: List[tuple] = []
        self._futures: List[tuple] = []
        self._visit_pending: List[int] = []
        self._visit: List[int] = []
        self._resolved: Optional[List[dict]] = None

    def begin_pass(self) -> None:
        self._pending = []
        self._visit_pending = []

    def on_block(self, partial_loss, partial_grad_norm, gap_estimate) -> None:
        self._pending.append((partial_loss, partial_grad_norm, gap_estimate))

    def note_visit(self, block: int) -> None:
        """Optional attribution hook: the block generator records each
        yielded block's TRUE index so ``last_pass`` labels stats by it
        instead of by enumerate position. Without it a degraded pass
        (on_block_error=skip) — or any non-natural visit order, like the
        residency plane's resident/streamed merge under skips — would
        silently misattribute every stat after the first gap."""
        self._visit_pending.append(int(block))

    def end_pass(self) -> None:
        # keep the futures; only the final completed pass is ever read, so
        # host resolution is deferred to the last_pass property — no D2H
        # sync on the intermediate line-search passes
        self._futures = self._pending
        self._visit = self._visit_pending
        self._pending = []
        self._visit_pending = []
        self._resolved = None

    @property
    def has_measurements(self) -> bool:
        """True once at least one streamed pass completed (the residency
        plane repins only on measured evidence)."""
        return bool(self._futures)

    @property
    def last_pass(self) -> List[dict]:
        if self._resolved is None:
            labels = (
                self._visit
                if len(self._visit) == len(self._futures)
                else list(range(len(self._futures)))
            )
            self._resolved = [
                {
                    "block": labels[i],
                    "partial_loss": float(f),
                    "partial_grad_norm": float(g),
                    "gap_estimate": float(gap),
                }
                for i, (f, g, gap) in enumerate(self._futures)
            ]
        return self._resolved


class StreamPrograms:
    """The jitted per-block programs of one streamed solve. Built once per
    objective (``for_objective`` memoizes) and reused across every block,
    every pass, and every CD outer iteration — so the trace count is
    independent of both block count and solve count."""

    _CACHE: Dict[GlmObjective, "StreamPrograms"] = {}

    @classmethod
    def for_objective(cls, objective: GlmObjective) -> "StreamPrograms":
        cached = cls._CACHE.get(objective)
        if cached is None:
            cached = cls._CACHE[objective] = cls(objective)
        return cached

    def __init__(self, objective: GlmObjective):
        # donated accumulators: f/g update in place, so a streamed pass
        # allocates no per-block device buffers — and because acc_vg
        # returns futures, the prefetcher's device_put of block k+1 is
        # dispatched while block k's value_and_grad is still executing
        # (the H2D/compute overlap measured as stream.upload_hidden_s)
        @partial(jax.jit, donate_argnums=(2, 3))
        def acc_vg(w, data, f_acc, g_acc):
            _note_trace("stream_vg")
            f, g = objective.value_and_grad(w, data, jnp.zeros((), w.dtype))
            return f_acc + f, g_acc + g

        @jax.jit
        def finalize(f, g, w, l2):
            _note_trace("stream_finalize")
            f_reg = f + 0.5 * l2 * jnp.dot(w, w)
            g_reg = g + l2 * w
            return f_reg, g_reg, jnp.linalg.norm(g_reg)

        @jax.jit
        def direction(g, s_hist, y_hist, rho, count):
            _note_trace("stream_direction")
            d = two_loop_direction(g, s_hist, y_hist, rho, count)
            dphi0 = jnp.dot(d, g)
            bad = dphi0 >= 0
            d = jnp.where(bad, -g, d)
            dphi0 = jnp.where(bad, -jnp.dot(g, g), dphi0)
            return d, dphi0, jnp.linalg.norm(d)

        @jax.jit
        def step(w, d, t):
            _note_trace("stream_step")
            return w + t * d

        @jax.jit
        def hist_update(s_hist, y_hist, rho, count, w_old, w_new, g_old, g_new):
            _note_trace("stream_history")
            s = (w_new - w_old).astype(s_hist.dtype)
            y = (g_new - g_old).astype(y_hist.dtype)
            return update_history(s_hist, y_hist, rho, count, s, y)

        @partial(jax.jit, donate_argnums=(2, 3))
        def acc_vg_probe(w, data, f_acc, g_acc):
            _note_trace("stream_vg_probe")
            f, g = objective.value_and_grad(w, data, jnp.zeros((), w.dtype))
            # convergence-plane extras: a few scalar reductions per block
            # (see BlockStatsProbe); compiled only when probing is on, so
            # the default path keeps the original acc_vg program
            gap = f + jnp.dot(w, g)
            return f_acc + f, g_acc + g, f, jnp.linalg.norm(g), gap

        @jax.jit
        def gap_probe(w, data):
            # the standalone gap scalar for the stochastic scheduler: same
            # first-order surrogate as acc_vg_probe but without the
            # accumulator plumbing (stochastic mode owns no f/g
            # accumulators). Returns a future; the epoch-end D2H resolve
            # is one host sync per epoch, not per block.
            _note_trace("stream_gap_probe")
            f, g = objective.value_and_grad(w, data, jnp.zeros((), w.dtype))
            return f + jnp.dot(w, g)

        self.acc_vg = acc_vg
        self.acc_vg_probe = acc_vg_probe
        self.gap_probe = gap_probe
        self.finalize = finalize
        self.direction = direction
        self.step = step
        self.hist_update = hist_update


@dataclasses.dataclass
class StreamSolveInfo:
    """Host-side accounting of one streamed solve."""

    passes: int = 0          # streamed accumulation passes over the dataset
    blocks: int = 0          # total blocks visited
    iterations: int = 0
    line_search_trials: int = 0


def _full_pass(
    programs: StreamPrograms, w, make_blocks: BlockFn, dim: int, l2, info,
    probe: Optional[BlockStatsProbe] = None,
):
    """One streamed accumulation of the EXACT full-batch (value, grad)."""
    f = jnp.zeros((), dtype=w.dtype)
    g = jnp.zeros((dim,), dtype=w.dtype)
    if probe is None:
        for data in make_blocks():
            f, g = programs.acc_vg(w, data, f, g)
            info.blocks += 1
    else:
        probe.begin_pass()
        for data in make_blocks():
            f, g, bf, bg, bgap = programs.acc_vg_probe(w, data, f, g)
            probe.on_block(bf, bg, bgap)
            info.blocks += 1
        probe.end_pass()
    info.passes += 1
    return programs.finalize(f, g, w, l2)


def solve_streaming(
    objective: GlmObjective,
    w0,
    make_blocks: Optional[BlockFn],
    configuration: GlmOptimizationConfiguration,
    l2_weight: Optional[float] = None,
    info: Optional[StreamSolveInfo] = None,
    probe: Optional[BlockStatsProbe] = None,
    pass_fn: Optional[Callable] = None,
) -> SolveResult:
    """Exact full-batch L-BFGS with the dataset streamed per pass.

    The line search is backtracking Armijo (each trial = one streamed
    value-and-grad pass, so the accepted point's gradient is free); with
    all blocks visited per pass the trajectory optimizes the identical
    full-batch objective as the in-memory solver and converges to the same
    optimum within solver tolerance.

    ``pass_fn`` replaces the local streamed accumulation with an external
    one — the cluster plane's distributed allreduce pass
    (``parallel/cluster``): called as ``pass_fn(w, l2)`` and expected to
    return the same ``(f_reg, g_reg, ||g_reg||)`` triple as
    ``StreamPrograms.finalize``, i.e. the EXACT full-batch regularized
    value and gradient at ``w``. The L-BFGS trajectory above the pass is
    then identical to single-host up to floating-point reassociation of
    the per-host partial sums.
    """
    if make_blocks is None and pass_fn is None:
        raise ValueError("solve_streaming needs make_blocks or pass_fn")
    cfg = configuration.optimizer_config
    if cfg.optimizer is OptimizerType.TRON:
        raise ValueError(
            "streaming full-batch mode supports first-order solvers (LBFGS);"
            " TRON needs Hessian-vector passes — use the in-memory trainer"
        )
    if configuration.l1_weight > 0:
        raise ValueError(
            "streaming full-batch mode does not support L1/OWL-QN yet; "
            "use stochastic mode or the in-memory trainer"
        )
    info = info if info is not None else StreamSolveInfo()
    w = jnp.asarray(w0, dtype=jnp.float32)
    dim = w.shape[-1]
    l2 = jnp.asarray(
        configuration.l2_weight if l2_weight is None else l2_weight,
        dtype=w.dtype,
    )
    programs = StreamPrograms.for_objective(objective)

    def _pass(w_at):
        if pass_fn is not None:
            info.passes += 1
            return pass_fn(w_at, l2)
        return _full_pass(programs, w_at, make_blocks, dim, l2, info, probe)

    f, g, g_norm = _pass(w)
    abs_f_tol, abs_g_tol = absolute_tolerances(f, g_norm, cfg.tolerance)
    abs_f_tol = float(abs_f_tol)
    abs_g_tol = float(abs_g_tol)

    m = cfg.history_length
    hdtype = resolve_history_dtype(cfg, w.dtype)
    s_hist = jnp.zeros((m, dim), dtype=hdtype)
    y_hist = jnp.zeros((m, dim), dtype=hdtype)
    rho = jnp.zeros((m,), dtype=w.dtype)
    count = jnp.int32(0)

    history = [float(f)]
    reason = ConvergenceReason.MAX_ITERATIONS
    if float(g_norm) <= abs_g_tol:
        reason = ConvergenceReason.GRADIENT_CONVERGED

    it = 0
    while it < cfg.max_iterations and reason is ConvergenceReason.MAX_ITERATIONS:
        d, dphi0, d_norm = programs.direction(g, s_hist, y_hist, rho, count)
        dphi0_f = float(dphi0)
        # Breeze's firstStepSize heuristic, then the quasi-Newton step t=1
        t = 1.0 / max(float(d_norm), 1e-12) if int(count) == 0 else 1.0
        f_host = float(f)

        accepted = None
        for _ in range(max(1, cfg.max_line_search_iterations)):
            info.line_search_trials += 1
            w_try = programs.step(w, d, jnp.asarray(t, dtype=w.dtype))
            f_try, g_try, g_try_norm = _pass(w_try)
            if float(f_try) <= f_host + 1e-4 * t * dphi0_f:
                accepted = (w_try, f_try, g_try, g_try_norm)
                break
            t *= 0.5
        if accepted is None:
            reason = ConvergenceReason.OBJECTIVE_NOT_IMPROVING
            break

        w_new, f_new, g_new, g_new_norm = accepted
        s_hist, y_hist, rho, count = programs.hist_update(
            s_hist, y_hist, rho, count, w, w_new, g, g_new
        )
        it += 1
        info.iterations = it
        history.append(float(f_new))
        if float(g_new_norm) <= abs_g_tol:
            reason = ConvergenceReason.GRADIENT_CONVERGED
        elif abs(f_host - float(f_new)) <= abs_f_tol:
            reason = ConvergenceReason.FUNCTION_VALUES_CONVERGED
        w, f, g, g_norm = w_new, f_new, g_new, g_new_norm

    value_history = np.full((cfg.max_iterations + 1,), np.nan, dtype=np.float32)
    value_history[: len(history)] = history
    return SolveResult(
        w=w,
        value=f,
        grad_norm=g_norm,
        iterations=jnp.int32(it),
        reason=jnp.int32(reason.value),
        value_history=jnp.asarray(value_history),
    )


@jax.jit
def _concat_group(*ds):
    _note_trace("stream_group_concat")
    return jax.tree_util.tree_map(lambda *xs: jnp.concatenate(xs, axis=0), *ds)


def _group_data(datas: List):
    """Concatenate a fixed-size group of identically-shaped LabeledData
    along rows (leaf-wise). Group size is static per run, so the
    module-level jit traces once per size."""
    return datas[0] if len(datas) == 1 else _concat_group(*datas)


# (objective, configuration, chunk_iters) -> jitted init→chunk→finalize
_STOCHASTIC_CACHE: Dict[Tuple, Callable] = {}


def _stochastic_step(
    objective: GlmObjective,
    cfg: GlmOptimizationConfiguration,
    chunk_iters: int,
) -> Callable:
    key = (objective, cfg, int(chunk_iters))
    cached = _STOCHASTIC_CACHE.get(key)
    if cached is not None:
        return cached

    @jax.jit
    def group_step(w_in, data, l2_eff):
        _note_trace("stream_stochastic_chunk")
        state = solve_init(objective, w_in, data, cfg, l2_weight=l2_eff)
        state = solve_chunk(
            objective, state, data, cfg, l2_weight=l2_eff,
            num_iters=chunk_iters,
        )
        return solve_finalize(state, cfg)

    _STOCHASTIC_CACHE[key] = group_step
    return group_step


def _run_stochastic(
    objective: GlmObjective,
    w,
    make_blocks_ordered: Callable[[Optional[np.ndarray]], Iterable],
    cfg: GlmOptimizationConfiguration,
    num_blocks: int,
    total_weight: float,
    epochs: int,
    chunk_iters: int,
    blocks_per_update: int,
    seed: int,
    l2_full: float,
    info: StreamSolveInfo,
    scheduler=None,
) -> SolveResult:
    """The stochastic epoch loop.

    With no scheduler the visit order is the blind per-epoch
    ``rng.permutation`` — bitwise identical to the historical trajectory
    (the CI parity gate pins this). With a :class:`GapScheduler` the order
    comes from ``scheduler.epoch_order()`` and each visited block's
    first-order gap is probed (``stream_gap_probe``, one extra jitted
    scalar program) at the iterate it was visited with; the epoch-end
    resolve feeds the magnitudes back via ``scheduler.update`` — one D2H
    sync per epoch.
    """
    rng = np.random.default_rng(seed)
    group_step = _stochastic_step(objective, cfg, chunk_iters)
    gap_probe = (
        StreamPrograms.for_objective(objective).gap_probe
        if scheduler is not None
        else None
    )

    result = None
    for _ in range(max(1, epochs)):
        if scheduler is None:
            order = rng.permutation(num_blocks)
        else:
            order = scheduler.epoch_order()
        epoch_blocks = len(order)
        gap_futures: List = []
        visited: List[int] = []
        group: List = []
        group_weight = 0.0
        blocks_seen = 0
        for blk in make_blocks_ordered(order):
            # the stream may yield fewer blocks than ordered (degraded
            # on_block_error=skip); gap attribution must follow the
            # block's OWN index, falling back to order position for
            # callers whose block wrappers carry none
            idx = getattr(blk, "index", -1)
            visited.append(
                int(idx) if int(idx) >= 0 else int(order[blocks_seen])
            )
            if gap_probe is not None:
                gap_futures.append(gap_probe(w, blk.data))
            group.append(blk.data)
            group_weight += blk.weight_sum
            blocks_seen += 1
            info.blocks += 1
            boundary = (
                len(group) == blocks_per_update or blocks_seen == epoch_blocks
            )
            if not boundary:
                continue
            # ragged final group: pad with repeats of the last block so the
            # concat shape (and therefore the program) stays fixed
            while len(group) < blocks_per_update:
                group.append(group[-1])
            data = _group_data(group)
            frac = group_weight / max(total_weight, 1e-30)
            l2_eff = jnp.asarray(l2_full * frac, dtype=w.dtype)
            result = group_step(w, data, l2_eff)
            w = result.w
            info.iterations += int(result.iterations)
            group = []
            group_weight = 0.0
        if group:
            # a skipped block kept blocks_seen short of epoch_blocks, so
            # the in-loop boundary never flushed the tail — flush it here
            # (unreachable on a clean pass: the boundary clears the group)
            while len(group) < blocks_per_update:
                group.append(group[-1])
            data = _group_data(group)
            frac = group_weight / max(total_weight, 1e-30)
            l2_eff = jnp.asarray(l2_full * frac, dtype=w.dtype)
            result = group_step(w, data, l2_eff)
            w = result.w
            info.iterations += int(result.iterations)
        if scheduler is not None:
            missing = set(int(b) for b in order) - set(visited)
            if missing:
                # ordered but never yielded: permanently failed and
                # skipped — exclude from every later epoch's schedule
                scheduler.mark_failed(sorted(missing))
            scheduler.update(
                {
                    visited[pos]: float(v)
                    for pos, v in enumerate(gap_futures)
                }
            )
        info.passes += 1
    if result is None:
        raise RuntimeError(
            "no blocks streamed (every block failed or was skipped)"
        )
    return result


def solve_streaming_stochastic(
    objective: GlmObjective,
    w0,
    make_blocks_ordered: Callable[[Optional[np.ndarray]], Iterable],
    configuration: GlmOptimizationConfiguration,
    num_blocks: int,
    total_weight: float,
    epochs: int = 5,
    chunk_iters: int = 4,
    blocks_per_update: int = 1,
    seed: int = 0,
    l2_weight: Optional[float] = None,
    info: Optional[StreamSolveInfo] = None,
    scheduler=None,
) -> SolveResult:
    """Stochastic block-sharded solving on the resumable solver seam.

    Per epoch the block order is reshuffled — or, when a
    :class:`~photon_ml_tpu.streaming.gapsched.GapScheduler` is passed,
    chosen by staleness-decayed duality-gap importance (DuHL, arxiv
    1702.07005); every ``blocks_per_update`` consecutive blocks form one
    update group, solved with
    ``solve_init → solve_chunk(num_iters=chunk_iters) → solve_finalize``
    warm-started from the running ``w``. λ is scaled by the group's share
    of the total example weight so each group optimizes a consistently
    regularized subproblem. The whole init/chunk/finalize composition is
    one jitted program (traced once), so block count never retraces.
    """
    info = info if info is not None else StreamSolveInfo()
    return _run_stochastic(
        objective,
        jnp.asarray(w0, dtype=jnp.float32),
        make_blocks_ordered,
        configuration,
        num_blocks,
        total_weight,
        epochs,
        chunk_iters,
        blocks_per_update,
        seed,
        float(
            configuration.l2_weight if l2_weight is None else l2_weight
        ),
        info,
        scheduler=scheduler,
    )


def streamed_objective_value(
    objective: GlmObjective,
    w,
    make_blocks: BlockFn,
    dim: int,
    l2: float,
    info: Optional[StreamSolveInfo] = None,
) -> float:
    """Exact full-batch objective at ``w`` via one streamed pass (used to
    report the full-batch objective after a stochastic run)."""
    programs = StreamPrograms.for_objective(objective)
    info = info if info is not None else StreamSolveInfo()
    f, _, _ = _full_pass(
        programs, jnp.asarray(w, dtype=jnp.float32), make_blocks, dim,
        jnp.asarray(l2, dtype=jnp.float32), info,
    )
    return float(f)
