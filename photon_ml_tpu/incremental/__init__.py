"""Nearline incremental training + delta artifact publishing.

Closes the train → serve → observe → retrain loop: ``incremental_update``
re-solves only the entities a fresh events batch touched (warm-started
through the estimator's own per-entity solvers), ``build_delta``/
``save_delta`` publish just those rows as a fingerprint-chained overlay,
and ``compact`` folds a delta chain back into a full serving artifact. The
serving-side consumer is ``photon_ml_tpu.serving.hotswap``.
"""

from photon_ml_tpu.incremental.delta import (
    DELTA_MANIFEST_FILE,
    DeltaArtifact,
    OverlayIndexMap,
    apply_delta,
    build_delta,
    compact,
    delta_dir_name,
    discover_deltas,
    fingerprint_dir,
    load_delta,
    rebase_delta,
    save_delta,
    verify_chain,
)
from photon_ml_tpu.incremental.trainer import (
    IncrementalUpdate,
    incremental_update,
)

__all__ = [
    "DELTA_MANIFEST_FILE",
    "DeltaArtifact",
    "IncrementalUpdate",
    "OverlayIndexMap",
    "apply_delta",
    "build_delta",
    "compact",
    "delta_dir_name",
    "discover_deltas",
    "fingerprint_dir",
    "incremental_update",
    "load_delta",
    "rebase_delta",
    "save_delta",
    "verify_chain",
]
