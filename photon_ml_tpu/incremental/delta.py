"""Delta artifact: a versioned overlay holding only the RE rows (and FE
vectors) an incremental update changed, chained to its base artifact by
content fingerprint.

A nearline update touches a few thousand entity rows out of a
multi-million-row serving artifact; republishing the full artifact per
update would make publish latency (and artifact storage) scale with the
model instead of the event batch. A delta directory stores just the
overlay:

    <dir>/delta-manifest.json                  # chain + coordinate descriptors
    <dir>/random-effect/<cid>/rows.npy         # [n_touched, dim] float32
    <dir>/fixed-effect/<cid>.npy               # full replacement vector

``base_fingerprint`` is the content fingerprint (sha256 over every file) of
the artifact or delta this overlay applies on top of — deltas form a hash
chain, so applying one to the wrong base (or to a base with a missing
intermediate delta) fails loudly instead of serving a silently-wrong
model. ``compact`` folds a verified chain back into a full artifact, which
restarts the chain.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import shutil
import tempfile
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from photon_ml_tpu.indexmap import IndexMap

DELTA_MANIFEST_FILE = "delta-manifest.json"
DELTA_FORMAT_VERSION = 1
DELTA_DIR_PREFIX = "delta-"
_ROWS_FILE = "rows.npy"


# Top-level files that never count toward an artifact's content identity.
# tuned-config.json is a serve-side sidecar (--auto-tune winner): writing it
# next to a live artifact must not orphan the delta chain anchored on the
# artifact's fingerprint.
FINGERPRINT_EXCLUDE = ("tuned-config.json",)


def fingerprint_dir(path: str, exclude: Tuple[str, ...] = FINGERPRINT_EXCLUDE) -> str:
    """Content fingerprint of a directory tree: sha256 over every file's
    relative path and bytes, in sorted path order. Any byte change — or a
    file added/removed — changes the fingerprint (except top-level names in
    ``exclude``, which are advisory sidecars, not model content)."""
    h = hashlib.sha256()
    files = []
    for root, _, names in os.walk(path):
        for name in names:
            full = os.path.join(root, name)
            rel = os.path.relpath(full, path)
            if rel in exclude:
                continue
            files.append((rel, full))
    for rel, full in sorted(files):
        h.update(rel.encode("utf-8"))
        h.update(b"\0")
        with open(full, "rb") as f:
            while True:
                chunk = f.read(1 << 20)
                if not chunk:
                    break
                h.update(chunk)
            h.update(b"\1")
    return h.hexdigest()[:16]


@dataclasses.dataclass
class DeltaArtifact:
    """In-memory overlay: per-coordinate touched RE rows + FE replacements.

    ``re_rows[cid] = (entity_ids, rows)`` with ``rows[i]`` the new
    global-space coefficient row of ``entity_ids[i]``; ids may be present in
    the base (in-place update) or new (appended). ``fingerprint`` is the
    content fingerprint of the delta's own directory — set by
    ``save_delta``/``load_delta``, None for an unsaved delta."""

    base_fingerprint: Optional[str]
    generation: int
    re_rows: Dict[str, Tuple[List[str], np.ndarray]]
    fe_updates: Dict[str, np.ndarray] = dataclasses.field(default_factory=dict)
    created_at_unix: float = 0.0
    fingerprint: Optional[str] = None

    @property
    def num_rows_updated(self) -> int:
        return sum(len(ids) for ids, _ in self.re_rows.values())

    def coordinates(self) -> Tuple[str, ...]:
        return tuple(sorted(set(self.re_rows) | set(self.fe_updates)))


def build_delta(
    re_updates: Dict[str, Dict[str, Dict[int, float]]],
    artifact,
    fe_updates: Optional[Dict[str, np.ndarray]] = None,
    base_fingerprint: Optional[str] = None,
    generation: int = 1,
    created_at_unix: float = 0.0,
) -> DeltaArtifact:
    """Densify an incremental trainer's sparse row updates against the base
    ``ServingArtifact``'s coordinate dims. ``re_updates[cid][entity_id]`` is
    a sparse global-space coefficient map (``RandomEffectModel.items()``
    format)."""
    re_rows: Dict[str, Tuple[List[str], np.ndarray]] = {}
    for cid, per_entity in re_updates.items():
        table = artifact.tables.get(cid)
        if table is None or not table.is_random_effect:
            raise ValueError(
                f"delta names coordinate {cid!r} which is not a random "
                "effect of the base artifact"
            )
        ids = sorted(str(e) for e in per_entity)
        rows = np.zeros((len(ids), table.dim), dtype=np.float32)
        for r, eid in enumerate(ids):
            for i, v in per_entity[eid].items():
                rows[r, int(i)] = v
        re_rows[cid] = (ids, rows)
    fe = {}
    for cid, w in (fe_updates or {}).items():
        table = artifact.tables.get(cid)
        if table is None or table.is_random_effect:
            raise ValueError(
                f"delta names coordinate {cid!r} which is not a fixed "
                "effect of the base artifact"
            )
        w = np.asarray(w, dtype=np.float32)
        if w.shape != (table.dim,):
            raise ValueError(
                f"fixed-effect update for {cid!r} has shape {w.shape}, "
                f"base artifact expects ({table.dim},)"
            )
        fe[cid] = w
    return DeltaArtifact(
        base_fingerprint=base_fingerprint,
        generation=int(generation),
        re_rows=re_rows,
        fe_updates=fe,
        created_at_unix=float(created_at_unix),
    )


def save_delta(delta: DeltaArtifact, output_dir: str) -> DeltaArtifact:
    """Atomically write a delta directory (tmp sibling + rename, same
    pattern as ``save_artifact``). Returns the delta with its content
    ``fingerprint`` filled in — that is what the NEXT delta chains to."""
    from photon_ml_tpu.serving.artifact import (
        FIXED_EFFECT_DIR,
        RANDOM_EFFECT_DIR,
    )

    parent = os.path.dirname(os.path.abspath(output_dir)) or "."
    os.makedirs(parent, exist_ok=True)
    tmp = tempfile.mkdtemp(prefix=".delta-tmp-", dir=parent)
    try:
        manifest: Dict[str, object] = {
            "format_version": DELTA_FORMAT_VERSION,
            "base_fingerprint": delta.base_fingerprint,
            "generation": delta.generation,
            "created_at_unix": delta.created_at_unix,
            "coordinates": {},
        }
        for cid, (ids, rows) in delta.re_rows.items():
            cdir = os.path.join(tmp, RANDOM_EFFECT_DIR, cid)
            os.makedirs(cdir)
            np.save(
                os.path.join(cdir, _ROWS_FILE),
                np.asarray(rows, dtype=np.float32),
            )
            manifest["coordinates"][cid] = {
                "kind": "random",
                "dim": int(rows.shape[1]),
                "entity_ids": list(ids),
            }
        for cid, w in delta.fe_updates.items():
            fdir = os.path.join(tmp, FIXED_EFFECT_DIR)
            os.makedirs(fdir, exist_ok=True)
            np.save(os.path.join(fdir, f"{cid}.npy"), w)
            manifest["coordinates"][cid] = {"kind": "fixed", "dim": int(w.shape[0])}
        mpath = os.path.join(tmp, DELTA_MANIFEST_FILE)
        with open(mpath, "w") as f:
            json.dump(manifest, f)
            f.flush()
            os.fsync(f.fileno())
        fingerprint = fingerprint_dir(tmp)
        old = None
        if os.path.isdir(output_dir):
            old = tempfile.mkdtemp(prefix=".delta-old-", dir=parent)
            os.rmdir(old)
            os.replace(output_dir, old)
        os.replace(tmp, output_dir)
        if old is not None:
            shutil.rmtree(old, ignore_errors=True)
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise
    return dataclasses.replace(delta, fingerprint=fingerprint)


def load_delta(delta_dir: str) -> DeltaArtifact:
    from photon_ml_tpu.serving.artifact import (
        FIXED_EFFECT_DIR,
        RANDOM_EFFECT_DIR,
    )

    with open(os.path.join(delta_dir, DELTA_MANIFEST_FILE)) as f:
        manifest = json.load(f)
    if manifest.get("format_version") != DELTA_FORMAT_VERSION:
        raise ValueError(
            f"unsupported delta format version: {manifest.get('format_version')}"
        )
    re_rows: Dict[str, Tuple[List[str], np.ndarray]] = {}
    fe_updates: Dict[str, np.ndarray] = {}
    for cid, desc in manifest["coordinates"].items():
        if desc["kind"] == "random":
            rows = np.load(
                os.path.join(delta_dir, RANDOM_EFFECT_DIR, cid, _ROWS_FILE)
            )
            ids = [str(e) for e in desc["entity_ids"]]
            if rows.shape != (len(ids), desc["dim"]):
                raise ValueError(
                    f"delta {delta_dir}: coordinate {cid!r} rows shape "
                    f"{rows.shape} does not match its manifest "
                    f"({len(ids)}, {desc['dim']})"
                )
            re_rows[cid] = (ids, rows)
        else:
            fe_updates[cid] = np.load(
                os.path.join(delta_dir, FIXED_EFFECT_DIR, f"{cid}.npy")
            )
    return DeltaArtifact(
        base_fingerprint=manifest.get("base_fingerprint"),
        generation=int(manifest["generation"]),
        re_rows=re_rows,
        fe_updates=fe_updates,
        created_at_unix=float(manifest.get("created_at_unix", 0.0)),
        fingerprint=fingerprint_dir(delta_dir),
    )


class OverlayIndexMap(IndexMap):
    """Entity index extended with appended rows, without rebuilding the
    (possibly off-heap, million-entry) base map: new entity ids resolve
    through a small host-side dict layered over the base store."""

    def __init__(self, base: IndexMap, added: Dict[str, int]):
        self._base = base
        self._added = dict(added)
        self._reverse = {int(i): name for name, i in self._added.items()}

    def get_index(self, name: str) -> int:
        idx = self._added.get(name)
        if idx is not None:
            return idx
        return self._base.get_index(name)

    def get_feature_name(self, index: int) -> Optional[str]:
        name = self._reverse.get(int(index))
        if name is not None:
            return name
        return self._base.get_feature_name(index)

    def __len__(self) -> int:
        return len(self._base) + len(self._added)

    def get_indices(self, names) -> np.ndarray:
        """Vectorized lookup: probe the (small) overlay dict first, then
        hand the misses to the base map's own vectorized path in one call —
        the serving route step resolves whole buckets through this, so the
        per-name generator fallback of the base class would put a Python
        loop on the hot path."""
        added = self._added
        if not added:
            return np.asarray(self._base.get_indices(names), dtype=np.int64)
        out = np.fromiter(
            (added.get(n, -1) for n in names),
            dtype=np.int64,
            count=len(names),
        )
        miss = out < 0
        if miss.any():
            missing = [n for n, m in zip(names, miss) if m]
            out[miss] = np.asarray(
                self._base.get_indices(missing), dtype=np.int64
            )
        return out


def rebase_delta(
    delta: DeltaArtifact, base_fingerprint: Optional[str]
) -> DeltaArtifact:
    """Retarget a delta onto a different chain head (a copy; the input is
    untouched). The multi-variant case: one nearline trainer emits a delta
    against the shared base artifact, and each variant rebases it onto its
    OWN chain head before applying, so every variant's hash chain stays
    unbroken without retraining per variant. The content ``fingerprint``
    is cleared — a rebased delta is new content and must be re-saved (or
    applied in memory) to earn one."""
    return dataclasses.replace(
        delta, base_fingerprint=base_fingerprint, fingerprint=None
    )


def apply_delta(artifact, delta: DeltaArtifact):
    """Fold a delta into a ``ServingArtifact`` → a NEW artifact (host-side;
    the input artifact and its possibly-mmap'd tables are not mutated).
    Existing entity rows are replaced in place; unknown ids are appended
    (sorted among themselves) behind an :class:`OverlayIndexMap`."""
    import dataclasses as dc

    from photon_ml_tpu.serving.artifact import ServingTable

    tables = dict(artifact.tables)
    for cid, (ids, rows) in delta.re_rows.items():
        table = tables.get(cid)
        if table is None or not table.is_random_effect:
            raise ValueError(
                f"delta touches {cid!r} which is not a random effect of the "
                "base artifact"
            )
        if rows.shape[1] != table.dim:
            raise ValueError(
                f"delta rows for {cid!r} have dim {rows.shape[1]}, base "
                f"table has dim {table.dim}"
            )
        targets = np.asarray(table.entity_index.get_indices(ids), dtype=np.int64)
        n_old = table.n_entities
        n_new = int((targets < 0).sum())
        weights = np.array(table.weights, dtype=np.float32, copy=True)
        entity_index = table.entity_index
        if n_new:
            weights = np.concatenate(
                [weights, np.zeros((n_new, table.dim), dtype=np.float32)]
            )
            added: Dict[str, int] = {}
            nxt = n_old
            for i, eid in enumerate(ids):
                if targets[i] < 0:
                    added[eid] = nxt
                    targets[i] = nxt
                    nxt += 1
            entity_index = OverlayIndexMap(table.entity_index, added)
        weights[targets] = np.asarray(rows, dtype=np.float32)
        tables[cid] = ServingTable(
            feature_shard=table.feature_shard,
            random_effect_type=table.random_effect_type,
            weights=weights,
            entity_index=entity_index,
        )
    for cid, w in delta.fe_updates.items():
        table = tables.get(cid)
        if table is None or table.is_random_effect:
            raise ValueError(
                f"delta replaces {cid!r} which is not a fixed effect of the "
                "base artifact"
            )
        if w.shape != (table.dim,):
            raise ValueError(
                f"delta fixed-effect vector for {cid!r} has shape {w.shape}, "
                f"base table has dim {table.dim}"
            )
        tables[cid] = dc.replace(table, weights=np.asarray(w, dtype=np.float32))
    return dc.replace(artifact, tables=tables)


def verify_chain(
    base_fingerprint: str, deltas: Sequence[DeltaArtifact]
) -> None:
    """Check that ``deltas`` form an unbroken hash chain rooted at
    ``base_fingerprint`` (each delta's ``base_fingerprint`` must equal its
    predecessor's content fingerprint)."""
    fp = base_fingerprint
    for i, delta in enumerate(deltas):
        if delta.base_fingerprint is not None and delta.base_fingerprint != fp:
            raise ValueError(
                f"delta chain broken at position {i} (generation "
                f"{delta.generation}): it chains to base "
                f"{delta.base_fingerprint}, expected {fp} — a delta is "
                "missing, reordered, or built against a different artifact"
            )
        fp = delta.fingerprint


def compact(
    base_artifact_dir: str,
    delta_dirs: Sequence[str],
    output_dir: str,
) -> str:
    """Fold a verified delta chain back into a full artifact at
    ``output_dir`` (atomic write). Returns the new artifact's content
    fingerprint — the root of the next chain."""
    from photon_ml_tpu.serving.artifact import load_artifact, save_artifact

    artifact = load_artifact(base_artifact_dir, mmap=False)
    deltas = [load_delta(d) for d in delta_dirs]
    verify_chain(fingerprint_dir(base_artifact_dir), deltas)
    for delta in deltas:
        artifact = apply_delta(artifact, delta)
    save_artifact(artifact, output_dir)
    return fingerprint_dir(output_dir)


def discover_deltas(watch_dir: str) -> List[str]:
    """Delta directories under ``watch_dir`` (``delta-*`` dirs containing a
    manifest), sorted by name — publish with zero-padded generation numbers
    (``delta-000042``) so name order is chain order."""
    if not os.path.isdir(watch_dir):
        return []
    out = []
    for name in sorted(os.listdir(watch_dir)):
        full = os.path.join(watch_dir, name)
        if name.startswith(DELTA_DIR_PREFIX) and os.path.isfile(
            os.path.join(full, DELTA_MANIFEST_FILE)
        ):
            out.append(full)
    return out


def delta_dir_name(generation: int) -> str:
    return f"{DELTA_DIR_PREFIX}{int(generation):06d}"
