"""Nearline incremental trainer: warm-started re-solves of only the
entities a fresh events batch touched.

A full retrain re-solves every entity of every random-effect coordinate;
a nearline batch of events touches a tiny fraction of them. The per-entity
problems are independent (the whole point of the random-effect block
structure), so re-solving JUST the touched rows against the current fixed
effects produces exactly the rows a full warm-started CD pass would — the
incremental-equals-full property the regression test pins down.

The mechanism is the estimator's own machinery, not a parallel code path:
``GameEstimator.resolve_coordinate`` builds the coordinate's dataset over
the events batch (which by construction contains exactly the touched
entities), scores the other coordinates' models as residual offsets, and
re-runs the same vmap'd per-entity solver with the old rows as warm starts
(``align_warm_start`` joins them by entity id; unseen entities start at
zero, i.e. fresh rows). Fixed effects can optionally be refreshed first
with K frozen-RE passes over the events batch.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple, Union

import numpy as np

from photon_ml_tpu.data.game_data import GameData
from photon_ml_tpu.estimators.game import (
    FixedEffectCoordinateConfiguration,
    GameEstimator,
    RandomEffectCoordinateConfiguration,
)
from photon_ml_tpu.models.game import GameModel
from photon_ml_tpu.models.glm import GeneralizedLinearModel
from photon_ml_tpu.models.random_effect import RandomEffectModel
from photon_ml_tpu.parallel.mesh import fetch_global
from photon_ml_tpu.telemetry import span


@dataclasses.dataclass
class IncrementalUpdate:
    """Result of one nearline update.

    ``re_updates[cid][entity_id]`` holds the re-solved sparse global-space
    coefficient row for every touched entity — exactly the payload of a
    delta artifact. ``models`` is the full merged sub-model map (old rows
    overlaid with the re-solved ones) unless the update ran with
    ``merge=False``, in which case RE entries contain only the touched
    entities."""

    models: Dict[str, object]
    re_updates: Dict[str, Dict[str, Dict[int, float]]]
    fe_updates: Dict[str, np.ndarray]
    touched_entities: Dict[str, Tuple[str, ...]]
    new_entities: Dict[str, Tuple[str, ...]]
    num_events: int
    # per-coordinate SolverStats (opt.tracking) from the warm-started RE
    # re-solves — the convergence-adaptive driver's lane telemetry; nearline
    # batches have the largest iteration skew so the savings show up here
    solver_stats: Dict[str, list] = dataclasses.field(default_factory=dict)
    # per-coordinate TransferStats (opt.tracking) from the same re-solves:
    # on the device score plane each re-solve uploads exactly one residual
    # array and regroups offsets on device (zero further row transfers)
    transfer_stats: Dict[str, object] = dataclasses.field(default_factory=dict)

    def game_model(self, estimator: GameEstimator) -> GameModel:
        return GameModel(
            models=dict(self.models), meta=estimator._meta(), task=estimator.task
        )


def _load_models(
    model: Union[GameModel, Dict[str, object], str],
) -> Dict[str, object]:
    if isinstance(model, GameModel):
        return dict(model.models)
    if isinstance(model, str):
        from photon_ml_tpu.checkpoint import load_training_checkpoint

        models, _, _ = load_training_checkpoint(model)
        return models
    return dict(model)


def incremental_update(
    estimator: GameEstimator,
    model: Union[GameModel, Dict[str, object], str],
    events: GameData,
    refresh_fixed_iterations: int = 0,
    merge: bool = True,
) -> IncrementalUpdate:
    """Warm-started nearline update of ``model`` with a batch of new events.

    ``model`` may be a trained ``GameModel``, its sub-model dict, or a
    training checkpoint directory. Coordinates are visited in the
    estimator's ``update_order``: first ``refresh_fixed_iterations`` passes
    over the fixed-effect coordinates with the random effects frozen, then
    one warm-started re-solve per plain random-effect coordinate covering
    exactly the entities present in ``events`` (later coordinates see
    earlier re-solves through the residual offsets — the CD invariant).
    Factored RE coordinates are passed through untouched.

    ``merge=False`` skips folding the re-solved rows back into full RE
    models (``models[cid]`` then holds ONLY the touched entities) — the
    cheap mode for delta-publishing pipelines that never score the merged
    model host-side.
    """
    with span(
        "incremental/update",
        num_events=events.num_rows,
        refresh_fixed_iterations=int(refresh_fixed_iterations),
        merge=merge,
    ):
        return _incremental_update_impl(
            estimator, model, events, refresh_fixed_iterations, merge
        )


def _incremental_update_impl(
    estimator: GameEstimator,
    model: Union[GameModel, Dict[str, object], str],
    events: GameData,
    refresh_fixed_iterations: int,
    merge: bool,
) -> IncrementalUpdate:
    models = _load_models(model)
    fe_cids = [
        cid
        for cid in estimator.update_order
        if isinstance(
            estimator.coordinate_configs.get(cid),
            FixedEffectCoordinateConfiguration,
        )
    ]
    re_cids = [
        cid
        for cid in estimator.update_order
        if isinstance(
            estimator.coordinate_configs.get(cid),
            RandomEffectCoordinateConfiguration,
        )
    ]

    fe_updates: Dict[str, np.ndarray] = {}
    for _ in range(max(0, int(refresh_fixed_iterations))):
        for cid in fe_cids:
            with span("incremental/resolve", coordinate=cid, kind="fixed"):
                sub = estimator.resolve_coordinate(cid, events, models)
            assert isinstance(sub, GeneralizedLinearModel)
            models[cid] = sub
            fe_updates[cid] = np.asarray(
                fetch_global(sub.coefficients.means), dtype=np.float32
            )

    re_updates: Dict[str, Dict[str, Dict[int, float]]] = {}
    touched: Dict[str, Tuple[str, ...]] = {}
    new: Dict[str, Tuple[str, ...]] = {}
    solver_stats: Dict[str, list] = {}
    transfer_stats: Dict[str, object] = {}
    for cid in re_cids:
        old = models.get(cid)
        if old is not None and not isinstance(old, RandomEffectModel):
            raise ValueError(
                f"coordinate {cid!r}: expected a RandomEffectModel, got "
                f"{type(old).__name__}"
            )
        with span("incremental/resolve", coordinate=cid, kind="random"):
            sub = estimator.resolve_coordinate(cid, events, models)
        if estimator.last_resolve_stats:
            solver_stats[cid] = list(estimator.last_resolve_stats)
        if estimator.last_resolve_transfers is not None:
            transfer_stats[cid] = estimator.last_resolve_transfers
        rows = {str(eid): coefs for eid, coefs in sub.items()}
        touched[cid] = tuple(sorted(rows))
        known = set(old.entity_to_loc) if old is not None else set()
        new[cid] = tuple(sorted(set(rows) - known))
        re_updates[cid] = rows
        if merge and old is not None:
            merged = {str(eid): coefs for eid, coefs in old.items()}
            merged.update(rows)
            models[cid] = RandomEffectModel.from_entity_coefficients(
                random_effect_type=sub.random_effect_type,
                task=estimator.task,
                entity_coefficients=merged,
                global_dim=sub.global_dim,
            )
        else:
            # the re-solved model covers exactly the touched entities —
            # sufficient for the residual offsets of later coordinates
            # (every events row's entity for this RE type IS touched)
            models[cid] = sub

    return IncrementalUpdate(
        models=models,
        re_updates=re_updates,
        fe_updates=fe_updates,
        touched_entities=touched,
        new_entities=new,
        num_events=events.num_rows,
        solver_stats=solver_stats,
        transfer_stats=transfer_stats,
    )
