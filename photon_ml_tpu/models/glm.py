"""Per-task generalized linear models.

Reference parity: supervised/model/GeneralizedLinearModel.scala:33
(computeScore / computeMean contract :68-117), LogisticRegressionModel.scala:31
(mean = sigmoid), LinearRegressionModel / PoissonRegressionModel (mean = exp),
SmoothedHingeLossLinearSVMModel, BinaryClassifier.predictClassWithThreshold.
One class parametrized by TaskType replaces the reference's four subclasses:
the task only changes the link-inverse and threshold semantics, and a static
enum field keeps the pytree jit-friendly.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import struct

from photon_ml_tpu.losses.pointwise import mean_function
from photon_ml_tpu.models.coefficients import Coefficients
from photon_ml_tpu.types import POSITIVE_RESPONSE_THRESHOLD, TaskType


@struct.dataclass
class GeneralizedLinearModel:
    coefficients: Coefficients
    task: TaskType = struct.field(pytree_node=False, default=TaskType.LOGISTIC_REGRESSION)

    @property
    def dim(self) -> int:
        return self.coefficients.dim

    def compute_score(self, features) -> jax.Array:
        """Margin z = X @ w (no offset; reference computeScore)."""
        return self.coefficients.compute_score(features)

    def compute_mean(self, features, offsets=None) -> jax.Array:
        """Posterior mean via the task link-inverse (reference computeMean)."""
        z = self.compute_score(features)
        if offsets is not None:
            z = z + offsets
        return mean_function(self.task, z)

    def to_summary_string(self) -> str:
        """Reference Summarizable.toSummaryString (GeneralizedLinearModel)."""
        import numpy as np

        from photon_ml_tpu.parallel.mesh import fetch_global

        w = np.asarray(fetch_global(self.coefficients.means))
        nnz = int(np.count_nonzero(w))
        head = (
            f"{self.task.value} GLM: {w.shape[0]} coefficients ({nnz} nonzero)"
        )
        if w.size:
            head += f", |w| max {np.abs(w).max():.4g} mean {np.abs(w).mean():.4g}"
        if self.coefficients.variances is not None:
            head += ", with variances"
        return head

    def predict_class(
        self, features, offsets=None, threshold: float = POSITIVE_RESPONSE_THRESHOLD
    ) -> jax.Array:
        """Binary prediction (reference BinaryClassifier.predictClassWithThreshold);
        only meaningful for classification tasks."""
        if not self.task.is_classification:
            raise ValueError(f"predict_class is not defined for {self.task}")
        mean = self.compute_mean(features, offsets)
        # SVM margins are thresholded at 0, probabilities at `threshold`
        cut = 0.0 if self.task is TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM else threshold
        return (mean > cut).astype(jnp.float32)

    @classmethod
    def zeros(cls, dim: int, task: TaskType) -> "GeneralizedLinearModel":
        return cls(coefficients=Coefficients.zeros(dim), task=task)
