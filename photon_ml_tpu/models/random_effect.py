"""Random-effect model: one small GLM per entity, stored as padded blocks.

Reference parity: model/RandomEffectModel.scala:38 — an RDD[(REId, GLM)]
scored via join by entity id — and RandomEffectModelInProjectedSpace (models
live in per-entity projected space and are projected back for export). Here
the per-bucket coefficient blocks [E, D_local] mirror the dataset layout;
scoring is an einsum against the matching bucket, and export materializes
per-entity sparse global-space coefficient maps through proj_indices.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Iterator, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.types import TaskType


@dataclasses.dataclass
class RandomEffectModel:
    """Per-bucket local-space coefficients, parallel to a
    RandomEffectDataset's buckets."""

    random_effect_type: str
    task: TaskType
    coefficients: List[jax.Array]            # per bucket [E_b, D_b]
    variances: List[Optional[jax.Array]]     # per bucket [E_b, D_b] or None
    proj_indices: List[jax.Array]            # per bucket [E_b, D_b] int32
    proj_valid: List[jax.Array]              # per bucket [E_b, D_b] bool
    entity_ids: List[List[str]]
    entity_to_loc: Dict[str, Tuple[int, int]]
    global_dim: int

    @property
    def num_entities(self) -> int:
        return sum(len(ids) for ids in self.entity_ids)

    def coefficients_for(self, entity_id: str) -> Optional[Dict[int, float]]:
        """Global-space sparse coefficients {feature_index: value} for one
        entity (host-side; model export / serving by id)."""
        loc = self.entity_to_loc.get(str(entity_id))
        if loc is None:
            return None
        b, e = loc
        w = np.asarray(self.coefficients[b][e])
        idx = np.asarray(self.proj_indices[b][e])
        valid = np.asarray(self.proj_valid[b][e])
        return {int(i): float(v) for i, v, ok in zip(idx, w, valid) if ok}

    def items(self) -> Iterator[Tuple[str, Dict[int, float]]]:
        """Iterate (entity_id, sparse global coefficients) — export order."""
        for b, ids in enumerate(self.entity_ids):
            w_b = np.asarray(self.coefficients[b])
            idx_b = np.asarray(self.proj_indices[b])
            val_b = np.asarray(self.proj_valid[b])
            for e, eid in enumerate(ids):
                yield eid, {
                    int(i): float(v)
                    for i, v, ok in zip(idx_b[e], w_b[e], val_b[e])
                    if ok
                }
