"""Random-effect model: one small GLM per entity, stored as padded blocks.

Reference parity: model/RandomEffectModel.scala:38 — an RDD[(REId, GLM)]
scored via join by entity id — and RandomEffectModelInProjectedSpace (models
live in per-entity projected space and are projected back for export). Here
the per-bucket coefficient blocks [E, D_local] mirror the dataset layout;
scoring is an einsum against the matching bucket, and export materializes
per-entity sparse global-space coefficient maps through proj_indices.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Iterator, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.parallel.mesh import fetch_global

from photon_ml_tpu.projector import ProjectorType, RandomProjectionMatrix
from photon_ml_tpu.types import TaskType


@dataclasses.dataclass
class RandomEffectModel:
    """Per-bucket local-space coefficients, parallel to a
    RandomEffectDataset's buckets."""

    random_effect_type: str
    task: TaskType
    coefficients: List[jax.Array]            # per bucket [E_b, D_b]
    variances: List[Optional[jax.Array]]     # per bucket [E_b, D_b] or None
    proj_indices: List[jax.Array]            # per bucket [E_b, D_b] int32
    proj_valid: List[jax.Array]              # per bucket [E_b, D_b] bool
    entity_ids: List[List[str]]
    entity_to_loc: Dict[str, Tuple[int, int]]
    global_dim: int
    # how local spaces map back to the original feature space (reference
    # RandomEffectModelInProjectedSpace): INDEX_MAP/IDENTITY use proj_indices;
    # RANDOM regenerates the shared Gaussian matrix from projection_seed
    projector_type: ProjectorType = ProjectorType.INDEX_MAP
    projection_seed: int = 0

    def _back_projection_matrix(self, projected_dim: int) -> RandomProjectionMatrix:
        return RandomProjectionMatrix(
            projected_dim=projected_dim,
            global_dim=self.global_dim,
            seed=self.projection_seed,
        )

    @property
    def num_entities(self) -> int:
        return sum(len(ids) for ids in self.entity_ids)

    def to_summary_string(self) -> str:
        """Reference Summarizable.toSummaryString (RandomEffectModel)."""
        dims = [int(c.shape[1]) for c in self.coefficients]
        dims_str = f"{min(dims)}-{max(dims)}" if dims else "n/a"
        return (
            f"random effect '{self.random_effect_type}': "
            f"{self.num_entities} entities in {len(self.coefficients)} "
            f"buckets (local dims {dims_str}), "
            f"global dim {self.global_dim}, "
            f"projector {self.projector_type.value}"
            + (", with variances" if any(
                v is not None for v in self.variances
            ) else "")
        )

    def coefficients_for(self, entity_id: str) -> Optional[Dict[int, float]]:
        """Global-space sparse coefficients {feature_index: value} for one
        entity (host-side; model export / serving by id)."""
        loc = self.entity_to_loc.get(str(entity_id))
        if loc is None:
            return None
        b, e = loc
        w = fetch_global(self.coefficients[b][e])
        if self.projector_type is ProjectorType.RANDOM:
            cols, vals = self._back_projection_matrix(w.shape[0]).project_coefficients_back(w)
            return {int(i): float(v) for i, v in zip(cols, vals)}
        idx = fetch_global(self.proj_indices[b][e])
        valid = fetch_global(self.proj_valid[b][e])
        return {int(i): float(v) for i, v, ok in zip(idx, w, valid) if ok}

    def items(self) -> Iterator[Tuple[str, Dict[int, float]]]:
        """Iterate (entity_id, sparse global coefficients) — export order."""
        b_full = None  # shared across buckets (same seed/global_dim/k)
        for b, ids in enumerate(self.entity_ids):
            w_b = fetch_global(self.coefficients[b])
            if self.projector_type is ProjectorType.RANDOM:
                # regenerate B once per export; back-project the whole bucket
                # with a single matmul (w_orig = B @ w_proj per entity)
                if b_full is None:
                    proj = self._back_projection_matrix(w_b.shape[1])
                    b_full = proj.rows(np.arange(self.global_dim, dtype=np.int64))
                vals_b = w_b @ b_full.T  # [Eb, global_dim]
                for e, eid in enumerate(ids):
                    yield eid, {int(i): float(v) for i, v in enumerate(vals_b[e])}
                continue
            idx_b = fetch_global(self.proj_indices[b])
            val_b = fetch_global(self.proj_valid[b])
            for e, eid in enumerate(ids):
                yield eid, {
                    int(i): float(v)
                    for i, v, ok in zip(idx_b[e], w_b[e], val_b[e])
                    if ok
                }

    @classmethod
    def from_entity_coefficients(
        cls,
        random_effect_type: str,
        task: TaskType,
        entity_coefficients: Dict[str, Dict[int, float]],
        global_dim: int,
        entity_variances: Optional[Dict[str, Dict[int, float]]] = None,
    ) -> "RandomEffectModel":
        """Build a (single-bucket, INDEX_MAP-projected) model from per-entity
        sparse global-space coefficients — the model-load path (reference
        loadModelsRDDFromHDFS builds RandomEffectModel from Avro records)."""
        ids = list(entity_coefficients)
        entity_variances = entity_variances or {}
        # local feature set per entity = union of mean and variance indices
        # (a feature may have zero mean but a stored variance)
        local: Dict[str, List[int]] = {
            eid: sorted(
                set(entity_coefficients[eid]) | set(entity_variances.get(eid, ()))
            )
            for eid in ids
        }
        d_local = max((len(f) for f in local.values()), default=1) or 1
        n = len(ids)
        idx = np.full((n, d_local), global_dim, dtype=np.int32)
        valid = np.zeros((n, d_local), dtype=bool)
        w = np.zeros((n, d_local), dtype=np.float32)
        var = np.zeros((n, d_local), dtype=np.float32)
        has_var = False
        for e, eid in enumerate(ids):
            coefs = entity_coefficients[eid]
            vars_e = entity_variances.get(eid)
            # sorted valid prefix: the scoring path binary-searches these
            for j, i in enumerate(local[eid]):
                idx[e, j] = i
                w[e, j] = coefs.get(i, 0.0)
                valid[e, j] = True
                if vars_e is not None:
                    var[e, j] = vars_e.get(i, 0.0)
            has_var = has_var or vars_e is not None
        return cls(
            random_effect_type=random_effect_type,
            task=task,
            coefficients=[jnp.asarray(w)],
            variances=[jnp.asarray(var) if has_var else None],
            proj_indices=[jnp.asarray(idx)],
            proj_valid=[jnp.asarray(valid)],
            entity_ids=[ids],
            entity_to_loc={eid: (0, e) for e, eid in enumerate(ids)},
            global_dim=global_dim,
            projector_type=ProjectorType.INDEX_MAP,
        )
