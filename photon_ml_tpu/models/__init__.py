from photon_ml_tpu.models.coefficients import Coefficients
from photon_ml_tpu.models.glm import GeneralizedLinearModel

__all__ = ["Coefficients", "GeneralizedLinearModel"]
