"""Model coefficients: means + optional variances.

Reference parity: model/Coefficients.scala:31 — means and optional variances
(the reference stores Breeze dense/sparse vectors; here both are device
arrays; sparsity of a trained model is represented by zeros, with the IO layer
writing only nonzeros like the reference's Avro writer).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from flax import struct


@struct.dataclass
class Coefficients:
    means: jax.Array                      # [d]
    variances: Optional[jax.Array] = None  # [d] or None

    @property
    def dim(self) -> int:
        return self.means.shape[-1]

    def compute_score(self, features) -> jax.Array:
        """Dot product with a FeatureMatrix batch (reference
        Coefficients.scala:53)."""
        return features.matvec(self.means)

    def l2_norm(self) -> jax.Array:
        return jnp.linalg.norm(self.means)

    @classmethod
    def zeros(cls, dim: int, dtype=jnp.float32) -> "Coefficients":
        return cls(means=jnp.zeros((dim,), dtype=dtype))
