"""GAME model: named sub-models summed into one score.

Reference parity: model/GameModel.scala:32 (map coordinateId -> sub-model,
``score`` sums sub-model scores, task-consistency check :163) and
model/{FixedEffectModel,RandomEffectModel}.scala scoring semantics: a
fixed-effect model scores every row; a random-effect model scores rows whose
entity it has seen (others contribute 0 — the reference's left join default).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Union

import numpy as np

from photon_ml_tpu.parallel.mesh import fetch_global

from photon_ml_tpu.data.game_data import GameData
from photon_ml_tpu.models.glm import GeneralizedLinearModel
from photon_ml_tpu.models.random_effect import RandomEffectModel
from photon_ml_tpu.projector import ProjectorType
from photon_ml_tpu.types import TaskType


@dataclasses.dataclass(frozen=True)
class CoordinateMeta:
    """What a coordinate consumes: which feature shard, and (for random
    effects) which id tag names its entity."""

    feature_shard: str
    random_effect_type: Optional[str] = None
    # sparse engine the coordinate was configured with (fixed effects):
    # scoring reuses the same representation instead of building a second
    sparse_engine: str = "auto"


SubModel = Union[
    GeneralizedLinearModel, RandomEffectModel, "FactoredRandomEffectModel"
]


@dataclasses.dataclass
class GameModel:
    models: Dict[str, SubModel]
    meta: Dict[str, CoordinateMeta]
    task: TaskType

    def __post_init__(self) -> None:
        for cid in self.models:
            if cid not in self.meta:
                raise ValueError(f"coordinate {cid} missing metadata")

    def to_summary_string(self) -> str:
        """Reference GameModel.toSummaryString: one line per coordinate."""
        lines = [f"GAME model ({self.task.value}), {len(self.models)} coordinates:"]
        for cid in self.models:
            sub = self.models[cid]
            detail = (
                sub.to_summary_string()
                if hasattr(sub, "to_summary_string")
                else type(sub).__name__
            )
            lines.append(f"  [{cid}] {detail}")
        return "\n".join(lines)

    def score_coordinate(self, cid: str, data: GameData) -> np.ndarray:
        """Raw scores of one sub-model over arbitrary GameData rows."""
        model = self.models[cid]
        m = self.meta[cid]
        shard = data.feature_shards[m.feature_shard]
        if isinstance(model, GeneralizedLinearModel):
            return np.asarray(
                model.compute_score(
                    data.sparse_features(m.feature_shard, engine=m.sparse_engine)
                )
            )
        assert m.random_effect_type is not None
        entity_ids = data.id_tags[m.random_effect_type]
        from photon_ml_tpu.algorithm.factored_random_effect import (
            FactoredRandomEffectModel,
        )

        if isinstance(model, FactoredRandomEffectModel):
            return _score_factored_re_rows(model, shard, entity_ids, data.num_rows)
        return _score_re_rows(model, shard, entity_ids, data.num_rows)

    def score(self, data: GameData) -> np.ndarray:
        """Sum of sub-model scores per row (no offsets; reference
        GameModel.score). Evaluation adds data.offsets on top."""
        total = np.zeros(data.num_rows, dtype=np.float32)
        for cid in self.models:
            total += self.score_coordinate(cid, data)
        return total


def _score_factored_re_rows(
    model, shard, entity_ids, num_rows: int
) -> np.ndarray:
    """Score arbitrary rows against a factored RE model: per nonzero
    (r, c, v), contrib = v * (B[c] . latent_{entity(r)}); unseen entities
    score 0 (reference FactoredRandomEffectModel scoring via the projected
    RandomEffectModel + projection matrix)."""
    # model gathers run UNCONDITIONALLY: fetch_global is a cross-process
    # collective in a multi-host run, and hosts may hold different (even
    # empty) row shards — a data-dependent skip would deadlock the cluster
    latent = model.latent
    B = fetch_global(model.projection_matrix)
    latents = [fetch_global(c) for c in latent.coefficients]
    out = np.zeros(num_rows, dtype=np.float32)
    if len(shard.rows) == 0:
        return out
    locs = [latent.entity_to_loc.get(str(e)) for e in entity_ids]
    bucket_of_row = np.array([l[0] if l is not None else -1 for l in locs], dtype=np.int64)
    erow_of_row = np.array([l[1] if l is not None else 0 for l in locs], dtype=np.int64)
    rows = np.asarray(shard.rows, dtype=np.int64)
    cols = np.asarray(shard.cols, dtype=np.int64)
    vals = np.asarray(shard.vals, dtype=np.float32)
    nz_bucket = bucket_of_row[rows]
    for b in range(len(latent.coefficients)):
        sel = nz_bucket == b
        if not sel.any():
            continue
        v_lat = latents[b]  # [Eb, k]
        r = rows[sel]
        contrib = vals[sel] * np.einsum(
            "nk,nk->n", B[cols[sel]], v_lat[erow_of_row[r]]
        )
        np.add.at(out, r, contrib.astype(np.float32))
    return out


def _score_re_rows(
    model: RandomEffectModel, shard, entity_ids, num_rows: int
) -> np.ndarray:
    """Vectorized scoring of arbitrary rows against per-entity local models.

    Per nonzero (r, c, v): find c in the entity's sorted local feature list
    (batched searchsorted via boolean-sum) and accumulate v * w_local. Rows
    whose entity is unseen score 0 (reference RandomEffectModel left join).
    Features outside the entity's projected space are dropped (reference
    index-map projection semantics).
    """
    # all model gathers hoisted above data-dependent control flow (see
    # _score_factored_re_rows: collectives must run on every host)
    if model.projector_type is ProjectorType.RANDOM:
        ws = [fetch_global(c) for c in model.coefficients]
        pidxs = pvals = None
    else:
        ws = [fetch_global(c) for c in model.coefficients]
        pidxs = [fetch_global(p) for p in model.proj_indices]
        pvals = [fetch_global(p) for p in model.proj_valid]
    out = np.zeros(num_rows, dtype=np.float32)
    if len(shard.rows) == 0:
        return out
    locs = [model.entity_to_loc.get(str(e)) for e in entity_ids]
    bucket_of_row = np.array([l[0] if l is not None else -1 for l in locs], dtype=np.int64)
    erow_of_row = np.array([l[1] if l is not None else 0 for l in locs], dtype=np.int64)

    rows = np.asarray(shard.rows, dtype=np.int64)
    cols = np.asarray(shard.cols, dtype=np.int64)
    vals = np.asarray(shard.vals, dtype=np.float32)
    nz_bucket = bucket_of_row[rows]

    if model.projector_type is ProjectorType.RANDOM:
        # model lives in the shared Gaussian-projected space: score each
        # nonzero as v * (B[c] . w_entity). One B regeneration serves every
        # bucket (all buckets share projected_dim).
        uniq_c, inv = np.unique(cols, return_inverse=True)
        k = model.coefficients[0].shape[1]  # global metadata, no fetch
        b_rows = model._back_projection_matrix(k).rows(uniq_c)
        for b in range(len(model.coefficients)):
            sel = nz_bucket == b
            if not sel.any():
                continue
            w = ws[b]  # [Eb, k]
            r = rows[sel]
            contrib = vals[sel] * np.einsum(
                "nk,nk->n", b_rows[inv[sel]], w[erow_of_row[r]]
            )
            np.add.at(out, r, contrib.astype(np.float32))
        return out

    for b in range(len(model.coefficients)):
        sel = nz_bucket == b
        if not sel.any():
            continue
        r = rows[sel]
        c = cols[sel]
        v = vals[sel]
        e = erow_of_row[r]
        pidx = pidxs[b]  # [Eb, Db], valid prefix sorted
        pval = pvals[b]
        w = ws[b]
        Db = pidx.shape[1]
        pe = pidx[e]          # [nnz, Db]
        ve = pval[e]
        j = ((pe < c[:, None]) & ve).sum(axis=1)
        j_clip = np.minimum(j, Db - 1)
        match = (j < Db) & ve[np.arange(len(j)), j_clip] & (
            pe[np.arange(len(j)), j_clip] == c
        )
        contrib = np.where(match, v * w[e, j_clip], 0.0)
        np.add.at(out, r, contrib.astype(np.float32))
    return out
