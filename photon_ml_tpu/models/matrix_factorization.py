"""Matrix-factorization model: row/col latent factor scoring.

Reference parity: model/MatrixFactorizationModel.scala:36 —
rowLatentFactors/colLatentFactors keyed by entity id; score(rowId, colId) =
dot(rowFactor, colFactor). The reference has no standalone MF trainer (the
FactoredRandomEffectCoordinate is the training path); this model exists for
scoring and tests, mirroring that.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Sequence

import numpy as np


@dataclasses.dataclass
class MatrixFactorizationModel:
    """Latent factors as dense blocks + host-side id maps."""

    row_effect_type: str
    col_effect_type: str
    row_factors: np.ndarray  # [num_rows, k]
    col_factors: np.ndarray  # [num_cols, k]
    row_index: Dict[str, int]
    col_index: Dict[str, int]

    @property
    def num_latent_factors(self) -> int:
        return int(self.row_factors.shape[1])

    def to_summary_string(self) -> str:
        """Reference Summarizable.toSummaryString (MatrixFactorizationModel)."""
        return (
            f"matrix factorization '{self.row_effect_type}' x "
            f"'{self.col_effect_type}': {self.row_factors.shape[0]} x "
            f"{self.col_factors.shape[0]} entities, "
            f"{self.num_latent_factors} latent factors"
        )

    def __post_init__(self) -> None:
        if self.row_factors.shape[1] != self.col_factors.shape[1]:
            raise ValueError(
                "row and column factors must share the latent dimension "
                f"({self.row_factors.shape[1]} vs {self.col_factors.shape[1]})"
            )

    def score(self, row_id: str, col_id: str) -> float:
        """dot(rowFactor, colFactor); unknown ids score 0 (the reference's
        left-join default for unseen entities)."""
        r = self.row_index.get(str(row_id))
        c = self.col_index.get(str(col_id))
        if r is None or c is None:
            return 0.0
        return float(self.row_factors[r] @ self.col_factors[c])

    def score_batch(
        self, row_ids: Sequence[str], col_ids: Sequence[str]
    ) -> np.ndarray:
        """Vectorized pairwise scoring of aligned (row_id, col_id) lists."""
        r = np.array([self.row_index.get(str(i), -1) for i in row_ids])
        c = np.array([self.col_index.get(str(i), -1) for i in col_ids])
        known = (r >= 0) & (c >= 0)
        out = np.zeros(len(r), dtype=np.float32)
        if known.any():
            out[known] = np.einsum(
                "nk,nk->n",
                self.row_factors[r[known]],
                self.col_factors[c[known]],
            )
        return out
