"""Slice sampling for marginalizing GP kernel hyperparameters.

Reference parity: SliceSampler.scala:53 — coordinate-wise slice sampling
with randomized direction order, step-out slice finding, and shrink-on-reject;
on a degenerate shrink the slice resets to the full range (:115-131).
"""

from __future__ import annotations

import math
from typing import Callable, Tuple

import numpy as np


class SliceSampler:
    """Draws samples from an unnormalized log-density ``logp``.

    ``range_`` bounds each coordinate (the reference defaults to
    (log 1e-5, log 1e5), matching kernel length-scale bounds).
    """

    def __init__(
        self,
        logp: Callable[[np.ndarray], float],
        range_: Tuple[float, float] = (math.log(1e-5), math.log(1e5)),
        step_size: float = 1.0,
        rng: np.random.Generator = None,
    ) -> None:
        self.logp = logp
        self.range = range_
        self.step_size = step_size
        self.rng = rng if rng is not None else np.random.default_rng()

    def draw(self, x: np.ndarray) -> np.ndarray:
        """One full sweep: sample along every coordinate in random order."""
        x = np.asarray(x, dtype=float).copy()
        for i in self.rng.permutation(x.shape[0]):
            x = self._draw_along(x, int(i))
        return x

    def _draw_along(self, x: np.ndarray, i: int) -> np.ndarray:
        # log U for U~Uniform(0,1] is -Exp(1); avoids log(0) from the
        # half-open uniform sampler.
        y = -self.rng.exponential() + self.logp(x)
        lower, upper = self._step_out(x, y, i)
        lo_bound, hi_bound = self.range
        while True:
            new_x = x.copy()
            new_x[i] = self.rng.uniform(lower, upper)
            if self.logp(new_x) > y:
                return new_x
            # shrink the slice toward x; on degenerate shrink reset to range
            if new_x[i] < x[i]:
                lower = new_x[i]
            elif new_x[i] > x[i]:
                upper = new_x[i]
            else:
                lower, upper = lo_bound, hi_bound

    def _step_out(self, x: np.ndarray, y: float, i: int) -> Tuple[float, float]:
        lo_bound, hi_bound = self.range
        lower = x.copy()
        lower[i] -= self.rng.uniform() * self.step_size
        upper = lower.copy()
        upper[i] += self.step_size
        while self.logp(lower) > y and lower[i] > lo_bound:
            lower[i] -= self.step_size
        while self.logp(upper) > y and upper[i] < hi_bound:
            upper[i] += self.step_size
        # The loops step first and test second, so clamp the final slice to
        # the declared range — samples must respect the kernel bounds.
        return (
            max(float(lower[i]), lo_bound),
            min(float(upper[i]), hi_bound),
        )
