"""Acquisition criteria for GP-guided search.

Reference parity: criteria/ExpectedImprovement.scala:* (PBO Eq. 1-2, sign
flipped by the evaluator's direction) and criteria/ConfidenceBound.scala:*
(UCB/LCB by direction; exploration factor scales √variance);
estimators/PredictionTransformation.scala:* is the shared interface.
"""

from __future__ import annotations

from typing import Callable, Protocol

import numpy as np
from scipy.stats import norm


class PredictionTransformation(Protocol):
    def __call__(
        self, predictive_means: np.ndarray, predictive_variances: np.ndarray
    ) -> np.ndarray: ...


class ExpectedImprovement:
    """Expected improvement over ``best_evaluation``.

    ``larger_is_better`` comes from the driving evaluator (AUC → True,
    RMSE → False), replacing the reference's ``evaluator.betterThan(1,-1)``
    direction probe.
    """

    def __init__(self, best_evaluation: float, larger_is_better: bool = True):
        self.best_evaluation = best_evaluation
        self.larger_is_better = larger_is_better

    def __call__(
        self, predictive_means: np.ndarray, predictive_variances: np.ndarray
    ) -> np.ndarray:
        std = np.sqrt(np.maximum(predictive_variances, 1e-18))
        direction = 1.0 if self.larger_is_better else -1.0
        gamma = direction * (predictive_means - self.best_evaluation) / std
        return std * (gamma * norm.cdf(gamma) + norm.pdf(gamma))


class ConfidenceBound:
    """Upper (maximizing) or lower (minimizing) confidence bound."""

    def __init__(self, larger_is_better: bool = True, exploration_factor: float = 2.0):
        self.larger_is_better = larger_is_better
        self.exploration_factor = exploration_factor

    def __call__(
        self, predictive_means: np.ndarray, predictive_variances: np.ndarray
    ) -> np.ndarray:
        bound = self.exploration_factor * np.sqrt(
            np.maximum(predictive_variances, 0.0)
        )
        if self.larger_is_better:
            return predictive_means + bound
        return predictive_means - bound
