"""Random and GP-guided hyperparameter search loops.

Reference parity: search/RandomSearch.scala:30 (uniform candidate draws;
find(n, observations) replays prior observations then alternates
draw→evaluate) and search/GaussianProcessSearch.scala:54 (Matérn-5/2 GP fit
to observations, confidence-bound acquisition with exploration factor
2·std(observed evals), candidate pool of 250, uniform fallback until there
are more observations than dimensions).

TPU-era deviation: candidates are drawn from a scrambled Sobol sequence
rather than i.i.d. uniform — strictly better space coverage at the same
cost, and the rest of the algorithm is unchanged.
"""

from __future__ import annotations

import math
from typing import Generic, List, Optional, Protocol, Sequence, Tuple, TypeVar

import numpy as np
from scipy.stats import qmc

from photon_ml_tpu.hyperparameter.criteria import (
    ConfidenceBound,
    ExpectedImprovement,
)
from photon_ml_tpu.hyperparameter.gp import (
    GaussianProcessEstimator,
    GaussianProcessModel,
)
from photon_ml_tpu.hyperparameter.kernels import Matern52

T = TypeVar("T")


class EvaluationFunction(Protocol[T]):
    """Integration point between the tuner and an estimator
    (reference EvaluationFunction.scala:25)."""

    def __call__(self, hyperparameters: np.ndarray) -> Tuple[float, T]: ...

    def vectorize_params(self, result: T) -> np.ndarray: ...

    def get_evaluation_value(self, result: T) -> float: ...


class RandomSearch(Generic[T]):
    def __init__(
        self,
        ranges: Sequence[Tuple[float, float]],
        evaluation_function: EvaluationFunction[T],
        seed: int = 0,
    ) -> None:
        if not ranges:
            raise ValueError("need at least one parameter range")
        self.ranges = [(float(lo), float(hi)) for lo, hi in ranges]
        self.num_params = len(ranges)
        self.evaluation_function = evaluation_function
        self.rng = np.random.default_rng(seed)
        self._sobol = qmc.Sobol(d=self.num_params, scramble=True, rng=self.rng)

    def find(self, n: int, observations: Sequence[T] = ()) -> List[T]:
        """Evaluate n new points; prior observations seed the search state."""
        if n <= 0:
            raise ValueError("the number of results must be greater than zero")
        prior = [
            (
                self.evaluation_function.vectorize_params(o),
                self.evaluation_function.get_evaluation_value(o),
            )
            for o in observations
        ]
        for candidate, value in prior[:-1]:
            self._on_observation(candidate, value)
        last = prior[-1] if prior else None

        results: List[T] = []
        for _ in range(n):
            if last is None:
                candidate = self._draw_candidates(1)[0]
            else:
                candidate = self._next(*last)
            value, result = self.evaluation_function(candidate)
            results.append(result)
            last = (candidate, value)
        return results

    def _next(self, last_candidate: np.ndarray, last_value: float) -> np.ndarray:
        self._on_observation(last_candidate, last_value)
        return self._draw_candidates(1)[0]

    def _on_observation(self, point: np.ndarray, value: float) -> None:
        pass

    def _draw_candidates(self, n: int) -> np.ndarray:
        # Sobol wants power-of-two draws for balance; round up and subsample.
        m = max(1, math.ceil(math.log2(max(n, 1))))
        unit = self._sobol.random(2**m)[:n]
        lo = np.array([r[0] for r in self.ranges])
        hi = np.array([r[1] for r in self.ranges])
        return lo + unit * (hi - lo)


class GaussianProcessSearch(RandomSearch[T]):
    def __init__(
        self,
        ranges: Sequence[Tuple[float, float]],
        evaluation_function: EvaluationFunction[T],
        larger_is_better: bool = True,
        candidate_pool_size: int = 250,
        seed: int = 0,
        num_mcmc_samples: int = 20,
        acquisition: str = "CB",
    ) -> None:
        super().__init__(ranges, evaluation_function, seed)
        self.larger_is_better = larger_is_better
        self.candidate_pool_size = candidate_pool_size
        acquisition = acquisition.upper()
        if acquisition not in ("CB", "EI"):
            raise ValueError(f"unknown acquisition: {acquisition}")
        self.acquisition = acquisition
        # Reference burns 100 + keeps 100 kernel samples; a smaller chain is
        # nearly as good and much cheaper between trials.
        self.num_mcmc_samples = num_mcmc_samples
        self._observed_points: Optional[np.ndarray] = None
        self._observed_evals: Optional[np.ndarray] = None
        self._best_eval = -np.inf if larger_is_better else np.inf
        self.last_model: Optional[GaussianProcessModel] = None

    def _next(self, last_candidate: np.ndarray, last_value: float) -> np.ndarray:
        self._on_observation(last_candidate, last_value)
        points, evals = self._observed_points, self._observed_evals
        if points is None or points.shape[0] <= self.num_params:
            # Underdetermined: uniform (Sobol) exploration, like the reference.
            return self._draw_candidates(1)[0]

        candidates = self._draw_candidates(self.candidate_pool_size)
        if self.acquisition == "EI":
            transformation: object = ExpectedImprovement(
                best_evaluation=self._best_eval,
                larger_is_better=self.larger_is_better,
            )
        else:
            # The reference floors the sample variance at 1.0
            # (GaussianProcessSearch.scala:97), which drowns the GP mean for
            # metrics with sub-unit spread (AUC, log-loss); a tiny floor keeps
            # the intended 2·std(evals) exploration factor meaningful.
            obs_std = math.sqrt(max(1e-12, float(np.var(evals, ddof=1))))
            transformation = ConfidenceBound(
                larger_is_better=self.larger_is_better,
                exploration_factor=2.0 * obs_std,
            )
        estimator = GaussianProcessEstimator(
            kernel=Matern52(),
            normalize_labels=True,
            prediction_transformation=transformation,
            num_burn_in_samples=self.num_mcmc_samples,
            num_samples=self.num_mcmc_samples,
            rng=self.rng,
        )
        self.last_model = estimator.fit(points, evals)
        predictions = self.last_model.predict_transformed(candidates)
        if self.larger_is_better:
            return candidates[int(np.argmax(predictions))]
        return candidates[int(np.argmin(predictions))]

    def _on_observation(self, point: np.ndarray, value: float) -> None:
        point = np.atleast_2d(np.asarray(point, dtype=float))
        if self._observed_points is None:
            self._observed_points = point
            self._observed_evals = np.array([value])
        else:
            self._observed_points = np.vstack([self._observed_points, point])
            self._observed_evals = np.append(self._observed_evals, value)
        better = value > self._best_eval if self.larger_is_better else (
            value < self._best_eval
        )
        if better:
            self._best_eval = value
