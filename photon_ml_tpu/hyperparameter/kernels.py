"""Stationary covariance kernels for GP hyperparameter search.

Reference parity: estimators/kernels/StationaryKernel.scala:* (pairwise
squared distances over length-scaled inputs; params stored in log space),
RBF.scala:* (K = exp(-r²/2)), Matern52.scala:* (K = (1 + √(5r²) + 5r²/3)·
exp(-√(5r²))). The reference computed distances with element loops; here
they are vectorized.
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

import numpy as np


def _pairwise_sq_dists(x1: np.ndarray, x2: np.ndarray) -> np.ndarray:
    """m×p matrix of squared Euclidean distances between row-points."""
    d = x1[:, None, :] - x2[None, :, :]
    return np.einsum("mpk,mpk->mp", d, d)


@dataclasses.dataclass(frozen=True)
class Kernel:
    """Stationary kernel with per-dimension length scales.

    ``length_scale`` may have one entry (isotropic, broadcast over input
    dimensions like the reference's ``expandDimensions``) or one per input
    dimension (ARD).
    """

    length_scale: np.ndarray = dataclasses.field(
        default_factory=lambda: np.ones(1)
    )
    length_scale_bounds: Tuple[float, float] = (1e-5, 1e5)

    def _scaled(self, x: np.ndarray) -> np.ndarray:
        ls = np.broadcast_to(
            np.asarray(self.length_scale, dtype=float), (x.shape[1],)
        )
        return x / ls

    def _from_sq_dists(self, sq_dists: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    def __call__(self, x1: np.ndarray, x2: np.ndarray = None) -> np.ndarray:
        x1 = np.atleast_2d(np.asarray(x1, dtype=float))
        if x1.size == 0:
            raise ValueError("empty kernel input")
        a = self._scaled(x1)
        if x2 is None:
            b = a
        else:
            x2 = np.atleast_2d(np.asarray(x2, dtype=float))
            if x2.shape[1] != x1.shape[1]:
                raise ValueError("inputs must have the same number of columns")
            b = self._scaled(x2)
        return self._from_sq_dists(_pairwise_sq_dists(a, b))

    def diag(self, x: np.ndarray) -> np.ndarray:
        """k(x_i, x_i) per row — constant for stationary kernels; avoids
        building the full q×q matrix when only the diagonal is needed."""
        x = np.atleast_2d(np.asarray(x, dtype=float))
        return self._from_sq_dists(np.zeros(x.shape[0]))

    # --- log-space parameterization (StationaryKernel.scala getParams etc.)

    def get_params(self) -> np.ndarray:
        return np.log(np.asarray(self.length_scale, dtype=float))

    def get_param_bounds(self) -> Tuple[float, float]:
        lo, hi = self.length_scale_bounds
        return (np.log(lo), np.log(hi))

    def with_params(self, theta: np.ndarray) -> "Kernel":
        return dataclasses.replace(self, length_scale=np.exp(np.asarray(theta)))

    def expand_dims(self, dim: int) -> np.ndarray:
        """Initial log-params expanded to one per input dimension
        (Kernel.expandDimensions in the reference)."""
        return np.broadcast_to(self.get_params(), (dim,)).copy() if (
            self.get_params().shape[0] == 1
        ) else self.get_params()


@dataclasses.dataclass(frozen=True)
class RBF(Kernel):
    def _from_sq_dists(self, sq_dists: np.ndarray) -> np.ndarray:
        return np.exp(-0.5 * sq_dists)


@dataclasses.dataclass(frozen=True)
class Matern52(Kernel):
    def _from_sq_dists(self, sq_dists: np.ndarray) -> np.ndarray:
        f = np.sqrt(5.0 * sq_dists)
        return (1.0 + f + (5.0 / 3.0) * sq_dists) * np.exp(-f)
