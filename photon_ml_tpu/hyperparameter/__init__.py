"""Bayesian/random hyperparameter search (driver-side math).

Reference parity: photon-lib hyperparameter/ — RandomSearch.scala:30,
GaussianProcessSearch.scala:54, GaussianProcessEstimator.scala:38,
SliceSampler.scala:53, kernels/{RBF,Matern52}, criteria/{ExpectedImprovement,
ConfidenceBound}, EvaluationFunction.scala:25.

This runs on the host between (expensive, TPU-resident) training trials, so
plain NumPy is the right tool — matrices are #trials × #trials.
"""

from photon_ml_tpu.hyperparameter.kernels import RBF, Kernel, Matern52
from photon_ml_tpu.hyperparameter.slice_sampler import SliceSampler
from photon_ml_tpu.hyperparameter.gp import (
    GaussianProcessEstimator,
    GaussianProcessModel,
)
from photon_ml_tpu.hyperparameter.criteria import (
    ConfidenceBound,
    ExpectedImprovement,
    PredictionTransformation,
)
from photon_ml_tpu.hyperparameter.search import (
    EvaluationFunction,
    GaussianProcessSearch,
    RandomSearch,
)

__all__ = [
    "RBF",
    "Kernel",
    "Matern52",
    "SliceSampler",
    "GaussianProcessEstimator",
    "GaussianProcessModel",
    "ConfidenceBound",
    "ExpectedImprovement",
    "PredictionTransformation",
    "EvaluationFunction",
    "GaussianProcessSearch",
    "RandomSearch",
]
