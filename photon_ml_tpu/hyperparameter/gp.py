"""Gaussian Process regression with kernel params integrated out by MCMC.

Reference parity: estimators/GaussianProcessEstimator.scala:38 (slice-sampled
kernel length scales, burn-in + samples, GPML Alg. 2.1 log-likelihood) and
GaussianProcessModel.scala:* (per-sampled-kernel Cholesky precompute; predict
averages mean/variance over sampled kernels; predictTransformed averages the
acquisition value per kernel).
"""

from __future__ import annotations

import math
from typing import List, Optional, Tuple

import numpy as np
from scipy.linalg import cho_factor, cho_solve, solve_triangular

from photon_ml_tpu.hyperparameter.criteria import PredictionTransformation
from photon_ml_tpu.hyperparameter.kernels import Kernel, RBF
from photon_ml_tpu.hyperparameter.slice_sampler import SliceSampler

# Diagonal jitter for numerical positive-definiteness (the reference relies
# on catching Cholesky failures instead; jitter is standard GP practice).
_JITTER = 1e-9


class GaussianProcessModel:
    def __init__(
        self,
        x_train: np.ndarray,
        y_train: np.ndarray,
        y_mean: float,
        kernels: List[Kernel],
        prediction_transformation: Optional[PredictionTransformation] = None,
    ) -> None:
        x_train = np.atleast_2d(np.asarray(x_train, dtype=float))
        y_train = np.asarray(y_train, dtype=float)
        if x_train.shape[0] != y_train.shape[0]:
            raise ValueError("features and labels must have the same length")
        self.x_train = x_train
        self.y_train = y_train
        self.y_mean = y_mean
        self.kernels = kernels
        self.prediction_transformation = prediction_transformation
        self.feature_dimension = x_train.shape[1]
        # GPML Alg 2.1 lines 2-3, precomputed per sampled kernel
        self._precomputed = []
        n = x_train.shape[0]
        for kernel in kernels:
            k = kernel(x_train) + _JITTER * np.eye(n)
            chol = np.linalg.cholesky(k)
            alpha = cho_solve((chol, True), y_train)
            self._precomputed.append((kernel, chol, alpha))

    def _predict_with(
        self, x: np.ndarray, kernel: Kernel, chol: np.ndarray, alpha: np.ndarray
    ) -> Tuple[np.ndarray, np.ndarray]:
        ktrans = kernel(self.x_train, x)  # n_train × n_query
        y_pred = ktrans.T @ alpha  # line 4
        v = solve_triangular(chol, ktrans, lower=True)  # line 5
        # line 6, diagonal only: var_i = k(x_i,x_i) - ||v_i||² — no q×q matrices
        y_var = np.maximum(
            kernel.diag(x) - np.einsum("ij,ij->j", v, v), 0.0
        )
        return y_pred + self.y_mean, y_var

    def predict(self, x: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        """Mean and variance of the response, averaged over sampled kernels."""
        x = np.atleast_2d(np.asarray(x, dtype=float))
        means, variances = zip(
            *(self._predict_with(x, k, c, a) for k, c, a in self._precomputed)
        )
        return np.mean(means, axis=0), np.mean(variances, axis=0)

    def predict_transformed(self, x: np.ndarray) -> np.ndarray:
        """Acquisition value per query point, averaged over sampled kernels."""
        x = np.atleast_2d(np.asarray(x, dtype=float))
        out = []
        for k, c, a in self._precomputed:
            mean, var = self._predict_with(x, k, c, a)
            if self.prediction_transformation is not None:
                out.append(self.prediction_transformation(mean, var))
            else:
                out.append(mean)
        return np.mean(out, axis=0)


class GaussianProcessEstimator:
    def __init__(
        self,
        kernel: Kernel = None,
        normalize_labels: bool = False,
        prediction_transformation: Optional[PredictionTransformation] = None,
        num_burn_in_samples: int = 100,
        num_samples: int = 100,
        rng: np.random.Generator = None,
    ) -> None:
        self.kernel = kernel if kernel is not None else RBF()
        self.normalize_labels = normalize_labels
        self.prediction_transformation = prediction_transformation
        self.num_burn_in_samples = num_burn_in_samples
        self.num_samples = num_samples
        self.rng = rng if rng is not None else np.random.default_rng()

    def fit(self, x: np.ndarray, y: np.ndarray) -> GaussianProcessModel:
        x = np.atleast_2d(np.asarray(x, dtype=float))
        y = np.asarray(y, dtype=float)
        if x.shape[0] == 0 or x.shape[0] != y.shape[0]:
            raise ValueError("bad training data shapes")
        y_mean = float(np.mean(y)) if self.normalize_labels else 0.0
        y_train = y - y_mean
        kernels = self._estimate_kernel_params(x, y_train)
        return GaussianProcessModel(
            x, y_train, y_mean, kernels, self.prediction_transformation
        )

    def _estimate_kernel_params(
        self, x: np.ndarray, y: np.ndarray
    ) -> List[Kernel]:
        """Slice-sample length scales from l(θ|x,y) ∝ p(y|θ,x) under a
        uniform prior; each sample becomes one kernel to average over."""
        sampler = SliceSampler(
            lambda theta: self._log_likelihood(x, y, theta),
            range_=self.kernel.get_param_bounds(),
            rng=self.rng,
        )
        theta = self.kernel.expand_dims(x.shape[1])
        for _ in range(self.num_burn_in_samples):
            theta = sampler.draw(theta)
        samples = []
        for _ in range(self.num_samples):
            theta = sampler.draw(theta)
            samples.append(theta)
        return [self.kernel.with_params(t) for t in samples]

    def _log_likelihood(
        self, x: np.ndarray, y: np.ndarray, theta: np.ndarray
    ) -> float:
        """GPML Alg 2.1 / Eq 2.30 marginal likelihood; -inf when the kernel
        matrix is not PD (reference catches the Cholesky exception)."""
        kern = self.kernel.with_params(theta)
        k = kern(x) + _JITTER * np.eye(x.shape[0])
        try:
            chol, lower = cho_factor(k, lower=True)
        except np.linalg.LinAlgError:
            return -np.inf
        except ValueError:
            return -np.inf
        alpha = cho_solve((chol, lower), y)
        logdet_half = float(np.sum(np.log(np.diag(chol))))
        if not np.isfinite(logdet_half):
            return -np.inf
        return float(
            -0.5 * (y @ alpha) - logdet_half - 0.5 * len(y) * math.log(2 * math.pi)
        )
