"""Live serving introspection: /metrics, /healthz, /varz over HTTP.

A running ``serve_game`` is opaque without this — the metrics snapshot
only surfaces when the replay finishes. :class:`IntrospectionServer` is a
stdlib-only (``http.server``) daemon-thread HTTP server exposing:

* ``/metrics`` — the process MetricsRegistry in Prometheus text exposition
  format (version 0.0.4), see :func:`prometheus_text` for the naming
  scheme;
* ``/healthz`` — JSON liveness + hot-swap/validation-gate state (HTTP 503
  when the supplied health callback reports unhealthy);
* ``/varz`` — JSON dump of the active (possibly auto-tuned) config;
* ``/quitquitquit`` — releases an ``--introspect-hold`` wait, so tests and
  operators can end a held server deterministically.

Bind is loopback by default; this is an operator port, not a public one.
"""
from __future__ import annotations

import json
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, Optional

__all__ = ["prometheus_text", "IntrospectionServer"]

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")
_PROM_PREFIX = "photon_"


def _prom_name(name: str, prefix: str = _PROM_PREFIX) -> str:
    """Registry metric name → Prometheus metric name.

    Scheme (documented in docs/OBSERVABILITY.md): prepend ``photon_``,
    replace every character outside ``[a-zA-Z0-9_:]`` with ``_`` (so
    ``serving.latency_p99_ms`` → ``photon_serving_latency_p99_ms``), and
    prefix a leading digit with ``_``."""
    body = _NAME_RE.sub("_", name)
    if body and body[0].isdigit():
        body = "_" + body
    return prefix + body


def _prom_value(value: Any) -> str:
    v = float(value)
    if v != v:
        return "NaN"
    if v in (float("inf"), float("-inf")):
        return "+Inf" if v > 0 else "-Inf"
    return repr(v) if not float(v).is_integer() else str(int(v))


def _split_name(name: str) -> tuple:
    """Registry name → (family, label block). ``MetricsRegistry.scoped``
    stores labeled metrics as ``base{k="v",...}``; everything else is an
    unlabeled family."""
    if name.endswith("}") and "{" in name:
        base, labels = name.split("{", 1)
        return base, "{" + labels
    return name, ""


def _sample(pname: str, labels: str, extra: str = "") -> str:
    """One sample name: the Prometheus family name plus the stored label
    block, with an optional extra label (``quantile="0.5"``) merged in."""
    inner = labels[1:-1] if labels else ""
    if extra:
        inner = f"{inner},{extra}" if inner else extra
    return f"{pname}{{{inner}}}" if inner else pname


def prometheus_text(snapshot: Dict[str, Any], prefix: str = _PROM_PREFIX) -> str:
    """Render a MetricsRegistry snapshot as Prometheus text exposition
    (format version 0.0.4).

    * counters → ``counter`` samples;
    * gauges → two ``gauge`` samples, the last value and ``<name>_peak``;
    * histograms → a ``summary``: ``<name>{quantile="0.5|0.95|0.99"}``,
      ``<name>_count``, and a ``<name>_max`` gauge (the registry keeps
      digests, not sums, so no ``_sum`` sample is emitted).

    Names carrying a label block (written through
    ``MetricsRegistry.scoped``, e.g. ``serving.requests{tenant="a"}``)
    render as labeled samples of one family: the ``# TYPE`` header is
    emitted once per family and only the family name is sanitized, so
    per-tenant series group under one metric the way Prometheus expects.
    """
    lines = []

    def families(section):
        fams: Dict[str, list] = {}
        for name, value in sorted((snapshot.get(section) or {}).items()):
            base, labels = _split_name(name)
            fams.setdefault(base, []).append((labels, value))
        return sorted(fams.items())

    for base, samples in families("counters"):
        pname = _prom_name(base, prefix)
        lines.append(f"# TYPE {pname} counter")
        for labels, value in samples:
            lines.append(f"{_sample(pname, labels)} {_prom_value(value)}")
    for base, samples in families("gauges"):
        pname = _prom_name(base, prefix)
        lines.append(f"# TYPE {pname} gauge")
        for labels, g in samples:
            lines.append(f"{_sample(pname, labels)} {_prom_value(g['last'])}")
        lines.append(f"# TYPE {pname}_peak gauge")
        for labels, g in samples:
            lines.append(
                f"{_sample(pname + '_peak', labels)} {_prom_value(g['peak'])}"
            )
    for base, samples in families("histograms"):
        pname = _prom_name(base, prefix)
        lines.append(f"# TYPE {pname} summary")
        max_lines = []
        for labels, h in samples:
            for q in ("p50", "p95", "p99"):
                if q in h:
                    quantile = {"p50": "0.5", "p95": "0.95", "p99": "0.99"}[q]
                    qlabel = 'quantile="%s"' % quantile
                    lines.append(
                        f"{_sample(pname, labels, qlabel)}"
                        f" {_prom_value(h[q])}"
                    )
            lines.append(
                f"{_sample(pname + '_count', labels)} "
                f"{_prom_value(h.get('count', 0))}"
            )
            if "max" in h:
                max_lines.append(
                    f"{_sample(pname + '_max', labels)} {_prom_value(h['max'])}"
                )
        if max_lines:
            lines.append(f"# TYPE {pname}_max gauge")
            lines.extend(max_lines)
    return "\n".join(lines) + "\n"


class IntrospectionServer:
    """Daemon-thread HTTP server for the three serving endpoints.

    ``registry`` backs /metrics; ``varz`` and ``health`` are zero-arg
    callables returning JSON-able dicts, re-evaluated per request so the
    endpoints always reflect live state (hot-swap generation, tuned
    config). ``health`` may include ``"healthy": False`` to flip /healthz
    to HTTP 503."""

    def __init__(
        self,
        registry=None,
        varz: Optional[Callable[[], Dict[str, Any]]] = None,
        health: Optional[Callable[[], Dict[str, Any]]] = None,
        host: str = "127.0.0.1",
        port: int = 0,
        extra_json: Optional[Dict[str, Callable[[], Any]]] = None,
    ):
        if registry is None:
            from photon_ml_tpu.telemetry.metrics import get_registry

            registry = get_registry()
        self.registry = registry
        self._varz = varz or (lambda: {})
        self._health = health or (lambda: {})
        # extra JSON endpoints (path -> zero-arg callable returning a
        # JSON-able value), re-evaluated per request like varz/health; the
        # training plane mounts /progress here
        self._extra = {
            "/" + p.strip("/"): fn for p, fn in (extra_json or {}).items()
        }
        self._quit = threading.Event()
        outer = self

        class _Handler(BaseHTTPRequestHandler):
            def _reply(self, code: int, body: str, content_type: str) -> None:
                data = body.encode("utf-8")
                self.send_response(code)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self) -> None:  # noqa: N802 (http.server API)
                path = self.path.split("?", 1)[0].rstrip("/") or "/"
                try:
                    if path == "/metrics":
                        self._reply(
                            200,
                            prometheus_text(outer.registry.snapshot()),
                            "text/plain; version=0.0.4; charset=utf-8",
                        )
                    elif path == "/healthz":
                        doc = {"healthy": True}
                        doc.update(outer._health() or {})
                        code = 200 if doc.get("healthy", True) else 503
                        self._reply(
                            code,
                            json.dumps(doc, indent=2, sort_keys=True, default=str),
                            "application/json",
                        )
                    elif path == "/varz":
                        self._reply(
                            200,
                            json.dumps(
                                outer._varz() or {},
                                indent=2,
                                sort_keys=True,
                                default=str,
                            ),
                            "application/json",
                        )
                    elif path == "/quitquitquit":
                        outer._quit.set()
                        self._reply(200, "bye\n", "text/plain")
                    elif path in outer._extra:
                        self._reply(
                            200,
                            json.dumps(
                                outer._extra[path](),
                                indent=2,
                                sort_keys=True,
                                default=str,
                            ),
                            "application/json",
                        )
                    else:
                        self._reply(404, "not found\n", "text/plain")
                except Exception as e:  # endpoint bugs must not kill serving
                    try:
                        self._reply(500, f"error: {e}\n", "text/plain")
                    except Exception:
                        pass

            do_POST = do_GET

            def log_message(self, fmt, *args):  # quiet: operator port
                pass

        self._server = ThreadingHTTPServer((host, port), _Handler)
        self._server.daemon_threads = True
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            name="photon-introspect",
            daemon=True,
        )

    @property
    def port(self) -> int:
        return int(self._server.server_address[1])

    @property
    def host(self) -> str:
        return str(self._server.server_address[0])

    def start(self) -> "IntrospectionServer":
        self._thread.start()
        return self

    def wait_quit(self, timeout: Optional[float] = None) -> bool:
        """Block until /quitquitquit is hit (or timeout); used by
        ``serve_game --introspect-hold``."""
        return self._quit.wait(timeout)

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
