"""Entity→(shard, slot) routing index for sharded device RE tables.

The single-table scorer resolves an entity to ONE row index in one device
table. The sharded scorer splits each random-effect table across ``S``
device shards (one per mesh device in multi-scorer mode), so resolution
becomes two coordinates: which shard holds the row, and which slot within
that shard. This module owns that mapping — pure host state, shared by
every scorer replica so they stay mutually consistent, with no device
arrays of its own.

Layout: the base resident set (rows ``0..R-1`` of the packed table, the
hottest rows when the artifact is popularity-sorted, all rows when the
device budget covers the table) is placed CYCLICALLY: global row ``r``
lives at ``(r % S, r // S)`` — the grid layout of
``parallel/grid_features.py`` applied to table rows, balancing both
capacity and gather traffic across shards for any contiguous hot prefix.
Rows beyond the budget start non-resident (slot −1) and are admitted
later into headroom slots by ``serving/admission.py``; when headroom runs
out the oldest ADMITTED row is evicted (the base set is pinned).

Publication ordering contract (what makes lock-free readers safe): a row
becomes resident only AFTER its device content is written (``publish`` is
the last step), and is evicted by first clearing ``slot_of`` (readers
immediately fall back to the cold slot → FE-only score) and only then
reusing the slot's device storage. A reader can therefore never gather
another entity's coefficients; the worst case is one FE-only score during
the handover, identical to the cold-entity degradation.

That contract covers READERS only. WRITERS (the background admission
thread, hot-swap row updates, rebinds) mutate ``_free``/``_admitted``/
``slot_of`` non-atomically, so every mutation sequence must hold
``CoordinateRouting.lock`` — otherwise two threads can pop the same free
slot or publish two rows into one slot. Lock ordering across the serving
stack: ``routing.lock`` (outer) → ``scorer.write_lock`` (inner); the
scoring thread takes only ``write_lock``, so the pair cannot deadlock.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Deque, Dict, List, Optional, Tuple

import numpy as np


class CoordinateRouting:
    """Routing state for ONE random-effect coordinate.

    ``num_shards`` device shards of ``shard_capacity`` data slots each
    (slot ``shard_capacity`` is every shard's permanently-zero cold slot).
    ``resident_rows`` rows of the backing table start device-resident in
    the cyclic layout; the remaining device slots are admission headroom.
    """

    #: batches between EWMA halvings of the request-frequency plane
    FREQ_DECAY_EVERY = 64

    def __init__(
        self,
        n_rows: int,
        num_shards: int,
        shard_capacity: int,
        resident_rows: Optional[int] = None,
        eviction_policy: str = "oldest",
        score_delta: bool = True,
    ):
        if num_shards < 1:
            raise ValueError(f"num_shards must be >= 1, got {num_shards}")
        if shard_capacity < 1:
            raise ValueError(
                f"shard_capacity must be >= 1, got {shard_capacity}"
            )
        if eviction_policy not in ("oldest", "importance"):
            raise ValueError(
                "eviction_policy must be 'oldest' or 'importance', got "
                f"{eviction_policy!r}"
            )
        self.n_rows = int(n_rows)
        self.num_shards = int(num_shards)
        self.shard_capacity = int(shard_capacity)
        self.eviction_policy = eviction_policy
        # serializes WRITERS (allocate/publish/grow/unpublish and every
        # multi-step sequence built on them); re-entrant so a caller
        # holding it for a compound mutation can still call the
        # individual methods. Acquire BEFORE any scorer write_lock.
        self.lock = threading.RLock()
        self.cold_slot = self.shard_capacity
        device_rows = self.num_shards * self.shard_capacity
        base = device_rows if resident_rows is None else int(resident_rows)
        base = max(0, min(base, self.n_rows, device_rows))
        self.base_rows = base  # pinned: never evicted

        # global row -> (shard, slot); slot -1 = not device-resident
        self._shard_of = np.zeros(max(self.n_rows, 1), dtype=np.int32)
        self._slot_of = np.full(max(self.n_rows, 1), -1, dtype=np.int32)
        if base:
            r = np.arange(base)
            self._shard_of[:base] = r % self.num_shards
            self._slot_of[:base] = r // self.num_shards

        # free device slots beyond the base set, round-robin across shards
        # (same cyclic order as the base layout)
        free = np.arange(base, device_rows)
        self._free: Deque[Tuple[int, int]] = deque(
            zip(
                (free % self.num_shards).tolist(),
                (free // self.num_shards).tolist(),
            )
        )
        # admitted (evictable) rows, oldest first
        self._admitted: Deque[int] = deque()

        # importance plane (DuHL-style cache value, arxiv 1702.07005):
        # per-row EWMA request frequency × coefficient-row magnitude — the
        # magnitude bounds the score delta vs the FE-only fallback
        # (|Δscore| <= ||w_r||·||x||), so freq × norm approximates the
        # expected score impact of keeping the row resident. Tracked only
        # under the "importance" policy (the default path allocates
        # nothing); both planes are stats-grade — written without the
        # routing lock from the scoring thread; eviction reads them under
        # the lock, and a torn read can at worst mis-rank one victim,
        # never corrupt placement.
        if eviction_policy == "importance":
            self._freq = np.zeros(max(self.n_rows, 1), dtype=np.float64)
            self._norm = np.zeros(max(self.n_rows, 1), dtype=np.float32)
        else:
            self._freq = None
            self._norm = None
        # MEASURED score impact: per-row EWMA of |score − fe_only_score|
        # observed on actual requests (the realized counterpart of the
        # freq × norm Cauchy–Schwarz BOUND above). importance_of takes the
        # max of the two — the bound covers rows never yet measured (just
        # admitted, or resident before the first scored hit), the
        # measurement rescues rows whose bound is loose in either
        # direction. Same stats-grade write discipline as _freq.
        self.score_delta = bool(score_delta) and eviction_policy == "importance"
        if self.score_delta:
            self._sdelta = np.zeros(max(self.n_rows, 1), dtype=np.float64)
        else:
            self._sdelta = None
        self._freq_batches = 0

        # lookup accounting (reset via reset_counters)
        self.resident_lookups = 0
        self.deferred_lookups = 0  # known entity, not yet device-resident
        self.cold_lookups = 0  # entity absent from the model
        self.admitted_total = 0
        self.evicted_total = 0
        self.evicted_oldest = 0
        self.evicted_importance = 0

    # ---------------------------------------------------------------- route

    def route(
        self, entity_rows: np.ndarray
    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Vectorized batch routing: global table rows (−1 = unknown) →
        int32 ``(shards, slots)`` arrays plus the unique DEFERRED rows
        (known entities currently not device-resident — they score through
        the cold slot this batch and should be queued for admission)."""
        rows = np.asarray(entity_rows, dtype=np.int64)
        shards = np.zeros(rows.shape, dtype=np.int32)
        slots = np.full(rows.shape, self.cold_slot, dtype=np.int32)
        known = rows >= 0
        n_known = int(np.count_nonzero(known))
        self.cold_lookups += rows.size - n_known
        if not n_known:
            return shards, slots, np.empty(0, dtype=np.int64)
        krows = rows[known]
        # a concurrent hot swap can hand out rows from a newer entity
        # index before this coordinate's routing has grown; such rows are
        # deferred (cold slot now, admitted once the swap lands), never an
        # out-of-bounds read of the placement arrays
        in_range = krows < self._slot_of.size
        safe = np.where(in_range, krows, 0)
        kslots = np.where(in_range, self._slot_of[safe], -1)
        kshards = np.where(in_range, self._shard_of[safe], 0)
        resident = kslots >= 0
        n_res = int(np.count_nonzero(resident))
        self.resident_lookups += n_res
        self.deferred_lookups += n_known - n_res
        out_slots = np.where(resident, kslots, self.cold_slot)
        out_shards = np.where(resident, kshards, 0)
        slots[known] = out_slots
        shards[known] = out_shards
        deferred = (
            np.unique(krows[~resident])
            if n_res < n_known
            else np.empty(0, dtype=np.int64)
        )
        return shards, slots, deferred

    # ------------------------------------------------- importance tracking

    @property
    def wants_feature_norms(self) -> bool:
        """Whether the scorer's route step should compute per-request
        feature-vector norms for :meth:`note_requests` (only the
        importance policy consumes them; the default path skips the
        O(B·k) norm entirely)."""
        return self._freq is not None

    def note_requests(
        self,
        entity_rows: np.ndarray,
        feature_norms: Optional[np.ndarray] = None,
    ) -> None:
        """Fold one request batch into the EWMA frequency plane (called by
        the scorer's route step; no-op under the default policy). Every
        ``FREQ_DECAY_EVERY`` batches the whole plane halves, so frequency
        is an exponential window over recent traffic, not an all-time
        count that would pin formerly-hot rows forever.

        ``feature_norms`` (aligned with ``entity_rows``) weights each
        request by its feature-vector magnitude ``||x||`` instead of 1.0:
        combined with the per-row coefficient norm (:meth:`note_row_norms`)
        the importance score becomes ``EWMA(Σ||x||) × ||w_r||`` — a
        Cauchy–Schwarz bound on the row's cumulative score delta vs the
        FE-only fallback, not just its hit count. Callers without norms
        fall back to pure frequency."""
        if self._freq is None:
            return
        rows = np.asarray(entity_rows, dtype=np.int64).ravel()
        keep = (rows >= 0) & (rows < self._freq.size)
        if keep.any():
            if feature_norms is not None:
                norms = np.asarray(feature_norms, dtype=np.float64).ravel()
                np.add.at(self._freq, rows[keep], norms[keep])
            else:
                np.add.at(self._freq, rows[keep], 1.0)
        self._freq_batches += 1
        if self._freq_batches >= self.FREQ_DECAY_EVERY:
            self._freq_batches = 0
            self._freq *= 0.5
            if self._sdelta is not None:
                self._sdelta *= 0.5

    def note_row_norms(self, rows: np.ndarray, norms: np.ndarray) -> None:
        """Record the L2 magnitude of rows' coefficient content (called on
        admission and hot-swap writes; no-op under the default policy)."""
        if self._norm is None:
            return
        rows = np.asarray(rows, dtype=np.int64).ravel()
        norms = np.asarray(norms, dtype=np.float32).ravel()
        keep = (rows >= 0) & (rows < self._norm.size)
        if keep.any():
            self._norm[rows[keep]] = norms[keep]

    @property
    def wants_score_deltas(self) -> bool:
        """Whether the scorer should compute measured per-request
        |score − fe_only| contributions for :meth:`note_score_deltas`
        (only the importance policy with the score-delta signal enabled
        consumes them — the default path never pays for the extra jit)."""
        return self._sdelta is not None

    def note_score_deltas(
        self, entity_rows: np.ndarray, deltas: np.ndarray
    ) -> None:
        """Fold one batch of MEASURED per-request score impacts
        (|score − fe_only_score| attributable to this coordinate) into the
        EWMA plane; decayed on the same cadence as the frequency plane
        (inside :meth:`note_requests`). No-op unless score-delta tracking
        is on. Non-resident rows gather the zero cold slot, so their
        measured contribution is 0 — the freq × norm bound governs them
        until first residency."""
        if self._sdelta is None:
            return
        rows = np.asarray(entity_rows, dtype=np.int64).ravel()
        deltas = np.asarray(deltas, dtype=np.float64).ravel()
        keep = (rows >= 0) & (rows < self._sdelta.size)
        if keep.any():
            np.add.at(self._sdelta, rows[keep], np.abs(deltas[keep]))

    def importance_of(self, rows: np.ndarray) -> np.ndarray:
        """max(freq × max(norm, ε), measured score delta) per row — ε
        keeps frequency meaningful for rows admitted through paths that
        never reported a norm; the measured plane (when tracked) rescues
        rows whose Cauchy–Schwarz bound is loose."""
        if self._freq is None:
            return np.zeros(np.asarray(rows).size, dtype=np.float64)
        rows = np.asarray(rows, dtype=np.int64).ravel()
        bound = self._freq[rows] * np.maximum(
            self._norm[rows].astype(np.float64), 1e-12
        )
        if self._sdelta is None:
            return bound
        return np.maximum(bound, self._sdelta[rows])

    def is_resident(self, row: int) -> bool:
        return 0 <= row < self.n_rows and self._slot_of[row] >= 0

    def placement(self, row: int) -> Tuple[int, int]:
        """(shard, slot) of a resident row (slot −1 when not resident)."""
        return int(self._shard_of[row]), int(self._slot_of[row])

    # ----------------------------------------------------- slot allocation

    def allocate(self, k: int) -> Tuple[np.ndarray, np.ndarray, List[int]]:
        """Claim ``k`` device slots for admission. Returns int arrays
        ``(shards, slots)`` plus the list of rows EVICTED to make room
        (already unpublished here — the caller must zero/overwrite their
        device slots before publishing new occupants). Raises when the
        coordinate has fewer than ``k`` evictable slots in total.

        Victim selection is the ``eviction_policy``: ``oldest`` (default,
        the historical FIFO — byte-identical behavior) pops the
        longest-admitted row; ``importance`` evicts the admitted rows with
        the LOWEST freq × norm score (see :meth:`importance_of`), so a hot
        long-tail row survives arbitrarily many admission waves while a
        one-hit row is recycled first — the DuHL cache policy applied to
        device residency."""
        with self.lock:
            if self.eviction_policy == "importance":
                return self._allocate_importance(k)
            shards = np.empty(k, dtype=np.int32)
            slots = np.empty(k, dtype=np.int32)
            evicted: List[int] = []
            for i in range(k):
                if self._free:
                    shard, slot = self._free.popleft()
                elif self._admitted:
                    victim = self._admitted.popleft()
                    shard, slot = self.placement(victim)
                    # unpublish BEFORE the slot is reused: readers of the
                    # victim fall back to FE-only from this point on
                    self._slot_of[victim] = -1
                    self.evicted_total += 1
                    self.evicted_oldest += 1
                    evicted.append(victim)
                else:
                    raise RuntimeError(
                        f"no admission headroom: {self.base_rows} base rows "
                        f"fill all {self.num_shards}x{self.shard_capacity} "
                        "device slots — raise the device budget or lower "
                        "the resident base"
                    )
                shards[i] = shard
                slots[i] = slot
            return shards, slots, evicted

    def _allocate_importance(
        self, k: int
    ) -> Tuple[np.ndarray, np.ndarray, List[int]]:
        """allocate() under the importance policy (caller holds the lock).

        Victims are chosen by POSITION in the admitted deque, not by row
        value: the deque can hold stale entries for rows already
        unpublished by a hot swap (and, after a re-admission, duplicates),
        so value-based removal would corrupt the capacity bookkeeping.
        Only the first live position of each row is evictable; stale
        positions are dropped during the rebuild."""
        shards = np.empty(k, dtype=np.int32)
        slots = np.empty(k, dtype=np.int32)
        evicted: List[int] = []
        take_free = min(k, len(self._free))
        for i in range(take_free):
            shards[i], slots[i] = self._free.popleft()
        need = k - take_free
        if need == 0:
            return shards, slots, evicted
        adm = np.fromiter(
            self._admitted, dtype=np.int64, count=len(self._admitted)
        )
        live = self._slot_of[adm] >= 0
        if live.any():
            # duplicates (re-published rows): only the first position per
            # row is "the" resident entry
            first = np.zeros(adm.size, dtype=bool)
            _, first_pos = np.unique(adm, return_index=True)
            first[first_pos] = True
            live &= first
        live_pos = np.nonzero(live)[0]
        if need > live_pos.size:
            raise RuntimeError(
                f"no admission headroom: {self.base_rows} base rows "
                f"fill all {self.num_shards}x{self.shard_capacity} "
                "device slots — raise the device budget or lower "
                "the resident base"
            )
        score = self.importance_of(adm[live_pos])
        if need < live_pos.size:
            pick = live_pos[np.argpartition(score, need - 1)[:need]]
        else:
            pick = live_pos
        for i, pos in enumerate(pick):
            victim = int(adm[pos])
            shard, slot = self.placement(victim)
            self._slot_of[victim] = -1
            self.evicted_total += 1
            self.evicted_importance += 1
            evicted.append(victim)
            shards[take_free + i] = shard
            slots[take_free + i] = slot
        # rebuild the deque: surviving live entries keep their order;
        # picked and stale positions drop out
        drop = set(int(p) for p in pick)
        stale = set(int(p) for p in np.nonzero(~live)[0])
        self._admitted = deque(
            int(r)
            for pos, r in enumerate(adm)
            if pos not in drop and pos not in stale
        )
        return shards, slots, evicted

    def publish(
        self, rows: np.ndarray, shards: np.ndarray, slots: np.ndarray
    ) -> None:
        """Make admitted rows visible to routing. Call ONLY after their
        device content is written in every scorer replica."""
        with self.lock:
            rows = np.asarray(rows, dtype=np.int64)
            self._shard_of[rows] = np.asarray(shards, dtype=np.int32)
            self._slot_of[rows] = np.asarray(slots, dtype=np.int32)
            self._admitted.extend(int(r) for r in rows)
            self.admitted_total += rows.size

    def grow(self, n_rows: int) -> None:
        """Extend the row space (hot-swap appended new entities to the
        backing table). New rows start non-resident; device capacity is
        unchanged — admission headroom absorbs them."""
        with self.lock:
            n_rows = int(n_rows)
            if n_rows <= self.n_rows:
                return
            extra = n_rows - self._slot_of.size
            if extra > 0:
                # over-allocate in chunks: a nearline loop claiming a few
                # dozen fresh overlay rows per applied delta would
                # otherwise memcpy the whole placement array every tick.
                # Rows past n_rows stay unroutable (no id maps to them)
                # and carry the non-resident defaults.
                extra = max(extra, min(4096, self._slot_of.size))
                # build the grown arrays fully, then install: lock-free
                # route() readers only ever see a complete placement array
                shard_of = np.concatenate(
                    [self._shard_of, np.zeros(extra, dtype=np.int32)]
                )
                slot_of = np.concatenate(
                    [self._slot_of, np.full(extra, -1, dtype=np.int32)]
                )
                self._shard_of = shard_of
                self._slot_of = slot_of
                if self._freq is not None:
                    self._freq = np.concatenate(
                        [self._freq, np.zeros(extra, dtype=np.float64)]
                    )
                    self._norm = np.concatenate(
                        [self._norm, np.zeros(extra, dtype=np.float32)]
                    )
                if self._sdelta is not None:
                    self._sdelta = np.concatenate(
                        [self._sdelta, np.zeros(extra, dtype=np.float64)]
                    )
            self.n_rows = n_rows

    def unpublish(self, rows: np.ndarray) -> None:
        """Drop rows from routing (hot-swap invalidation). Their slots are
        NOT freed for reuse — a subsequent admission re-publishes them."""
        with self.lock:
            rows = np.asarray(rows, dtype=np.int64)
            keep = rows[(rows >= 0) & (rows < self.n_rows)]
            self._slot_of[keep] = -1

    # ------------------------------------------------------------ counters

    @property
    def resident_rows(self) -> int:
        return int(np.count_nonzero(self._slot_of[: self.n_rows] >= 0))

    @property
    def device_rows(self) -> int:
        return self.num_shards * self.shard_capacity

    @property
    def free_slots(self) -> int:
        return len(self._free)

    def reset_counters(self) -> None:
        self.resident_lookups = 0
        self.deferred_lookups = 0
        self.cold_lookups = 0

    def stats(self) -> Dict[str, float]:
        total = (
            self.resident_lookups + self.deferred_lookups + self.cold_lookups
        )
        out = {
            "num_shards": self.num_shards,
            "shard_capacity": self.shard_capacity,
            "device_rows": self.device_rows,
            "resident_rows": self.resident_rows,
            "base_rows": self.base_rows,
            "resident_lookups": self.resident_lookups,
            "deferred_lookups": self.deferred_lookups,
            "cold_lookups": self.cold_lookups,
            "total_lookups": total,
            "admitted_total": self.admitted_total,
            "evicted_total": self.evicted_total,
            "eviction_policy": self.eviction_policy,
            "evicted_oldest": self.evicted_oldest,
            "evicted_importance": self.evicted_importance,
        }
        if self._freq is not None:
            with self.lock:
                adm = np.fromiter(
                    self._admitted, dtype=np.int64, count=len(self._admitted)
                )
                adm = adm[self._slot_of[adm] >= 0] if adm.size else adm
            imp = self.importance_of(adm)
            out["importance_mean"] = float(imp.mean()) if imp.size else 0.0
            out["importance_max"] = float(imp.max()) if imp.size else 0.0
            out["score_delta"] = self.score_delta
        return out


class RoutingIndex:
    """Per-coordinate :class:`CoordinateRouting`, shared across every
    scorer replica in multi-scorer mode (one device table per replica, ONE
    routing truth — replicas can only disagree about content mid-admission,
    never about where a row lives)."""

    def __init__(self, coordinates: Dict[str, CoordinateRouting]):
        self.coordinates = dict(coordinates)

    def __getitem__(self, cid: str) -> CoordinateRouting:
        return self.coordinates[cid]

    def __contains__(self, cid: str) -> bool:
        return cid in self.coordinates

    def stats(self) -> Dict[str, Dict[str, float]]:
        return {cid: c.stats() for cid, c in self.coordinates.items()}

    def reset_counters(self) -> None:
        for c in self.coordinates.values():
            c.reset_counters()


def build_routing(
    re_tables: Dict[str, int],
    num_shards: int,
    device_budget_rows: Optional[int] = None,
    headroom_fraction: float = 0.25,
    eviction_policy: str = "oldest",
    score_delta: bool = True,
) -> RoutingIndex:
    """Routing for a set of RE coordinates (``cid -> n_rows``).

    ``device_budget_rows`` caps TOTAL device data rows per coordinate
    (across shards). ``None`` = full residency: every row resident, plus
    ``headroom_fraction`` extra slots so hot-swaps can append new entities
    without a table rebuild. A finite budget splits into a resident base
    (the first ``(1 - headroom_fraction) * budget`` rows — the packed
    table's hot prefix) and admission headroom for the long tail.
    ``eviction_policy`` picks the admission victim rule: ``oldest`` (FIFO,
    the default) or ``importance`` (evict lowest importance score);
    ``score_delta`` additionally tracks measured |score − fe_only| per row
    under the importance policy (see ``note_score_deltas``).
    """
    coords: Dict[str, CoordinateRouting] = {}
    for cid, n_rows in re_tables.items():
        n_rows = int(n_rows)
        if device_budget_rows is None:
            base = n_rows
            budget = n_rows + max(num_shards, int(n_rows * headroom_fraction))
        else:
            budget = max(int(device_budget_rows), num_shards)
            base = min(n_rows, int(budget * (1.0 - headroom_fraction)))
            if budget >= n_rows + num_shards:
                base = n_rows  # budget covers the table: all pinned
        cap = max(1, -(-budget // num_shards))  # ceil
        coords[cid] = CoordinateRouting(
            n_rows=n_rows,
            num_shards=num_shards,
            shard_capacity=cap,
            resident_rows=base,
            eviction_policy=eviction_policy,
            score_delta=score_delta,
        )
    return RoutingIndex(coords)
