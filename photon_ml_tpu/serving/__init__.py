"""Online serving subsystem: score individual requests against a trained
GAME model at low latency.

The offline path (``cli/score_game.py``) reloads the Avro model and scores a
static dataset in one pass; this package is the other half of the stack —
the Photon-ML GLMix design (fixed-effect prior + per-entity random-effect
corrections) was built for per-member online serving, and the pieces here
map onto that design:

- :mod:`photon_ml_tpu.serving.artifact` — pack a trained ``GameModel`` into
  a serving artifact: dense FE coefficient arrays plus per-coordinate RE
  coefficient tables as contiguous ``(n_entities, dim)`` matrices behind an
  entity-id → row off-heap index (the PHIX store from ``indexmap/offheap``).
- :mod:`photon_ml_tpu.serving.scorer` — a jit'd fixed-shape score function:
  ``mean(x·β_FE + Σ_re x·β_RE[entity])`` with gathered RE rows; cold
  entities degrade to the FE-only score (RE prior mean = 0).
- :mod:`photon_ml_tpu.serving.batcher` — a microbatcher coalescing
  ``ScoreRequest``s into padded batches drawn from a small set of bucket
  sizes, so XLA compiles once per bucket and never per request.
- :mod:`photon_ml_tpu.serving.cache` — an LRU device-resident cache of hot
  RE coefficient rows over a host-side backing store.
- :mod:`photon_ml_tpu.serving.metrics` — latency percentiles, queue depth,
  batch fill ratio and cache hit rate as a dict snapshot.
- :mod:`photon_ml_tpu.serving.replay` — turn a scoring dataset into a
  request stream and pump it through the batcher (CLI + bench driver).
- :mod:`photon_ml_tpu.serving.hotswap` — apply nearline delta artifacts
  (``photon_ml_tpu.incremental``) to a live scorer between batches: in-place
  table mutation with no retrace, per-row cache invalidation, AUC validation
  gate with rollback to the previous generation.
- :mod:`photon_ml_tpu.serving.routing` /
  :mod:`photon_ml_tpu.serving.sharded` — the device-resident hot path: RE
  tables partitioned across a serving mesh behind an entity→(shard, slot)
  routing index, one jitted gather per shard per batch.
- :mod:`photon_ml_tpu.serving.admission` — asynchronous admission of the
  cold long tail into device headroom slots (double-buffered host→device
  copies off the request path).
- :mod:`photon_ml_tpu.serving.continuous` — continuous microbatching:
  requests join in-flight buckets up to a deadline, scored by per-replica
  threads with backpressure-bounded queues.
- :mod:`photon_ml_tpu.serving.deltawatch` — the ``--watch-deltas`` poll as
  a supervised daemon (``photon_ml_tpu.resilience``): crashes restart with
  backoff, corrupt deltas are skipped without advancing the generation.
- :mod:`photon_ml_tpu.serving.requestplane` — sampled per-request
  lifecycle tracing: a seeded sampler tags ~1/N requests, stage
  boundaries (queue → featurize → route → dispatch → device → reply) are
  stamped through the batcher/scorer, hot-swap and admission stalls are
  folded in as interference, and records drain to the run ledger for
  ``analyze_run --requests`` tail attribution.
- :mod:`photon_ml_tpu.serving.slo` — availability + latency objectives
  over a rolling window with error-budget burn-rate accounting
  (``/healthz`` degraded reason + ``serving.slo.*`` gauges).
- :mod:`photon_ml_tpu.serving.overload` — closed-loop overload control:
  SLO burn rate drives batch-deadline shrink and FE-only shedding with
  hysteresis (``serving.overload.*`` gauges).
- :mod:`photon_ml_tpu.serving.scenarios` — seeded traffic-shape scenarios
  (steady, diurnal, burst storm, cold-entity flood, hot-swap under load,
  plus the tenancy trio: tenant isolation, ramped rollout, nearline loop)
  driving ``replay_requests`` for the ``bench.py --scenarios`` harness.
- :mod:`photon_ml_tpu.serving.tenancy` — the tenancy plane: N GLMix model
  variants as fingerprint-chained delta overlays on ONE shared sharded
  scorer, seeded deterministic variant routing with hot ramp percentages,
  per-tenant admission quotas with priority-aware shedding, and per-tenant
  SLO error budgets (tenant-labeled ``serving.slo.*`` series).
"""

from photon_ml_tpu.serving.artifact import (
    ServingArtifact,
    ServingTable,
    load_artifact,
    load_tuned_config,
    pack_game_model,
    save_artifact,
    save_tuned_config,
)
from photon_ml_tpu.serving.introspect import IntrospectionServer, prometheus_text
from photon_ml_tpu.serving.admission import AdmissionController
from photon_ml_tpu.serving.batcher import MicroBatcher
from photon_ml_tpu.serving.cache import HotEntityCache
from photon_ml_tpu.serving.continuous import ContinuousBatcher, PendingResult
from photon_ml_tpu.serving.deltawatch import DeltaWatcher
from photon_ml_tpu.serving.hotswap import (
    CoordinatedHotSwap,
    HotSwapManager,
    SwapReport,
    ValidationGate,
)
from photon_ml_tpu.serving.metrics import ServingMetrics
from photon_ml_tpu.serving.replay import replay_requests, requests_from_game_data
from photon_ml_tpu.serving.requestplane import REQUEST_STAGES, RequestPlane
from photon_ml_tpu.serving.scenarios import (
    DEFAULT_TENANTS,
    SCENARIO_NAMES,
    TENANCY_SCENARIOS,
    build_scenario,
    run_scenario,
)
from photon_ml_tpu.serving.tenancy import (
    TenancyPlane,
    TenantBudget,
    TenantQuota,
    VariantRegistry,
    VariantRouter,
    VariantScorer,
    build_tenant_slos,
    make_nearline_fn,
    tag_requests,
)
from photon_ml_tpu.serving.overload import OverloadController
from photon_ml_tpu.serving.slo import SLOTracker
from photon_ml_tpu.serving.routing import (
    CoordinateRouting,
    RoutingIndex,
    build_routing,
)
from photon_ml_tpu.serving.scorer import GameScorer, ScoreRequest, ScoreResult
from photon_ml_tpu.serving.sharded import (
    ShardedGameScorer,
    ShardedReTable,
    serving_mesh,
)

__all__ = [
    "AdmissionController",
    "ContinuousBatcher",
    "DEFAULT_TENANTS",
    "REQUEST_STAGES",
    "RequestPlane",
    "SCENARIO_NAMES",
    "SLOTracker",
    "TENANCY_SCENARIOS",
    "TenancyPlane",
    "TenantBudget",
    "TenantQuota",
    "VariantRegistry",
    "VariantRouter",
    "VariantScorer",
    "build_scenario",
    "build_tenant_slos",
    "make_nearline_fn",
    "run_scenario",
    "tag_requests",
    "CoordinateRouting",
    "CoordinatedHotSwap",
    "DeltaWatcher",
    "GameScorer",
    "HotEntityCache",
    "HotSwapManager",
    "MicroBatcher",
    "OverloadController",
    "PendingResult",
    "RoutingIndex",
    "ScoreRequest",
    "ScoreResult",
    "ShardedGameScorer",
    "ShardedReTable",
    "ServingArtifact",
    "ServingMetrics",
    "ServingTable",
    "SwapReport",
    "ValidationGate",
    "IntrospectionServer",
    "build_routing",
    "load_artifact",
    "load_tuned_config",
    "pack_game_model",
    "prometheus_text",
    "replay_requests",
    "requests_from_game_data",
    "save_artifact",
    "save_tuned_config",
    "serving_mesh",
]
