"""Serving SLOs: availability + latency objectives with error-budget burn.

An SLO here is two objectives over a rolling window:

- **availability**: at least ``availability_objective`` of requests must
  complete without a scorer error (default 99.9%);
- **latency**: at least ``latency_objective`` of requests must finish
  under ``latency_threshold_s`` (default: 99% under 50ms).

Each objective's **error budget** is its allowed bad fraction
(``1 - objective``) of the window's traffic. The **burn rate** is the
observed bad fraction divided by the allowed one — burn 1.0 means the
budget is being consumed exactly as fast as it accrues; burn 2.0 means
the window will exhaust twice over. Budget remaining is ``1 - burn``
clamped at zero, and the tracker turns unhealthy (``/healthz`` degraded
reason, ``serving.slo.*`` gauges) when either objective's budget is
exhausted — the standard SRE error-budget alarm, scoped to a window so a
single historic incident does not poison the gauge forever.

The window is a ring of time buckets (default 30 x 10s): observation is
O(1) per batch (three integer adds into the current bucket), ``status``
is O(buckets). The clock is injectable so tests and the scenario harness
drive it deterministically.
"""

from __future__ import annotations

import time
from typing import Callable, Dict, Optional


class SLOTracker:
    def __init__(
        self,
        latency_threshold_s: float = 0.050,
        latency_objective: float = 0.99,
        availability_objective: float = 0.999,
        window_s: float = 300.0,
        num_buckets: int = 30,
        clock: Callable[[], float] = time.monotonic,
        registry=None,
    ):
        if not 0.0 < latency_objective < 1.0:
            raise ValueError(
                f"latency_objective must be in (0, 1), got {latency_objective}"
            )
        if not 0.0 < availability_objective < 1.0:
            raise ValueError(
                "availability_objective must be in (0, 1), got "
                f"{availability_objective}"
            )
        if latency_threshold_s <= 0:
            raise ValueError(
                f"latency_threshold_s must be > 0, got {latency_threshold_s}"
            )
        self.latency_threshold_s = float(latency_threshold_s)
        self.latency_objective = float(latency_objective)
        self.availability_objective = float(availability_objective)
        self.window_s = float(window_s)
        self.num_buckets = max(1, int(num_buckets))
        self._bucket_s = self.window_s / self.num_buckets
        self._clock = clock
        self._registry = registry
        # ring of [total, slow, errors] per time bucket
        self._ring = [[0, 0, 0] for _ in range(self.num_buckets)]
        self._epoch: Optional[float] = None
        self._head = 0  # absolute bucket index currently written
        self.total_observed = 0

    # ------------------------------------------------------------ observing

    def _current(self) -> list:
        now = self._clock()
        if self._epoch is None:
            self._epoch = now
        idx = int((now - self._epoch) / self._bucket_s)
        if idx > self._head:
            # zero every bucket the clock skipped over (bounded by ring size)
            for k in range(self._head + 1, min(idx, self._head + self.num_buckets) + 1):
                self._ring[k % self.num_buckets] = [0, 0, 0]
            self._head = idx
        return self._ring[self._head % self.num_buckets]

    def observe_many(self, latencies, errors: int = 0) -> None:
        """Fold one drained batch in: ``latencies`` are the seconds of the
        requests that completed, ``errors`` counts requests that failed
        (they consume availability budget; no latency sample exists)."""
        bucket = self._current()
        n = len(latencies)
        slow = 0
        if n:
            thr = self.latency_threshold_s
            try:  # ndarray fast path (one vectorized compare per batch)
                slow = int((latencies > thr).sum())
            except TypeError:
                slow = sum(1 for s in latencies if s > thr)
        bucket[0] += n + int(errors)
        bucket[1] += slow
        bucket[2] += int(errors)
        self.total_observed += n + int(errors)

    def observe(self, latency_s: float) -> None:
        self.observe_many((latency_s,))

    # ------------------------------------------------------------- reporting

    def _window_counts(self):
        # advance the ring so stale buckets age out even without traffic
        self._current()
        total = slow = errors = 0
        for t, s, e in self._ring:
            total += t
            slow += s
            errors += e
        return total, slow, errors

    def status(self) -> dict:
        """Window verdict + burn accounting; also refreshes the
        ``serving.slo.*`` gauges when a registry is attached."""
        total, slow, errors = self._window_counts()
        ok_latency = total - errors - slow
        completed = total - errors
        availability = 1.0 if total == 0 else 1.0 - errors / total
        latency_ok_rate = 1.0 if completed <= 0 else ok_latency / completed
        avail_burn = (
            0.0
            if total == 0
            else (errors / total) / (1.0 - self.availability_objective)
        )
        lat_burn = (
            0.0
            if completed <= 0
            else (slow / completed) / (1.0 - self.latency_objective)
        )
        burn = max(avail_burn, lat_burn)
        budget_remaining = max(0.0, 1.0 - burn)
        exhausted = []
        if avail_burn >= 1.0:
            exhausted.append("availability")
        if lat_burn >= 1.0:
            exhausted.append("latency")
        doc = {
            "objectives": {
                "availability": self.availability_objective,
                "latency": self.latency_objective,
                "latency_threshold_s": self.latency_threshold_s,
            },
            "window_s": self.window_s,
            "window_requests": total,
            "window_errors": errors,
            "window_slow": slow,
            "availability": round(availability, 6),
            "latency_ok_rate": round(latency_ok_rate, 6),
            "burn_rate": round(burn, 4),
            "availability_burn_rate": round(avail_burn, 4),
            "latency_burn_rate": round(lat_burn, 4),
            "error_budget_remaining": round(budget_remaining, 4),
            "verdict": (
                "budget_exhausted:" + "+".join(exhausted) if exhausted else "ok"
            ),
            "healthy": not exhausted,
        }
        if self._registry is not None:
            self._registry.gauge("serving.slo.availability", availability)
            self._registry.gauge(
                "serving.slo.latency_ok_rate", latency_ok_rate
            )
            self._registry.gauge("serving.slo.burn_rate", burn)
            self._registry.gauge(
                "serving.slo.error_budget_remaining", budget_remaining
            )
            self._registry.gauge(
                "serving.slo.budget_exhausted", 1.0 if exhausted else 0.0
            )
        return doc

    def health(self) -> Dict[str, object]:
        """``/healthz`` contribution: unhealthy while the rolling error
        budget is exhausted (serving keeps answering — the SLO alarm is a
        paging signal, not a kill switch)."""
        status = self.status()
        doc: Dict[str, object] = {"healthy": status["healthy"]}
        if not status["healthy"]:
            doc["degraded"] = (
                f"slo {status['verdict']} (burn {status['burn_rate']:.2f}x, "
                f"availability {status['availability']:.4f}, "
                f"latency_ok {status['latency_ok_rate']:.4f})"
            )
        return doc
