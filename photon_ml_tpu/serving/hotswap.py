"""Zero-downtime hot-swap of a live scorer's tables from delta artifacts.

The scorer's coefficient tables are jit ARGUMENTS, not captured constants
(scorer.py), so new table CONTENT never retraces — the swap cost is the
table mutation itself, not a recompile. The manager turns a published
delta into the narrowest possible mutation of a live ``GameScorer``:

- fixed effects: same-shape vector replacement;
- full-table RE coordinates: in-place row scatter on device when the rows
  fit the table's padding headroom, a rebuild at the next power-of-two
  size bucket when they don't (the one case that retraces, reported in
  ``SwapReport.regrew``);
- cache-backed RE coordinates: O(1) backing-store rebind + invalidation of
  only the touched rows — everything else stays warm on device.

The mutation runs in one critical section; its *blackout* is the
request-path BLOCKING time, not the section's wall clock — a sharded
scorer stages row content into the spare generation half of its
double-buffered device tables off the request path and blocks scoring only
for the atomic generation flip (microseconds), while the single-table
scorer's live-table mutation keeps wall-clock accounting. A generation
counter tracks the live version. An optional validation gate replays a
held-out slice through the swapped scorer and rolls back to the previous
generation when AUC regresses past a threshold — the inverse mutation is
applied from an undo snapshot of exactly the touched rows (on a sharded
scorer: the same stage-and-flip-back), so rollback is as cheap as the swap.
"""

from __future__ import annotations

import dataclasses
import logging
import time
from typing import Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from photon_ml_tpu.resilience.failures import record_failure
from photon_ml_tpu.resilience.faultpoints import fault_point, register_fault_site
from photon_ml_tpu.resilience.retry import DEFAULT_IO_RETRY, RetryPolicy
from photon_ml_tpu.serving.artifact import ServingArtifact
from photon_ml_tpu.serving.cache import HotEntityCache
from photon_ml_tpu.serving.metrics import ServingMetrics
from photon_ml_tpu.serving.scorer import GameScorer, ScoreRequest
from photon_ml_tpu.telemetry import span

_log = logging.getLogger("photon_ml_tpu.serving.hotswap")

FAULT_DELTA_LOAD = register_fault_site(
    "serve.delta.load",
    "loading one published delta artifact inside the watch loop",
)

# Delta loads race the publisher: a partially-written or corrupt artifact
# must not kill the watcher OR advance the processed set — the old
# generation keeps serving and the same path is retried on the next poll
# (by then the atomic publish has usually completed).
_DELTA_RETRY = RetryPolicy(
    max_attempts=DEFAULT_IO_RETRY.max_attempts,
    base_delay_s=0.01,
    retryable=(OSError, ValueError, KeyError, EOFError),
)


@dataclasses.dataclass
class ValidationGate:
    """Held-out replay slice scored through the swapped scorer: the swap
    only sticks when AUC does not regress more than ``max_auc_regression``
    below the previous generation's AUC on the same slice.

    The baseline is (re)measured through the LIVE scorer right before the
    first swap and after every accepted one, so the comparison is always
    generation-to-generation on identical requests. Score the slice once
    through the scorer at startup (or reuse a serving bucket size) to keep
    the gate itself from compiling during a swap."""

    requests: Sequence[ScoreRequest]
    labels: np.ndarray
    max_auc_regression: float = 0.01
    bucket_size: Optional[int] = None

    def evaluate(self, scorer: GameScorer) -> float:
        from photon_ml_tpu.evaluation.evaluators import AUC

        bucket = self.bucket_size or len(self.requests)
        results = []
        for i in range(0, len(self.requests), bucket):
            results.extend(scorer.score_batch(
                self.requests[i:i + bucket], bucket_size=bucket
            ))
        scores = np.asarray([r.score for r in results], dtype=np.float32)
        labels = np.asarray(self.labels, dtype=np.float32)
        return AUC.evaluate_host(scores, labels, np.ones_like(labels))


@dataclasses.dataclass
class SwapReport:
    generation: int
    fingerprint: Optional[str]
    coordinates: Tuple[str, ...]
    rows_updated: int
    blackout_s: float
    staleness_s: Optional[float]
    rolled_back: bool
    validation_metric: Optional[float]
    baseline_metric: Optional[float]
    regrew: Tuple[str, ...]  # full tables rebuilt at a larger size bucket
    compiles_added: int


@dataclasses.dataclass
class _Undo:
    """Inverse of one swap: enough to restore the previous generation."""

    artifact: ServingArtifact
    fingerprint: Optional[str]
    fe: Dict[str, np.ndarray]
    re_inplace: Dict[str, Tuple[np.ndarray, np.ndarray]]  # cid -> (rows, old)
    # cid -> (previous provider, the routing coordinate it was built
    # against, or None for non-sharded providers). A regrowing rebind
    # replaces the shared routing coordinate too, so rollback must restore
    # the (provider, routing) pair together — a provider gathered through a
    # mismatched layout serves other rows' bytes.
    re_rebuilt: Dict[str, Tuple[object, Optional[object]]]
    cache_rebinds: Dict[str, Tuple[object, np.ndarray]]  # cid -> (old backing, rows)


class HotSwapManager:
    """Applies delta artifacts to a live :class:`GameScorer`.

    ``fingerprint`` roots the hash chain — pass the base artifact
    directory's content fingerprint (``incremental.fingerprint_dir``) when
    serving from disk; ``None`` disables chain verification (in-memory
    artifacts have no content identity). One level of undo is kept: a
    failed validation gate (or an explicit ``rollback()``) restores the
    previous generation."""

    def __init__(
        self,
        scorer: GameScorer,
        fingerprint: Optional[str] = None,
        gate: Optional[ValidationGate] = None,
        metrics: Optional[ServingMetrics] = None,
        emitter=None,
        model_id: Optional[str] = None,
        clock=time.time,
    ):
        self._scorer = scorer
        self.fingerprint = fingerprint
        self.gate = gate
        self.generation = 0
        self._metrics = metrics
        self._emitter = emitter
        self._model_id = model_id or scorer.artifact.model_name
        self._clock = clock
        self._baseline_metric: Optional[float] = None
        self._undo: Optional[_Undo] = None
        self._processed_dirs: set = set()
        self.delta_load_failures = 0

    # ------------------------------------------------------------- swapping

    def apply_delta(self, delta) -> SwapReport:
        """Swap one delta (a ``DeltaArtifact`` or a delta directory path)
        into the live scorer. Raises on a broken fingerprint chain; returns
        a report (``rolled_back=True`` when the validation gate rejected
        the candidate and the previous generation was restored)."""
        with span(
            "serve/hotswap_apply", model_id=self._model_id, generation=self.generation
        ):
            return self._apply_delta_impl(delta)

    def _apply_delta_impl(self, delta) -> SwapReport:
        from photon_ml_tpu.incremental.delta import (
            DeltaArtifact,
            apply_delta as fold_delta,
            load_delta,
        )

        if not isinstance(delta, DeltaArtifact):
            delta = load_delta(str(delta))
        if (
            self.fingerprint is not None
            and delta.base_fingerprint is not None
            and delta.base_fingerprint != self.fingerprint
        ):
            raise ValueError(
                f"delta generation {delta.generation} chains to base "
                f"{delta.base_fingerprint}, live scorer is at "
                f"{self.fingerprint} — missing intermediate delta or wrong "
                "base artifact"
            )

        old_artifact = self._scorer.artifact
        candidate = fold_delta(old_artifact, delta)

        # establish the gate baseline through the LIVE scorer before any
        # mutation (also warms the gate's bucket, so post-swap evaluation
        # never compiles)
        if self.gate is not None and self._baseline_metric is None:
            self._baseline_metric = self.gate.evaluate(self._scorer)

        # plan every mutation (and its inverse) outside the critical section
        fe_plan: Dict[str, np.ndarray] = dict(delta.fe_updates)
        undo = _Undo(
            artifact=old_artifact,
            fingerprint=self.fingerprint,
            fe={
                cid: np.array(old_artifact.tables[cid].weights, dtype=np.float32)
                for cid in fe_plan
            },
            re_inplace={},
            re_rebuilt={},
            cache_rebinds={},
        )
        inplace_plan: Dict[str, Tuple[np.ndarray, np.ndarray]] = {}
        rebind_plan: Dict[str, Tuple[np.ndarray, np.ndarray]] = {}
        cache_plan: Dict[str, Tuple[np.ndarray, np.ndarray]] = {}
        for cid, (ids, _) in delta.re_rows.items():
            if not ids:
                continue
            new_table = candidate.tables[cid]
            old_table = old_artifact.tables[cid]
            targets = np.asarray(
                new_table.entity_index.get_indices(ids), dtype=np.int64
            )
            values = np.asarray(new_table.weights, dtype=np.float32)[targets]
            provider = self._scorer._providers[cid]
            if isinstance(provider, HotEntityCache):
                cache_plan[cid] = (np.asarray(new_table.weights), targets)
                undo.cache_rebinds[cid] = (old_table.weights, targets)
                continue
            fits = getattr(provider, "fits", None)
            if (
                fits(targets)
                if fits is not None
                else targets.max() < provider.capacity
            ):
                inplace_plan[cid] = (targets, values)
                n_old = old_table.n_entities
                old_rows = np.zeros_like(values)
                in_base = targets < n_old
                if in_base.any():
                    old_rows[in_base] = np.asarray(
                        old_table.weights, dtype=np.float32
                    )[targets[in_base]]
                undo.re_inplace[cid] = (targets, old_rows)
            else:
                rebind_plan[cid] = (np.asarray(new_table.weights), targets)
                undo.re_rebuilt[cid] = (
                    provider,
                    getattr(provider, "routing", None),
                )

        # ------------------------- critical section: the blackout -------
        # blackout_s is the REQUEST-PATH blocking time, not the wall clock
        # of the section: a sharded scorer's row updates stage into the
        # spare generation half off the request path and return only the
        # generation-flip window (see ShardedReTable.update_rows), so that
        # staging work is subtracted from the wall clock. Hooks returning
        # None (the single-table GameScorer mutates live tables) keep the
        # historical wall-clock accounting.
        compiles_before = self._scorer.compile_count
        t0 = time.perf_counter()
        nonblocking_s = 0.0
        regrew: List[str] = []
        self._scorer.set_artifact(candidate)
        for cid, w in fe_plan.items():
            self._scorer.update_fixed_effect(cid, w)
        for cid, (rows, values) in inplace_plan.items():
            u0 = time.perf_counter()
            ret = self._scorer.update_random_effect_rows(cid, rows, values)
            if isinstance(ret, float):
                nonblocking_s += max(
                    0.0, (time.perf_counter() - u0) - ret
                )
        for cid, (backing, _) in rebind_plan.items():
            if self._scorer.rebind_random_effect(cid, backing):
                regrew.append(cid)
        for cid, (backing, rows) in cache_plan.items():
            cache = self._scorer.caches[cid]
            cache.rebind(backing)
            cache.invalidate(rows)
        blackout_s = max(0.0, time.perf_counter() - t0 - nonblocking_s)
        # ----------------------------------------------------------------

        self.generation += 1
        candidate_fp = delta.fingerprint
        now = self._clock()
        staleness_s = (
            max(0.0, now - delta.created_at_unix)
            if delta.created_at_unix
            else None
        )

        validation_metric: Optional[float] = None
        rolled_back = False
        if self.gate is not None:
            validation_metric = self.gate.evaluate(self._scorer)
            floor = self._baseline_metric - self.gate.max_auc_regression
            if not validation_metric >= floor:  # NaN fails the gate too
                _log.warning(
                    "validation gate failed: AUC %.6f < floor %.6f "
                    "(baseline %.6f - threshold %g) — rolling back to "
                    "generation %d",
                    validation_metric, floor, self._baseline_metric,
                    self.gate.max_auc_regression, self.generation - 1,
                )
                self._undo = undo
                self.rollback()
                rolled_back = True
            else:
                self._baseline_metric = validation_metric
        compiles_added = self._scorer.compile_count - compiles_before

        if not rolled_back:
            self.fingerprint = candidate_fp
            self._undo = undo
        report = SwapReport(
            generation=self.generation,
            fingerprint=self.fingerprint,
            coordinates=delta.coordinates(),
            rows_updated=delta.num_rows_updated,
            blackout_s=blackout_s,
            staleness_s=staleness_s,
            rolled_back=rolled_back,
            validation_metric=validation_metric,
            baseline_metric=self._baseline_metric,
            regrew=tuple(regrew),
            compiles_added=compiles_added,
        )
        if self._metrics is not None:
            self._metrics.observe_swap(
                generation=self.generation,
                rows_updated=report.rows_updated,
                blackout_s=blackout_s,
                staleness_s=staleness_s,
                rolled_back=rolled_back,
            )
        if self._emitter is not None:
            from photon_ml_tpu.event import ModelSwapEvent

            self._emitter.send_event(
                ModelSwapEvent(
                    model_id=self._model_id,
                    generation=self.generation,
                    fingerprint=self.fingerprint,
                    coordinates=report.coordinates,
                    rows_updated=report.rows_updated,
                    blackout_s=blackout_s,
                    rolled_back=rolled_back,
                    validation_metric=validation_metric,
                )
            )
        return report

    def rollback(self) -> None:
        """Restore the previous generation from the undo snapshot (applies
        the inverse mutation: old artifact reference, old FE vectors, old
        rows scattered back, old providers for regrown tables, old cache
        backings with the touched rows re-invalidated)."""
        undo = self._undo
        if undo is None:
            raise ValueError("no previous generation to roll back to")
        self._scorer.set_artifact(undo.artifact)
        for cid, w in undo.fe.items():
            self._scorer.update_fixed_effect(cid, w)
        for cid, (rows, old_rows) in undo.re_inplace.items():
            self._scorer.update_random_effect_rows(cid, rows, old_rows)
        for cid, (provider, routing) in undo.re_rebuilt.items():
            restore = getattr(self._scorer, "restore_random_effect", None)
            if restore is not None:
                restore(cid, provider, routing)
            else:
                self._scorer._providers[cid] = provider
        for cid, (backing, rows) in undo.cache_rebinds.items():
            cache = self._scorer.caches[cid]
            cache.rebind(np.asarray(backing))
            cache.invalidate(rows)
        self.generation -= 1
        self.fingerprint = undo.fingerprint
        self._undo = None

    # ------------------------------------------------------------ watching

    def poll_directory_deltas(self, watch_dir: str):
        """Yield (path, delta) for unprocessed deltas without applying —
        used by :class:`CoordinatedHotSwap` to fan one delta out to every
        replica before marking it processed."""
        from photon_ml_tpu.incremental.delta import discover_deltas, load_delta

        for path in discover_deltas(watch_dir):
            if path in self._processed_dirs:
                continue

            def _load(p=path):
                fault_point(FAULT_DELTA_LOAD)
                return load_delta(p)

            try:
                delta = _DELTA_RETRY.run("serve.delta.load", _load)
            except Exception as exc:
                # partial write or corruption: keep the live generation,
                # leave the path unprocessed so the next poll retries it
                # once the publisher finishes, and move on to any later
                # delta that IS complete.
                self.delta_load_failures += 1
                record_failure(
                    "delta_load_failed",
                    "serve.delta.load",
                    f"{type(exc).__name__}: {exc}",
                    path=str(path),
                )
                _log.warning(
                    "skipping unreadable delta %s (kept generation %d): %s",
                    path, self.generation, exc,
                )
                continue
            if (
                delta.fingerprint is not None
                and delta.fingerprint == self.fingerprint
            ):
                self._processed_dirs.add(path)
                continue
            yield path, delta

    def poll_directory(self, watch_dir: str) -> List[SwapReport]:
        """Apply any newly published deltas under ``watch_dir`` (``delta-*``
        directories, name order = chain order). Already-processed
        directories are skipped; a delta whose own fingerprint equals the
        live one is recognized as already applied. Safe to call from the
        serving loop between batches."""
        reports: List[SwapReport] = []
        for path, delta in self.poll_directory_deltas(watch_dir):
            try:
                reports.append(self.apply_delta(delta))
            except Exception as exc:
                # a delta that loads but won't apply (broken chain after a
                # skipped predecessor, corrupt content past the header)
                # must not kill the watch loop; the live generation stands.
                self.delta_load_failures += 1
                record_failure(
                    "delta_apply_failed",
                    "serve.delta.load",
                    f"{type(exc).__name__}: {exc}",
                    path=str(path),
                )
                _log.warning(
                    "delta %s failed to apply (kept generation %d): %s",
                    path, self.generation, exc,
                )
                continue
            self._processed_dirs.add(path)
        return reports


class CoordinatedHotSwap:
    """One hot-swap control plane over N scorer replicas (multi-scorer
    mode): a delta is applied to EVERY replica's :class:`HotSwapManager`
    before it counts as processed, so all devices serve the same
    generation. Replicas sharing a routing index coordinate implicitly —
    the first replica's swap allocates/publishes any new rows, later
    replicas find them resident and only rewrite the bytes on their own
    device tables.

    A replica that rolls back (validation gate) aborts the fan-out and
    rolls back the replicas already swapped, so the group never splits
    across generations."""

    def __init__(self, managers: Sequence[HotSwapManager]):
        managers = list(managers)
        if not managers:
            raise ValueError("need at least one HotSwapManager")
        self._managers = managers

    @property
    def managers(self) -> List[HotSwapManager]:
        return list(self._managers)

    @property
    def generation(self) -> int:
        return self._managers[0].generation

    def apply_delta(self, delta) -> List[SwapReport]:
        """Apply one delta to every replica. Returns one report per replica
        actually swapped (all of them, or the prefix up to and including a
        rolled-back one — whose predecessors are rolled back again here)."""
        reports: List[SwapReport] = []
        for i, mgr in enumerate(self._managers):
            report = mgr.apply_delta(delta)
            reports.append(report)
            if report.rolled_back:
                for prev in self._managers[:i]:
                    prev.rollback()
                break
        return reports

    def poll_directory(self, watch_dir: str) -> List[SwapReport]:
        """Fan newly published deltas out to every replica (lead replica
        discovers; a delta is marked processed on all replicas only after
        the full fan-out)."""
        lead = self._managers[0]
        reports: List[SwapReport] = []
        for path, delta in list(lead.poll_directory_deltas(watch_dir)):
            group = self.apply_delta(delta)
            reports.extend(group)
            if not any(r.rolled_back for r in group):
                for mgr in self._managers:
                    mgr._processed_dirs.add(path)
        return reports
