"""Hot-entity cache: device-resident LRU over RE coefficient rows.

The packed RE table (``artifact.ServingTable.weights``) is the host-side
backing store — potentially a memory-mapped ``(n_entities, dim)`` file for
million-entity coordinates. Serving gathers one row per request; keeping the
full table on device wastes HBM and keeping none forces a host→device copy
per request. Entity popularity is heavy-tailed (the Snap ML observation:
hot model state belongs device-resident behind a hierarchical cache), so a
small device table of the hottest rows makes the steady-state gather never
leave the chip.

Layout: a device array ``[capacity + 1, dim]``. Slots ``0..capacity-1``
hold cached entity rows; slot ``capacity`` is permanently zero — the *cold
slot* that unknown entities gather from, which realizes the FE-only
fallback (RE prior mean = 0) without any branching in the jit'd scorer.
Misses within one batch are filled with a single scatter.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Dict, List, Optional

import numpy as np


class HotEntityCache:
    """LRU cache of backing-store rows on device.

    ``lookup`` maps backing-table row indices (−1 = unknown entity) to slots
    in the device ``table``; rows already cached are hits, others are copied
    in from the backing store (evicting least-recently-used slots when
    full). Rows referenced by the *current* batch are pinned: they cannot be
    evicted by later misses in the same lookup, so a batch is always
    internally consistent. That requires ``capacity >= distinct entities per
    batch``; the scorer enforces ``capacity >= max bucket size``.
    """

    def __init__(self, backing: np.ndarray, capacity: int):
        if backing.ndim != 2:
            raise ValueError(f"backing store must be 2-D, got {backing.shape}")
        if capacity < 1:
            raise ValueError(f"cache capacity must be >= 1, got {capacity}")
        import jax.numpy as jnp

        self._backing = backing
        self.capacity = int(capacity)
        self.cold_slot = self.capacity
        self._table = jnp.zeros(
            (self.capacity + 1, backing.shape[1]), dtype=jnp.float32
        )
        # entity row -> slot, in LRU order (oldest first)
        self._slot_of: "OrderedDict[int, int]" = OrderedDict()
        self._free: List[int] = list(range(self.capacity - 1, -1, -1))
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.cold = 0  # lookups of entities absent from the model

    @property
    def table(self):
        """Device array [capacity + 1, dim]; last row is the zero cold slot."""
        return self._table

    def lookup(self, entity_rows: np.ndarray) -> np.ndarray:
        """Backing rows (−1 = cold) → device slots, filling misses.

        Returns an int32 array the same length as ``entity_rows``.
        """
        entity_rows = np.asarray(entity_rows, dtype=np.int64)
        slots = np.full(len(entity_rows), self.cold_slot, dtype=np.int32)
        pinned: set = set()
        fill_slots: List[int] = []
        fill_rows: List[int] = []
        for i, row in enumerate(entity_rows):
            row = int(row)
            if row < 0:
                self.cold += 1
                continue
            slot = self._slot_of.get(row)
            if slot is not None:
                self.hits += 1
                self._slot_of.move_to_end(row)
            else:
                self.misses += 1
                slot = self._allocate_slot(pinned)
                self._slot_of[row] = slot
                fill_slots.append(slot)
                fill_rows.append(row)
            pinned.add(slot)
            slots[i] = slot
        if fill_slots:
            rows = np.ascontiguousarray(
                self._backing[np.asarray(fill_rows)], dtype=np.float32
            )
            self._table = self._table.at[np.asarray(fill_slots)].set(rows)
        return slots

    def _allocate_slot(self, pinned: set) -> int:
        if self._free:
            return self._free.pop()
        for row, slot in self._slot_of.items():  # oldest first
            if slot not in pinned:
                del self._slot_of[row]
                self.evictions += 1
                return slot
        raise RuntimeError(
            f"cache capacity {self.capacity} smaller than the distinct "
            f"entities of one batch — raise capacity above the largest "
            f"bucket size"
        )

    def invalidate(self, rows) -> int:
        """Drop the given backing rows from the device table if resident
        (hot-swap: only the rows a delta touched get invalidated; everything
        else stays warm). Freed slots are reused by later misses — stale
        values linger in device memory but are unreachable. Returns how many
        resident rows were dropped."""
        dropped = 0
        for row in np.asarray(rows, dtype=np.int64).ravel():
            slot = self._slot_of.pop(int(row), None)
            if slot is not None:
                self._free.append(slot)
                dropped += 1
        return dropped

    def rebind(self, backing: np.ndarray) -> int:
        """Point the cache at a new backing store (hot-swap / rollback:
        the delta-applied table replaces the old array in O(1) — the device
        table and its resident rows are kept). The caller must ``invalidate``
        the rows whose CONTENT changed; rows beyond the new store's end
        (rollback after appends) are dropped here. Returns the number of
        rows dropped for being out of range."""
        if backing.ndim != 2 or backing.shape[1] != self._backing.shape[1]:
            raise ValueError(
                f"rebind backing shape {backing.shape} incompatible with "
                f"cached row dim {self._backing.shape[1]}"
            )
        out_of_range = [
            row for row in self._slot_of if row >= backing.shape[0]
        ]
        self._backing = backing
        return self.invalidate(out_of_range) if out_of_range else 0

    def hit_rate(self) -> float:
        total = self.hits + self.misses
        return self.hits / total if total else 0.0

    def cached_entities(self) -> List[int]:
        """Backing rows currently resident, LRU → MRU (test/debug hook)."""
        return list(self._slot_of)

    def stats(self) -> Dict[str, float]:
        return {
            "capacity": self.capacity,
            "resident": len(self._slot_of),
            "hits": self.hits,
            "misses": self.misses,
            "evictions": self.evictions,
            "cold_lookups": self.cold,
            "hit_rate": round(self.hit_rate(), 6),
        }
