"""Sharded device-resident serving: per-shard RE tables + entity routing.

The single-table :class:`~photon_ml_tpu.serving.scorer.GameScorer` keeps
one ``[rows+1, dim]`` device table per RE coordinate (or a host-side LRU
cache in front of it — whose per-request scatter fill is exactly the
compile-storm and host-hop this module removes from the hot path). Here
each coordinate's table is partitioned across ``S`` shards of a serving
mesh (``parallel/mesh.py``; the cyclic row layout mirrors the grid
placement of ``parallel/grid_features.py``), stacked as ONE device array
``[S, cap+1, dim]`` sharded over its leading axis — so a batch of B
requests becomes a single jitted two-coordinate gather
``table[shard, slot]`` (one gather per shard after XLA partitioning),
with no host work beyond the O(B) routing-index probe.

Residency semantics, in order of degradation:

- resident entity  → its device row, bit-identical to the packed table;
- known, non-resident (cold long tail beyond the device budget) → the
  zero cold slot NOW + queued for asynchronous admission
  (``serving/admission.py``), so the next request finds it resident;
- unknown entity → the zero cold slot, the Photon-ML left-join FE-only
  fallback — same as the single-table scorer.

The scorer mirrors ``GameScorer``'s public surface (score_batch,
compile_count, hot-swap hooks) so ``MicroBatcher``/``ContinuousBatcher``,
``HotSwapManager``, and ``replay_requests`` drive either interchangeably.
"""

from __future__ import annotations

import contextlib
import operator
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from photon_ml_tpu.serving.artifact import ServingArtifact
from photon_ml_tpu.serving.routing import (
    CoordinateRouting,
    RoutingIndex,
    build_routing,
)
from photon_ml_tpu.serving.scorer import (
    _REQ_ENTITY_IDS,
    ScoreRequest,
    ScoreResult,
    featurize_requests,
)
from photon_ml_tpu.telemetry import note_jit_trace, span


_SCATTER_FN = None


def _donated_scatter():
    """Cached jitted row scatter with the table buffer DONATED: the write
    lands in place instead of copying the whole ``[S, cap+1, dim]`` table
    per admission step (a ~23x step-cost difference at a 16k-row budget —
    the copy was the dominant p99 spike under continuous load). Donation
    invalidates the previous array object, so every caller must hold the
    owning scorer's ``write_lock`` (scoring holds it across its gather)."""
    global _SCATTER_FN
    if _SCATTER_FN is None:
        import jax

        _SCATTER_FN = jax.jit(
            lambda table, shards, slots, values: table.at[shards, slots].set(
                values
            ),
            donate_argnums=0,
        )
    return _SCATTER_FN


def _pow2_bucket(n: int) -> int:
    """Smallest power of two >= n: hot-swap scatter writes pad to these
    buckets so the donated scatter's compiled-program count stays
    logarithmic in the largest write, not linear in distinct delta sizes."""
    return 1 << max(0, int(n) - 1).bit_length()


def serving_mesh(num_devices: Optional[int] = None):
    """1-D serving mesh over the available devices (the shard axis of the
    stacked RE tables is laid out over it). Degenerates to a single-device
    mesh on CPU; on a real slice each table shard lives in its own HBM."""
    from photon_ml_tpu.parallel.mesh import data_parallel_mesh

    return data_parallel_mesh(num_devices=num_devices)


class ShardedReTable:
    """One RE coordinate's device storage for one scorer replica.

    Stacked array ``[S, cap+1, dim]``: shard ``s`` holds data slots
    ``0..cap-1`` plus the permanently-zero cold slot ``cap``. WHERE a row
    lives is owned by the shared :class:`CoordinateRouting`; this object
    owns only the bytes (each replica has its own copy of the bytes, all
    replicas share one routing truth).

    The host backing store (the packed artifact table, possibly mmap'd)
    stays authoritative for non-resident rows; hot-swap row updates that
    diverge from it are kept in an override map so an evicted row re-admits
    with its swapped content, not the stale packed bytes.
    """

    def __init__(
        self,
        backing: np.ndarray,
        routing: CoordinateRouting,
        mesh=None,
    ):
        import jax
        import jax.numpy as jnp

        if backing.ndim != 2:
            raise ValueError(f"backing store must be 2-D, got {backing.shape}")
        self._backing = backing
        self._overrides: Dict[int, np.ndarray] = {}
        self.routing = routing
        self._mesh = mesh
        S, cap, dim = routing.num_shards, routing.shard_capacity, backing.shape[1]
        host = np.zeros((S, cap + 1, dim), dtype=np.float32)
        base = routing.base_rows
        if base:
            r = np.arange(base)
            host[r % S, r // S] = np.asarray(backing[:base], dtype=np.float32)
        self._table = self._place(host)

    def _place(self, host: np.ndarray):
        import jax
        import jax.numpy as jnp

        if self._mesh is not None:
            from jax.sharding import PartitionSpec as P

            from photon_ml_tpu.parallel.mesh import DATA_AXIS, place

            n_dev = self._mesh.devices.size
            if host.shape[0] % n_dev == 0:
                return place(host, self._mesh, P(DATA_AXIS))
        return jnp.asarray(host)

    # ------------------------------------------------------------- reading

    @property
    def table(self):
        """Device array [S, cap+1, dim]; slot ``cap`` of every shard is the
        zero cold slot."""
        return self._table

    @property
    def cold_slot(self) -> int:
        return self.routing.cold_slot

    @property
    def capacity(self) -> int:
        """Total device data rows across shards."""
        return self.routing.device_rows

    def host_rows(self, rows: np.ndarray) -> np.ndarray:
        """Authoritative host-side content for global rows: the backing
        store with hot-swap overrides applied; rows beyond the store (new
        entities appended by a swap) default to zero unless overridden."""
        rows = np.asarray(rows, dtype=np.int64)
        out = np.zeros((rows.size, self._backing.shape[1]), dtype=np.float32)
        in_store = rows < self._backing.shape[0]
        if in_store.any():
            out[in_store] = np.asarray(
                self._backing[rows[in_store]], dtype=np.float32
            )
        if self._overrides:
            for i, r in enumerate(rows):
                ov = self._overrides.get(int(r))
                if ov is not None:
                    out[i] = ov
        return out

    # ------------------------------------------------------------- writing

    def write_slots(
        self, shards: np.ndarray, slots: np.ndarray, values: np.ndarray
    ) -> None:
        """Scatter rows into (shard, slot) storage — genuinely in place
        (the table buffer is donated to the jitted scatter, no full-table
        copy), no shape change, no retrace. Callers padding to a fixed
        batch shape (the admission tier) aim the pad writes at
        ``(0, cold_slot)`` with zero values, which keeps the cold slot
        zero and the scatter program count at one.

        Donation invalidates the prior table array object: hold the owning
        scorer's ``write_lock`` so no in-flight gather still references it.
        """
        import jax.numpy as jnp

        self._table = _donated_scatter()(
            self._table,
            jnp.asarray(np.asarray(shards, dtype=np.int32)),
            jnp.asarray(np.asarray(slots, dtype=np.int32)),
            jnp.asarray(np.ascontiguousarray(values, dtype=np.float32)),
        )

    def update_rows(
        self,
        rows: np.ndarray,
        values: np.ndarray,
        replicas: Optional[Sequence[Tuple[object, "ShardedReTable"]]] = None,
    ) -> None:
        """Hot-swap hook: update/append global rows in place. Resident rows
        are overwritten in their slots; non-resident rows are admitted
        immediately (allocating headroom slots, evicting the oldest
        admitted rows when full). Raises only when the coordinate has no
        headroom left for genuinely new rows.

        ``replicas`` is the multi-scorer fan-out: ``(write_lock, table)``
        pairs for EVERY replica of this coordinate (including this one).
        Newly admitted rows are written to every replica's device table
        before the shared routing publishes them — the same
        write-everywhere-then-publish contract the admission controller
        upholds, so no replica's scoring thread can route a fresh row to a
        slot still holding the evicted victim's bytes. Defaults to this
        table alone with no lock (single-replica callers already hold
        their scorer's write_lock or run single-threaded).

        The whole sequence runs under ``routing.lock`` so concurrent
        admission steps and swaps cannot interleave allocate/publish."""
        rows = np.asarray(rows, dtype=np.int64).ravel()
        values = np.asarray(values, dtype=np.float32).reshape(rows.size, -1)
        if rows.size == 0:
            return
        if replicas is None:
            replicas = [(contextlib.nullcontext(), self)]
        routing = self.routing
        with routing.lock:
            if rows.max() >= routing.n_rows:
                routing.grow(int(rows.max()) + 1)
            # importance plane: swapped-in content defines the rows' new
            # magnitude (no-op under the default eviction policy)
            routing.note_row_norms(rows, np.linalg.norm(values, axis=1))
            for _, table in replicas:
                for r, v in zip(rows, values):
                    table._overrides[int(r)] = np.array(v, dtype=np.float32)
            res_slots = routing._slot_of[rows]
            resident = res_slots >= 0
            new_rows = np.unique(rows[~resident])
            if new_rows.size:
                # evicted rows are unpublished inside allocate(); their
                # slots are exactly the ones reused here, so the new
                # content below overwrites them with no separate zeroing
                # pass — and publish() runs only after EVERY replica holds
                # the bytes. Writes are padded to power-of-two shapes
                # (pads aim zeros at shard 0's cold slot, the admission
                # tier's idiom): the donated scatter compiles per shape,
                # and a nearline loop applying variable-size deltas every
                # tick would otherwise trace a fresh program under
                # routing.lock + write_lock — a multi-hundred-ms stall
                # for every concurrent scoring thread
                a_shards, a_slots, _ = routing.allocate(new_rows.size)
                n = int(new_rows.size)
                k = _pow2_bucket(n)
                shards = np.zeros(k, dtype=np.int32)
                slots = np.full(k, routing.cold_slot, dtype=np.int32)
                shards[:n] = a_shards
                slots[:n] = a_slots
                content = np.zeros((k, values.shape[1]), dtype=np.float32)
                for lock, table in replicas:
                    with lock:
                        content[:n] = table.host_rows(new_rows)
                        table.write_slots(shards, slots, content)
                routing.publish(new_rows, a_shards, a_slots)
                res_slots = routing._slot_of[rows]
            # only still-resident rows get the in-place write: a row of
            # this batch evicted to make room stays FE-only until
            # re-admission (its override already carries the new content)
            resident = res_slots >= 0
            if resident.any():
                n = int(resident.sum())
                k = _pow2_bucket(n)
                w_shards = np.zeros(k, dtype=np.int32)
                w_slots = np.full(k, routing.cold_slot, dtype=np.int32)
                w_shards[:n] = routing._shard_of[rows[resident]]
                w_slots[:n] = res_slots[resident]
                w_values = np.zeros((k, values.shape[1]), dtype=np.float32)
                w_values[:n] = values[resident]
                for lock, table in replicas:
                    with lock:
                        table.write_slots(w_shards, w_slots, w_values)

    def fits(self, targets: np.ndarray) -> bool:
        """Whether a hot-swap touching these global rows stays in-shape:
        every non-resident target can claim a headroom slot (free or by
        evicting an admitted row)."""
        targets = np.asarray(targets, dtype=np.int64).ravel()
        with self.routing.lock:
            known = targets[targets < self.routing.n_rows]
            resident = (
                self.routing._slot_of[known] >= 0
                if known.size
                else np.empty(0, dtype=bool)
            )
            n_new = np.unique(targets).size - np.unique(known[resident]).size
            return n_new <= self.routing.free_slots + len(
                self.routing._admitted
            )

    def stats(self) -> Dict[str, float]:
        return self.routing.stats()


class ShardedGameScorer:
    """``GameScorer`` with sharded device-resident RE tables.

    Public surface mirrors :class:`GameScorer` (``score_batch`` /
    ``compile_count`` / hot-swap hooks / empty ``caches``), so every
    existing driver works unchanged. Differences:

    - RE rows come from one two-coordinate gather over the stacked
      ``[S, cap+1, dim]`` table per coordinate — the gathered bytes (and
      therefore the scores) are bit-identical to the single-table scorer.
    - ``num_shards`` / ``device_budget_rows`` bound device memory; the
      long tail beyond the budget starts cold and is pulled on-device by
      an :class:`~photon_ml_tpu.serving.admission.AdmissionController`
      attached via :meth:`attach_admission`.
    - ``routing`` may be a shared :class:`RoutingIndex` (multi-scorer
      mode: every replica gathers through the same entity placement).
    """

    def __init__(
        self,
        artifact: ServingArtifact,
        max_nnz: Optional[Union[int, Dict[str, int]]] = None,
        num_shards: int = 4,
        device_budget_rows: Optional[int] = None,
        mesh=None,
        routing: Optional[RoutingIndex] = None,
        headroom_fraction: float = 0.25,
        eviction_policy: str = "oldest",
    ):
        import jax
        import jax.numpy as jnp

        from photon_ml_tpu.losses.pointwise import mean_function

        self._artifact = artifact
        self._task = artifact.task
        self.num_shards = int(num_shards)
        self.device_budget_rows = device_budget_rows
        dims = artifact.shard_dims()
        self._shard_nnz: Dict[str, int] = {}
        for shard, dim in dims.items():
            if isinstance(max_nnz, dict):
                k = max_nnz.get(shard, dim)
            elif max_nnz is not None:
                k = int(max_nnz)
            else:
                k = dim
            self._shard_nnz[shard] = max(1, min(int(k), dim))
        self._shard_dim = dims

        self._fe_specs: List[Tuple[str, str]] = []
        self._re_specs: List[Tuple[str, str, str]] = []
        self.caches: Dict[str, object] = {}  # no host cache on this path
        self._providers: Dict[str, ShardedReTable] = {}
        self._mesh = mesh
        self._headroom_fraction = float(headroom_fraction)
        self._admission = None
        # multi-scorer mode: every replica sharing this scorer's routing
        # index (including self); hot-swap row admission writes all of
        # their tables before publishing. None = this scorer alone.
        self._replica_group: Optional[List["ShardedGameScorer"]] = None
        # serializes donated table writes against in-flight gathers: the
        # scoring thread holds it across param capture + score + sync,
        # writers (admission, hot swap) hold it across write_slots
        self.write_lock = threading.Lock()
        fe_params: Dict[str, object] = {}
        re_rows = {
            cid: t.n_entities
            for cid, t in artifact.tables.items()
            if t.is_random_effect
        }
        if routing is None:
            # eviction_policy only applies when this scorer builds its own
            # routing; a shared RoutingIndex carries its own policy
            routing = build_routing(
                re_rows,
                num_shards=self.num_shards,
                device_budget_rows=device_budget_rows,
                headroom_fraction=self._headroom_fraction,
                eviction_policy=eviction_policy,
            )
        self._routing = routing
        for cid in sorted(artifact.tables):
            table = artifact.tables[cid]
            if table.is_random_effect:
                self._re_specs.append(
                    (cid, table.feature_shard, table.random_effect_type)
                )
                self._providers[cid] = ShardedReTable(
                    np.asarray(table.weights),
                    routing[cid],
                    mesh=mesh,
                )
            else:
                self._fe_specs.append((cid, table.feature_shard))
                fe_params[cid] = jnp.asarray(
                    np.ascontiguousarray(table.weights, dtype=np.float32)
                )
        self._fe_params = fe_params
        self._compiles = 0

        fe_specs = tuple(self._fe_specs)
        re_specs = tuple(self._re_specs)
        task = self._task

        def _score(params, batch):
            # trace-time side effect: runs once per compiled shape signature
            self._compiles += 1
            note_jit_trace("serving_score")
            z = batch["offsets"]
            for cid, shard in fe_specs:
                vals, idx = batch["shards"][shard]
                z = z + (vals * params["fe"][cid][idx]).sum(axis=1)
            for cid, shard, _ in re_specs:
                vals, idx = batch["shards"][shard]
                # THE sharded gather: [B] shard ids + [B] slots against the
                # stacked [S, cap+1, dim] table — XLA partitions this into
                # one gather per shard over the mesh
                rows = params["re"][cid][
                    batch["re_shards"][cid], batch["slots"][cid]
                ]
                z = z + (vals * jnp.take_along_axis(rows, idx, axis=1)).sum(axis=1)
            return z, mean_function(task, z)

        self._score_fn = jax.jit(_score)

    # ---------------------------------------------------------- properties

    @property
    def compile_count(self) -> int:
        """XLA traces of the score function — one per bucket size."""
        return self._compiles

    @property
    def task(self):
        return self._task

    @property
    def artifact(self) -> ServingArtifact:
        return self._artifact

    @property
    def routing(self) -> RoutingIndex:
        return self._routing

    def cache_stats(self) -> Dict[str, Dict[str, float]]:
        return {}

    def residency_stats(self) -> Dict[str, Dict[str, float]]:
        """Per-coordinate device residency + lookup accounting (the sharded
        replacement for ``cache_stats``/``cache_hit_rate``)."""
        return self._routing.stats()

    def attach_admission(self, controller) -> None:
        """Route deferred (known, non-resident) lookups to an admission
        controller; without one they are only counted. When the controller
        spans several replicas of this scorer's routing index, they become
        this scorer's replica group: hot-swap row admission then writes
        every replica's table before publishing (same contract as the
        controller's own admits)."""
        self._admission = controller
        peers = [
            s
            for s in getattr(controller, "scorers", [])
            if getattr(s, "_routing", None) is self._routing
        ]
        if len(peers) > 1 and self in peers:
            self.set_replica_group(peers)

    def set_replica_group(
        self, scorers: Sequence["ShardedGameScorer"]
    ) -> None:
        """Declare the replicas (including this scorer) that share this
        scorer's routing index, so row-level hot swaps keep the
        write-everywhere-before-publish ordering across all of them."""
        scorers = list(scorers)
        if self not in scorers:
            raise ValueError("replica group must include this scorer")
        for s in scorers:
            if s._routing is not self._routing:
                raise ValueError(
                    "replica group must share one routing index"
                )
        self._replica_group = scorers

    # ------------------------------------------------------ hot-swap hooks

    def set_artifact(self, artifact: ServingArtifact) -> None:
        fe = [
            (cid, t.feature_shard)
            for cid, t in sorted(artifact.tables.items())
            if not t.is_random_effect
        ]
        re = [
            (cid, t.feature_shard, t.random_effect_type)
            for cid, t in sorted(artifact.tables.items())
            if t.is_random_effect
        ]
        if fe != self._fe_specs or re != self._re_specs:
            raise ValueError(
                "candidate artifact changes the coordinate structure "
                f"(have fe={self._fe_specs} re={self._re_specs}, candidate "
                f"fe={fe} re={re}) — a structural change needs a new scorer, "
                "not a hot swap"
            )
        for cid, shard in self._fe_specs:
            if artifact.tables[cid].dim != self._artifact.tables[cid].dim:
                raise ValueError(
                    f"candidate artifact changes fixed-effect dim of {cid!r}"
                )
        # grow every RE coordinate's routing BEFORE the new entity indexes
        # go live: a concurrent score_batch may resolve candidate-only
        # entities the instant the artifact reference flips, and route()
        # must already know the larger row space (they start non-resident,
        # score FE-only, and queue for admission — never an index error)
        for cid, _, _ in self._re_specs:
            n_new = artifact.tables[cid].n_entities
            routing = self._routing[cid]
            if n_new > routing.n_rows:
                routing.grow(n_new)
        self._artifact = artifact

    def update_fixed_effect(self, cid: str, weights: np.ndarray) -> None:
        import jax.numpy as jnp

        old = self._fe_params.get(cid)
        if old is None:
            raise ValueError(f"{cid!r} is not a fixed-effect coordinate")
        w = np.ascontiguousarray(weights, dtype=np.float32)
        if w.shape != old.shape:
            raise ValueError(
                f"fixed-effect update for {cid!r} has shape {w.shape}, "
                f"scorer holds {old.shape}"
            )
        self._fe_params[cid] = jnp.asarray(w)

    def update_random_effect_rows(
        self, cid: str, rows: np.ndarray, values: np.ndarray
    ) -> None:
        provider = self._providers.get(cid)
        if provider is None:
            raise ValueError(f"{cid!r} is not a random-effect coordinate")
        group = self._replica_group or [self]
        # routing.lock (taken inside update_rows) is the OUTER lock; each
        # replica's write_lock is taken per device write inside it
        provider.update_rows(
            rows,
            values,
            replicas=[(s.write_lock, s._providers[cid]) for s in group],
        )

    def rebind_random_effect(self, cid: str, backing: np.ndarray) -> bool:
        """Rebuild one coordinate's device shards from a new backing table.
        Stays in-shape (False) when the shared routing's shard capacity
        already accommodates the new row count — then only the bytes are
        rebuilt; grows the routing (True, one expected retrace) otherwise.
        In multi-scorer mode the first replica to regrow updates the SHARED
        routing; later replicas see it already sized and rebuild in-shape.
        """
        provider = self._providers.get(cid)
        if provider is None:
            raise ValueError(f"{cid!r} is not a random-effect coordinate")
        backing = np.asarray(backing)
        n_new = backing.shape[0]
        routing = self._routing[cid]
        # hold the OLD routing's lock across the whole swap: an admission
        # step serialized behind it re-reads the provider afterwards and
        # retries against the new routing (see AdmissionController._admit)
        with routing.lock:
            old_cap = routing.shard_capacity
            if n_new > routing.device_rows or routing.n_rows != n_new:
                fresh = build_routing(
                    {cid: n_new},
                    num_shards=routing.num_shards,
                    device_budget_rows=self.device_budget_rows,
                    headroom_fraction=self._headroom_fraction,
                )[cid]
                if fresh.shard_capacity < old_cap:
                    # never shrink a shared layout other replicas still
                    # serve
                    fresh = CoordinateRouting(
                        n_rows=n_new,
                        num_shards=routing.num_shards,
                        shard_capacity=old_cap,
                        resident_rows=fresh.base_rows,
                    )
                self._routing.coordinates[cid] = fresh
                routing = fresh
            with self.write_lock:
                self._providers[cid] = ShardedReTable(
                    backing, routing, mesh=self._mesh
                )
            return routing.shard_capacity != old_cap

    def restore_random_effect(
        self, cid: str, provider, routing=None
    ) -> None:
        """Rollback hook: reinstall a snapshotted provider and — when the
        forward swap regrew the shared layout — the routing coordinate it
        was built against, as ONE step. Restoring only the provider would
        leave the scorer routing with the grown layout while gathering
        from the old-shape table (slots beyond the old capacity would read
        other rows' bytes)."""
        current = self._routing[cid]
        with current.lock:
            if routing is not None and routing is not current:
                self._routing.coordinates[cid] = routing
            with self.write_lock:
                self._providers[cid] = provider

    # -------------------------------------------------------------- scoring

    def _featurize(self, requests: Sequence[ScoreRequest], bucket: int):
        return featurize_requests(
            requests, len(requests), bucket, self._shard_nnz, self._shard_dim
        )

    def score_batch(
        self,
        requests: Sequence[ScoreRequest],
        bucket_size: Optional[int] = None,
        stages: Optional[dict] = None,
        view: Optional[Tuple[ServingArtifact, Dict[str, object]]] = None,
    ) -> List[ScoreResult]:
        """Score one bucket. ``view`` is the multi-model hook: an
        ``(artifact, fe_params)`` pair that overrides WHICH entity indexes
        resolve rows and WHICH fixed-effect vectors the jitted program
        reads — same shapes, same compiled program, same shared RE tables.
        The tenancy plane's :class:`VariantRegistry` builds one view per
        variant; ``view=None`` is the plain single-model path, bitwise
        unchanged."""
        n = len(requests)
        bucket = int(bucket_size) if bucket_size is not None else n
        if n == 0:
            return []
        if n > bucket:
            raise ValueError(f"{n} requests do not fit bucket size {bucket}")
        with span("serve/score_batch", n=n, bucket=bucket):
            return self._score_batch_impl(requests, n, bucket, stages, view)

    def _score_batch_impl(
        self,
        requests: Sequence[ScoreRequest],
        n: int,
        bucket: int,
        stages: Optional[dict] = None,
        view: Optional[Tuple[ServingArtifact, Dict[str, object]]] = None,
    ) -> List[ScoreResult]:
        import jax.numpy as jnp

        artifact = self._artifact if view is None else view[0]
        fe_params = self._fe_params if view is None else view[1]
        with span("serve/featurize", n=n):
            shards, offsets = self._featurize(requests, bucket)
        if stages is not None:
            stages["featurize_done"] = time.perf_counter()
        re_shards: Dict[str, np.ndarray] = {}
        slots: Dict[str, np.ndarray] = {}
        cold: Dict[int, List[str]] = {}
        with span("serve/route", n=n):
            for cid, feature_shard, re_type in self._re_specs:
                table = artifact.tables[cid]
                entity_rows = np.full(bucket, -1, dtype=np.int64)
                # mirror of GameScorer's route: ids stay C-level, and
                # the common every-request-carries-an-id case hands the
                # whole list to one vectorized lookup. Artifact entity
                # indexes are keyed by str, so non-str ids (ints from
                # upstream id tags) are coerced like ServingArtifact
                # .entity_row does.
                ids = [
                    e if type(e) is str or e is None else str(e)
                    for e in map(
                        operator.methodcaller("get", re_type),
                        map(_REQ_ENTITY_IDS, requests),
                    )
                ]
                if None not in ids:
                    entity_rows[:n] = table.entity_index.get_indices(ids)
                else:
                    where = [i for i, e in enumerate(ids) if e is not None]
                    if where:
                        entity_rows[np.asarray(where)] = (
                            table.entity_index.get_indices(
                                [ids[i] for i in where]
                            )
                        )
                routing = self._routing[cid]
                cid_shards, cid_slots, deferred = routing.route(
                    entity_rows[:n]
                )
                # importance plane: fold this batch into the EWMA request
                # frequencies (no-op under the default eviction policy).
                # Under the importance policy each request also deposits
                # its feature-vector norm, so importance bounds the row's
                # cumulative score-delta-vs-FE-only, not just its hit count.
                if routing.wants_feature_norms:
                    vals = shards[feature_shard][0]
                    routing.note_requests(
                        entity_rows[:n],
                        feature_norms=np.linalg.norm(vals[:n], axis=1),
                    )
                else:
                    routing.note_requests(entity_rows[:n])
                if deferred.size and self._admission is not None:
                    self._admission.note_deferred(cid, deferred)
                # pad rows (and this batch's FE-only rows) gather the zero
                # cold slot of shard 0
                full_shards = np.zeros(bucket, dtype=np.int32)
                full_slots = np.full(
                    bucket, routing.cold_slot, dtype=np.int32
                )
                full_shards[:n] = cid_shards
                full_slots[:n] = cid_slots
                re_shards[cid] = full_shards
                slots[cid] = full_slots
                served_cold = np.nonzero(
                    full_slots[:n] == routing.cold_slot
                )[0]
                for i in served_cold:
                    cold.setdefault(int(i), []).append(cid)

        if stages is not None:
            stages["route_done"] = time.perf_counter()
        batch = {
            "offsets": jnp.asarray(offsets),
            "shards": {
                shard: (jnp.asarray(v), jnp.asarray(i))
                for shard, (v, i) in shards.items()
            },
            "re_shards": {
                cid: jnp.asarray(s) for cid, s in re_shards.items()
            },
            "slots": {cid: jnp.asarray(s) for cid, s in slots.items()},
        }
        # write_lock spans table capture through host sync: a donated
        # admission scatter between the capture and the gather would
        # invalidate the captured array
        with self.write_lock:
            params = {
                "fe": fe_params,
                "re": {
                    cid: self._providers[cid].table
                    for cid, _, _ in self._re_specs
                },
            }
            with span("serve/gather_score", n=n, bucket=bucket):
                z, mean = self._score_fn(params, batch)
                if stages is not None:
                    # closes H2D + dispatch (includes any write_lock wait —
                    # admission interference spans make that attributable);
                    # the materialization below blocks on the device
                    stages["dispatch_done"] = time.perf_counter()
                z_list = np.asarray(z)[:n].tolist()
                mean_list = np.asarray(mean)[:n].tolist()
        if stages is not None:
            stages["device_done"] = time.perf_counter()
        empty: Tuple[str, ...] = ()
        return [
            ScoreResult(
                request_id=req.request_id,
                score=z_list[i],
                mean=mean_list[i],
                cold_coordinates=tuple(cold[i]) if i in cold else empty,
            )
            for i, req in enumerate(requests)
        ]
