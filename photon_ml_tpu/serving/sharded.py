"""Sharded device-resident serving: per-shard RE tables + entity routing.

The single-table :class:`~photon_ml_tpu.serving.scorer.GameScorer` keeps
one ``[rows+1, dim]`` device table per RE coordinate (or a host-side LRU
cache in front of it — whose per-request scatter fill is exactly the
compile-storm and host-hop this module removes from the hot path). Here
each coordinate's table is partitioned across ``S`` shards of a serving
mesh (``parallel/mesh.py``; the cyclic row layout mirrors the grid
placement of ``parallel/grid_features.py``), stacked as a device array
``[S, cap+1, dim]`` sharded over its leading axis — so a batch of B
requests becomes a single jitted two-coordinate gather
``table[shard, slot]`` (one gather per shard after XLA partitioning),
with no host work beyond the O(B) routing-index probe. Each table is
DOUBLE-BUFFERED (logically ``[2, S, cap+1, dim]``): hot swaps stage into
the spare generation half and flip an atomic index, so publishing a
delta never pauses the gather path (see :class:`ShardedReTable`).

Residency semantics, in order of degradation:

- resident entity  → its device row, bit-identical to the packed table;
- known, non-resident (cold long tail beyond the device budget) → the
  zero cold slot NOW + queued for asynchronous admission
  (``serving/admission.py``), so the next request finds it resident;
- unknown entity → the zero cold slot, the Photon-ML left-join FE-only
  fallback — same as the single-table scorer.

The scorer mirrors ``GameScorer``'s public surface (score_batch,
compile_count, hot-swap hooks) so ``MicroBatcher``/``ContinuousBatcher``,
``HotSwapManager``, and ``replay_requests`` drive either interchangeably.
"""

from __future__ import annotations

import contextlib
import operator
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from photon_ml_tpu.serving.artifact import ServingArtifact
from photon_ml_tpu.serving.routing import (
    CoordinateRouting,
    RoutingIndex,
    build_routing,
)
from photon_ml_tpu.serving.scorer import (
    _REQ_ENTITY_IDS,
    ScoreRequest,
    ScoreResult,
    featurize_requests,
)
from photon_ml_tpu.telemetry import note_jit_trace, span


_SCATTER_FN = None


def _donated_scatter():
    """Cached jitted row scatter with the table buffer DONATED: the write
    lands in place instead of copying the whole ``[S, cap+1, dim]`` table
    per admission step (a ~23x step-cost difference at a 16k-row budget —
    the copy was the dominant p99 spike under continuous load). Donation
    invalidates the previous array object, so every caller must hold the
    owning scorer's ``write_lock`` (scoring holds it across its gather)."""
    global _SCATTER_FN
    if _SCATTER_FN is None:
        import jax

        _SCATTER_FN = jax.jit(
            lambda table, shards, slots, values: table.at[shards, slots].set(
                values
            ),
            donate_argnums=0,
        )
    return _SCATTER_FN


def _pow2_bucket(n: int) -> int:
    """Smallest power of two >= n: hot-swap scatter writes pad to these
    buckets so the donated scatter's compiled-program count stays
    logarithmic in the largest write, not linear in distinct delta sizes."""
    return 1 << max(0, int(n) - 1).bit_length()


def serving_mesh(num_devices: Optional[int] = None):
    """1-D serving mesh over the available devices (the shard axis of the
    stacked RE tables is laid out over it). Degenerates to a single-device
    mesh on CPU; on a real slice each table shard lives in its own HBM."""
    from photon_ml_tpu.parallel.mesh import data_parallel_mesh

    return data_parallel_mesh(num_devices=num_devices)


class ShardedReTable:
    """One RE coordinate's device storage for one scorer replica.

    DOUBLE-BUFFERED stacked array — logically ``[2, S, cap+1, dim]``, held
    as two independent ``[S, cap+1, dim]`` device arrays (donation into a
    slice of one stacked array would invalidate the half still being
    gathered): shard ``s`` holds data slots ``0..cap-1`` plus the
    permanently-zero cold slot ``cap``. ``table`` always resolves the
    ACTIVE half via an atomic generation index; hot-swap writes stage into
    the spare half off the request path and then flip the index
    (:meth:`update_rows`), so a swap never pauses the gather path. Outside
    an in-flight :meth:`update_rows` both halves hold identical bytes —
    steady-state writers (the admission tier) write both. Memory cost: 2x
    table HBM per coordinate.

    WHERE a row lives is owned by the shared :class:`CoordinateRouting`;
    this object owns only the bytes (each replica has its own copy of the
    bytes, all replicas share one routing truth).

    The host backing store (the packed artifact table, possibly mmap'd)
    stays authoritative for non-resident rows; hot-swap row updates that
    diverge from it are kept in an override map so an evicted row re-admits
    with its swapped content, not the stale packed bytes.
    """

    def __init__(
        self,
        backing: np.ndarray,
        routing: CoordinateRouting,
        mesh=None,
    ):
        import jax
        import jax.numpy as jnp

        if backing.ndim != 2:
            raise ValueError(f"backing store must be 2-D, got {backing.shape}")
        self._backing = backing
        self._overrides: Dict[int, np.ndarray] = {}
        self.routing = routing
        self._mesh = mesh
        S, cap, dim = routing.num_shards, routing.shard_capacity, backing.shape[1]
        host = np.zeros((S, cap + 1, dim), dtype=np.float32)
        base = routing.base_rows
        if base:
            r = np.arange(base)
            host[r % S, r // S] = np.asarray(backing[:base], dtype=np.float32)
        # both generation halves start converged (identical bytes)
        self._tables = [self._place(host), self._place(host)]
        self._gen = 0

    def _place(self, host: np.ndarray):
        import jax
        import jax.numpy as jnp

        if self._mesh is not None:
            from jax.sharding import PartitionSpec as P

            from photon_ml_tpu.parallel.mesh import DATA_AXIS, place

            n_dev = self._mesh.devices.size
            if host.shape[0] % n_dev == 0:
                return place(host, self._mesh, P(DATA_AXIS))
        return jnp.asarray(host)

    # ------------------------------------------------------------- reading

    @property
    def table(self):
        """ACTIVE generation half — device array [S, cap+1, dim]; slot
        ``cap`` of every shard is the zero cold slot."""
        return self._tables[self._gen]

    @property
    def generation(self) -> int:
        """Index (0/1) of the active table half."""
        return self._gen

    @property
    def spare_gen(self) -> int:
        """Index of the spare (write-staging) table half."""
        return 1 - self._gen

    def flip(self) -> None:
        """Atomically switch the active half. Callers must hold the owning
        scorer's ``write_lock`` (so no in-flight gather still references
        the half being retired) — see :meth:`update_rows`."""
        self._gen = 1 - self._gen

    @property
    def cold_slot(self) -> int:
        return self.routing.cold_slot

    @property
    def capacity(self) -> int:
        """Total device data rows across shards."""
        return self.routing.device_rows

    def host_rows(self, rows: np.ndarray) -> np.ndarray:
        """Authoritative host-side content for global rows: the backing
        store with hot-swap overrides applied; rows beyond the store (new
        entities appended by a swap) default to zero unless overridden."""
        rows = np.asarray(rows, dtype=np.int64)
        out = np.zeros((rows.size, self._backing.shape[1]), dtype=np.float32)
        in_store = rows < self._backing.shape[0]
        if in_store.any():
            out[in_store] = np.asarray(
                self._backing[rows[in_store]], dtype=np.float32
            )
        if self._overrides:
            for i, r in enumerate(rows):
                ov = self._overrides.get(int(r))
                if ov is not None:
                    out[i] = ov
        return out

    # ------------------------------------------------------------- writing

    def write_slots(
        self,
        shards: np.ndarray,
        slots: np.ndarray,
        values: np.ndarray,
        gen: Optional[int] = None,
    ) -> None:
        """Scatter rows into (shard, slot) storage — genuinely in place
        (the table buffer is donated to the jitted scatter, no full-table
        copy), no shape change, no retrace. Callers padding to a fixed
        batch shape (the admission tier) aim the pad writes at
        ``(0, cold_slot)`` with zero values, which keeps the cold slot
        zero and the scatter program count at one.

        ``gen`` selects the table half (default: active). Donation
        invalidates the prior half's array object: writes to the ACTIVE
        half need the owning scorer's ``write_lock`` so no in-flight
        gather still references it; writes to the SPARE half need only
        ``routing.lock`` (which keeps the generation index stable and
        serializes writers) — the request path never captures that half.
        """
        import jax.numpy as jnp

        g = self._gen if gen is None else int(gen)
        self._tables[g] = _donated_scatter()(
            self._tables[g],
            jnp.asarray(np.asarray(shards, dtype=np.int32)),
            jnp.asarray(np.asarray(slots, dtype=np.int32)),
            jnp.asarray(np.ascontiguousarray(values, dtype=np.float32)),
        )

    def update_rows(
        self,
        rows: np.ndarray,
        values: np.ndarray,
        replicas: Optional[Sequence[Tuple[object, "ShardedReTable"]]] = None,
    ) -> float:
        """Hot-swap hook: update/append global rows via a PAUSELESS
        generation flip. Resident rows are overwritten; non-resident rows
        are admitted immediately (allocating headroom slots, evicting the
        oldest admitted rows when full). Raises only when the coordinate
        has no headroom left for genuinely new rows. Returns the
        request-path blocking seconds: the width of the flip window during
        which every replica's ``write_lock`` is held (lock handoff only —
        no device work happens inside it).

        Three phases, all under ``routing.lock``:

        1. STAGE — pad every write to a power-of-two shape (pads aim zeros
           at shard 0's cold slot; the donated scatter compiles per shape,
           so a nearline loop applying variable-size deltas would
           otherwise trace a fresh program per tick) and scatter it into
           every replica's SPARE half. No ``write_lock``: the request path
           gathers only the active half, and ``routing.lock`` keeps every
           ``_gen`` stable.
        2. FLIP — acquire EVERY replica's ``write_lock`` (once held, no
           gather is in flight on any replica) and flip all generation
           indexes, all-or-nothing. This is the only blocking window and
           the returned duration. New rows publish() only AFTER the flip:
           before it, the still-active old half holds the evicted victims'
           bytes in the reused slots (victims themselves were unpublished
           inside ``allocate()`` and route FE-only from that moment).
        3. CONVERGE — replay the same writes into the old (now spare)
           halves. The flip held every ``write_lock``, so no in-flight
           gather still references them; afterwards the invariant "both
           halves identical outside this call" holds again.

        ``replicas`` is the multi-scorer fan-out: ``(write_lock, table)``
        pairs for EVERY replica of this coordinate (including this one).
        Defaults to this table alone with no lock (single-replica callers
        run single-threaded). ``routing.lock`` (outer) ordering vs
        ``write_lock`` (inner) is preserved; concurrent admission steps
        and swaps cannot interleave allocate/publish."""
        rows = np.asarray(rows, dtype=np.int64).ravel()
        values = np.asarray(values, dtype=np.float32).reshape(rows.size, -1)
        if rows.size == 0:
            return 0.0
        if replicas is None:
            replicas = [(contextlib.nullcontext(), self)]
        routing = self.routing
        with routing.lock:
            if rows.max() >= routing.n_rows:
                routing.grow(int(rows.max()) + 1)
            # importance plane: swapped-in content defines the rows' new
            # magnitude (no-op under the default eviction policy)
            routing.note_row_norms(rows, np.linalg.norm(values, axis=1))
            for _, table in replicas:
                for r, v in zip(rows, values):
                    table._overrides[int(r)] = np.array(v, dtype=np.float32)
            eff_slots = routing._slot_of[rows].copy()
            eff_shards = routing._shard_of[rows].copy()
            new_rows = np.unique(rows[eff_slots < 0])
            publish_args = None
            # (shards, slots, per-replica values) staged to BOTH halves
            writes: List[Tuple[np.ndarray, np.ndarray, List[np.ndarray]]] = []
            if new_rows.size:
                a_shards, a_slots, _ = routing.allocate(new_rows.size)
                n = int(new_rows.size)
                k = _pow2_bucket(n)
                shards = np.zeros(k, dtype=np.int32)
                slots = np.full(k, routing.cold_slot, dtype=np.int32)
                shards[:n] = a_shards
                slots[:n] = a_slots
                per_replica = []
                for _, table in replicas:
                    content = np.zeros((k, values.shape[1]), dtype=np.float32)
                    content[:n] = table.host_rows(new_rows)
                    per_replica.append(content)
                writes.append((shards, slots, per_replica))
                publish_args = (new_rows, a_shards, a_slots)
                # residency as it will stand after publish(): overlay the
                # fresh allocations on the current map (victims already
                # cleared by allocate). A row of THIS batch evicted to
                # make room stays FE-only until re-admission (its override
                # already carries the new content).
                eff_slots = routing._slot_of[rows].copy()
                eff_shards = routing._shard_of[rows].copy()
                pos = {int(r): i for i, r in enumerate(new_rows)}
                for j, r in enumerate(rows):
                    i = pos.get(int(r))
                    if i is not None:
                        eff_slots[j] = a_slots[i]
                        eff_shards[j] = a_shards[i]
            resident = eff_slots >= 0
            if resident.any():
                n = int(resident.sum())
                k = _pow2_bucket(n)
                w_shards = np.zeros(k, dtype=np.int32)
                w_slots = np.full(k, routing.cold_slot, dtype=np.int32)
                w_shards[:n] = eff_shards[resident]
                w_slots[:n] = eff_slots[resident]
                w_values = np.zeros((k, values.shape[1]), dtype=np.float32)
                w_values[:n] = values[resident]
                writes.append((w_shards, w_slots, [w_values] * len(replicas)))
            if not writes:
                return 0.0
            # phase 1: stage into every spare half, off the request path
            for shards, slots, per_replica in writes:
                for (_, table), content in zip(replicas, per_replica):
                    table.write_slots(
                        shards, slots, content, gen=table.spare_gen
                    )
            # phase 2: the flip — the only request-path blocking window
            t0 = time.perf_counter()
            with contextlib.ExitStack() as stack:
                for lock, _ in replicas:
                    stack.enter_context(lock)
                for _, table in replicas:
                    table.flip()
            blocking_s = time.perf_counter() - t0
            if publish_args is not None:
                routing.publish(*publish_args)
            # phase 3: converge the retired halves (now spare)
            for shards, slots, per_replica in writes:
                for (_, table), content in zip(replicas, per_replica):
                    table.write_slots(
                        shards, slots, content, gen=table.spare_gen
                    )
            return blocking_s

    def fits(self, targets: np.ndarray) -> bool:
        """Whether a hot-swap touching these global rows stays in-shape:
        every non-resident target can claim a headroom slot (free or by
        evicting an admitted row)."""
        targets = np.asarray(targets, dtype=np.int64).ravel()
        with self.routing.lock:
            known = targets[targets < self.routing.n_rows]
            resident = (
                self.routing._slot_of[known] >= 0
                if known.size
                else np.empty(0, dtype=bool)
            )
            n_new = np.unique(targets).size - np.unique(known[resident]).size
            return n_new <= self.routing.free_slots + len(
                self.routing._admitted
            )

    def stats(self) -> Dict[str, float]:
        return self.routing.stats()


class ShardedGameScorer:
    """``GameScorer`` with sharded device-resident RE tables.

    Public surface mirrors :class:`GameScorer` (``score_batch`` /
    ``compile_count`` / hot-swap hooks / empty ``caches``), so every
    existing driver works unchanged. Differences:

    - RE rows come from one two-coordinate gather over the stacked
      ``[S, cap+1, dim]`` table per coordinate — the gathered bytes (and
      therefore the scores) are bit-identical to the single-table scorer.
    - ``num_shards`` / ``device_budget_rows`` bound device memory; the
      long tail beyond the budget starts cold and is pulled on-device by
      an :class:`~photon_ml_tpu.serving.admission.AdmissionController`
      attached via :meth:`attach_admission`.
    - ``routing`` may be a shared :class:`RoutingIndex` (multi-scorer
      mode: every replica gathers through the same entity placement).
    """

    def __init__(
        self,
        artifact: ServingArtifact,
        max_nnz: Optional[Union[int, Dict[str, int]]] = None,
        num_shards: int = 4,
        device_budget_rows: Optional[int] = None,
        mesh=None,
        routing: Optional[RoutingIndex] = None,
        headroom_fraction: float = 0.25,
        eviction_policy: str = "oldest",
        score_delta: bool = True,
    ):
        import jax
        import jax.numpy as jnp

        from photon_ml_tpu.losses.pointwise import mean_function

        self._artifact = artifact
        self._task = artifact.task
        self.num_shards = int(num_shards)
        self.device_budget_rows = device_budget_rows
        dims = artifact.shard_dims()
        self._shard_nnz: Dict[str, int] = {}
        for shard, dim in dims.items():
            if isinstance(max_nnz, dict):
                k = max_nnz.get(shard, dim)
            elif max_nnz is not None:
                k = int(max_nnz)
            else:
                k = dim
            self._shard_nnz[shard] = max(1, min(int(k), dim))
        self._shard_dim = dims

        self._fe_specs: List[Tuple[str, str]] = []
        self._re_specs: List[Tuple[str, str, str]] = []
        self.caches: Dict[str, object] = {}  # no host cache on this path
        self._providers: Dict[str, ShardedReTable] = {}
        self._mesh = mesh
        self._headroom_fraction = float(headroom_fraction)
        self._admission = None
        # multi-scorer mode: every replica sharing this scorer's routing
        # index (including self); hot-swap row admission writes all of
        # their tables before publishing. None = this scorer alone.
        self._replica_group: Optional[List["ShardedGameScorer"]] = None
        # serializes donated table writes against in-flight gathers: the
        # scoring thread holds it across param capture + score + sync,
        # writers (admission, hot swap) hold it across write_slots
        self.write_lock = threading.Lock()
        fe_params: Dict[str, object] = {}
        re_rows = {
            cid: t.n_entities
            for cid, t in artifact.tables.items()
            if t.is_random_effect
        }
        if routing is None:
            # eviction_policy only applies when this scorer builds its own
            # routing; a shared RoutingIndex carries its own policy
            routing = build_routing(
                re_rows,
                num_shards=self.num_shards,
                device_budget_rows=device_budget_rows,
                headroom_fraction=self._headroom_fraction,
                eviction_policy=eviction_policy,
                score_delta=score_delta,
            )
        self._routing = routing
        for cid in sorted(artifact.tables):
            table = artifact.tables[cid]
            if table.is_random_effect:
                self._re_specs.append(
                    (cid, table.feature_shard, table.random_effect_type)
                )
                self._providers[cid] = ShardedReTable(
                    np.asarray(table.weights),
                    routing[cid],
                    mesh=mesh,
                )
            else:
                self._fe_specs.append((cid, table.feature_shard))
                fe_params[cid] = jnp.asarray(
                    np.ascontiguousarray(table.weights, dtype=np.float32)
                )
        self._fe_params = fe_params
        self._compiles = 0

        fe_specs = tuple(self._fe_specs)
        re_specs = tuple(self._re_specs)
        task = self._task

        def _score(params, batch):
            # trace-time side effect: runs once per compiled shape signature
            self._compiles += 1
            note_jit_trace("serving_score")
            z = batch["offsets"]
            for cid, shard in fe_specs:
                vals, idx = batch["shards"][shard]
                z = z + (vals * params["fe"][cid][idx]).sum(axis=1)
            for cid, shard, _ in re_specs:
                vals, idx = batch["shards"][shard]
                # THE sharded gather: [B] shard ids + [B] slots against the
                # stacked [S, cap+1, dim] table — XLA partitions this into
                # one gather per shard over the mesh
                rows = params["re"][cid][
                    batch["re_shards"][cid], batch["slots"][cid]
                ]
                z = z + (vals * jnp.take_along_axis(rows, idx, axis=1)).sum(axis=1)
            return z, mean_function(task, z)

        self._score_fn = jax.jit(_score)

        def _redelta(params, batch):
            # per-coordinate |RE contribution| = the request's measured
            # |score − fe_only_score| attributable to that coordinate.
            # Traced/dispatched ONLY when a routing coordinate tracks
            # measured score deltas (importance policy + score_delta) —
            # the default path never pays for it.
            out = {}
            for cid, shard, _ in re_specs:
                vals, idx = batch["shards"][shard]
                rows = params["re"][cid][
                    batch["re_shards"][cid], batch["slots"][cid]
                ]
                out[cid] = jnp.abs(
                    (vals * jnp.take_along_axis(rows, idx, axis=1)).sum(
                        axis=1
                    )
                )
            return out

        self._redelta_fn = jax.jit(_redelta)

    # ---------------------------------------------------------- properties

    @property
    def compile_count(self) -> int:
        """XLA traces of the score function — one per bucket size."""
        return self._compiles

    @property
    def task(self):
        return self._task

    @property
    def artifact(self) -> ServingArtifact:
        return self._artifact

    @property
    def routing(self) -> RoutingIndex:
        return self._routing

    def cache_stats(self) -> Dict[str, Dict[str, float]]:
        return {}

    def residency_stats(self) -> Dict[str, Dict[str, float]]:
        """Per-coordinate device residency + lookup accounting (the sharded
        replacement for ``cache_stats``/``cache_hit_rate``)."""
        return self._routing.stats()

    def attach_admission(self, controller) -> None:
        """Route deferred (known, non-resident) lookups to an admission
        controller; without one they are only counted. When the controller
        spans several replicas of this scorer's routing index, they become
        this scorer's replica group: hot-swap row admission then writes
        every replica's table before publishing (same contract as the
        controller's own admits)."""
        self._admission = controller
        peers = [
            s
            for s in getattr(controller, "scorers", [])
            if getattr(s, "_routing", None) is self._routing
        ]
        if len(peers) > 1 and self in peers:
            self.set_replica_group(peers)

    def set_replica_group(
        self, scorers: Sequence["ShardedGameScorer"]
    ) -> None:
        """Declare the replicas (including this scorer) that share this
        scorer's routing index, so row-level hot swaps keep the
        write-everywhere-before-publish ordering across all of them."""
        scorers = list(scorers)
        if self not in scorers:
            raise ValueError("replica group must include this scorer")
        for s in scorers:
            if s._routing is not self._routing:
                raise ValueError(
                    "replica group must share one routing index"
                )
        self._replica_group = scorers

    # ------------------------------------------------------ hot-swap hooks

    def set_artifact(self, artifact: ServingArtifact) -> None:
        fe = [
            (cid, t.feature_shard)
            for cid, t in sorted(artifact.tables.items())
            if not t.is_random_effect
        ]
        re = [
            (cid, t.feature_shard, t.random_effect_type)
            for cid, t in sorted(artifact.tables.items())
            if t.is_random_effect
        ]
        if fe != self._fe_specs or re != self._re_specs:
            raise ValueError(
                "candidate artifact changes the coordinate structure "
                f"(have fe={self._fe_specs} re={self._re_specs}, candidate "
                f"fe={fe} re={re}) — a structural change needs a new scorer, "
                "not a hot swap"
            )
        for cid, shard in self._fe_specs:
            if artifact.tables[cid].dim != self._artifact.tables[cid].dim:
                raise ValueError(
                    f"candidate artifact changes fixed-effect dim of {cid!r}"
                )
        # grow every RE coordinate's routing BEFORE the new entity indexes
        # go live: a concurrent score_batch may resolve candidate-only
        # entities the instant the artifact reference flips, and route()
        # must already know the larger row space (they start non-resident,
        # score FE-only, and queue for admission — never an index error)
        for cid, _, _ in self._re_specs:
            n_new = artifact.tables[cid].n_entities
            routing = self._routing[cid]
            if n_new > routing.n_rows:
                routing.grow(n_new)
        self._artifact = artifact

    def update_fixed_effect(self, cid: str, weights: np.ndarray) -> None:
        import jax.numpy as jnp

        old = self._fe_params.get(cid)
        if old is None:
            raise ValueError(f"{cid!r} is not a fixed-effect coordinate")
        w = np.ascontiguousarray(weights, dtype=np.float32)
        if w.shape != old.shape:
            raise ValueError(
                f"fixed-effect update for {cid!r} has shape {w.shape}, "
                f"scorer holds {old.shape}"
            )
        self._fe_params[cid] = jnp.asarray(w)

    def update_random_effect_rows(
        self, cid: str, rows: np.ndarray, values: np.ndarray
    ) -> float:
        """Returns the request-path blocking seconds — the generation-flip
        window of :meth:`ShardedReTable.update_rows`. Callers doing
        blackout accounting (hot-swap manager, scenario swappers) subtract
        the non-blocking staging work from their wall clock."""
        provider = self._providers.get(cid)
        if provider is None:
            raise ValueError(f"{cid!r} is not a random-effect coordinate")
        group = self._replica_group or [self]
        # routing.lock (taken inside update_rows) is the OUTER lock; each
        # replica's write_lock is taken only across the generation flip
        return provider.update_rows(
            rows,
            values,
            replicas=[(s.write_lock, s._providers[cid]) for s in group],
        )

    def rebind_random_effect(self, cid: str, backing: np.ndarray) -> bool:
        """Rebuild one coordinate's device shards from a new backing table.
        Stays in-shape (False) when the shared routing's shard capacity
        already accommodates the new row count — then only the bytes are
        rebuilt; grows the routing (True, one expected retrace) otherwise.
        In multi-scorer mode the first replica to regrow updates the SHARED
        routing; later replicas see it already sized and rebuild in-shape.
        """
        provider = self._providers.get(cid)
        if provider is None:
            raise ValueError(f"{cid!r} is not a random-effect coordinate")
        backing = np.asarray(backing)
        n_new = backing.shape[0]
        routing = self._routing[cid]
        # hold the OLD routing's lock across the whole swap: an admission
        # step serialized behind it re-reads the provider afterwards and
        # retries against the new routing (see AdmissionController._admit)
        with routing.lock:
            old_cap = routing.shard_capacity
            if n_new > routing.device_rows or routing.n_rows != n_new:
                fresh = build_routing(
                    {cid: n_new},
                    num_shards=routing.num_shards,
                    device_budget_rows=self.device_budget_rows,
                    headroom_fraction=self._headroom_fraction,
                )[cid]
                if fresh.shard_capacity < old_cap:
                    # never shrink a shared layout other replicas still
                    # serve
                    fresh = CoordinateRouting(
                        n_rows=n_new,
                        num_shards=routing.num_shards,
                        shard_capacity=old_cap,
                        resident_rows=fresh.base_rows,
                    )
                self._routing.coordinates[cid] = fresh
                routing = fresh
            # build the replacement table (device placement of both
            # generation halves) OUTSIDE write_lock — concurrent scoring
            # keeps gathering the old provider; only the pointer install
            # blocks, and only for a reference assignment
            fresh_provider = ShardedReTable(backing, routing, mesh=self._mesh)
            with self.write_lock:
                self._providers[cid] = fresh_provider
            return routing.shard_capacity != old_cap

    def restore_random_effect(
        self, cid: str, provider, routing=None
    ) -> None:
        """Rollback hook: reinstall a snapshotted provider and — when the
        forward swap regrew the shared layout — the routing coordinate it
        was built against, as ONE step. Restoring only the provider would
        leave the scorer routing with the grown layout while gathering
        from the old-shape table (slots beyond the old capacity would read
        other rows' bytes)."""
        current = self._routing[cid]
        with current.lock:
            if routing is not None and routing is not current:
                self._routing.coordinates[cid] = routing
            with self.write_lock:
                self._providers[cid] = provider

    # -------------------------------------------------------------- scoring

    def _featurize(self, requests: Sequence[ScoreRequest], bucket: int):
        return featurize_requests(
            requests, len(requests), bucket, self._shard_nnz, self._shard_dim
        )

    def score_batch(
        self,
        requests: Sequence[ScoreRequest],
        bucket_size: Optional[int] = None,
        stages: Optional[dict] = None,
        view: Optional[Tuple[ServingArtifact, Dict[str, object]]] = None,
    ) -> List[ScoreResult]:
        """Score one bucket. ``view`` is the multi-model hook: an
        ``(artifact, fe_params)`` pair that overrides WHICH entity indexes
        resolve rows and WHICH fixed-effect vectors the jitted program
        reads — same shapes, same compiled program, same shared RE tables.
        The tenancy plane's :class:`VariantRegistry` builds one view per
        variant; ``view=None`` is the plain single-model path, bitwise
        unchanged."""
        n = len(requests)
        bucket = int(bucket_size) if bucket_size is not None else n
        if n == 0:
            return []
        if n > bucket:
            raise ValueError(f"{n} requests do not fit bucket size {bucket}")
        with span("serve/score_batch", n=n, bucket=bucket):
            return self._score_batch_impl(requests, n, bucket, stages, view)

    def _score_batch_impl(
        self,
        requests: Sequence[ScoreRequest],
        n: int,
        bucket: int,
        stages: Optional[dict] = None,
        view: Optional[Tuple[ServingArtifact, Dict[str, object]]] = None,
    ) -> List[ScoreResult]:
        import jax.numpy as jnp

        artifact = self._artifact if view is None else view[0]
        fe_params = self._fe_params if view is None else view[1]
        with span("serve/featurize", n=n):
            shards, offsets = self._featurize(requests, bucket)
        if stages is not None:
            stages["featurize_done"] = time.perf_counter()
        re_shards: Dict[str, np.ndarray] = {}
        slots: Dict[str, np.ndarray] = {}
        cold: Dict[int, List[str]] = {}
        sdelta_rows: Dict[str, np.ndarray] = {}
        with span("serve/route", n=n):
            for cid, feature_shard, re_type in self._re_specs:
                table = artifact.tables[cid]
                entity_rows = np.full(bucket, -1, dtype=np.int64)
                # mirror of GameScorer's route: ids stay C-level, and
                # the common every-request-carries-an-id case hands the
                # whole list to one vectorized lookup. Artifact entity
                # indexes are keyed by str, so non-str ids (ints from
                # upstream id tags) are coerced like ServingArtifact
                # .entity_row does.
                ids = [
                    e if type(e) is str or e is None else str(e)
                    for e in map(
                        operator.methodcaller("get", re_type),
                        map(_REQ_ENTITY_IDS, requests),
                    )
                ]
                if None not in ids:
                    entity_rows[:n] = table.entity_index.get_indices(ids)
                else:
                    where = [i for i, e in enumerate(ids) if e is not None]
                    if where:
                        entity_rows[np.asarray(where)] = (
                            table.entity_index.get_indices(
                                [ids[i] for i in where]
                            )
                        )
                routing = self._routing[cid]
                cid_shards, cid_slots, deferred = routing.route(
                    entity_rows[:n]
                )
                # importance plane: fold this batch into the EWMA request
                # frequencies (no-op under the default eviction policy).
                # Under the importance policy each request also deposits
                # its feature-vector norm, so importance bounds the row's
                # cumulative score-delta-vs-FE-only, not just its hit count.
                if routing.wants_feature_norms:
                    vals = shards[feature_shard][0]
                    routing.note_requests(
                        entity_rows[:n],
                        feature_norms=np.linalg.norm(vals[:n], axis=1),
                    )
                else:
                    routing.note_requests(entity_rows[:n])
                if routing.wants_score_deltas:
                    sdelta_rows[cid] = entity_rows[:n].copy()
                if deferred.size and self._admission is not None:
                    self._admission.note_deferred(cid, deferred)
                # pad rows (and this batch's FE-only rows) gather the zero
                # cold slot of shard 0
                full_shards = np.zeros(bucket, dtype=np.int32)
                full_slots = np.full(
                    bucket, routing.cold_slot, dtype=np.int32
                )
                full_shards[:n] = cid_shards
                full_slots[:n] = cid_slots
                re_shards[cid] = full_shards
                slots[cid] = full_slots
                served_cold = np.nonzero(
                    full_slots[:n] == routing.cold_slot
                )[0]
                for i in served_cold:
                    cold.setdefault(int(i), []).append(cid)

        if stages is not None:
            stages["route_done"] = time.perf_counter()
        batch = {
            "offsets": jnp.asarray(offsets),
            "shards": {
                shard: (jnp.asarray(v), jnp.asarray(i))
                for shard, (v, i) in shards.items()
            },
            "re_shards": {
                cid: jnp.asarray(s) for cid, s in re_shards.items()
            },
            "slots": {cid: jnp.asarray(s) for cid, s in slots.items()},
        }
        # write_lock spans table capture through host sync: a donated
        # admission scatter between the capture and the gather would
        # invalidate the captured array
        with self.write_lock:
            params = {
                "fe": fe_params,
                "re": {
                    cid: self._providers[cid].table
                    for cid, _, _ in self._re_specs
                },
            }
            with span("serve/gather_score", n=n, bucket=bucket):
                z, mean = self._score_fn(params, batch)
                if stages is not None:
                    # closes H2D + dispatch (includes any write_lock wait —
                    # admission interference spans make that attributable);
                    # the materialization below blocks on the device
                    stages["dispatch_done"] = time.perf_counter()
                z_list = np.asarray(z)[:n].tolist()
                mean_list = np.asarray(mean)[:n].tolist()
            deltas_host = None
            if sdelta_rows:
                # measured importance: the aux gather must run while the
                # captured params are still valid (a donated write after
                # the lock would invalidate them)
                with span("serve/score_delta", n=n):
                    d = self._redelta_fn(params, batch)
                    deltas_host = {
                        cid: np.asarray(d[cid])[:n] for cid in sdelta_rows
                    }
        if deltas_host is not None:
            for cid, rows_arr in sdelta_rows.items():
                self._routing[cid].note_score_deltas(
                    rows_arr, deltas_host[cid]
                )
        if stages is not None:
            stages["device_done"] = time.perf_counter()
        empty: Tuple[str, ...] = ()
        return [
            ScoreResult(
                request_id=req.request_id,
                score=z_list[i],
                mean=mean_list[i],
                cold_coordinates=tuple(cold[i]) if i in cold else empty,
            )
            for i, req in enumerate(requests)
        ]
