"""Request microbatcher: coalesce requests into fixed-shape padded batches.

Per-request scoring would make XLA dispatch (and on a cold scorer, compile)
the price of every request; per-request shapes would make it compile *per
request*. The batcher holds a FIFO of pending requests and drains them in
batches padded to one of a small, fixed set of bucket sizes — so the jit'd
scorer sees at most ``len(bucket_sizes)`` distinct shapes, ever.

Draining is synchronous: ``submit`` drains a full max-size batch whenever
enough requests are pending and returns any completed results; ``flush``
drains the remainder through the smallest bucket that fits. A real server
runs the deadline policy instead: construct with ``max_wait_s`` and call
``poll()`` from its event loop — once the OLDEST pending request has
waited past the deadline, everything pending drains through the smallest
fitting buckets, bounding queue wait without manual ``flush`` calls.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Callable, List, Optional, Sequence, Tuple

import numpy as np

from photon_ml_tpu.serving.metrics import ServingMetrics
from photon_ml_tpu.serving.scorer import GameScorer, ScoreRequest, ScoreResult
from photon_ml_tpu.telemetry import span

DEFAULT_BUCKET_SIZES = (1, 2, 4, 8, 16, 32)


class MicroBatcher:
    def __init__(
        self,
        scorer: GameScorer,
        bucket_sizes: Sequence[int] = DEFAULT_BUCKET_SIZES,
        metrics: Optional[ServingMetrics] = None,
        clock: Callable[[], float] = time.perf_counter,
        max_wait_s: Optional[float] = None,
        plane=None,
    ):
        if max_wait_s is not None and max_wait_s < 0:
            raise ValueError(f"max_wait_s must be >= 0, got {max_wait_s}")
        buckets = sorted({int(b) for b in bucket_sizes})
        if not buckets or buckets[0] < 1:
            raise ValueError(f"bucket sizes must be positive, got {bucket_sizes}")
        self.bucket_sizes: Tuple[int, ...] = tuple(buckets)
        self.max_bucket = buckets[-1]
        for cid, cache in scorer.caches.items():
            if cache.capacity < self.max_bucket:
                raise ValueError(
                    f"hot-entity cache for {cid!r} holds {cache.capacity} "
                    f"rows < max bucket size {self.max_bucket}; a single "
                    f"batch could evict rows it is about to gather"
                )
        self._scorer = scorer
        self._metrics = metrics
        # request plane (serving/requestplane.py): lifecycle sampling +
        # SLO feed; None (the default) costs one check per drained batch
        self._plane = plane
        self._stage_capable: Optional[bool] = None
        self._clock = clock
        self.max_wait_s = max_wait_s
        self._pending: "deque[Tuple[ScoreRequest, float]]" = deque()

    @property
    def queue_depth(self) -> int:
        return len(self._pending)

    def _bucket_for(self, n: int) -> int:
        for b in self.bucket_sizes:
            if b >= n:
                return b
        return self.max_bucket

    def submit(self, request: ScoreRequest) -> List[ScoreResult]:
        """Enqueue one request; returns results completed by this call
        (empty until a full max-size batch has accumulated)."""
        self._pending.append((request, self._clock()))
        out: List[ScoreResult] = []
        while len(self._pending) >= self.max_bucket:
            out.extend(self._drain(self.max_bucket))
        return out

    def submit_many(self, requests: Sequence[ScoreRequest]) -> List[ScoreResult]:
        """Enqueue a pre-collected run of requests in one call (the
        tenancy plane's bulk replay path). Same drain policy as
        :meth:`submit` — full max-size batches drain as they accumulate —
        but one clock read and one Python frame for the whole run instead
        of one per request."""
        if not requests:
            return []
        now = self._clock()
        self._pending.extend((r, now) for r in requests)
        out: List[ScoreResult] = []
        while len(self._pending) >= self.max_bucket:
            out.extend(self._drain(self.max_bucket))
        return out

    def flush(self) -> List[ScoreResult]:
        """Score everything still pending (smallest buckets that fit)."""
        out: List[ScoreResult] = []
        while self._pending:
            out.extend(self._drain(min(len(self._pending), self.max_bucket)))
        return out

    def poll(self, now: Optional[float] = None) -> List[ScoreResult]:
        """Deadline check: when the OLDEST pending request has waited at
        least ``max_wait_s``, drain everything pending through the smallest
        fitting buckets (younger requests ride along — padding slots are
        cheaper than a second dispatch). Otherwise a no-op. ``now`` defaults
        to the batcher's clock; pass it explicitly from an event loop that
        already read the time."""
        if self.max_wait_s is None:
            raise ValueError(
                "poll() needs a deadline: construct the batcher with "
                "max_wait_s"
            )
        if now is None:
            now = self._clock()
        out: List[ScoreResult] = []
        while self._pending and now - self._pending[0][1] >= self.max_wait_s:
            out.extend(self._drain(min(len(self._pending), self.max_bucket)))
        return out

    def _supports_stages(self) -> bool:
        """Whether the scorer's ``score_batch`` accepts a stage clock
        (checked once: drivers may pass scorers without stage support)."""
        cap = self._stage_capable
        if cap is None:
            import inspect

            try:
                cap = "stages" in inspect.signature(
                    self._scorer.score_batch
                ).parameters
            except (TypeError, ValueError):
                cap = False
            self._stage_capable = cap
        return cap

    def _drain(self, n: int) -> List[ScoreResult]:
        batch = [self._pending.popleft() for _ in range(n)]
        dequeued = self._clock()
        bucket = self._bucket_for(n)
        plane = self._plane
        sampled: Optional[List[int]] = None
        stages: Optional[dict] = None
        if plane is not None:
            sampled = plane.sample_indices(
                [req.request_id for req, _ in batch]
            )
            if sampled and self._supports_stages():
                stages = {}
        with span("serve/drain", n=n, bucket=bucket):
            if stages is not None:
                results = self._scorer.score_batch(
                    [req for req, _ in batch], bucket, stages=stages
                )
            else:
                results = self._scorer.score_batch(
                    [req for req, _ in batch], bucket
                )
        done = self._clock()
        if self._metrics is not None or plane is not None:
            enqueued = np.fromiter(
                (t for _, t in batch), dtype=np.float64, count=n
            )
            latencies = done - enqueued
            if self._metrics is not None:
                self._metrics.observe_batch(
                    n_real=n, bucket_size=bucket,
                    queue_depth=len(self._pending),
                )
                self._metrics.observe_queue_waits(dequeued - enqueued)
                self._metrics.observe_latencies(latencies, bucket_size=bucket)
            if plane is not None:
                if getattr(plane, "wants_request_ids", False):
                    # multi-tenant attribution: the id list is built only
                    # when the plane carries per-tenant SLO trackers
                    plane.observe_complete(
                        latencies,
                        request_ids=[req.request_id for req, _ in batch],
                    )
                else:
                    plane.observe_complete(latencies)
                if sampled:
                    plane.record_batch(
                        "sealed", bucket, n,
                        [
                            (batch[i][0].request_id, batch[i][1])
                            for i in sampled
                        ],
                        dequeued, stages, done,
                    )
        return results
