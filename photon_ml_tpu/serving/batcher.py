"""Request microbatcher: coalesce requests into fixed-shape padded batches.

Per-request scoring would make XLA dispatch (and on a cold scorer, compile)
the price of every request; per-request shapes would make it compile *per
request*. The batcher holds a FIFO of pending requests and drains them in
batches padded to one of a small, fixed set of bucket sizes — so the jit'd
scorer sees at most ``len(bucket_sizes)`` distinct shapes, ever.

Draining is synchronous: ``submit`` drains a full max-size batch whenever
enough requests are pending and returns any completed results; ``flush``
drains the remainder through the smallest bucket that fits. A real server
runs the deadline policy instead: construct with ``max_wait_s`` and call
``poll()`` from its event loop — once the OLDEST pending request has
waited past the deadline, everything pending drains through the smallest
fitting buckets, bounding queue wait without manual ``flush`` calls.

Two priority lanes mirror the continuous batcher: ``live`` (default)
holds request traffic; ``background`` holds admission warmups and
nearline replays and drains only when no live request is pending, so
background work never seals a bucket ahead of a live request. An
optional ``quota`` (tenancy token bucket) is consulted at drain time:
an over-budget tenant's requests are dropped from the bucket and
reported to the plane as errors charged to that tenant, instead of
occupying padded device slots.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Callable, List, Optional, Sequence, Tuple

import numpy as np

from photon_ml_tpu.serving.metrics import ServingMetrics
from photon_ml_tpu.serving.requestplane import tenant_of_request_id
from photon_ml_tpu.serving.scorer import GameScorer, ScoreRequest, ScoreResult
from photon_ml_tpu.telemetry import span

DEFAULT_BUCKET_SIZES = (1, 2, 4, 8, 16, 32)


class MicroBatcher:
    def __init__(
        self,
        scorer: GameScorer,
        bucket_sizes: Sequence[int] = DEFAULT_BUCKET_SIZES,
        metrics: Optional[ServingMetrics] = None,
        clock: Callable[[], float] = time.perf_counter,
        max_wait_s: Optional[float] = None,
        plane=None,
        quota=None,
    ):
        if max_wait_s is not None and max_wait_s < 0:
            raise ValueError(f"max_wait_s must be >= 0, got {max_wait_s}")
        buckets = sorted({int(b) for b in bucket_sizes})
        if not buckets or buckets[0] < 1:
            raise ValueError(f"bucket sizes must be positive, got {bucket_sizes}")
        self.bucket_sizes: Tuple[int, ...] = tuple(buckets)
        self.max_bucket = buckets[-1]
        for cid, cache in scorer.caches.items():
            if cache.capacity < self.max_bucket:
                raise ValueError(
                    f"hot-entity cache for {cid!r} holds {cache.capacity} "
                    f"rows < max bucket size {self.max_bucket}; a single "
                    f"batch could evict rows it is about to gather"
                )
        self._scorer = scorer
        self._metrics = metrics
        # request plane (serving/requestplane.py): lifecycle sampling +
        # SLO feed; None (the default) costs one check per drained batch
        self._plane = plane
        # tenant token bucket (tenancy/quota.py), consulted at DRAIN time
        self._quota = quota
        # set by OverloadController.attach(); consulted at submit (shed)
        # and polled from the drain path
        self._overload = None
        self._stage_capable: Optional[bool] = None
        self._clock = clock
        self.max_wait_s = max_wait_s
        self._pending: "deque[Tuple[ScoreRequest, float]]" = deque()
        # background lane: drains only when the live lane is empty
        self._pending_bg: "deque[Tuple[ScoreRequest, float]]" = deque()
        self.quota_shed_total = 0

    @property
    def queue_depth(self) -> int:
        return len(self._pending) + len(self._pending_bg)

    def _bucket_for(self, n: int) -> int:
        for b in self.bucket_sizes:
            if b >= n:
                return b
        return self.max_bucket

    def _drain_full(self, out: List[ScoreResult]) -> None:
        """Drain full live buckets; background buckets only once the live
        lane is empty (lane ordering: background never seals a bucket
        ahead of a live request)."""
        while len(self._pending) >= self.max_bucket:
            out.extend(self._drain(self.max_bucket))
        while (
            not self._pending and len(self._pending_bg) >= self.max_bucket
        ):
            out.extend(self._drain(self.max_bucket, lane=self._pending_bg))

    def submit(
        self, request: ScoreRequest, priority: str = "live"
    ) -> List[ScoreResult]:
        """Enqueue one request; returns results completed by this call
        (empty until a full max-size batch has accumulated)."""
        # single-request fast path: this runs once per request on the
        # sealed serving loop, so it must not pay submit_many's framing
        ovl = self._overload
        if priority != "live" or (ovl is not None and ovl.active):
            return self.submit_many((request,), priority=priority)
        self._pending.append((request, self._clock()))
        if len(self._pending) < self.max_bucket:
            return []
        out: List[ScoreResult] = []
        self._drain_full(out)
        return out

    def submit_many(
        self, requests: Sequence[ScoreRequest], priority: str = "live"
    ) -> List[ScoreResult]:
        """Enqueue a pre-collected run of requests in one call (the
        tenancy plane's bulk replay path). Same drain policy as
        :meth:`submit` — full max-size batches drain as they accumulate —
        but one clock read and one Python frame for the whole run instead
        of one per request. ``priority="background"`` routes to the
        background lane (drained only when no live request is pending).
        While an attached overload controller is active, live requests it
        can answer FE-only are resolved inline without queueing."""
        if priority not in ("live", "background"):
            raise ValueError(f"unknown priority {priority!r}")
        if not requests:
            return []
        out: List[ScoreResult] = []
        ovl = self._overload
        if ovl is not None and priority == "live" and ovl.active:
            kept = []
            for r in requests:
                res = ovl.try_shed(r)
                if res is None:
                    kept.append(r)
                else:
                    out.append(res)
            if out:
                plane = self._plane
                if plane is not None:
                    # shed answers ARE completions (FE-only, ~0 queue
                    # wait): feeding them lets the burn rate recover
                    lat = np.zeros(len(out), dtype=np.float64)
                    if getattr(plane, "wants_request_ids", False):
                        plane.observe_complete(
                            lat,
                            request_ids=[r.request_id for r in out],
                        )
                    else:
                        plane.observe_complete(lat)
            requests = kept
        now = self._clock()
        lane = self._pending if priority == "live" else self._pending_bg
        lane.extend((r, now) for r in requests)
        self._drain_full(out)
        return out

    def flush(self) -> List[ScoreResult]:
        """Score everything still pending (live lane first, then
        background, through the smallest buckets that fit)."""
        out: List[ScoreResult] = []
        while self._pending:
            out.extend(self._drain(min(len(self._pending), self.max_bucket)))
        while self._pending_bg:
            out.extend(
                self._drain(
                    min(len(self._pending_bg), self.max_bucket),
                    lane=self._pending_bg,
                )
            )
        return out

    def poll(self, now: Optional[float] = None) -> List[ScoreResult]:
        """Deadline check: when the OLDEST pending request has waited at
        least ``max_wait_s``, drain everything pending through the smallest
        fitting buckets (younger requests ride along — padding slots are
        cheaper than a second dispatch). Otherwise a no-op. ``now`` defaults
        to the batcher's clock; pass it explicitly from an event loop that
        already read the time. The background lane is deadline-drained only
        once the live lane is empty."""
        if self.max_wait_s is None:
            raise ValueError(
                "poll() needs a deadline: construct the batcher with "
                "max_wait_s"
            )
        if now is None:
            now = self._clock()
        out: List[ScoreResult] = []
        while self._pending and now - self._pending[0][1] >= self.max_wait_s:
            out.extend(self._drain(min(len(self._pending), self.max_bucket)))
        while (
            not self._pending
            and self._pending_bg
            and now - self._pending_bg[0][1] >= self.max_wait_s
        ):
            out.extend(
                self._drain(
                    min(len(self._pending_bg), self.max_bucket),
                    lane=self._pending_bg,
                )
            )
        return out

    def _supports_stages(self) -> bool:
        """Whether the scorer's ``score_batch`` accepts a stage clock
        (checked once: drivers may pass scorers without stage support)."""
        cap = self._stage_capable
        if cap is None:
            import inspect

            try:
                cap = "stages" in inspect.signature(
                    self._scorer.score_batch
                ).parameters
            except (TypeError, ValueError):
                cap = False
            self._stage_capable = cap
        return cap

    def _drain(self, n: int, lane=None) -> List[ScoreResult]:
        if lane is None:
            lane = self._pending
        batch = [lane.popleft() for _ in range(n)]
        if self._quota is not None:
            batch = self._apply_quota(batch)
            if not batch:
                if self._overload is not None:
                    self._overload.maybe_poll()
                return []
            n = len(batch)
        dequeued = self._clock()
        bucket = self._bucket_for(n)
        plane = self._plane
        sampled: Optional[List[int]] = None
        stages: Optional[dict] = None
        if plane is not None:
            sampled = plane.sample_indices(
                [req.request_id for req, _ in batch]
            )
            if sampled and self._supports_stages():
                stages = {}
        with span("serve/drain", n=n, bucket=bucket):
            if stages is not None:
                results = self._scorer.score_batch(
                    [req for req, _ in batch], bucket, stages=stages
                )
            else:
                results = self._scorer.score_batch(
                    [req for req, _ in batch], bucket
                )
        done = self._clock()
        if self._metrics is not None or plane is not None:
            enqueued = np.fromiter(
                (t for _, t in batch), dtype=np.float64, count=n
            )
            latencies = done - enqueued
            if self._metrics is not None:
                self._metrics.observe_batch(
                    n_real=n, bucket_size=bucket,
                    queue_depth=len(self._pending),
                )
                self._metrics.observe_queue_waits(dequeued - enqueued)
                self._metrics.observe_latencies(latencies, bucket_size=bucket)
            if plane is not None:
                if getattr(plane, "wants_request_ids", False):
                    # multi-tenant attribution: the id list is built only
                    # when the plane carries per-tenant SLO trackers
                    plane.observe_complete(
                        latencies,
                        request_ids=[req.request_id for req, _ in batch],
                    )
                else:
                    plane.observe_complete(latencies)
                if sampled:
                    plane.record_batch(
                        "sealed", bucket, n,
                        [
                            (batch[i][0].request_id, batch[i][1])
                            for i in sampled
                        ],
                        dequeued, stages, done,
                    )
        if self._overload is not None:
            # drain-path control step (rate-limited inside the controller)
            self._overload.maybe_poll()
        return results

    def _apply_quota(self, batch):
        """Drain-time tenant admission: requests from a tenant whose
        token bucket is exhausted are dropped from the bucket here and
        reported as errors charged to that tenant, instead of occupying
        padded device slots ahead of in-budget tenants. Untagged requests
        (no ``tenant!`` prefix) always pass."""
        quota = self._quota
        kept = []
        shed_ids: List[str] = []
        for item in batch:
            tenant = tenant_of_request_id(item[0].request_id)
            if tenant is None or quota.try_admit(tenant):
                kept.append(item)
            else:
                shed_ids.append(item[0].request_id)
        if shed_ids:
            self.quota_shed_total += len(shed_ids)
            plane = self._plane
            if plane is not None:
                if getattr(plane, "wants_request_ids", False):
                    plane.observe_errors(
                        len(shed_ids), request_ids=shed_ids
                    )
                else:
                    plane.observe_errors(len(shed_ids))
        return kept
