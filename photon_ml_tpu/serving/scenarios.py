"""Seeded traffic-shape scenarios for the serving replay harness.

Steady-state replay (``bench.py --serving``) regression-gates one traffic
shape. Production regressions live in the others: a diurnal ramp that
outruns admission, a burst storm that fills the backpressure queue, a
cold-entity flood that craters device residency, a hot-swap landing under
load. Each scenario here is a deterministic (seeded) reshaping of a base
request stream into phases driven through
:func:`~photon_ml_tpu.serving.replay.replay_requests`, with the request
plane sampling lifecycles and the SLO tracker keeping the verdict — so
``bench.py --scenarios`` emits one per-stage p50/p99 breakdown, residency
rate, and SLO verdict per traffic shape into ``BENCH_SCENARIOS.json``,
and the CI scenario sentinel gates them all.

Scenario catalog (``SCENARIO_NAMES``):

``steady``
    The base stream in even phases — the control arm; matches the
    ``--serving`` bench's shape.
``diurnal``
    A one-day load curve compressed into the replay: sinusoidal phase
    sizes (peak ~3x trough) with idle gaps before the troughs, so the
    batcher's deadline path and the admission tier see both regimes.
``burst_storm``
    Quiet trickle phases alternating with full-queue bursts — the shape
    that exposes backpressure and queue-wait tails.
``cold_entity_flood``
    A steady warmup, then phases whose entity ids are remapped (seeded)
    to the least-popular tail — device residency collapses and the
    admission tier has to re-admit under traffic.
``hot_swap_under_load``
    The steady shape with concurrent hot-swap row updates during the
    middle phases (a swapper thread contends with scoring through the
    write locks) — the arm that proves swap pauses land in the p99
    breakdown as ``swap_pause`` interference, not as unexplained time.

Tenancy scenarios (``TENANCY_SCENARIOS``, run through a
:class:`~photon_ml_tpu.serving.tenancy.TenancyPlane` instead of plain
replay; their requests are tenant-tagged and their result docs carry
per-tenant SLO verdicts):

``tenant_isolation``
    Round-robin multi-tenant traffic, with the FIRST tenant flooding at
    several times its contracted rate during the middle phases. The
    quota must shed the flood onto the flooder's own error budget while
    every other tenant's p99 and budget hold — the noisy-neighbour gate.
``ramped_rollout``
    Steady multi-tenant traffic while a candidate variant's ramp walks
    1% -> 50% -> 100% across phases, hot, without draining the server;
    variant routing stays sticky per request id as the boundary moves.
``nearline_loop``
    The end-to-end loop: a nearline trainer emits fingerprint-chained
    per-variant deltas (save -> discover -> chain-check -> apply) while
    the scorer hot-swaps them per variant under replayed multi-tenant
    traffic.
"""

from __future__ import annotations

import dataclasses
import math
import threading
import time
from collections import Counter
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from photon_ml_tpu.serving.replay import replay_requests
from photon_ml_tpu.serving.scorer import ScoreRequest

SCENARIO_NAMES = (
    "steady",
    "diurnal",
    "burst_storm",
    "cold_entity_flood",
    "hot_swap_under_load",
    "tenant_isolation",
    "ramped_rollout",
    "nearline_loop",
)

# the scenarios that need a TenancyPlane (multi-tenant, variant-routed)
TENANCY_SCENARIOS = (
    "tenant_isolation",
    "ramped_rollout",
    "nearline_loop",
)

DEFAULT_TENANTS = ("alpha", "beta", "gamma")

# how much harder the flooding tenant pushes than its round-robin share
# in ``tenant_isolation``
FLOOD_FACTOR = 3

# stable per-scenario seed offsets: the same (seed, name) always produces
# the same phase layout and entity remapping
_NAME_SEEDS = {name: 1000 + i for i, name in enumerate(SCENARIO_NAMES)}


@dataclasses.dataclass
class ScenarioPhase:
    """One replay leg: a request slice, an optional idle gap before it,
    and whether hot-swap updates run concurrently with it. Tenancy
    phases may additionally move a variant ramp before replaying
    (``ramp_percent``) or run the nearline emit->swap loop concurrently
    (``nearline``)."""

    requests: List[ScoreRequest]
    pause_before_s: float = 0.0
    swap: bool = False
    ramp_percent: Optional[float] = None
    nearline: bool = False


@dataclasses.dataclass
class Scenario:
    name: str
    seed: int
    phases: List[ScenarioPhase]
    description: str = ""
    # tenancy scenarios: the tenants the stream is tagged with, and the
    # variant whose ramp the phases' ``ramp_percent`` steps drive
    tenants: tuple = ()
    ramp_variant: Optional[str] = None

    @property
    def num_requests(self) -> int:
        return sum(len(p.requests) for p in self.phases)


def _cold_remap(
    requests: Sequence[ScoreRequest], rng: np.random.Generator
) -> List[ScoreRequest]:
    """Rewrite entity ids to the least-popular half of the observed id
    population (per RE type) — a flood of entities that are known to the
    model but unlikely to be device-resident."""
    freq: Dict[str, Counter] = {}
    for req in requests:
        for re_type, eid in req.entity_ids.items():
            freq.setdefault(re_type, Counter())[eid] += 1
    tails: Dict[str, List[str]] = {}
    for re_type, counts in freq.items():
        ranked = [e for e, _ in counts.most_common()]
        tail = ranked[len(ranked) // 2:]
        tails[re_type] = tail if tail else ranked
    out: List[ScoreRequest] = []
    for req in requests:
        remapped = {
            re_type: tails[re_type][int(rng.integers(len(tails[re_type])))]
            for re_type in req.entity_ids
        }
        out.append(
            ScoreRequest(
                request_id=f"{req.request_id}-cold",
                features=req.features,
                entity_ids=remapped,
                offset=req.offset,
            )
        )
    return out


def _tag(request: ScoreRequest, tenant: str) -> ScoreRequest:
    """Tenant-tag one request (see ``requestplane.TENANT_SEP``)."""
    from photon_ml_tpu.serving.requestplane import TENANT_SEP

    return dataclasses.replace(
        request, request_id=f"{tenant}{TENANT_SEP}{request.request_id}"
    )


# ramp walk for ``ramped_rollout``: interpolated onto num_phases, always
# starting dark and ending fully ramped
_RAMP_STEPS = (0.0, 1.0, 5.0, 10.0, 25.0, 50.0, 75.0, 100.0)


def build_scenario(
    name: str,
    requests: Sequence[ScoreRequest],
    seed: int = 0,
    num_phases: int = 8,
    pause_s: float = 0.01,
    tenants: Sequence[str] = DEFAULT_TENANTS,
    ramp_variant: str = "candidate",
) -> Scenario:
    """Deterministically reshape ``requests`` into the named scenario.

    ``pause_s`` scales the idle gaps (diurnal troughs, storm quiets);
    smoke/CI callers shrink it, the committed bench uses the default.
    ``tenants``/``ramp_variant`` apply only to the tenancy scenarios:
    the stream is tagged round-robin across ``tenants``, and the
    ``ramped_rollout`` phases drive ``ramp_variant``'s ramp.
    """
    if name not in SCENARIO_NAMES:
        raise ValueError(
            f"unknown scenario {name!r} (expected one of {SCENARIO_NAMES})"
        )
    requests = list(requests)
    n = len(requests)
    if n == 0:
        raise ValueError("scenario needs a non-empty request stream")
    num_phases = max(2, int(num_phases))
    rng = np.random.default_rng(int(seed) + _NAME_SEEDS[name])
    if name in TENANCY_SCENARIOS:
        tenants = tuple(tenants)
        if len(tenants) < 2:
            raise ValueError(
                f"tenancy scenario {name!r} needs >= 2 tenants, got {tenants}"
            )
        requests = [
            _tag(req, tenants[i % len(tenants)])
            for i, req in enumerate(requests)
        ]
    even = [
        requests[(k * n) // num_phases : ((k + 1) * n) // num_phases]
        for k in range(num_phases)
    ]

    if name == "steady":
        phases = [ScenarioPhase(chunk) for chunk in even if chunk]
        desc = "even phases, no idle gaps (control arm)"
    elif name == "diurnal":
        # sinusoidal weights, peak ~3x trough; idle gaps ahead of troughs
        w = np.array(
            [
                1.0 + 0.5 * math.sin(2.0 * math.pi * k / num_phases)
                for k in range(num_phases)
            ]
        )
        bounds = np.floor(np.cumsum(w) / w.sum() * n).astype(int)
        lo = 0
        phases = []
        w_min, w_max = float(w.min()), float(w.max())
        for k, hi in enumerate(bounds):
            chunk = requests[lo:int(hi)]
            lo = int(hi)
            if not chunk:
                continue
            # trough phases idle first: low weight -> long gap
            frac = (w_max - float(w[k])) / max(w_max - w_min, 1e-9)
            phases.append(ScenarioPhase(chunk, pause_before_s=pause_s * frac))
        desc = "sinusoidal load curve, peak ~3x trough, idle troughs"
    elif name == "burst_storm":
        # odd phases are trickles, even phases dump a double share at once
        phases = []
        for k, chunk in enumerate(even):
            if not chunk:
                continue
            if k % 2 == 0:
                phases.append(ScenarioPhase(chunk, pause_before_s=pause_s))
            else:
                keep = chunk[: max(1, len(chunk) // 8)]
                spill = chunk[len(keep):]
                phases.append(ScenarioPhase(keep))
                if spill:
                    if k + 1 < num_phases:
                        # the spilled share rides the NEXT storm
                        even[k + 1] = spill + even[k + 1]
                    else:
                        # trailing trickle: its spill lands as a closing
                        # burst so the stream is preserved exactly
                        phases.append(
                            ScenarioPhase(spill, pause_before_s=pause_s)
                        )
        desc = "idle gaps then full-queue bursts (backpressure shape)"
    elif name == "cold_entity_flood":
        warm = num_phases // 2
        phases = [ScenarioPhase(chunk) for chunk in even[:warm] if chunk]
        for chunk in even[warm:]:
            if chunk:
                phases.append(ScenarioPhase(_cold_remap(chunk, rng)))
        desc = "steady warmup, then entity ids remapped to the cold tail"
    elif name == "hot_swap_under_load":
        phases = []
        for k, chunk in enumerate(even):
            if not chunk:
                continue
            swap = 0 < k < num_phases - 1  # swaps land mid-run, under load
            phases.append(ScenarioPhase(chunk, swap=swap))
        desc = "steady load with concurrent hot-swap row updates mid-run"
    elif name == "tenant_isolation":
        flooder = tenants[0]
        phases = []
        for k, chunk in enumerate(even):
            if not chunk:
                continue
            if num_phases // 3 <= k < (2 * num_phases) // 3:
                # the flooder replays its share FLOOD_FACTOR extra times
                # on top of everyone's normal traffic, same instant
                flood = [
                    _tag(
                        dataclasses.replace(
                            req, request_id=f"{req.request_id}-f{j}"
                        ),
                        flooder,
                    )
                    for j in range(FLOOD_FACTOR)
                    for req in chunk
                ]
                chunk = chunk + flood
            phases.append(ScenarioPhase(chunk))
        desc = (
            f"tenant {flooder!r} floods {FLOOD_FACTOR + 1}x mid-run; other "
            "tenants' latency and error budgets must hold"
        )
    elif name == "ramped_rollout":
        steps = np.interp(
            np.linspace(0.0, 1.0, num_phases),
            np.linspace(0.0, 1.0, len(_RAMP_STEPS)),
            _RAMP_STEPS,
        )
        phases = []
        for k, chunk in enumerate(even):
            if not chunk:
                continue
            phases.append(
                ScenarioPhase(chunk, ramp_percent=float(steps[k]))
            )
        desc = (
            f"variant {ramp_variant!r} ramps "
            f"{'->'.join(f'{s:g}%' for s in _RAMP_STEPS)} under steady "
            "multi-tenant load"
        )
    else:  # nearline_loop
        phases = []
        for k, chunk in enumerate(even):
            if not chunk:
                continue
            nearline = 0 < k < num_phases - 1  # deltas land mid-run
            phases.append(ScenarioPhase(chunk, nearline=nearline))
        desc = (
            "nearline trainer emits chained per-variant deltas; the "
            "scorer discovers and hot-swaps them under replayed traffic"
        )
    return Scenario(
        name=name,
        seed=int(seed),
        phases=phases,
        description=desc,
        tenants=tuple(tenants) if name in TENANCY_SCENARIOS else (),
        ramp_variant=ramp_variant if name in TENANCY_SCENARIOS else None,
    )


def make_row_swap_fn(
    scorers,
    metrics,
    rows_per_swap: int = 32,
    scale: float = 0.01,
    seed: int = 0,
) -> Optional[Callable[[], None]]:
    """A hot-swap driver for ``hot_swap_under_load``: each call rewrites
    ``rows_per_swap`` random rows of one RE coordinate in place through
    the lead scorer's ``update_random_effect_rows`` (fanning out to every
    replica) and reports the measured pause via ``metrics.observe_swap``
    — the real write-lock contention path, generation bumps included.
    Returns None when the scorer exposes no updatable RE coordinate."""
    scorers = list(scorers) if isinstance(scorers, (list, tuple)) else [scorers]
    lead = scorers[0]
    artifact = getattr(lead, "artifact", None)
    if artifact is None:
        return None
    re_cids = [
        cid for cid, t in sorted(artifact.tables.items()) if t.is_random_effect
    ]
    if not re_cids:
        return None
    rng = np.random.default_rng(seed + 77)
    state = {"generation": getattr(metrics, "current_generation", 0)}

    def _swap() -> None:
        cid = re_cids[int(rng.integers(len(re_cids)))]
        table = artifact.tables[cid]
        n_rows, dim = table.weights.shape
        k = min(rows_per_swap, n_rows)
        rows = rng.choice(n_rows, size=k, replace=False)
        values = (
            np.asarray(table.weights[rows], dtype=np.float32)
            + rng.standard_normal((k, dim)).astype(np.float32) * scale
        )
        t0 = time.perf_counter()
        ret = lead.update_random_effect_rows(cid, rows, values)
        # sharded scorers stage into the spare generation half and return
        # the request-path blocking seconds (the flip window) — that is
        # the pause scoring threads actually saw; a None return (the
        # single-table scorer mutates live tables) keeps wall clock
        pause = ret if isinstance(ret, float) else time.perf_counter() - t0
        state["generation"] += 1
        if metrics is not None:
            metrics.observe_swap(
                generation=state["generation"], rows_updated=k,
                blackout_s=pause,
            )

    return _swap


def run_scenario(
    scenario: Scenario,
    scorers,
    bucket_sizes: Sequence[int],
    metrics,
    plane=None,
    slo=None,
    admission=None,
    continuous: bool = True,
    max_wait_s: float = 0.002,
    max_queue: Optional[int] = None,
    swap_fn: Optional[Callable[[], None]] = None,
    swap_interval_s: float = 0.01,
    tenancy=None,
    nearline_fn: Optional[Callable[[], object]] = None,
    nearline_interval_s: float = 0.02,
    overload=None,
) -> dict:
    """Drive one scenario through ``replay_requests`` phase by phase and
    return its result document: per-stage p50/p99 breakdown (from the
    request plane), residency rate, throughput, and the SLO verdict.

    The caller owns the metrics/plane/slo objects (fresh per scenario for
    isolated verdicts) and the scorers/admission (shared across scenarios
    for realistic warm state, or fresh for isolation).

    Tenancy scenarios additionally take ``tenancy`` (a
    :class:`~photon_ml_tpu.serving.tenancy.TenancyPlane`; phases then
    replay through it — quota, router, per-variant batchers — instead of
    plain replay) and, for ``nearline_loop``, ``nearline_fn`` (one
    nearline trainer tick: emit + swap one delta generation per variant),
    which runs concurrently with every ``nearline`` phase the way
    ``swap_fn`` does for hot-swap phases. The result doc then carries
    per-tenant requests/sheds/SLO verdicts, observed variant shares, and
    the nearline swap ledger.

    ``overload`` (an
    :class:`~photon_ml_tpu.serving.overload.OverloadController`) closes
    the SLO-burn loop on the non-tenancy path: ``replay_requests``
    attaches it to the batcher it builds, and the doc carries its final
    ``status()``."""
    if tenancy is None and scenario.tenants:
        raise ValueError(
            f"scenario {scenario.name!r} declares tenants "
            f"{scenario.tenants} and needs a TenancyPlane (tenancy=...)"
        )
    results = []
    nearline_reports: List[object] = []
    t0 = time.perf_counter()
    for phase in scenario.phases:
        if phase.pause_before_s > 0:
            time.sleep(phase.pause_before_s)
        if phase.ramp_percent is not None and tenancy is not None:
            # hot ramp move: no drain, no pause — the router boundary
            # shifts and the very next routed request sees it
            tenancy.router.set_ramp(
                scenario.ramp_variant, phase.ramp_percent
            )
        stop_swapper = None
        swapper = None
        background = swap_fn if phase.swap else None
        interval = swap_interval_s
        if phase.nearline and nearline_fn is not None:

            def _nearline_tick():
                nearline_reports.extend(nearline_fn() or ())

            background = _nearline_tick
            interval = nearline_interval_s
        if background is not None:
            stop_swapper = threading.Event()

            def _swap_loop(evt=stop_swapper, fn=background, wait=interval):
                while not evt.is_set():
                    fn()
                    evt.wait(wait)

            swapper = threading.Thread(
                target=_swap_loop, name="scenario-swapper", daemon=True
            )
            swapper.start()
        try:
            if tenancy is not None:
                res = tenancy.replay(phase.requests)
                snapshot = None
            else:
                res, snapshot = replay_requests(
                    scorers,
                    phase.requests,
                    bucket_sizes=bucket_sizes,
                    metrics=metrics,
                    model_id=f"scenario-{scenario.name}",
                    continuous=continuous,
                    max_wait_s=max_wait_s,
                    max_queue=max_queue,
                    admission=admission,
                    plane=plane,
                    overload=overload,
                )
            results.extend(res)
        finally:
            if stop_swapper is not None:
                stop_swapper.set()
                swapper.join()
    wall = time.perf_counter() - t0
    if tenancy is not None:
        # the tenancy path batches in-process; build the same snapshot
        # replay_requests would have, from the shared metrics object
        lead = tenancy.registry.lead
        snapshot = metrics.snapshot(
            cache_stats=lead.cache_stats(),
            compile_count=lead.compile_count,
            residency=(
                lead.residency_stats()
                if hasattr(lead, "residency_stats")
                else None
            ),
        )

    doc: dict = {
        "name": scenario.name,
        "description": scenario.description,
        "seed": scenario.seed,
        "num_phases": len(scenario.phases),
        "num_requests": len(results),
        "wall_seconds": round(wall, 6),
        "requests_per_s": round(len(results) / wall, 3) if wall > 0 else 0.0,
    }
    for key in (
        "latency_p50_s", "latency_p99_s", "batch_fill_ratio",
        "device_resident_rate", "deferred_rate",
    ):
        if key in snapshot:
            doc[key] = snapshot[key]
    if "swaps" in snapshot:
        doc["swaps"] = snapshot["swaps"]
    if plane is not None:
        report = plane.live_report()
        report.pop("slo", None)
        doc["request_plane"] = report
    tracker = slo if slo is not None else getattr(plane, "_slo", None)
    if tracker is not None:
        status = tracker.status()
        doc["slo"] = status
        doc["slo_verdict"] = status["verdict"]
    if overload is not None:
        doc["overload"] = overload.status()
    if tenancy is not None:
        doc["tenants"] = {}
        flooder = scenario.tenants[0] if scenario.tenants else None
        for tenant, tslo in sorted(tenancy.plane.tenant_slos.items()):
            status = tslo.status()
            doc["tenants"][tenant] = {
                "requests": tenancy.plane.tenant_requests.get(tenant, 0),
                "errors": tenancy.plane.tenant_errors.get(tenant, 0),
                "slo": status,
                "slo_verdict": status["verdict"],
            }
        if tenancy.quota is not None:
            qstats = tenancy.quota.stats()["tenants"]
            doc["tenant_shed"] = {
                t: s["shed"] for t, s in qstats.items() if s["shed"]
            }
        doc["variant_shares"] = {
            v: round(s, 6) for v, s in tenancy.router.shares().items()
        }
        doc["variants"] = tenancy.registry.stats()
        if scenario.name == "tenant_isolation" and flooder is not None:
            # the gate: every NON-flooding tenant's budget must hold
            doc["isolation_ok"] = all(
                info["slo_verdict"] == "ok"
                for tenant, info in doc["tenants"].items()
                if tenant != flooder
            )
            doc["flooding_tenant"] = flooder
            if tenancy.quota is not None:
                # the quota gate: the flood was shed onto the FLOODER's
                # budget only — a shed landing on any other tenant means
                # the token bucket charged the wrong neighbour
                qstats = tenancy.quota.stats()["tenants"]
                doc["flood_shed_ok"] = qstats.get(flooder, {}).get(
                    "shed", 0
                ) > 0 and all(
                    s["shed"] == 0
                    for t, s in qstats.items()
                    if t != flooder
                )
        if nearline_reports:
            doc["nearline"] = {
                "deltas_applied": sum(
                    1 for r in nearline_reports if not r.rolled_back
                ),
                "rollbacks": sum(
                    1 for r in nearline_reports if r.rolled_back
                ),
                "generations": {
                    vid: tenancy.registry.state(vid).generation
                    for vid in sorted(
                        {r.variant_id for r in nearline_reports}
                    )
                },
            }
    return doc
