"""Seeded traffic-shape scenarios for the serving replay harness.

Steady-state replay (``bench.py --serving``) regression-gates one traffic
shape. Production regressions live in the others: a diurnal ramp that
outruns admission, a burst storm that fills the backpressure queue, a
cold-entity flood that craters device residency, a hot-swap landing under
load. Each scenario here is a deterministic (seeded) reshaping of a base
request stream into phases driven through
:func:`~photon_ml_tpu.serving.replay.replay_requests`, with the request
plane sampling lifecycles and the SLO tracker keeping the verdict — so
``bench.py --scenarios`` emits one per-stage p50/p99 breakdown, residency
rate, and SLO verdict per traffic shape into ``BENCH_SCENARIOS.json``,
and the CI scenario sentinel gates them all.

Scenario catalog (``SCENARIO_NAMES``):

``steady``
    The base stream in even phases — the control arm; matches the
    ``--serving`` bench's shape.
``diurnal``
    A one-day load curve compressed into the replay: sinusoidal phase
    sizes (peak ~3x trough) with idle gaps before the troughs, so the
    batcher's deadline path and the admission tier see both regimes.
``burst_storm``
    Quiet trickle phases alternating with full-queue bursts — the shape
    that exposes backpressure and queue-wait tails.
``cold_entity_flood``
    A steady warmup, then phases whose entity ids are remapped (seeded)
    to the least-popular tail — device residency collapses and the
    admission tier has to re-admit under traffic.
``hot_swap_under_load``
    The steady shape with concurrent hot-swap row updates during the
    middle phases (a swapper thread contends with scoring through the
    write locks) — the arm that proves swap pauses land in the p99
    breakdown as ``swap_pause`` interference, not as unexplained time.
"""

from __future__ import annotations

import dataclasses
import math
import threading
import time
from collections import Counter
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from photon_ml_tpu.serving.replay import replay_requests
from photon_ml_tpu.serving.scorer import ScoreRequest

SCENARIO_NAMES = (
    "steady",
    "diurnal",
    "burst_storm",
    "cold_entity_flood",
    "hot_swap_under_load",
)

# stable per-scenario seed offsets: the same (seed, name) always produces
# the same phase layout and entity remapping
_NAME_SEEDS = {name: 1000 + i for i, name in enumerate(SCENARIO_NAMES)}


@dataclasses.dataclass
class ScenarioPhase:
    """One replay leg: a request slice, an optional idle gap before it,
    and whether hot-swap updates run concurrently with it."""

    requests: List[ScoreRequest]
    pause_before_s: float = 0.0
    swap: bool = False


@dataclasses.dataclass
class Scenario:
    name: str
    seed: int
    phases: List[ScenarioPhase]
    description: str = ""

    @property
    def num_requests(self) -> int:
        return sum(len(p.requests) for p in self.phases)


def _cold_remap(
    requests: Sequence[ScoreRequest], rng: np.random.Generator
) -> List[ScoreRequest]:
    """Rewrite entity ids to the least-popular half of the observed id
    population (per RE type) — a flood of entities that are known to the
    model but unlikely to be device-resident."""
    freq: Dict[str, Counter] = {}
    for req in requests:
        for re_type, eid in req.entity_ids.items():
            freq.setdefault(re_type, Counter())[eid] += 1
    tails: Dict[str, List[str]] = {}
    for re_type, counts in freq.items():
        ranked = [e for e, _ in counts.most_common()]
        tail = ranked[len(ranked) // 2:]
        tails[re_type] = tail if tail else ranked
    out: List[ScoreRequest] = []
    for req in requests:
        remapped = {
            re_type: tails[re_type][int(rng.integers(len(tails[re_type])))]
            for re_type in req.entity_ids
        }
        out.append(
            ScoreRequest(
                request_id=f"{req.request_id}-cold",
                features=req.features,
                entity_ids=remapped,
                offset=req.offset,
            )
        )
    return out


def build_scenario(
    name: str,
    requests: Sequence[ScoreRequest],
    seed: int = 0,
    num_phases: int = 8,
    pause_s: float = 0.01,
) -> Scenario:
    """Deterministically reshape ``requests`` into the named scenario.

    ``pause_s`` scales the idle gaps (diurnal troughs, storm quiets);
    smoke/CI callers shrink it, the committed bench uses the default.
    """
    if name not in SCENARIO_NAMES:
        raise ValueError(
            f"unknown scenario {name!r} (expected one of {SCENARIO_NAMES})"
        )
    requests = list(requests)
    n = len(requests)
    if n == 0:
        raise ValueError("scenario needs a non-empty request stream")
    num_phases = max(2, int(num_phases))
    rng = np.random.default_rng(int(seed) + _NAME_SEEDS[name])
    even = [
        requests[(k * n) // num_phases : ((k + 1) * n) // num_phases]
        for k in range(num_phases)
    ]

    if name == "steady":
        phases = [ScenarioPhase(chunk) for chunk in even if chunk]
        desc = "even phases, no idle gaps (control arm)"
    elif name == "diurnal":
        # sinusoidal weights, peak ~3x trough; idle gaps ahead of troughs
        w = np.array(
            [
                1.0 + 0.5 * math.sin(2.0 * math.pi * k / num_phases)
                for k in range(num_phases)
            ]
        )
        bounds = np.floor(np.cumsum(w) / w.sum() * n).astype(int)
        lo = 0
        phases = []
        w_min, w_max = float(w.min()), float(w.max())
        for k, hi in enumerate(bounds):
            chunk = requests[lo:int(hi)]
            lo = int(hi)
            if not chunk:
                continue
            # trough phases idle first: low weight -> long gap
            frac = (w_max - float(w[k])) / max(w_max - w_min, 1e-9)
            phases.append(ScenarioPhase(chunk, pause_before_s=pause_s * frac))
        desc = "sinusoidal load curve, peak ~3x trough, idle troughs"
    elif name == "burst_storm":
        # odd phases are trickles, even phases dump a double share at once
        phases = []
        for k, chunk in enumerate(even):
            if not chunk:
                continue
            if k % 2 == 0:
                phases.append(ScenarioPhase(chunk, pause_before_s=pause_s))
            else:
                keep = chunk[: max(1, len(chunk) // 8)]
                spill = chunk[len(keep):]
                phases.append(ScenarioPhase(keep))
                if spill:
                    if k + 1 < num_phases:
                        # the spilled share rides the NEXT storm
                        even[k + 1] = spill + even[k + 1]
                    else:
                        # trailing trickle: its spill lands as a closing
                        # burst so the stream is preserved exactly
                        phases.append(
                            ScenarioPhase(spill, pause_before_s=pause_s)
                        )
        desc = "idle gaps then full-queue bursts (backpressure shape)"
    elif name == "cold_entity_flood":
        warm = num_phases // 2
        phases = [ScenarioPhase(chunk) for chunk in even[:warm] if chunk]
        for chunk in even[warm:]:
            if chunk:
                phases.append(ScenarioPhase(_cold_remap(chunk, rng)))
        desc = "steady warmup, then entity ids remapped to the cold tail"
    else:  # hot_swap_under_load
        phases = []
        for k, chunk in enumerate(even):
            if not chunk:
                continue
            swap = 0 < k < num_phases - 1  # swaps land mid-run, under load
            phases.append(ScenarioPhase(chunk, swap=swap))
        desc = "steady load with concurrent hot-swap row updates mid-run"
    return Scenario(name=name, seed=int(seed), phases=phases, description=desc)


def make_row_swap_fn(
    scorers,
    metrics,
    rows_per_swap: int = 32,
    scale: float = 0.01,
    seed: int = 0,
) -> Optional[Callable[[], None]]:
    """A hot-swap driver for ``hot_swap_under_load``: each call rewrites
    ``rows_per_swap`` random rows of one RE coordinate in place through
    the lead scorer's ``update_random_effect_rows`` (fanning out to every
    replica) and reports the measured pause via ``metrics.observe_swap``
    — the real write-lock contention path, generation bumps included.
    Returns None when the scorer exposes no updatable RE coordinate."""
    scorers = list(scorers) if isinstance(scorers, (list, tuple)) else [scorers]
    lead = scorers[0]
    artifact = getattr(lead, "artifact", None)
    if artifact is None:
        return None
    re_cids = [
        cid for cid, t in sorted(artifact.tables.items()) if t.is_random_effect
    ]
    if not re_cids:
        return None
    rng = np.random.default_rng(seed + 77)
    state = {"generation": getattr(metrics, "current_generation", 0)}

    def _swap() -> None:
        cid = re_cids[int(rng.integers(len(re_cids)))]
        table = artifact.tables[cid]
        n_rows, dim = table.weights.shape
        k = min(rows_per_swap, n_rows)
        rows = rng.choice(n_rows, size=k, replace=False)
        values = (
            np.asarray(table.weights[rows], dtype=np.float32)
            + rng.standard_normal((k, dim)).astype(np.float32) * scale
        )
        t0 = time.perf_counter()
        lead.update_random_effect_rows(cid, rows, values)
        pause = time.perf_counter() - t0
        state["generation"] += 1
        if metrics is not None:
            metrics.observe_swap(
                generation=state["generation"], rows_updated=k,
                blackout_s=pause,
            )

    return _swap


def run_scenario(
    scenario: Scenario,
    scorers,
    bucket_sizes: Sequence[int],
    metrics,
    plane=None,
    slo=None,
    admission=None,
    continuous: bool = True,
    max_wait_s: float = 0.002,
    max_queue: Optional[int] = None,
    swap_fn: Optional[Callable[[], None]] = None,
    swap_interval_s: float = 0.01,
) -> dict:
    """Drive one scenario through ``replay_requests`` phase by phase and
    return its result document: per-stage p50/p99 breakdown (from the
    request plane), residency rate, throughput, and the SLO verdict.

    The caller owns the metrics/plane/slo objects (fresh per scenario for
    isolated verdicts) and the scorers/admission (shared across scenarios
    for realistic warm state, or fresh for isolation)."""
    results = []
    t0 = time.perf_counter()
    for phase in scenario.phases:
        if phase.pause_before_s > 0:
            time.sleep(phase.pause_before_s)
        stop_swapper = None
        swapper = None
        if phase.swap and swap_fn is not None:
            stop_swapper = threading.Event()

            def _swap_loop(evt=stop_swapper):
                while not evt.is_set():
                    swap_fn()
                    evt.wait(swap_interval_s)

            swapper = threading.Thread(
                target=_swap_loop, name="scenario-swapper", daemon=True
            )
            swapper.start()
        try:
            res, snapshot = replay_requests(
                scorers,
                phase.requests,
                bucket_sizes=bucket_sizes,
                metrics=metrics,
                model_id=f"scenario-{scenario.name}",
                continuous=continuous,
                max_wait_s=max_wait_s,
                max_queue=max_queue,
                admission=admission,
                plane=plane,
            )
            results.extend(res)
        finally:
            if stop_swapper is not None:
                stop_swapper.set()
                swapper.join()
    wall = time.perf_counter() - t0

    doc: dict = {
        "name": scenario.name,
        "description": scenario.description,
        "seed": scenario.seed,
        "num_phases": len(scenario.phases),
        "num_requests": len(results),
        "wall_seconds": round(wall, 6),
        "requests_per_s": round(len(results) / wall, 3) if wall > 0 else 0.0,
    }
    for key in (
        "latency_p50_s", "latency_p99_s", "batch_fill_ratio",
        "device_resident_rate", "deferred_rate",
    ):
        if key in snapshot:
            doc[key] = snapshot[key]
    if "swaps" in snapshot:
        doc["swaps"] = snapshot["swaps"]
    if plane is not None:
        report = plane.live_report()
        report.pop("slo", None)
        doc["request_plane"] = report
    tracker = slo if slo is not None else getattr(plane, "_slo", None)
    if tracker is not None:
        status = tracker.status()
        doc["slo"] = status
        doc["slo_verdict"] = status["verdict"]
    return doc
