"""Serving metrics: latency percentiles, queue depth, batch fill, cache hits.

The batcher feeds per-request latencies (enqueue → scored) and per-batch
fill/queue observations; ``snapshot`` renders everything as one plain dict
so it can be logged, JSON-dumped by the CLI/bench, or attached to a
``ScoringFinishEvent``. Latencies additionally land in a fixed log-spaced
histogram (100µs … 10s) whose bucket counts survive in the snapshot even
if a future caller decides to drop the raw samples.
"""

from __future__ import annotations

import time
from typing import Callable, Dict, List, Optional

import numpy as np

# log-spaced upper bounds, seconds: 1e-4 .. 1e1 (8 per decade is plenty to
# localize a p99 shift; the exact percentiles come from the raw samples)
LATENCY_BUCKET_BOUNDS = tuple(
    float(b) for b in np.logspace(-4, 1, num=5 * 8 + 1)
)


class ServingMetrics:
    def __init__(self, clock: Callable[[], float] = time.perf_counter):
        self._clock = clock
        self._latencies: List[float] = []
        self._hist = np.zeros(len(LATENCY_BUCKET_BOUNDS) + 1, dtype=np.int64)
        self._fill_real = 0
        self._fill_padded = 0
        self._queue_depths: List[int] = []
        self._queue_waits: List[float] = []
        self.num_requests = 0
        self.num_batches = 0
        self._t_first: Optional[float] = None
        self._t_last: Optional[float] = None
        # hot-swap counters (fed by serving.hotswap.HotSwapManager)
        self.num_swaps = 0
        self.num_rollbacks = 0
        self.rows_updated_total = 0
        self.current_generation = 0
        self._last_swap_blackout_s: Optional[float] = None
        self._max_swap_blackout_s = 0.0
        self._last_update_staleness_s: Optional[float] = None

    def observe_batch(
        self, n_real: int, bucket_size: int, queue_depth: int
    ) -> None:
        now = self._clock()
        if self._t_first is None:
            self._t_first = now
        self._t_last = now
        self.num_batches += 1
        self.num_requests += n_real
        self._fill_real += n_real
        self._fill_padded += bucket_size
        self._queue_depths.append(queue_depth)

    def observe_latency(self, seconds: float) -> None:
        self._latencies.append(float(seconds))
        self._hist[np.searchsorted(LATENCY_BUCKET_BOUNDS, seconds)] += 1

    def observe_queue_wait(self, seconds: float) -> None:
        """Time a request sat in the batcher queue before its batch was
        drained — tracked separately from total latency so queueing policy
        (deadline vs. fill) is visible independently of scoring cost."""
        self._queue_waits.append(float(seconds))

    def observe_swap(
        self,
        generation: int,
        rows_updated: int,
        blackout_s: float,
        staleness_s: Optional[float] = None,
        rolled_back: bool = False,
    ) -> None:
        """One hot-swap attempt. ``blackout_s`` is the time the scorer's
        tables were mid-flip (no requests may run); ``staleness_s`` is
        swap-visible time minus the update's event-batch timestamp — how old
        the freshest served coefficients are at the moment they go live."""
        self.num_swaps += 1
        self._last_swap_blackout_s = float(blackout_s)
        self._max_swap_blackout_s = max(
            self._max_swap_blackout_s, float(blackout_s)
        )
        if rolled_back:
            self.num_rollbacks += 1
            return
        self.current_generation = int(generation)
        self.rows_updated_total += int(rows_updated)
        if staleness_s is not None:
            self._last_update_staleness_s = float(staleness_s)

    def snapshot(
        self,
        cache_stats: Optional[Dict[str, Dict[str, float]]] = None,
        compile_count: Optional[int] = None,
    ) -> dict:
        lat = np.asarray(self._latencies, dtype=np.float64)
        out: dict = {
            "num_requests": self.num_requests,
            "num_batches": self.num_batches,
            "batch_fill_ratio": (
                round(self._fill_real / self._fill_padded, 6)
                if self._fill_padded
                else 0.0
            ),
            "queue_depth_mean": (
                round(float(np.mean(self._queue_depths)), 3)
                if self._queue_depths
                else 0.0
            ),
            "queue_depth_max": (
                int(max(self._queue_depths)) if self._queue_depths else 0
            ),
        }
        if lat.size:
            p50, p95, p99 = np.percentile(lat, [50, 95, 99])
            out.update(
                latency_p50_s=round(float(p50), 6),
                latency_p95_s=round(float(p95), 6),
                latency_p99_s=round(float(p99), 6),
                latency_mean_s=round(float(lat.mean()), 6),
                latency_max_s=round(float(lat.max()), 6),
            )
            nz = np.nonzero(self._hist)[0]
            out["latency_histogram"] = {
                (
                    f"le_{LATENCY_BUCKET_BOUNDS[i]:.6g}s"
                    if i < len(LATENCY_BUCKET_BOUNDS)
                    else "inf"
                ): int(self._hist[i])
                for i in nz
            }
        if self._queue_waits:
            qw = np.asarray(self._queue_waits, dtype=np.float64)
            q50, q99 = np.percentile(qw, [50, 99])
            out.update(
                queue_wait_p50_s=round(float(q50), 6),
                queue_wait_p99_s=round(float(q99), 6),
                queue_wait_max_s=round(float(qw.max()), 6),
            )
        if self.num_swaps:
            out["swaps"] = {
                "num_swaps": self.num_swaps,
                "num_rollbacks": self.num_rollbacks,
                "current_generation": self.current_generation,
                "rows_updated_total": self.rows_updated_total,
                "last_blackout_s": (
                    round(self._last_swap_blackout_s, 6)
                    if self._last_swap_blackout_s is not None
                    else None
                ),
                "max_blackout_s": round(self._max_swap_blackout_s, 6),
                "last_staleness_s": (
                    round(self._last_update_staleness_s, 6)
                    if self._last_update_staleness_s is not None
                    else None
                ),
            }
        if self._t_first is not None and self._t_last > self._t_first:
            wall = self._t_last - self._t_first
            out["wall_seconds"] = round(wall, 6)
            out["requests_per_s"] = round(self.num_requests / wall, 3)
        if compile_count is not None:
            out["xla_compiles"] = int(compile_count)
        if cache_stats:
            out["caches"] = dict(cache_stats)
            hits = sum(c.get("hits", 0) for c in cache_stats.values())
            misses = sum(c.get("misses", 0) for c in cache_stats.values())
            out["cache_hit_rate"] = (
                round(hits / (hits + misses), 6) if hits + misses else 0.0
            )
        return out
