"""Serving metrics: latency percentiles, queue depth, batch fill, cache hits.

The batcher feeds per-request latencies (enqueue → scored) and per-batch
fill/queue observations; ``snapshot`` renders everything as one plain dict
so it can be logged, JSON-dumped by the CLI/bench, or attached to a
``ScoringFinishEvent``. Latencies additionally land in a fixed log-spaced
histogram (100µs … 10s) whose bucket counts are EXACT for the lifetime of
the collector.

Memory is bounded: a long-lived scorer observes millions of requests, so
raw per-observation lists would grow without limit. Percentile estimates
come from fixed-size uniform reservoirs (Vitter's Algorithm R); counts,
sums, maxima, and the histogram are exact running aggregates.
"""

from __future__ import annotations

import time
from typing import Callable, Dict, Optional

import numpy as np

# log-spaced upper bounds, seconds: 1e-4 .. 1e1 (8 per decade is plenty to
# localize a p99 shift; the exact percentiles come from the raw samples)
LATENCY_BUCKET_BOUNDS = tuple(
    float(b) for b in np.logspace(-4, 1, num=5 * 8 + 1)
)

# Reservoir capacity for percentile estimation. Below this many
# observations the samples are exact; beyond it each kept sample is a
# uniform draw, so a p99 over 4096 samples has ~40 tail points — stable to
# well under a histogram bucket width.
RESERVOIR_SIZE = 4096


class _Reservoir:
    """Uniform fixed-size sample of a stream (Vitter's Algorithm R) plus
    exact running count/sum/max. Deterministic for a given observation
    sequence (seeded generator) so snapshots are reproducible in tests."""

    __slots__ = ("capacity", "count", "total", "maximum", "_samples", "_rng")

    def __init__(self, capacity: int = RESERVOIR_SIZE, seed: int = 0):
        self.capacity = int(capacity)
        self.count = 0
        self.total = 0.0
        self.maximum = 0.0
        self._samples: np.ndarray = np.empty(self.capacity, dtype=np.float64)
        self._rng = np.random.default_rng(seed)

    def __len__(self) -> int:
        return min(self.count, self.capacity)

    def add(self, value: float) -> None:
        value = float(value)
        if self.count == 0 or value > self.maximum:
            self.maximum = value
        self.total += value
        if self.count < self.capacity:
            self._samples[self.count] = value
        else:
            j = int(self._rng.integers(0, self.count + 1))
            if j < self.capacity:
                self._samples[j] = value
        self.count += 1

    def add_many(self, values: np.ndarray) -> None:
        """Vectorized :meth:`add` — one RNG draw per overflow element, same
        keep-probability as the sequential loop (later duplicates win, as
        they would one at a time). The batcher feeds per-batch latency
        arrays through this so steady-state metrics cost is O(batch), not
        O(requests) Python calls."""
        values = np.asarray(values, dtype=np.float64).ravel()
        m = values.size
        if m == 0:
            return
        vmax = float(values.max())
        if self.count == 0 or vmax > self.maximum:
            self.maximum = vmax
        self.total += float(values.sum())
        fill = min(self.capacity - self.count, m) if self.count < self.capacity else 0
        if fill > 0:
            self._samples[self.count:self.count + fill] = values[:fill]
        if m > fill:
            tail = values[fill:]
            prior = np.arange(
                self.count + fill, self.count + m, dtype=np.int64
            )
            j = self._rng.integers(0, prior + 1)
            keep = j < self.capacity
            if keep.any():
                self._samples[j[keep]] = tail[keep]
        self.count += m

    @property
    def mean(self) -> float:
        return self.total / self.count if self.count else 0.0

    def samples(self) -> np.ndarray:
        return self._samples[: len(self)]

    def percentile(self, q) -> np.ndarray:
        """Percentile(s) of the kept sample. An EMPTY reservoir returns
        NaN shaped like ``q`` (scalar q -> scalar NaN, array q -> NaN
        array) instead of letting numpy raise — callers guard on
        ``count`` for display, but analysis paths may probe blind."""
        samples = self.samples()
        if samples.size == 0:
            return np.full(np.shape(q), np.nan)[()]
        return np.percentile(samples, q)


class ServingMetrics:
    def __init__(
        self,
        clock: Callable[[], float] = time.perf_counter,
        request_plane=None,
    ):
        self._clock = clock
        # request plane (serving/requestplane.py): hot-swap blackouts are
        # forwarded as interference spans so swap pauses show up in the
        # sampled requests' p99 breakdown instead of vanishing from every
        # latency attribution
        self.request_plane = request_plane
        self._latencies = _Reservoir(seed=0)
        self._hist = np.zeros(len(LATENCY_BUCKET_BOUNDS) + 1, dtype=np.int64)
        self._fill_real = 0
        self._fill_padded = 0
        self._queue_depth_sum = 0
        self._queue_depth_count = 0
        self._queue_depth_max = 0
        self._queue_waits = _Reservoir(seed=1)
        # per-bucket-size latency reservoirs: which bucket a request drained
        # through is the serving-side shape signature, so tail latency is
        # attributable per compiled program, not just in aggregate
        self._bucket_latencies: Dict[int, _Reservoir] = {}
        self.deferred_lookups = 0  # known entities awaiting device admission
        self.num_requests = 0
        self.num_batches = 0
        self._t_first: Optional[float] = None
        self._t_last: Optional[float] = None
        # hot-swap counters (fed by serving.hotswap.HotSwapManager)
        self.num_swaps = 0
        self.num_rollbacks = 0
        self.rows_updated_total = 0
        self.current_generation = 0
        self._last_swap_blackout_s: Optional[float] = None
        self._max_swap_blackout_s = 0.0
        self._last_update_staleness_s: Optional[float] = None

    def observe_batch(
        self, n_real: int, bucket_size: int, queue_depth: int
    ) -> None:
        now = self._clock()
        if self._t_first is None:
            self._t_first = now
        self._t_last = now
        self.num_batches += 1
        self.num_requests += n_real
        self._fill_real += n_real
        self._fill_padded += bucket_size
        self._queue_depth_sum += int(queue_depth)
        self._queue_depth_count += 1
        self._queue_depth_max = max(self._queue_depth_max, int(queue_depth))

    def observe_latency(
        self, seconds: float, bucket_size: Optional[int] = None
    ) -> None:
        self._latencies.add(seconds)
        self._hist[np.searchsorted(LATENCY_BUCKET_BOUNDS, seconds)] += 1
        if bucket_size is not None:
            self._bucket_reservoir(bucket_size).add(seconds)

    def observe_latencies(
        self, seconds: np.ndarray, bucket_size: Optional[int] = None
    ) -> None:
        """Batched :meth:`observe_latency`: one call per drained batch."""
        seconds = np.asarray(seconds, dtype=np.float64).ravel()
        if seconds.size == 0:
            return
        self._latencies.add_many(seconds)
        np.add.at(
            self._hist, np.searchsorted(LATENCY_BUCKET_BOUNDS, seconds), 1
        )
        if bucket_size is not None:
            self._bucket_reservoir(bucket_size).add_many(seconds)

    def _bucket_reservoir(self, bucket_size: int) -> _Reservoir:
        res = self._bucket_latencies.get(int(bucket_size))
        if res is None:
            # deterministic per-bucket seed so snapshots are reproducible
            res = _Reservoir(seed=100 + int(bucket_size))
            self._bucket_latencies[int(bucket_size)] = res
        return res

    def observe_queue_wait(self, seconds: float) -> None:
        """Time a request sat in the batcher queue before its batch was
        drained — tracked separately from total latency so queueing policy
        (deadline vs. fill) is visible independently of scoring cost."""
        self._queue_waits.add(seconds)

    def observe_queue_waits(self, seconds: np.ndarray) -> None:
        self._queue_waits.add_many(np.asarray(seconds, dtype=np.float64))

    def observe_deferred(self, count: int) -> None:
        """RE lookups that found a known entity not yet device-resident —
        served FE-only this request, queued for asynchronous admission."""
        self.deferred_lookups += int(count)

    def observe_swap(
        self,
        generation: int,
        rows_updated: int,
        blackout_s: float,
        staleness_s: Optional[float] = None,
        rolled_back: bool = False,
    ) -> None:
        """One hot-swap attempt. ``blackout_s`` is the time the scorer's
        tables were mid-flip (no requests may run); ``staleness_s`` is
        swap-visible time minus the update's event-batch timestamp — how old
        the freshest served coefficients are at the moment they go live."""
        self.num_swaps += 1
        self._last_swap_blackout_s = float(blackout_s)
        self._max_swap_blackout_s = max(
            self._max_swap_blackout_s, float(blackout_s)
        )
        if self.request_plane is not None and blackout_s > 0:
            # the swap manager calls this right after its critical section,
            # so the pause window is [now - blackout, now] on the shared
            # perf_counter timebase — in-flight and queued sampled requests
            # overlap it and attribute the pause as swap_pause interference
            end = self._clock()
            self.request_plane.note_interference(
                "swap_pause", end - float(blackout_s), end
            )
        if rolled_back:
            self.num_rollbacks += 1
            return
        self.current_generation = int(generation)
        self.rows_updated_total += int(rows_updated)
        if staleness_s is not None:
            self._last_update_staleness_s = float(staleness_s)

    def snapshot(
        self,
        cache_stats: Optional[Dict[str, Dict[str, float]]] = None,
        compile_count: Optional[int] = None,
        residency: Optional[Dict[str, Dict[str, float]]] = None,
        admission: Optional[Dict[str, float]] = None,
    ) -> dict:
        out: dict = {
            "num_requests": self.num_requests,
            "num_batches": self.num_batches,
            "batch_fill_ratio": (
                round(self._fill_real / self._fill_padded, 6)
                if self._fill_padded
                else 0.0
            ),
            "queue_depth_mean": (
                round(self._queue_depth_sum / self._queue_depth_count, 3)
                if self._queue_depth_count
                else 0.0
            ),
            "queue_depth_max": self._queue_depth_max,
        }
        if self._latencies.count:
            # percentiles from the reservoir sample (exact below capacity);
            # mean/max are exact running aggregates
            p50, p95, p99 = self._latencies.percentile([50, 95, 99])
            out.update(
                latency_p50_s=round(float(p50), 6),
                latency_p95_s=round(float(p95), 6),
                latency_p99_s=round(float(p99), 6),
                latency_mean_s=round(self._latencies.mean, 6),
                latency_max_s=round(self._latencies.maximum, 6),
            )
            nz = np.nonzero(self._hist)[0]
            out["latency_histogram"] = {
                (
                    f"le_{LATENCY_BUCKET_BOUNDS[i]:.6g}s"
                    if i < len(LATENCY_BUCKET_BOUNDS)
                    else "inf"
                ): int(self._hist[i])
                for i in nz
            }
        if self._bucket_latencies:
            # one entry per compiled program signature (bucket size): the
            # serving analogue of per-kernel attribution
            per_bucket: dict = {}
            for size in sorted(self._bucket_latencies):
                res = self._bucket_latencies[size]
                if not res.count:
                    continue
                b50, b95, b99 = res.percentile([50, 95, 99])
                per_bucket[str(size)] = {
                    "count": res.count,
                    "latency_p50_s": round(float(b50), 6),
                    "latency_p95_s": round(float(b95), 6),
                    "latency_p99_s": round(float(b99), 6),
                    "latency_max_s": round(res.maximum, 6),
                }
            if per_bucket:
                out["per_bucket_latency"] = per_bucket
        if self._queue_waits.count:
            q50, q99 = self._queue_waits.percentile([50, 99])
            out.update(
                queue_wait_p50_s=round(float(q50), 6),
                queue_wait_p99_s=round(float(q99), 6),
                queue_wait_max_s=round(self._queue_waits.maximum, 6),
            )
        if self.deferred_lookups:
            out["deferred_lookups"] = self.deferred_lookups
            if self.num_requests:
                out["deferred_rate"] = round(
                    self.deferred_lookups / self.num_requests, 6
                )
        if self.num_swaps:
            out["swaps"] = {
                "num_swaps": self.num_swaps,
                "num_rollbacks": self.num_rollbacks,
                "current_generation": self.current_generation,
                "rows_updated_total": self.rows_updated_total,
                "last_blackout_s": (
                    round(self._last_swap_blackout_s, 6)
                    if self._last_swap_blackout_s is not None
                    else None
                ),
                "max_blackout_s": round(self._max_swap_blackout_s, 6),
                "last_staleness_s": (
                    round(self._last_update_staleness_s, 6)
                    if self._last_update_staleness_s is not None
                    else None
                ),
            }
        if self._t_first is not None and self._t_last > self._t_first:
            wall = self._t_last - self._t_first
            out["wall_seconds"] = round(wall, 6)
            out["requests_per_s"] = round(self.num_requests / wall, 3)
        if compile_count is not None:
            out["xla_compiles"] = int(compile_count)
        if cache_stats:
            out["caches"] = dict(cache_stats)
            hits = sum(c.get("hits", 0) for c in cache_stats.values())
            misses = sum(c.get("misses", 0) for c in cache_stats.values())
            out["cache_hit_rate"] = (
                round(hits / (hits + misses), 6) if hits + misses else 0.0
            )
        if residency:
            # device-resident fraction per RE coordinate: what share of
            # lookups hit rows already on device (replaces cache_hit_rate in
            # sharded mode, where there is no per-request host cache)
            out["residency"] = dict(residency)
            on = sum(r.get("resident_lookups", 0) for r in residency.values())
            tot = sum(r.get("total_lookups", 0) for r in residency.values())
            out["device_resident_rate"] = round(on / tot, 6) if tot else 0.0
        if admission:
            out["admission"] = dict(admission)
        return out
