"""Asynchronous admission of cold-tail entities into device headroom.

The sharded scorer serves entities beyond its device budget FE-only (cold
slot) and reports them here; a background step copies their coefficient
rows host→device OFF the request path — the serving analogue of the
pipelined host↔accelerator movement in Snap ML / the GPU-DUHL scheme:
request latency never waits on a host copy, it only determines whether
THIS request sees the row or the next one does.

Two properties keep the request path clean:

- **Fixed-shape scatters.** Every admission batch is padded to exactly
  ``admit_batch`` rows (pad writes aim zero values at shard 0's cold
  slot, which keeps it zero), so the device scatter compiles ONCE — the
  per-distinct-miss-count compile storm of the synchronous LRU fill is
  structurally impossible here.
- **Double-buffered staging.** Rows are gathered from the (possibly
  mmap'd) host backing store into one of two pinned staging buffers while
  the other buffer's transfer is still in flight, so disk faults and the
  device copy overlap across steps.

Publication ordering (see ``routing.py``): evictions unpublish first,
device content is written to EVERY scorer replica next, routing publishes
last — a reader never gathers another entity's bytes.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict, deque
from typing import Deque, Dict, List, Optional, Tuple

import numpy as np

from photon_ml_tpu.resilience.failures import record_failure
from photon_ml_tpu.resilience.faultpoints import fault_point, register_fault_site
from photon_ml_tpu.resilience.retry import DEFAULT_IO_RETRY
from photon_ml_tpu.resilience.supervisor import SupervisedThread
from photon_ml_tpu.telemetry import span

FAULT_STEP = register_fault_site(
    "serve.admission.step",
    "admission controller step(): an uncaught error here used to kill the"
    " daemon silently; now the supervisor restarts it",
)
FAULT_STAGE = register_fault_site(
    "serve.admission.stage",
    "host-row gather into the staging buffer (mmap-backed IO; retried)",
)


class AdmissionController:
    """Admits deferred entity rows into the headroom slots of one or more
    scorer replicas' :class:`~photon_ml_tpu.serving.sharded.ShardedReTable`
    s. Construct with every replica's scorer so a row becomes resident on
    all devices before routing publishes it (the routing index is shared).

    Drive it synchronously with :meth:`step` (replay loop, tests) or as a
    background thread via :meth:`start`/:meth:`stop`.
    """

    def __init__(
        self,
        scorers,
        admit_batch: int = 64,
        max_queue: int = 65536,
    ):
        if admit_batch < 1:
            raise ValueError(f"admit_batch must be >= 1, got {admit_batch}")
        scorers = list(scorers) if isinstance(scorers, (list, tuple)) else [scorers]
        if not scorers:
            raise ValueError("need at least one scorer")
        self._scorers = scorers
        self.admit_batch = int(admit_batch)
        self.max_queue = int(max_queue)
        self._lock = threading.Lock()
        # per-coordinate FIFO of deferred rows; OrderedDict dedups repeats
        # of a hot-but-not-yet-admitted entity while keeping arrival order
        self._queues: Dict[str, "OrderedDict[int, None]"] = {}
        # double staging buffers per coordinate, allocated lazily at the
        # first admit (dim known then); index flips every step
        self._staging: Dict[str, List[np.ndarray]] = {}
        self._flip: Dict[str, int] = {}
        self._thread: Optional[SupervisedThread] = None
        self._stop = threading.Event()
        # request plane (serving/requestplane.py): admit steps hold the
        # scorers' write locks, so their windows are interference sampled
        # requests attribute their stalls to
        self.request_plane = None
        self.admitted_total = 0
        self.evicted_total = 0
        self.deferred_total = 0
        self.dropped_total = 0  # queue overflow (admission can't keep up)
        self.steps = 0
        self.admit_failures = 0  # per-coordinate admit errors (requeued)

    # -------------------------------------------------------------- intake

    def note_deferred(self, cid: str, rows: np.ndarray) -> None:
        """Record rows a request batch served FE-only (called by the scorer
        on the request path — O(deferred) dict inserts, no device work)."""
        rows = np.asarray(rows, dtype=np.int64).ravel()
        if rows.size == 0:
            return
        with self._lock:
            q = self._queues.get(cid)
            if q is None:
                q = self._queues[cid] = OrderedDict()
            self.deferred_total += rows.size
            for r in rows.tolist():
                if r in q:
                    continue
                if len(q) >= self.max_queue:
                    self.dropped_total += 1
                    continue
                q[r] = None

    @property
    def queue_depth(self) -> int:
        with self._lock:
            return sum(len(q) for q in self._queues.values())

    @property
    def scorers(self) -> List[object]:
        """The scorer replicas this controller writes before publishing."""
        return list(self._scorers)

    def _requeue(self, cid: str, rows: np.ndarray) -> None:
        """Put rows back at the queue HEAD so the next step takes them
        first (they were dequeued earliest)."""
        with self._lock:
            q = self._queues.get(cid)
            if q is None:
                q = self._queues[cid] = OrderedDict()
            for r in rows.tolist()[::-1]:
                q[r] = None
                q.move_to_end(r, last=False)

    # ------------------------------------------------------------- admit

    def step(self) -> int:
        """Admit up to ``admit_batch`` rows per coordinate. Returns the
        number of rows admitted across coordinates.

        One coordinate's failure must not starve the others (or kill a
        background driver): a failed admit puts its rows back at the
        queue head, records the failure, and the loop moves on — the
        next step naturally retries them."""
        fault_point(FAULT_STEP)
        admitted = 0
        for cid in list(self._queues):
            with self._lock:
                q = self._queues[cid]
                take = min(len(q), self.admit_batch)
                rows = [q.popitem(last=False)[0] for _ in range(take)]
            if not rows:
                continue
            batch = np.asarray(rows, dtype=np.int64)
            try:
                admitted += self._admit(cid, batch)
            except Exception as exc:  # noqa: BLE001 - contained per-cid
                self._requeue(cid, batch)
                self.admit_failures += 1
                record_failure(
                    "admit_failed",
                    "serve.admission.step",
                    f"{type(exc).__name__}: {exc}",
                    coordinate=cid,
                    rows=int(batch.size),
                )
        if admitted:
            self.steps += 1
        return admitted

    def _admit(self, cid: str, rows: np.ndarray) -> int:
        while True:
            primary = self._scorers[0]._providers[cid]
            routing = primary.routing
            # routing.lock serializes this step against hot-swap
            # update_rows/rebind on other threads: allocate's
            # check-then-pop and the write-everywhere-then-publish
            # sequence must not interleave with theirs
            with routing.lock:
                if self._scorers[0]._providers[cid] is not primary:
                    # a rebind swapped the provider (and its routing)
                    # between the read above and the lock acquisition;
                    # retry against the new pair
                    continue
                if any(
                    s._providers[cid].routing is not routing
                    for s in self._scorers[1:]
                ):
                    # mid-fan-out of a regrowing coordinated hot swap:
                    # replica tables briefly disagree on layout, so slots
                    # allocated here could land out of bounds on a
                    # not-yet-rebound replica — requeue for a later step
                    self._requeue(cid, rows)
                    return 0
                return self._admit_locked(cid, primary, routing, rows)

    def _admit_locked(self, cid: str, primary, routing, rows) -> int:
        # a hot swap can defer rows from a newer entity index before this
        # coordinate's routing has grown; they re-enter the queue through
        # route() once the swap lands, so just skip them this step
        rows = rows[rows < routing.n_rows]
        # rows can have been admitted since they were queued (hot-swap
        # update_rows, or a previous step when the same row was queued twice
        # under different coordinates); they may also have been evicted
        # again — that is fine, admission is idempotent on content
        fresh = rows[routing._slot_of[rows] < 0]
        if fresh.size == 0:
            return 0
        # a single step can only claim slots that are free or already
        # admitted (rows admitted THIS step are not evictable until
        # published); overflow goes back to the queue head for next step
        capacity = routing.free_slots + len(routing._admitted)
        if capacity == 0:
            self.dropped_total += int(fresh.size)
            return 0
        if fresh.size > capacity:
            overflow = fresh[capacity:]
            fresh = fresh[:capacity]
            self._requeue(cid, overflow)
        t_admit0 = time.perf_counter() if self.request_plane is not None else 0.0
        with span("serve/admit", cid=cid, rows=int(fresh.size)):
            k = self.admit_batch
            shards = np.zeros(k, dtype=np.int32)
            # pad writes target shard 0's cold slot with zeros: the cold
            # slot stays zero and the scatter keeps ONE compiled shape
            slots = np.full(k, routing.cold_slot, dtype=np.int32)
            a_shards, a_slots, evicted = routing.allocate(fresh.size)
            shards[: fresh.size] = a_shards
            slots[: fresh.size] = a_slots
            buf = self._stage(cid, primary, fresh, k)
            # importance plane: the staged rows ARE the admitted content,
            # so their L2 norms are free here (no-op under the default
            # eviction policy)
            routing.note_row_norms(
                fresh, np.linalg.norm(buf[: fresh.size], axis=1)
            )
            for scorer in self._scorers:
                provider = scorer._providers[cid]
                # double-buffered providers: keep the spare generation half
                # converged (invariant: both halves identical outside an
                # in-flight flip) so the next hot-swap flip doesn't lose
                # admitted rows. No write_lock needed — the request path
                # never captures the spare half, and routing.lock (held
                # here) keeps the generation index stable.
                spare = getattr(provider, "spare_gen", None)
                if spare is not None:
                    provider.write_slots(shards, slots, buf, gen=spare)
                # the donated scatter invalidates the replica's previous
                # table array; its write_lock keeps that away from a
                # gather in flight on the replica's scoring thread
                with scorer.write_lock:
                    provider.write_slots(shards, slots, buf)
            routing.publish(fresh, a_shards, a_slots)
            self.admitted_total += int(fresh.size)
            self.evicted_total += len(evicted)
        if self.request_plane is not None:
            self.request_plane.note_interference(
                "admission", t_admit0, time.perf_counter()
            )
        return int(fresh.size)

    def _stage(self, cid: str, provider, rows: np.ndarray, k: int) -> np.ndarray:
        """Gather host rows into the next staging buffer (double-buffered:
        the buffer written last step may still back an in-flight device
        copy, so this step fills the other one)."""
        bufs = self._staging.get(cid)
        dim = provider._backing.shape[1]
        if bufs is None or bufs[0].shape != (k, dim):
            bufs = self._staging[cid] = [
                np.zeros((k, dim), dtype=np.float32) for _ in range(2)
            ]
            self._flip[cid] = 0
        self._flip[cid] ^= 1
        buf = bufs[self._flip[cid]]
        buf[:] = 0.0
        if rows.size:
            # mmap-backed gather: page-in can hit transient IO errors, and
            # the step holds routing.lock — retry in place (state untouched
            # until the buffer is written) rather than unwinding the admit
            def _gather():
                fault_point(FAULT_STAGE)
                buf[: rows.size] = provider.host_rows(rows)

            DEFAULT_IO_RETRY.run("serve.admission.stage", _gather)
        return buf

    def warmup(self) -> None:
        """Compile every replica's fixed-shape admission scatter (and
        allocate the staging buffers) before serving: an all-pad batch
        writes zeros at shard 0's cold slot, so content is untouched but
        the first real admit runs compile-free off the request path."""
        k = self.admit_batch
        shards = np.zeros(k, dtype=np.int32)
        for scorer in self._scorers:
            for cid, provider in scorer._providers.items():
                slots = np.full(k, provider.cold_slot, dtype=np.int32)
                buf = self._stage(
                    cid, provider, np.empty(0, dtype=np.int64), k
                )
                with scorer.write_lock:
                    provider.write_slots(shards, slots, buf)

    # --------------------------------------------------------- background

    def start(
        self,
        interval_s: float = 0.001,
        max_restarts: int = 5,
        emitter=None,
    ) -> None:
        """Run :meth:`step` on a supervised background thread every
        ``interval_s`` (sooner when a step admitted a full batch — drain
        bursts fast). A crash in :meth:`step` is captured and the tick
        restarted with backoff up to ``max_restarts``; past the cap the
        thread is declared dead and :meth:`health` turns degraded while
        the scorer keeps serving cold entities FE-only."""
        if self._thread is not None:
            raise RuntimeError("admission thread already running")
        self._stop.clear()

        def _tick():
            n = self.step()
            if n < self.admit_batch:
                self._stop.wait(interval_s)

        self._thread = SupervisedThread(
            "serving-admission",
            _tick,
            mode="tick",
            stop_event=self._stop,
            max_restarts=max_restarts,
            emitter=emitter,
        )
        self._thread.start()

    def stop(self) -> None:
        if self._thread is None:
            return
        self._stop.set()
        self._thread.join()
        self._thread = None

    def drain(self, max_steps: int = 1 << 20) -> int:
        """Synchronously admit until the queue is empty (tests, shutdown)."""
        total = 0
        for _ in range(max_steps):
            n = self.step()
            total += n
            if n == 0 and self.queue_depth == 0:
                break
        return total

    def stats(self) -> Dict[str, float]:
        # eviction reasons, aggregated over the (shared) routing truth —
        # scorer 0's providers see every eviction the replicas share
        evicted_by_policy = {"oldest": 0, "importance": 0}
        for provider in getattr(self._scorers[0], "_providers", {}).values():
            r = provider.routing
            evicted_by_policy["oldest"] += getattr(r, "evicted_oldest", 0)
            evicted_by_policy["importance"] += getattr(
                r, "evicted_importance", 0
            )
        stats = {
            "admit_batch": self.admit_batch,
            "admitted_total": self.admitted_total,
            "evicted_total": self.evicted_total,
            "deferred_total": self.deferred_total,
            "dropped_total": self.dropped_total,
            "queue_depth": self.queue_depth,
            "steps": self.steps,
            "replicas": len(self._scorers),
            "evicted_by_policy": evicted_by_policy,
            "admit_failures": self.admit_failures,
            "thread_restarts": 0,
            "thread_crashes": 0,
            "thread_dead": False,
        }
        thread = self._thread
        if isinstance(thread, SupervisedThread):
            sup = thread.stats()
            stats["thread_restarts"] = sup["restarts"]
            stats["thread_crashes"] = sup["crashes"]
            stats["thread_dead"] = sup["dead"]
            stats["supervisor"] = sup
        return stats

    def health(self) -> Dict[str, object]:
        """Health contribution for ``/healthz``: degraded (unhealthy)
        once the supervised thread is declared dead — serving itself
        stays up, cold entities just score FE-only forever."""
        thread = self._thread
        if isinstance(thread, SupervisedThread):
            doc = thread.health()
            doc["running"] = thread.is_alive()
            return doc
        return {"healthy": True, "running": thread is not None}
