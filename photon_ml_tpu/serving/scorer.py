"""Jit'd fixed-shape request scoring against a packed serving artifact.

One request carries sparse features per shard and one entity id per
random-effect type; a batch of B requests is scored as

    z   = offset + Σ_fe x·β_fe + Σ_re x·β_re[entity]
    out = mean(z)                      (task link-inverse, e.g. sigmoid)

with every array shaped ``[B, K_shard]`` (K fixed per shard, nonzeros
padded with zero values at index 0). RE rows are gathered from a device
table through slot indices produced by the hot-entity cache (or the full
device-resident table); entities absent from the model gather the
permanently-zero cold slot, so they degrade to the FE-only score — the
Photon-ML left-join semantics — without a branch.

Because shapes are fixed per (bucket size, shard-K) signature, XLA compiles
the score function once per bucket size and never per request;
``compile_count`` counts actual traces (incremented by a Python side effect
that only runs when jit traces).
"""

from __future__ import annotations

import dataclasses
import operator
import time
from itertools import chain
from typing import Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from photon_ml_tpu.serving.artifact import ServingArtifact
from photon_ml_tpu.serving.cache import HotEntityCache
from photon_ml_tpu.telemetry import note_jit_trace, span


@dataclasses.dataclass
class ScoreRequest:
    """One item to score: sparse features per shard + entity ids."""

    request_id: str
    features: Dict[str, Dict[int, float]]  # shard -> {feature index: value}
    entity_ids: Dict[str, str] = dataclasses.field(default_factory=dict)
    offset: float = 0.0


@dataclasses.dataclass(slots=True)
class ScoreResult:
    request_id: str
    score: float  # margin z including the request offset (GameModel.score + offset)
    mean: float   # task link-inverse of the margin
    cold_coordinates: Tuple[str, ...] = ()  # RE coordinates served FE-only


_EMPTY_FEATS: Dict[int, float] = {}
_FEAT_VALUES = operator.methodcaller("values")
_REQ_OFFSET = operator.attrgetter("offset")
_REQ_ENTITY_IDS = operator.attrgetter("entity_ids")


def featurize_requests(
    requests: Sequence[ScoreRequest],
    n: int,
    bucket: int,
    shard_nnz: Dict[str, int],
    shard_dim: Dict[str, int],
) -> Tuple[Dict[str, Tuple[np.ndarray, np.ndarray]], np.ndarray]:
    """Pack ``n`` requests into padded ``[bucket, K]`` value/index arrays
    per shard plus a ``[bucket]`` offsets vector.

    One flat ``np.fromiter`` pass over all nonzeros per shard, fed by
    C-level ``chain.from_iterable`` iteration (the per-row dict loop this
    replaces was the second-largest serving cost after the cache fill, and
    a nested generator expression here costs two frame resumes per
    nonzero); output is bit-identical to the row-at-a-time packing — same
    dict iteration order, same zero padding. Shared by the single-table
    and the sharded scorer so their featurization cannot drift apart."""
    shards: Dict[str, Tuple[np.ndarray, np.ndarray]] = {}
    for shard, k in shard_nnz.items():
        dim = shard_dim[shard]
        vals = np.zeros((bucket, k), dtype=np.float32)
        idx = np.zeros((bucket, k), dtype=np.int32)
        feats_list = [req.features.get(shard) or _EMPTY_FEATS
                      for req in requests]
        lens = np.fromiter(map(len, feats_list), dtype=np.int64, count=n)
        total = int(lens.sum())
        if total:
            if int(lens.max()) > k:
                i = int(np.argmax(lens))
                raise ValueError(
                    f"request {requests[i].request_id!r} has {int(lens[i])} "
                    f"nonzeros in shard {shard!r} but the scorer was built "
                    f"with max_nnz={k} — raise max_nnz"
                )
            flat_idx = np.fromiter(
                chain.from_iterable(feats_list),
                dtype=np.int64, count=total,
            )
            if flat_idx.size and (
                int(flat_idx.min()) < 0 or int(flat_idx.max()) >= dim
            ):
                rows_of = np.repeat(np.arange(n), lens)
                bad = int(rows_of[(flat_idx < 0) | (flat_idx >= dim)][0])
                bad_c = next(
                    c for c in requests[bad].features[shard]
                    if not 0 <= int(c) < dim
                )
                raise ValueError(
                    f"request {requests[bad].request_id!r}: feature index "
                    f"{int(bad_c)} out of range for shard {shard!r} "
                    f"(dim {dim})"
                )
            flat_val = np.fromiter(
                chain.from_iterable(map(_FEAT_VALUES, feats_list)),
                dtype=np.float32, count=total,
            )
            rows = np.repeat(np.arange(n), lens)
            starts = np.repeat(np.cumsum(lens) - lens, lens)
            cols = np.arange(total) - starts
            idx[rows, cols] = flat_idx
            vals[rows, cols] = flat_val
        shards[shard] = (vals, idx)
    offsets = np.zeros(bucket, dtype=np.float32)
    if n:
        offsets[:n] = np.fromiter(
            map(_REQ_OFFSET, requests), dtype=np.float32, count=n
        )
    return shards, offsets


class _FullTable:
    """No-cache RE row provider: whole table device-resident, plus the
    trailing zero cold row. Same lookup contract as HotEntityCache.

    ``pad_rows`` reserves headroom BETWEEN the live rows and the cold slot
    (device shape ``[pad_rows + 1, dim]``, cold slot at ``pad_rows``): a
    hot-swap can then append new entities into the zero headroom rows
    without changing the table shape — and therefore without retracing the
    jit'd scorer. The headroom rows are all-zero until claimed, so an
    accidental gather of one degrades to the FE-only score, same as cold.
    """

    def __init__(self, backing: np.ndarray, pad_rows: Optional[int] = None):
        import jax.numpy as jnp

        n, dim = backing.shape
        pad = n if pad_rows is None else max(int(pad_rows), n)
        self._table = jnp.concatenate(
            [
                jnp.asarray(np.ascontiguousarray(backing, dtype=np.float32)),
                jnp.zeros((pad - n + 1, dim), dtype=jnp.float32),
            ]
        )
        self.num_rows = n  # live rows; grows as headroom is claimed
        self.cold_slot = pad

    @property
    def table(self):
        return self._table

    @property
    def capacity(self) -> int:
        """Rows the device table can hold without a shape change."""
        return self.cold_slot

    def lookup(self, entity_rows: np.ndarray) -> np.ndarray:
        rows = np.asarray(entity_rows, dtype=np.int64)
        return np.where(rows < 0, self.cold_slot, rows).astype(np.int32)

    def update_rows(self, rows: np.ndarray, values: np.ndarray) -> None:
        """In-place row update/append on device — no shape change, no
        retrace. Rows must fit below the cold slot; the hot-swap manager
        rebuilds the provider at the next size bucket when they don't."""
        import jax.numpy as jnp

        rows = np.asarray(rows, dtype=np.int64)
        if rows.size == 0:
            return
        if rows.min() < 0 or rows.max() >= self.cold_slot:
            raise ValueError(
                f"row update [{rows.min()}, {rows.max()}] exceeds table "
                f"capacity {self.cold_slot} — table must grow (re-pad to "
                "the next size bucket)"
            )
        self._table = self._table.at[rows].set(
            jnp.asarray(np.ascontiguousarray(values, dtype=np.float32))
        )
        self.num_rows = max(self.num_rows, int(rows.max()) + 1)

    def stats(self) -> Dict[str, float]:
        return {}


class GameScorer:
    """Scores request batches against a :class:`ServingArtifact`.

    - ``max_nnz``: per-shard padded nonzero capacity K (int applies to all
      shards; default: the shard's full dimension, always correct).
    - ``cache_capacity``: device rows per RE coordinate. None keeps each
      full RE table device-resident; an int puts an LRU
      :class:`HotEntityCache` in front of the host backing store (must be
      >= the largest batch the caller will score).
    - ``growth_headroom``: pad full device-resident RE tables to the next
      power-of-two size bucket so a hot-swap can append new entities
      in-shape (no retrace). Cached coordinates have a fixed device shape
      and never need it. Off by default — steady-state memory is the
      padded bucket.
    """

    def __init__(
        self,
        artifact: ServingArtifact,
        max_nnz: Optional[Union[int, Dict[str, int]]] = None,
        cache_capacity: Optional[int] = None,
        growth_headroom: bool = False,
    ):
        import jax
        import jax.numpy as jnp

        from photon_ml_tpu.losses.pointwise import mean_function

        self._artifact = artifact
        self._task = artifact.task
        dims = artifact.shard_dims()
        self._shard_nnz: Dict[str, int] = {}
        for shard, dim in dims.items():
            if isinstance(max_nnz, dict):
                k = max_nnz.get(shard, dim)
            elif max_nnz is not None:
                k = int(max_nnz)
            else:
                k = dim
            self._shard_nnz[shard] = max(1, min(int(k), dim))
        self._shard_dim = dims

        self._fe_specs: List[Tuple[str, str]] = []  # (cid, shard)
        self._re_specs: List[Tuple[str, str, str]] = []  # (cid, shard, re_type)
        self.caches: Dict[str, HotEntityCache] = {}
        self._providers: Dict[str, object] = {}
        self._growth_headroom = bool(growth_headroom)
        fe_params: Dict[str, object] = {}
        for cid in sorted(artifact.tables):
            table = artifact.tables[cid]
            if table.is_random_effect:
                self._re_specs.append(
                    (cid, table.feature_shard, table.random_effect_type)
                )
                if cache_capacity is not None:
                    cache = HotEntityCache(table.weights, cache_capacity)
                    self.caches[cid] = cache
                    self._providers[cid] = cache
                else:
                    self._providers[cid] = _FullTable(
                        np.asarray(table.weights),
                        pad_rows=self._pad_rows_for(table.n_entities),
                    )
            else:
                self._fe_specs.append((cid, table.feature_shard))
                fe_params[cid] = jnp.asarray(
                    np.ascontiguousarray(table.weights, dtype=np.float32)
                )
        self._fe_params = fe_params
        self._compiles = 0

        fe_specs = tuple(self._fe_specs)
        re_specs = tuple(self._re_specs)
        task = self._task

        def _score(params, batch):
            # trace-time side effect: runs once per compiled shape signature
            self._compiles += 1
            note_jit_trace("serving_score")
            z = batch["offsets"]
            for cid, shard in fe_specs:
                vals, idx = batch["shards"][shard]
                z = z + (vals * params["fe"][cid][idx]).sum(axis=1)
            for cid, shard, _ in re_specs:
                vals, idx = batch["shards"][shard]
                rows = params["re"][cid][batch["slots"][cid]]  # [B, dim]
                z = z + (vals * jnp.take_along_axis(rows, idx, axis=1)).sum(axis=1)
            return z, mean_function(task, z)

        self._score_fn = jax.jit(_score)

    @property
    def compile_count(self) -> int:
        """Number of XLA traces so far — one per distinct bucket size."""
        return self._compiles

    @property
    def task(self):
        return self._task

    @property
    def artifact(self) -> ServingArtifact:
        return self._artifact

    def cache_stats(self) -> Dict[str, Dict[str, float]]:
        return {cid: c.stats() for cid, c in self.caches.items()}

    # ------------------------------------------------------ hot-swap hooks

    def _pad_rows_for(self, n: int) -> Optional[int]:
        """Full-table headroom: pad to the next power-of-two size bucket so
        moderate entity growth stays in-shape (None = tight, no headroom)."""
        if not self._growth_headroom:
            return None
        bucket = 1
        while bucket <= n:  # strictly greater: never a zero-headroom bucket
            bucket <<= 1
        return bucket

    def set_artifact(self, artifact: ServingArtifact) -> None:
        """Flip the scorer's artifact reference (entity indexes, dims) to a
        delta-applied candidate. The candidate must keep the coordinate
        structure — same coordinate ids, shards, RE types, and FE dims — or
        the jit'd score function would no longer match; table CONTENT is
        swapped separately via ``update_fixed_effect`` /
        ``update_random_effect_rows`` / ``rebind_random_effect``."""
        fe = [
            (cid, t.feature_shard)
            for cid, t in sorted(artifact.tables.items())
            if not t.is_random_effect
        ]
        re = [
            (cid, t.feature_shard, t.random_effect_type)
            for cid, t in sorted(artifact.tables.items())
            if t.is_random_effect
        ]
        if fe != self._fe_specs or re != self._re_specs:
            raise ValueError(
                "candidate artifact changes the coordinate structure "
                f"(have fe={self._fe_specs} re={self._re_specs}, candidate "
                f"fe={fe} re={re}) — a structural change needs a new scorer, "
                "not a hot swap"
            )
        for cid, shard in self._fe_specs:
            if artifact.tables[cid].dim != self._artifact.tables[cid].dim:
                raise ValueError(
                    f"candidate artifact changes fixed-effect dim of {cid!r}"
                )
        self._artifact = artifact

    def update_fixed_effect(self, cid: str, weights: np.ndarray) -> None:
        """Replace one FE coefficient vector in place (same shape — the
        params are jit ARGUMENTS, so new content never retraces)."""
        import jax.numpy as jnp

        old = self._fe_params.get(cid)
        if old is None:
            raise ValueError(f"{cid!r} is not a fixed-effect coordinate")
        w = np.ascontiguousarray(weights, dtype=np.float32)
        if w.shape != old.shape:
            raise ValueError(
                f"fixed-effect update for {cid!r} has shape {w.shape}, "
                f"scorer holds {old.shape}"
            )
        self._fe_params[cid] = jnp.asarray(w)

    def update_random_effect_rows(
        self, cid: str, rows: np.ndarray, values: np.ndarray
    ) -> None:
        """In-place update/append of full-table RE rows on device (raises
        if the rows exceed the table's headroom — then use
        ``rebind_random_effect``). Cached coordinates take content changes
        through ``rebind_random_effect`` + cache invalidation instead."""
        provider = self._providers.get(cid)
        if provider is None:
            raise ValueError(f"{cid!r} is not a random-effect coordinate")
        if isinstance(provider, HotEntityCache):
            raise ValueError(
                f"{cid!r} is cache-backed; rebind its backing store and "
                "invalidate the touched rows instead of updating in place"
            )
        provider.update_rows(rows, values)

    def rebind_random_effect(self, cid: str, backing: np.ndarray) -> bool:
        """Point one RE coordinate at a new backing table.

        Cache-backed: O(1) pointer swap, device shape unchanged → never
        retraces (the caller invalidates the rows whose content changed).
        Full-table: rebuilds the device table — same shape when the new
        row count fits the current padding bucket, next bucket otherwise
        (one expected retrace). Returns True when the device table shape
        changed."""
        provider = self._providers.get(cid)
        if provider is None:
            raise ValueError(f"{cid!r} is not a random-effect coordinate")
        if isinstance(provider, HotEntityCache):
            provider.rebind(backing)
            return False
        n = backing.shape[0]
        pad = self._pad_rows_for(n)
        rebuilt = _FullTable(np.asarray(backing), pad_rows=pad)
        shape_changed = rebuilt.table.shape != provider.table.shape
        self._providers[cid] = rebuilt
        return shape_changed

    def restore_random_effect(self, cid: str, provider, routing=None) -> None:
        """Rollback hook (see HotSwapManager.rollback): reinstall a
        snapshotted provider object. ``routing`` only exists for the
        sharded scorer's shared-layout snapshots and is ignored here."""
        self._providers[cid] = provider

    def _featurize(self, requests: Sequence[ScoreRequest], bucket: int):
        return featurize_requests(
            requests, len(requests), bucket, self._shard_nnz, self._shard_dim
        )

    def score_batch(
        self,
        requests: Sequence[ScoreRequest],
        bucket_size: Optional[int] = None,
        stages: Optional[dict] = None,
    ) -> List[ScoreResult]:
        """Score up to ``bucket_size`` requests, padding the batch to exactly
        that size (defaults to ``len(requests)``). Results keep request order.

        ``stages`` is the request plane's stage clock: when a dict is
        passed (only for batches carrying a sampled request), monotonic
        stage-boundary timestamps are stamped into it (featurize_done,
        route_done, dispatch_done, device_done). ``None`` — the default —
        costs nothing."""
        import jax.numpy as jnp

        n = len(requests)
        bucket = int(bucket_size) if bucket_size is not None else n
        if n == 0:
            return []
        if n > bucket:
            raise ValueError(f"{n} requests do not fit bucket size {bucket}")

        with span("serve/score_batch", n=n, bucket=bucket):
            return self._score_batch_impl(requests, n, bucket, stages)

    def _score_batch_impl(
        self,
        requests: Sequence[ScoreRequest],
        n: int,
        bucket: int,
        stages: Optional[dict] = None,
    ) -> List[ScoreResult]:
        import jax.numpy as jnp

        shards, offsets = self._featurize(requests, bucket)
        if stages is not None:
            stages["featurize_done"] = time.perf_counter()
        slots: Dict[str, np.ndarray] = {}
        cold: List[List[str]] = [[] for _ in range(n)]
        for cid, _, re_type in self._re_specs:
            table = self._artifact.tables[cid]
            entity_rows = np.full(bucket, -1, dtype=np.int64)
            # ids stay C-level; the common every-request-carries-an-id
            # case hands the whole list to one vectorized lookup. Artifact
            # entity indexes are keyed by str, so non-str ids (ints from
            # upstream id tags) are coerced like ServingArtifact
            # .entity_row does.
            ids = [
                e if type(e) is str or e is None else str(e)
                for e in map(
                    operator.methodcaller("get", re_type),
                    map(_REQ_ENTITY_IDS, requests),
                )
            ]
            if None not in ids:
                entity_rows[:n] = table.entity_index.get_indices(ids)
            else:
                where = [i for i, e in enumerate(ids) if e is not None]
                if where:
                    entity_rows[np.asarray(where)] = (
                        table.entity_index.get_indices([ids[i] for i in where])
                    )
            for i in range(n):
                if entity_rows[i] < 0:
                    cold[i].append(cid)
            # pad rows bypass the provider: they would otherwise count as
            # cold lookups in the cache statistics
            provider = self._providers[cid]
            cid_slots = np.full(bucket, provider.cold_slot, dtype=np.int32)
            cid_slots[:n] = np.asarray(
                provider.lookup(entity_rows[:n]), dtype=np.int32
            )
            slots[cid] = cid_slots

        if stages is not None:
            stages["route_done"] = time.perf_counter()
        batch = {
            "offsets": jnp.asarray(offsets),
            "shards": {
                shard: (jnp.asarray(v), jnp.asarray(i))
                for shard, (v, i) in shards.items()
            },
            "slots": {cid: jnp.asarray(s) for cid, s in slots.items()},
        }
        params = {
            "fe": self._fe_params,
            "re": {cid: self._providers[cid].table for cid, _, _ in self._re_specs},
        }
        z, mean = self._score_fn(params, batch)
        if stages is not None:
            # jit dispatch is asynchronous: this boundary closes H2D +
            # program dispatch; the host materialization below blocks on
            # the device, closing the "device" stage
            stages["dispatch_done"] = time.perf_counter()
        z = np.asarray(z)
        mean = np.asarray(mean)
        if stages is not None:
            stages["device_done"] = time.perf_counter()
        return [
            ScoreResult(
                request_id=req.request_id,
                score=float(z[i]),
                mean=float(mean[i]),
                cold_coordinates=tuple(cold[i]),
            )
            for i, req in enumerate(requests)
        ]
