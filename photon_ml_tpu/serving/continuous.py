"""Continuous microbatching: requests join in-flight buckets to a deadline.

``MicroBatcher`` seals a batch at submit time: the submitting caller
scores a full bucket inline, and deadline draining only happens when the
caller remembers to ``poll()``. Under load that serializes admission and
scoring in one thread, and a request arriving just after a seal waits a
full scoring pass before its bucket even forms.

The continuous batcher decouples the two: ``submit`` is an O(1) enqueue
returning a :class:`PendingResult`; a dedicated scoring thread drains the
queue whenever a full max-size bucket is pending OR the oldest request
has waited ``max_wait_s`` — so requests keep joining the forming bucket
right up to its deadline while the previous bucket is still on device.
Shapes stay fixed: a drain pads to one of ``bucket_sizes``, and the
compiled-program count per scorer stays at ``len(bucket_sizes)``.

Backpressure bounds the tail: ``max_queue`` caps pending requests, and a
full queue blocks ``submit`` — p99 latency is then roughly
``max_queue / throughput + one bucket's scoring time`` instead of
unbounded queue growth.

Two priority lanes keep serving work ahead of everything else: the
``live`` lane (default) holds request traffic; the ``background`` lane
(``submit(..., priority="background")``) holds admission warmups, swap
probes, and nearline replays, and drains ONLY when no live request is
pending — background work can never queue ahead of a live request. Each
lane is independently capped at ``max_queue``, so a background flood
cannot backpressure live submitters.

Two optional controls act at the queue boundary: a
:class:`~photon_ml_tpu.serving.tenancy.quota.TenantQuota` (``quota=``)
is consulted at DRAIN time — a tenant over budget has its requests
resolved with an error before they reach the device, charged to that
tenant's own error budget via the plane — and an attached
:class:`~photon_ml_tpu.serving.overload.OverloadController` may answer
FE-only-able requests at SUBMIT time while the SLO budget is burning.

``scorers`` accepts one scorer or several replicas (multi-scorer mode:
one ``GameScorer`` per device, shared routing index) — drained buckets
round-robin across replicas, one scoring thread per replica, so replica
scoring overlaps wherever the backend allows.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, List, Optional, Sequence, Tuple

import numpy as np

from photon_ml_tpu.resilience.supervisor import SupervisedThread
from photon_ml_tpu.serving.batcher import DEFAULT_BUCKET_SIZES
from photon_ml_tpu.serving.metrics import ServingMetrics
from photon_ml_tpu.serving.requestplane import tenant_of_request_id
from photon_ml_tpu.serving.scorer import ScoreRequest, ScoreResult
from photon_ml_tpu.telemetry import span


class PendingResult:
    """Handle for one submitted request; ``result()`` blocks until its
    bucket is scored. Deliberately lighter than ``concurrent.futures``:
    no per-handle lock/condition — completion is signalled through the
    batcher's single condition, so creating one costs an allocation, not
    kernel objects."""

    __slots__ = ("_batcher", "value", "error", "done")

    def __init__(self, batcher: "ContinuousBatcher"):
        self._batcher = batcher
        self.value: Optional[ScoreResult] = None
        self.error: Optional[BaseException] = None
        self.done = False

    def result(self, timeout: Optional[float] = None) -> ScoreResult:
        if not self.done:
            self._batcher._wait_for(self, timeout)
        if self.error is not None:
            raise self.error
        return self.value  # type: ignore[return-value]


class ContinuousBatcher:
    def __init__(
        self,
        scorers,
        bucket_sizes: Sequence[int] = DEFAULT_BUCKET_SIZES,
        metrics: Optional[ServingMetrics] = None,
        max_wait_s: float = 0.002,
        max_queue: Optional[int] = None,
        clock: Callable[[], float] = time.perf_counter,
        plane=None,
        quota=None,
    ):
        scorers = (
            list(scorers) if isinstance(scorers, (list, tuple)) else [scorers]
        )
        if not scorers:
            raise ValueError("need at least one scorer")
        if max_wait_s < 0:
            raise ValueError(f"max_wait_s must be >= 0, got {max_wait_s}")
        buckets = sorted({int(b) for b in bucket_sizes})
        if not buckets or buckets[0] < 1:
            raise ValueError(f"bucket sizes must be positive, got {bucket_sizes}")
        for scorer in scorers:
            for cid, cache in getattr(scorer, "caches", {}).items():
                if cache.capacity < buckets[-1]:
                    raise ValueError(
                        f"hot-entity cache for {cid!r} holds {cache.capacity} "
                        f"rows < max bucket size {buckets[-1]}"
                    )
        self._scorers = scorers
        self.bucket_sizes: Tuple[int, ...] = tuple(buckets)
        self.max_bucket = buckets[-1]
        self.max_wait_s = float(max_wait_s)
        self.max_queue = (
            int(max_queue) if max_queue is not None else 2 * self.max_bucket
        )
        if self.max_queue < self.max_bucket:
            raise ValueError(
                f"max_queue {self.max_queue} < max bucket {self.max_bucket}"
            )
        self._metrics = metrics
        # request plane (serving/requestplane.py): lifecycle sampling +
        # SLO feed; None (the default) costs one check per drained batch
        self._plane = plane
        # tenant token bucket (tenancy/quota.py), consulted at DRAIN time:
        # an over-budget tenant's requests resolve with an error instead of
        # occupying device bucket slots
        self._quota = quota
        # set by OverloadController.attach(); consulted at submit (shed)
        # and polled from the drain path
        self._overload = None
        self._stage_capable: dict = {}
        self._clock = clock
        self._cond = threading.Condition()
        self._pending: "deque[Tuple[ScoreRequest, float, PendingResult]]" = (
            deque()
        )
        # background lane: drains only when the live lane is empty
        self._pending_bg: (
            "deque[Tuple[ScoreRequest, float, PendingResult]]"
        ) = deque()
        self.quota_shed_total = 0
        self._inflight = 0  # requests popped but not yet resolved
        self._running = False
        self._stop_event = threading.Event()
        self._threads: List[SupervisedThread] = []
        self._scorer_errors = 0

    # ------------------------------------------------------------ lifecycle

    def start(
        self, max_restarts: int = 5, emitter=None
    ) -> "ContinuousBatcher":
        with self._cond:
            if self._running:
                raise RuntimeError("batcher already running")
            self._running = True
        self._stop_event = threading.Event()
        # mode="loop": _serve_loop returns cleanly when _running flips
        # False; a crash anywhere else is contained and the loop re-enters
        # after backoff instead of silently stranding its replica.
        self._threads = [
            SupervisedThread(
                f"serving-batcher-{i}",
                (lambda s=scorer: self._serve_loop(s)),
                mode="loop",
                stop_event=self._stop_event,
                max_restarts=max_restarts,
                emitter=emitter,
            )
            for i, scorer in enumerate(self._scorers)
        ]
        for t in self._threads:
            t.start()
        return self

    def stop(self) -> None:
        with self._cond:
            self._running = False
            self._cond.notify_all()
        self._stop_event.set()
        for t in self._threads:
            t.join()
        self._threads = []
        # resolve anything stranded (stop before flush): submitters must
        # not block forever on a dead batcher
        with self._cond:
            for lane in (self._pending, self._pending_bg):
                while lane:
                    _, _, handle = lane.popleft()
                    handle.error = RuntimeError(
                        "batcher stopped before scoring"
                    )
                    handle.done = True
            self._cond.notify_all()

    def __enter__(self) -> "ContinuousBatcher":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def thread_stats(self) -> List[dict]:
        return [t.stats() for t in self._threads]

    def health(self) -> dict:
        """Healthy while at least one replica worker is not dead; every
        dead worker contributes a ``degraded`` reason."""
        workers = [t.health() for t in self._threads]
        degraded = [w["degraded"] for w in workers if not w["healthy"]]
        doc = {
            "healthy": not workers or len(degraded) < len(workers),
            "workers": workers,
            "scorer_errors": self._scorer_errors,
        }
        if degraded:
            doc["degraded"] = "; ".join(degraded)
        return doc

    # --------------------------------------------------------------- intake

    @property
    def queue_depth(self) -> int:
        return len(self._pending) + len(self._pending_bg)

    def submit(
        self, request: ScoreRequest, priority: str = "live"
    ) -> PendingResult:
        """Enqueue one request (blocks only on backpressure)."""
        return self.submit_many((request,), priority=priority)[0]

    def submit_many(
        self, requests: Sequence[ScoreRequest], priority: str = "live"
    ) -> List[PendingResult]:
        """Enqueue a burst under one lock acquisition (amortizes the
        condition handshake for high-rate closed-loop clients).

        ``priority="background"`` routes to the background lane, which
        drains only when no live request is pending. While an attached
        overload controller is active, live requests it can answer
        FE-only are resolved here without ever entering the queue."""
        if priority not in ("live", "background"):
            raise ValueError(f"unknown priority {priority!r}")
        handles = [PendingResult(self) for _ in requests]
        pairs = list(zip(requests, handles))
        ovl = self._overload
        if ovl is not None and priority == "live" and ovl.active:
            kept = []
            shed_ids: List[str] = []
            for req, handle in pairs:
                res = ovl.try_shed(req)
                if res is None:
                    kept.append((req, handle))
                else:
                    handle.value = res
                    handle.done = True
                    shed_ids.append(req.request_id)
            pairs = kept
            if shed_ids:
                plane = self._plane
                if plane is not None:
                    # shed answers ARE completions (FE-only, ~0 queue
                    # wait): feeding them lets the burn rate recover
                    lat = np.zeros(len(shed_ids), dtype=np.float64)
                    if getattr(plane, "wants_request_ids", False):
                        plane.observe_complete(lat, request_ids=shed_ids)
                    else:
                        plane.observe_complete(lat)
        lane = self._pending if priority == "live" else self._pending_bg
        with self._cond:
            if not self._running:
                raise RuntimeError("batcher is not running — call start()")
            i = 0
            while i < len(pairs):
                while len(lane) >= self.max_queue and self._running:
                    self._cond.wait()
                if not self._running:
                    raise RuntimeError("batcher stopped")
                room = self.max_queue - len(lane)
                now = self._clock()
                # C-level bulk extend: the lock is held, so per-item
                # appends would serialize against the scoring threads
                lane.extend(
                    (req, now, handle)
                    for req, handle in pairs[i : i + room]
                )
                i += room
                self._cond.notify_all()
        return handles

    def flush(self, timeout: Optional[float] = None) -> None:
        """Block until every submitted request has been scored."""
        deadline = None if timeout is None else self._clock() + timeout
        with self._cond:
            while self._pending or self._pending_bg or self._inflight:
                remaining = (
                    None if deadline is None else deadline - self._clock()
                )
                if remaining is not None and remaining <= 0:
                    raise TimeoutError("flush timed out")
                self._cond.wait(remaining)

    def _wait_for(
        self, handle: PendingResult, timeout: Optional[float]
    ) -> None:
        deadline = None if timeout is None else self._clock() + timeout
        with self._cond:
            while not handle.done:
                remaining = (
                    None if deadline is None else deadline - self._clock()
                )
                if remaining is not None and remaining <= 0:
                    raise TimeoutError("result not ready")
                self._cond.wait(remaining)

    # -------------------------------------------------------------- serving

    def _bucket_for(self, n: int) -> int:
        for b in self.bucket_sizes:
            if b >= n:
                return b
        return self.max_bucket

    def _serve_loop(self, scorer) -> None:
        while True:
            batch = None
            with self._cond:
                while self._running:
                    # live lane first; background only when live is empty
                    lane = self._pending if self._pending else self._pending_bg
                    n = len(lane)
                    if n >= self.max_bucket:
                        break
                    if n:
                        oldest_wait = self._clock() - lane[0][1]
                        if oldest_wait >= self.max_wait_s:
                            break
                        self._cond.wait(self.max_wait_s - oldest_wait)
                    else:
                        self._cond.wait()
                if not self._running:
                    return
                lane = self._pending if self._pending else self._pending_bg
                take = min(len(lane), self.max_bucket)
                if take == len(lane):
                    batch = list(lane)
                    lane.clear()
                else:
                    batch = [lane.popleft() for _ in range(take)]
                self._inflight += take
                # queue room just opened: wake blocked submitters (and any
                # sibling replica thread waiting for work)
                self._cond.notify_all()
            self._score(scorer, batch)

    def _supports_stages(self, scorer) -> bool:
        """Whether this replica's ``score_batch`` accepts a stage clock
        (checked once per scorer: drivers may pass stage-less scorers)."""
        key = id(scorer)
        cap = self._stage_capable.get(key)
        if cap is None:
            import inspect

            try:
                cap = "stages" in inspect.signature(
                    scorer.score_batch
                ).parameters
            except (TypeError, ValueError):
                cap = False
            self._stage_capable[key] = cap
        return cap

    def _apply_quota(self, batch):
        """Drain-time tenant admission: requests from a tenant whose token
        bucket is exhausted resolve with an error here — charged to that
        tenant's own error budget through the plane — instead of occupying
        device bucket slots ahead of in-budget tenants. Untagged requests
        (no ``tenant!`` prefix) always pass."""
        quota = self._quota
        kept = []
        shed = []
        for item in batch:
            tenant = tenant_of_request_id(item[0].request_id)
            if tenant is None or quota.try_admit(tenant):
                kept.append(item)
            else:
                shed.append(item)
        if shed:
            shed_ids = [req.request_id for req, _, _ in shed]
            with self._cond:
                for _, _, handle in shed:
                    handle.error = RuntimeError(
                        "request shed: tenant over quota at drain"
                    )
                    handle.done = True
                self.quota_shed_total += len(shed)
                self._inflight -= len(shed)
                self._cond.notify_all()
            plane = self._plane
            if plane is not None:
                if getattr(plane, "wants_request_ids", False):
                    plane.observe_errors(len(shed), request_ids=shed_ids)
                else:
                    plane.observe_errors(len(shed))
        return kept

    def _score(self, scorer, batch) -> None:
        if self._quota is not None:
            batch = self._apply_quota(batch)
            if not batch:
                if self._overload is not None:
                    self._overload.maybe_poll()
                return
        n = len(batch)
        dequeued = self._clock()
        bucket = self._bucket_for(n)
        plane = self._plane
        sampled: Optional[List[int]] = None
        stages: Optional[dict] = None
        if plane is not None:
            sampled = plane.sample_indices(
                [req.request_id for req, _, _ in batch]
            )
            if sampled and self._supports_stages(scorer):
                stages = {}
        results: Optional[List[ScoreResult]] = None
        error: Optional[BaseException] = None
        try:
            with span("serve/drain", n=n, bucket=bucket):
                if stages is not None:
                    results = scorer.score_batch(
                        [req for req, _, _ in batch], bucket, stages=stages
                    )
                else:
                    results = scorer.score_batch(
                        [req for req, _, _ in batch], bucket
                    )
        except BaseException as e:  # resolve handles, keep the loop alive
            error = e
            self._scorer_errors += 1
        done = self._clock()
        with self._cond:
            for i, (_, _, handle) in enumerate(batch):
                if error is None:
                    handle.value = results[i]
                else:
                    handle.error = error
                handle.done = True
            self._inflight -= n
            self._cond.notify_all()
        if plane is not None and error is not None:
            if getattr(plane, "wants_request_ids", False):
                plane.observe_errors(
                    n, request_ids=[req.request_id for req, _, _ in batch]
                )
            else:
                plane.observe_errors(n)
        if error is None and (self._metrics is not None or plane is not None):
            enqueued = np.fromiter(
                (t for _, t, _ in batch), dtype=np.float64, count=n
            )
            latencies = done - enqueued
            if self._metrics is not None:
                self._metrics.observe_batch(
                    n_real=n, bucket_size=bucket,
                    queue_depth=len(self._pending),
                )
                self._metrics.observe_queue_waits(dequeued - enqueued)
                self._metrics.observe_latencies(latencies, bucket_size=bucket)
            if plane is not None:
                if getattr(plane, "wants_request_ids", False):
                    # multi-tenant attribution: the id list is built only
                    # when the plane carries per-tenant SLO trackers
                    plane.observe_complete(
                        latencies,
                        request_ids=[req.request_id for req, _, _ in batch],
                    )
                else:
                    plane.observe_complete(latencies)
                if sampled:
                    plane.record_batch(
                        "continuous", bucket, n,
                        [
                            (batch[i][0].request_id, batch[i][1])
                            for i in sampled
                        ],
                        dequeued, stages, done,
                    )
        if self._overload is not None:
            # drain-path control step (rate-limited inside the controller):
            # the freshly fed SLO window drives shrink/shed for the NEXT
            # submissions, no dedicated poller thread required
            self._overload.maybe_poll()
