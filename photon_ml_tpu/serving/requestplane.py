"""Request plane: sampled per-request lifecycle tracing for the serving path.

The serving stack exposes aggregate reservoir percentiles (``metrics.py``),
but an aggregate p99 cannot say WHICH stage ate the budget — queue wait,
batch fill, entity routing, device dispatch, the device gather itself, or
an interference source off the request path (an admission scatter holding
the write lock, a hot-swap blackout). This module adds the missing
per-request view the way Snap ML attributes cost per pipeline level
(arxiv 1803.06333): a deterministic seeded sampler tags ~1/N requests at
submit, the batcher and scorer stamp monotonic timestamps at each stage
boundary, and the finished trace is drained to the run ledger as a
schema-validated ``request`` record plus a bounded in-memory ring for the
live ``/requests`` introspection route.

Cost discipline — the reason sampling exists at all:

- **Disabled (no plane attached) is the default** and costs one
  ``is None`` check per drained batch. The request-plane disabled-path
  parity gate pins replay scores bitwise-identical with the plane off.
- **Unsampled requests** in a batch that carries no sampled request cost
  one hash probe per request and nothing else: no stage clock is
  allocated, the scorer takes no timestamps.
- **Sampled requests** share their batch's stage stamps (stages are batch
  boundaries, queue wait is per-request), so a sampled batch costs a
  handful of ``perf_counter`` calls and one ledger line per sampled
  request — never a per-request device sync.

Stage semantics (all monotonic ``perf_counter`` seconds, telescoping so
the per-stage durations sum EXACTLY to the end-to-end latency):

====================  ====================================================
``queue``             submit → batch formed (bucket fill or deadline)
``featurize``         batch formed → sparse features packed/padded
``route``             featurize done → entity rows resolved to slots
``dispatch``          route done → device program dispatched (H2D + call)
``device``            dispatch returned → results materialized on host
``reply``             host results → caller's handle resolved
====================  ====================================================

Interference accounting: off-request-path work that can stall scoring
(admission scatters under the write lock, hot-swap blackouts) registers
``note_interference(kind, start, end)`` spans; each sampled request
records its overlap with them, so a p99 regression under swap load shows
up as ``swap_pause`` seconds inside the affected requests instead of
unexplained ``dispatch`` time.
"""

from __future__ import annotations

import threading
import time
import zlib
from collections import deque
from typing import Callable, Deque, Dict, List, Optional, Sequence, Tuple

# batch-level stage boundaries the scorer stamps into the stage clock dict
STAGE_FEATURIZE_DONE = "featurize_done"
STAGE_ROUTE_DONE = "route_done"
STAGE_DISPATCH_DONE = "dispatch_done"
STAGE_DEVICE_DONE = "device_done"

# per-request exclusive stages, in timeline order
REQUEST_STAGES = (
    "queue",
    "featurize",
    "route",
    "dispatch",
    "device",
    "reply",
)

# interference kinds folded into sampled records (seconds of overlap with
# the request's submit→reply window)
INTERFERENCE_KINDS = ("swap_pause", "admission")

# tenant prefix separator inside request ids: the tenancy plane submits
# requests as "<tenant>!<request_id>" so per-tenant SLO attribution needs
# no extra per-request field anywhere in the batcher/scorer path
TENANT_SEP = "!"


def tenant_of_request_id(request_id: str) -> Optional[str]:
    """The tenant a request id carries (``None`` for untagged ids)."""
    sep = request_id.find(TENANT_SEP)
    return request_id[:sep] if sep > 0 else None


def sample_hash(request_id: str, seed: int) -> int:
    """Deterministic 32-bit hash of a request id under a seed. Stateless —
    the same (id, seed) samples identically regardless of submission order
    or which batcher thread drains it."""
    return zlib.crc32(request_id.encode("utf-8", "surrogatepass"), seed & 0xFFFFFFFF)


class RequestPlane:
    """Collector for sampled request lifecycles + interference spans.

    Attach one instance per serving process: the batchers probe it per
    drained batch, the scorers stamp stage boundaries into the clock dict
    it hands out, admission/hot-swap register interference spans, and
    finished records land in the ledger (when given) and a bounded ring
    the live ``/requests`` route reads.

    ``sample_rate`` is the N of "sample ~1/N requests": 1 samples every
    request (tests, scenario harness), 0 disables sampling entirely while
    keeping the SLO feed alive. The sampler is a seeded hash of the
    request id — deterministic and thread-free.
    """

    def __init__(
        self,
        sample_rate: int = 64,
        seed: int = 0,
        ledger=None,
        capacity: int = 4096,
        slo=None,
        clock: Callable[[], float] = time.perf_counter,
        interference_capacity: int = 512,
        tenant_slos: Optional[Dict[str, object]] = None,
        tenant_of: Optional[Callable[[str], Optional[str]]] = None,
    ):
        if sample_rate < 0:
            raise ValueError(f"sample_rate must be >= 0, got {sample_rate}")
        self.sample_rate = int(sample_rate)
        self.seed = int(seed)
        self._ledger = ledger
        self._slo = slo
        self._clock = clock
        # per-tenant SLO trackers (tenancy plane): completions are
        # attributed by resolving each request id through ``tenant_of``
        # (default: the "<tenant>!" id prefix). Empty/None = single-tenant
        # process; the batchers then never materialize id lists.
        self.tenant_slos: Dict[str, object] = dict(tenant_slos or {})
        self._tenant_of = tenant_of or tenant_of_request_id
        self.tenant_requests: Dict[str, int] = {}
        self.tenant_errors: Dict[str, int] = {}
        self._lock = threading.Lock()
        self._records: Deque[dict] = deque(maxlen=max(1, int(capacity)))
        self._interference: Deque[Tuple[str, float, float]] = deque(
            maxlen=max(1, int(interference_capacity))
        )
        self.sampled_total = 0
        self.requests_total = 0
        self.errors_total = 0

    # ------------------------------------------------------------- sampling

    def sampled(self, request_id: str) -> bool:
        """Whether this request id is tagged for lifecycle tracing."""
        rate = self.sample_rate
        if rate <= 0:
            return False
        if rate == 1:
            return True
        return sample_hash(request_id, self.seed) % rate == 0

    def sample_indices(self, request_ids: Sequence[str]) -> List[int]:
        """Indices of sampled ids within one drained batch (empty list =
        the batch carries no sampled request and needs no stage clock)."""
        rate = self.sample_rate
        if rate <= 0:
            return []
        if rate == 1:
            return list(range(len(request_ids)))
        seed = self.seed
        return [
            i
            for i, rid in enumerate(request_ids)
            if sample_hash(rid, seed) % rate == 0
        ]

    # --------------------------------------------------------- interference

    def note_interference(self, kind: str, start: float, end: float) -> None:
        """Register an off-request-path stall window (``clock`` timebase):
        admission scatters, hot-swap blackouts. Sampled requests record
        their overlap with these spans at reply time."""
        if end <= start:
            return
        with self._lock:
            self._interference.append((str(kind), float(start), float(end)))

    def _interference_overlap(
        self, t_start: float, t_end: float
    ) -> Dict[str, float]:
        out: Dict[str, float] = {}
        with self._lock:
            spans = list(self._interference)
        for kind, s, e in spans:
            ov = min(t_end, e) - max(t_start, s)
            if ov > 0:
                out[kind] = out.get(kind, 0.0) + ov
        return out

    # ------------------------------------------------------------ recording

    @property
    def wants_request_ids(self) -> bool:
        """Whether the batchers should hand ``observe_complete`` /
        ``observe_errors`` the batch's request ids (only multi-tenant
        attribution needs them; the single-tenant path skips the list)."""
        return bool(self.tenant_slos)

    def observe_complete(
        self, latencies, errors: int = 0, request_ids=None
    ) -> None:
        """Per-batch completion feed (EVERY request, sampled or not): keeps
        the SLO tracker and the aggregate counters honest at O(1) per
        batch. ``latencies`` is an array-like of seconds. ``request_ids``
        (aligned with ``latencies``; only handed over when
        :attr:`wants_request_ids`) routes each completion to its tenant's
        SLO tracker as well."""
        n = len(latencies)
        self.requests_total += n
        self.errors_total += int(errors)
        if self._slo is not None:
            self._slo.observe_many(latencies, errors=errors)
        if self.tenant_slos and request_ids is not None:
            by: Dict[str, List[float]] = {}
            for rid, lat in zip(request_ids, latencies):
                tenant = self._tenant_of(rid)
                if tenant is not None and tenant in self.tenant_slos:
                    by.setdefault(tenant, []).append(float(lat))
            for tenant, lats in by.items():
                self.tenant_requests[tenant] = (
                    self.tenant_requests.get(tenant, 0) + len(lats)
                )
                self.tenant_slos[tenant].observe_many(lats)

    def observe_errors(self, n: int, request_ids=None) -> None:
        """Requests that failed before producing a latency (scorer error
        resolved through their handles)."""
        self.errors_total += int(n)
        if self._slo is not None:
            self._slo.observe_many((), errors=n)
        if self.tenant_slos and request_ids is not None:
            for rid in request_ids:
                tenant = self._tenant_of(rid)
                if tenant is not None and tenant in self.tenant_slos:
                    self.observe_tenant_errors(tenant, 1)

    def observe_tenant_errors(self, tenant: str, n: int) -> None:
        """Charge ``n`` failed/shed requests to ONE tenant's error budget
        (quota sheds land here — on the shedding tenant, never on the
        global SLO or on other tenants)."""
        slo = self.tenant_slos.get(tenant)
        if slo is not None:
            slo.observe_many((), errors=n)
        self.tenant_errors[tenant] = self.tenant_errors.get(tenant, 0) + int(n)

    def record_batch(
        self,
        batcher: str,
        bucket: int,
        n_real: int,
        entries: Sequence[Tuple[str, float]],
        t_dequeue: float,
        stages: Optional[dict],
        t_reply: float,
    ) -> None:
        """Finalize the sampled requests of one drained batch.

        ``entries`` are ``(request_id, t_submit)`` pairs for the SAMPLED
        requests only; ``stages`` is the clock dict the scorer stamped
        (missing boundaries collapse to zero-duration stages, so a scorer
        without stage support still yields queue/device-lumped records).
        """
        stages = stages or {}
        fd = stages.get(STAGE_FEATURIZE_DONE, t_dequeue)
        rd = stages.get(STAGE_ROUTE_DONE, fd)
        dd = stages.get(STAGE_DISPATCH_DONE, rd)
        vd = stages.get(STAGE_DEVICE_DONE, dd)
        for request_id, t_submit in entries:
            # clamp the boundary chain monotonic: a stage boundary can
            # never precede the previous one (or the submit itself)
            b0 = t_submit
            b1 = max(b0, t_dequeue)
            b2 = max(b1, fd)
            b3 = max(b2, rd)
            b4 = max(b3, dd)
            b5 = max(b4, vd)
            b6 = max(b5, t_reply)
            rec = {
                "request_id": str(request_id),
                "batcher": batcher,
                "bucket": int(bucket),
                "n_real": int(n_real),
                "stages": {
                    "queue": b1 - b0,
                    "featurize": b2 - b1,
                    "route": b3 - b2,
                    "dispatch": b4 - b3,
                    "device": b5 - b4,
                    "reply": b6 - b5,
                },
                "total_s": b6 - b0,
            }
            interference = self._interference_overlap(b0, b6)
            if interference:
                rec["interference"] = {
                    f"{k}_s": round(v, 9) for k, v in sorted(interference.items())
                }
            self.sampled_total += 1
            with self._lock:
                self._records.append(rec)
            if self._ledger is not None:
                self._ledger.write("request", **rec)

    # ------------------------------------------------------------ reporting

    def records(self) -> List[dict]:
        """Snapshot of the in-memory ring (most recent ``capacity``
        sampled records), shaped like the ledger's ``request`` records."""
        with self._lock:
            return [dict(r) for r in self._records]

    def reset_records(self) -> None:
        """Drop the in-memory ring (scenario harness: one ring per
        scenario). Ledger records and totals are untouched."""
        with self._lock:
            self._records.clear()

    def live_report(self) -> dict:
        """The tail-latency attribution over the in-memory ring — the
        ``/requests`` introspection payload. Mirrors
        ``analyze_run --requests`` over a ledger."""
        from photon_ml_tpu.telemetry.analyze import request_report

        report = request_report(
            [dict(r, type="request") for r in self.records()]
        )
        doc = {
            "sample_rate": self.sample_rate,
            "seed": self.seed,
            "sampled_total": self.sampled_total,
            "requests_total": self.requests_total,
            "errors_total": self.errors_total,
        }
        if report is not None:
            doc.update(report)
        if self._slo is not None:
            doc["slo"] = self._slo.status()
        if self.tenant_slos:
            doc["tenants"] = {
                tenant: {
                    "requests": self.tenant_requests.get(tenant, 0),
                    "errors": self.tenant_errors.get(tenant, 0),
                    "slo": slo.status(),
                }
                for tenant, slo in sorted(self.tenant_slos.items())
            }
        return doc
