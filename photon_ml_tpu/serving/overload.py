"""Closed-loop overload control: SLO burn rate drives serving actuation.

PR 15's request plane made overload *visible* (burn-rate gauges, tail
attribution); this module makes it an *actuator*. An
:class:`OverloadController` reads one :class:`~photon_ml_tpu.serving.slo
.SLOTracker`'s burn rate and, through a hysteresis state machine, drives
two knobs on the batchers attached to it:

- **deadline shrink** — while overloaded, every attached batcher's
  ``max_wait_s`` is scaled by ``shrink_factor`` (smaller buckets dispatch
  sooner: queue wait is traded for batch fill exactly when queue wait is
  what burns the latency budget);
- **FE-only shed** — requests whose random-effect entities are ALL
  absent or non-resident would gather the zero cold slot and score
  FE-only anyway; while overloaded those requests are answered inline on
  the host (same left-join FE-only semantics, no queue, no device
  dispatch), so the queue drains for requests whose scores actually need
  the device.

Control loop: ``burn >= burn_high`` (default 1.0 — the budget is burning
faster than it accrues) enters overload; ``burn <= burn_low`` (default
0.5) recovers. The gap is the hysteresis band that keeps the controller
from flapping at the boundary. The batchers poll the controller from
their own drain paths (``maybe_poll``), so no extra thread is required —
``start()`` runs an optional background poller for servers whose traffic
can stall entirely.

Observability: ``serving.overload.*`` gauges (burn rate, active flag,
deadline scale, sheds) when a metrics registry is attached, plus
``status()`` for ``/varz`` and the scenario result docs.
"""

from __future__ import annotations

import math
import threading
import time
from typing import Callable, Dict, List, Optional

import numpy as np

from photon_ml_tpu.serving.scorer import ScoreRequest, ScoreResult
from photon_ml_tpu.types import TaskType


def _host_mean(task, z: float) -> float:
    """Host-side task link-inverse (mirrors ``losses.pointwise
    .mean_function`` without a device dispatch); numerically stable
    sigmoid for the logistic task."""
    if task is TaskType.LOGISTIC_REGRESSION:
        if z >= 0:
            return 1.0 / (1.0 + math.exp(-z))
        e = math.exp(z)
        return e / (1.0 + e)
    if task is TaskType.POISSON_REGRESSION:
        return math.exp(min(z, 700.0))
    return z


class OverloadController:
    """SLO-burn-driven overload control over one serving replica group.

    ``attach()`` batchers (their native deadlines are recorded and
    restored on recovery/detach); ``attach_scorer()`` the scorer whose
    fixed-effect tables back the FE-only shed path. ``poll()`` reads the
    tracker and actuates; ``try_shed()`` is the batchers' intake hook.

    All actuation is reversible and bounded: deadlines never shrink below
    ``shrink_factor`` of their configured value, and shedding only ever
    answers requests with the score the full path would have produced
    FE-only anyway (cold/non-resident entities gather the zero cold
    slot)."""

    def __init__(
        self,
        slo,
        shrink_factor: float = 0.5,
        burn_high: float = 1.0,
        burn_low: float = 0.5,
        poll_interval_s: float = 0.05,
        registry=None,
        clock: Callable[[], float] = time.monotonic,
    ):
        if not 0.0 < shrink_factor <= 1.0:
            raise ValueError(
                f"shrink_factor must be in (0, 1], got {shrink_factor}"
            )
        if burn_low > burn_high:
            raise ValueError(
                f"burn_low {burn_low} > burn_high {burn_high} — the "
                "hysteresis band must be ordered"
            )
        self._slo = slo
        self.shrink_factor = float(shrink_factor)
        self.burn_high = float(burn_high)
        self.burn_low = float(burn_low)
        self.poll_interval_s = float(poll_interval_s)
        self._registry = registry
        self._clock = clock
        self._lock = threading.RLock()
        # id(batcher) -> (batcher, native max_wait_s or None)
        self._batchers: Dict[int, tuple] = {}
        self._scorer = None
        self._fe_specs: List[tuple] = []
        self._re_specs: List[tuple] = []
        self._fe_host: Dict[str, np.ndarray] = {}
        self._fe_src: Dict[str, int] = {}
        self.active = False
        self.last_burn = 0.0
        self.activations = 0
        self.recoveries = 0
        self.shed_total = 0
        self._last_poll = -math.inf
        self._thread: Optional[threading.Thread] = None
        self._stop_evt = threading.Event()

    # ----------------------------------------------------------- attachment

    def attach(self, batcher) -> None:
        """Register a batcher for deadline actuation and shed intake.
        Applies the current state immediately (attaching mid-overload
        shrinks right away)."""
        with self._lock:
            native = getattr(batcher, "max_wait_s", None)
            self._batchers[id(batcher)] = (batcher, native)
            batcher._overload = self
            if self.active and native is not None:
                batcher.max_wait_s = native * self.shrink_factor

    def detach(self, batcher) -> None:
        """Unregister and restore the batcher's native deadline."""
        with self._lock:
            entry = self._batchers.pop(id(batcher), None)
            if entry is not None:
                _, native = entry
                if native is not None:
                    batcher.max_wait_s = native
            if getattr(batcher, "_overload", None) is self:
                batcher._overload = None

    def attach_scorer(self, scorer) -> None:
        """Bind the scorer whose FE tables and routing back the shed
        path. Host copies of the FE vectors are cached and refreshed
        whenever a hot swap replaces the device arrays (identity check
        per coordinate, O(1) when nothing changed)."""
        with self._lock:
            self._scorer = scorer
            self._fe_specs = list(getattr(scorer, "_fe_specs", []))
            self._re_specs = list(getattr(scorer, "_re_specs", []))
            self._fe_host.clear()
            self._fe_src.clear()

    # ------------------------------------------------------------- control

    def poll(self) -> bool:
        """One control step: read the burn rate, move the hysteresis
        state machine, actuate deadlines, refresh gauges. Returns the
        post-step overload state."""
        status = self._slo.status()
        burn = float(status.get("burn_rate", 0.0))
        with self._lock:
            self.last_burn = burn
            if not self.active and burn >= self.burn_high:
                self.active = True
                self.activations += 1
                for batcher, native in self._batchers.values():
                    if native is not None:
                        batcher.max_wait_s = native * self.shrink_factor
            elif self.active and burn <= self.burn_low:
                self.active = False
                self.recoveries += 1
                for batcher, native in self._batchers.values():
                    if native is not None:
                        batcher.max_wait_s = native
            active = self.active
        if self._registry is not None:
            self._registry.gauge("serving.overload.burn_rate", burn)
            self._registry.gauge(
                "serving.overload.active", 1.0 if active else 0.0
            )
            self._registry.gauge(
                "serving.overload.deadline_scale",
                self.shrink_factor if active else 1.0,
            )
            self._registry.gauge(
                "serving.overload.shed_total", float(self.shed_total)
            )
        return active

    def maybe_poll(self, now: Optional[float] = None) -> None:
        """Rate-limited :meth:`poll` for the batchers' drain paths: a
        no-op within ``poll_interval_s`` of the last step, and contention
        -free (a second thread arriving mid-poll skips instead of
        queueing)."""
        now = self._clock() if now is None else now
        if now - self._last_poll < self.poll_interval_s:
            return
        if not self._lock.acquire(blocking=False):
            return
        try:
            if now - self._last_poll < self.poll_interval_s:
                return
            self._last_poll = now
        finally:
            self._lock.release()
        self.poll()

    # ------------------------------------------------------------ shedding

    def _fe_vector(self, cid: str) -> Optional[np.ndarray]:
        params = getattr(self._scorer, "_fe_params", None)
        if params is None:
            return None
        dev = params.get(cid)
        if dev is None:
            return None
        if self._fe_src.get(cid) != id(dev):
            self._fe_host[cid] = np.asarray(dev, dtype=np.float32)
            self._fe_src[cid] = id(dev)
        return self._fe_host[cid]

    def try_shed(self, request: ScoreRequest) -> Optional[ScoreResult]:
        """Answer a request FE-only on the host, IF overload is active
        and every random-effect entity of the request is absent or
        non-resident (the full path would score it FE-only through the
        cold slot anyway — shedding changes latency, not semantics).
        Returns None when the request must take the device path."""
        if not self.active:
            return None
        scorer = self._scorer
        if scorer is None:
            return None
        artifact = scorer.artifact
        routing = getattr(scorer, "_routing", None)
        cold: List[str] = []
        for cid, _, re_type in self._re_specs:
            eid = request.entity_ids.get(re_type)
            if eid is None:
                cold.append(cid)
                continue
            if type(eid) is not str:
                eid = str(eid)
            row = int(
                artifact.tables[cid].entity_index.get_indices([eid])[0]
            )
            if row < 0:
                cold.append(cid)
                continue
            if routing is None:
                return None  # no cheap residency probe: keep the device path
            coord = routing[cid]
            if (
                row < coord._slot_of.size
                and coord._slot_of[row] >= 0
            ):
                return None  # resident row: a shed would change the score
            cold.append(cid)
        z = float(request.offset)
        for cid, shard in self._fe_specs:
            w = self._fe_vector(cid)
            if w is None:
                return None
            feats = request.features.get(shard)
            if feats:
                for i, v in feats.items():
                    z += float(v) * float(w[i])
        with self._lock:
            self.shed_total += 1
        return ScoreResult(
            request_id=request.request_id,
            score=z,
            mean=float(_host_mean(scorer.task, z)),
            cold_coordinates=tuple(cold),
        )

    # ------------------------------------------------------------ lifecycle

    def start(self, interval_s: Optional[float] = None) -> "OverloadController":
        """Optional background poller (the batcher drain paths already
        poll; this covers servers whose traffic can stall entirely, so
        recovery is observed even with zero drains)."""
        if self._thread is not None:
            raise RuntimeError("overload controller already started")
        interval = (
            self.poll_interval_s if interval_s is None else float(interval_s)
        )
        self._stop_evt = threading.Event()

        def _loop():
            while not self._stop_evt.is_set():
                self.poll()
                self._stop_evt.wait(interval)

        self._thread = threading.Thread(
            target=_loop, name="overload-controller", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        """Stop the background poller and restore every attached
        batcher's native deadline."""
        if self._thread is not None:
            self._stop_evt.set()
            self._thread.join()
            self._thread = None
        with self._lock:
            if self.active:
                self.active = False
                self.recoveries += 1
            for batcher, native in self._batchers.values():
                if native is not None:
                    batcher.max_wait_s = native

    def __enter__(self) -> "OverloadController":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # ----------------------------------------------------------- reporting

    def status(self) -> dict:
        """``/varz`` + scenario-doc contribution."""
        with self._lock:
            return {
                "active": self.active,
                "last_burn_rate": round(self.last_burn, 4),
                "burn_high": self.burn_high,
                "burn_low": self.burn_low,
                "shrink_factor": self.shrink_factor,
                "activations": self.activations,
                "recoveries": self.recoveries,
                "shed_total": self.shed_total,
                "attached_batchers": len(self._batchers),
            }
