"""Request-stream replay: drive the serving stack from a scoring dataset.

Turns ``GameData`` rows into ``ScoreRequest``s (one per row: sparse
features per shard the artifact consumes, the row's entity id per
random-effect type, its offset) and pumps them through a microbatcher with
full metrics/event instrumentation. This is the shared driver behind
``cli/serve_game.py`` and the serving mode of ``bench.py``; tests use it to
prove the online path reproduces the offline ``GameModel.score``.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from photon_ml_tpu.data.game_data import GameData
from photon_ml_tpu.serving.artifact import ServingArtifact
from photon_ml_tpu.serving.batcher import DEFAULT_BUCKET_SIZES, MicroBatcher
from photon_ml_tpu.serving.continuous import ContinuousBatcher
from photon_ml_tpu.serving.metrics import ServingMetrics
from photon_ml_tpu.serving.scorer import GameScorer, ScoreRequest, ScoreResult
from photon_ml_tpu.telemetry import span


def requests_from_game_data(
    data: GameData,
    artifact: ServingArtifact,
    uids: Optional[Sequence[Optional[str]]] = None,
    max_requests: Optional[int] = None,
) -> List[ScoreRequest]:
    """One ScoreRequest per dataset row, restricted to the shards and
    random-effect types the artifact actually consumes."""
    n = data.num_rows
    if max_requests is not None:
        n = min(n, int(max_requests))
    shards = sorted({t.feature_shard for t in artifact.tables.values()})
    re_types = [t for t in artifact.random_effect_types() if t in data.id_tags]

    per_row: Dict[str, List[Dict[int, float]]] = {}
    for shard_name in shards:
        shard = data.feature_shards[shard_name]
        feats: List[Dict[int, float]] = [{} for _ in range(n)]
        keep = shard.rows < n
        for r, c, v in zip(
            shard.rows[keep], shard.cols[keep], shard.vals[keep]
        ):
            feats[int(r)][int(c)] = float(v)
        per_row[shard_name] = feats

    requests = []
    for i in range(n):
        rid = None
        if uids is not None and i < len(uids):
            rid = uids[i]
        requests.append(
            ScoreRequest(
                request_id=str(rid) if rid is not None else f"row-{i}",
                features={s: per_row[s][i] for s in shards},
                entity_ids={t: str(data.id_tags[t][i]) for t in re_types},
                offset=float(data.offsets[i]),
            )
        )
    return requests


def max_nnz_of(
    requests: Sequence[ScoreRequest], round_pow2: bool = True
) -> Dict[str, int]:
    """Per-shard max nonzero count over a request stream — a tight
    ``GameScorer(max_nnz=...)`` choice for replay (rounded up to a power of
    two so near-boundary streams do not split compile signatures)."""
    out: Dict[str, int] = {}
    for req in requests:
        for shard, feats in req.features.items():
            out[shard] = max(out.get(shard, 1), len(feats))
    if round_pow2:
        out = {s: 1 << (int(k - 1)).bit_length() for s, k in out.items()}
    return out


def replay_requests(
    scorer: GameScorer,
    requests: Sequence[ScoreRequest],
    bucket_sizes: Sequence[int] = DEFAULT_BUCKET_SIZES,
    metrics: Optional[ServingMetrics] = None,
    emitter=None,
    model_id: str = "game-model",
    swap_manager=None,
    watch_dir: Optional[str] = None,
    poll_every: int = 256,
    continuous: bool = False,
    max_wait_s: float = 0.002,
    max_queue: Optional[int] = None,
    admission=None,
    plane=None,
    overload=None,
    quota=None,
) -> Tuple[List[ScoreResult], dict]:
    """Pump a request stream through a fresh microbatcher.

    Returns (results in submission order, metrics snapshot). When an
    ``EventEmitter`` is given, a ``ScoringStartEvent`` fires before the
    first request and a ``ScoringFinishEvent`` (carrying the snapshot)
    after the flush. When a ``HotSwapManager`` and ``watch_dir`` are given,
    the batcher is flushed and ``swap_manager.poll_directory(watch_dir)``
    called every ``poll_every`` requests — new deltas land between batches,
    never under an in-flight one; swap reports ride in the snapshot under
    ``"swap_reports"``.

    ``continuous=True`` drives a :class:`ContinuousBatcher` instead of the
    sealed ``MicroBatcher``: ``scorer`` may then be ONE scorer or a list
    of replicas (multi-scorer mode), requests are submitted in bursts and
    scored by the batcher's threads, and ``max_wait_s``/``max_queue``
    bound deadline and backpressure. An ``AdmissionController`` passed as
    ``admission`` runs for the duration of the replay (started/stopped
    here when not already running) and its stats ride in the snapshot.

    A :class:`~photon_ml_tpu.serving.requestplane.RequestPlane` passed as
    ``plane`` is threaded through the batcher (lifecycle sampling + SLO
    feed), the metrics (hot-swap pauses become interference spans), and
    the admission controller (admit windows likewise); its summary — and
    the SLO status when the plane carries a tracker — ride in the
    snapshot under ``"request_plane"`` / ``"slo"``. ``plane=None`` (the
    default) is the bitwise-pinned zero-cost path.

    An :class:`~photon_ml_tpu.serving.overload.OverloadController` passed
    as ``overload`` is attached to the batcher for the duration of the
    replay (deadline shrink + FE-only shed, detached on exit; its scorer
    binding defaults to the lead scorer when not already bound) and its
    status rides in the snapshot under ``"overload"``. A ``quota``
    (tenancy token bucket) is forwarded to the batcher for drain-time
    tenant admission.
    """
    from photon_ml_tpu.event import ScoringFinishEvent, ScoringStartEvent

    scorers = list(scorer) if isinstance(scorer, (list, tuple)) else [scorer]
    lead = scorers[0]
    metrics = metrics if metrics is not None else ServingMetrics()
    if plane is not None:
        # interference producers: hot-swap pauses via the metrics hook,
        # admission windows via the controller hook
        metrics.request_plane = plane
        if admission is not None:
            admission.request_plane = plane
    if emitter is not None:
        emitter.send_event(
            ScoringStartEvent(model_id=model_id, num_requests=len(requests))
        )
    watching = swap_manager is not None and watch_dir is not None
    poll_every = max(1, int(poll_every))
    swap_reports: List[object] = []
    results: List[ScoreResult] = []

    started_admission = False
    if admission is not None and admission._thread is None:
        admission.start()
        started_admission = True
    try:
        t0 = time.perf_counter()
        with span(
            "serve/replay", num_requests=len(requests), model_id=model_id
        ):
            if overload is not None and overload._scorer is None:
                overload.attach_scorer(lead)
            if continuous:
                batcher = ContinuousBatcher(
                    scorers,
                    bucket_sizes=bucket_sizes,
                    metrics=metrics,
                    max_wait_s=max_wait_s,
                    max_queue=max_queue,
                    plane=plane,
                    quota=quota,
                ).start()
                if overload is not None:
                    overload.attach(batcher)
                try:
                    handles = []
                    chunk = batcher.max_bucket
                    for i in range(0, len(requests), chunk):
                        if watching and (i // chunk) % max(
                            1, poll_every // chunk
                        ) == 0:
                            batcher.flush()
                            swap_reports.extend(
                                swap_manager.poll_directory(watch_dir)
                            )
                        handles.extend(
                            batcher.submit_many(requests[i : i + chunk])
                        )
                    batcher.flush()
                finally:
                    if overload is not None:
                        overload.detach(batcher)
                    batcher.stop()
                if quota is None:
                    results = [h.result(timeout=0) for h in handles]
                else:
                    results = []
                    for h in handles:
                        try:
                            results.append(h.result(timeout=0))
                        except RuntimeError:
                            # drain-time quota shed: the request was
                            # answered with an error and charged to its
                            # tenant; the replay stream continues
                            pass
            else:
                if len(scorers) != 1:
                    raise ValueError(
                        "sealed replay drives one scorer; pass "
                        "continuous=True for multi-scorer mode"
                    )
                batcher = MicroBatcher(
                    lead, bucket_sizes=bucket_sizes, metrics=metrics,
                    plane=plane, quota=quota,
                )
                if overload is not None:
                    overload.attach(batcher)
                for i, req in enumerate(requests):
                    if watching and i % poll_every == 0:
                        results.extend(batcher.flush())
                        swap_reports.extend(
                            swap_manager.poll_directory(watch_dir)
                        )
                    results.extend(batcher.submit(req))
                results.extend(batcher.flush())
                if overload is not None:
                    overload.detach(batcher)
        wall = time.perf_counter() - t0
    finally:
        if started_admission:
            admission.stop()

    residency = None
    if hasattr(lead, "residency_stats"):
        residency = lead.residency_stats() or None
    snapshot = metrics.snapshot(
        cache_stats=lead.cache_stats() or None,
        compile_count=max(s.compile_count for s in scorers),
        residency=residency,
        admission=admission.stats() if admission is not None else None,
    )
    snapshot["replay_wall_seconds"] = round(wall, 6)
    if wall > 0:
        snapshot["replay_requests_per_s"] = round(len(requests) / wall, 3)
    if plane is not None:
        report = plane.live_report()
        slo = report.pop("slo", None)
        snapshot["request_plane"] = report
        if slo is not None:
            snapshot["slo"] = slo
    if overload is not None:
        snapshot["overload"] = overload.status()
    if watching:
        snapshot["swap_reports"] = [
            {
                "generation": r.generation,
                "fingerprint": r.fingerprint,
                "rows_updated": r.rows_updated,
                "rolled_back": r.rolled_back,
                "blackout_s": round(r.blackout_s, 6),
            }
            for r in swap_reports
        ]
    if emitter is not None:
        emitter.send_event(
            ScoringFinishEvent(
                model_id=model_id,
                num_requests=len(results),
                wall_seconds=wall,
                metrics=dict(snapshot),
            )
        )
    return results, snapshot
