"""Serving artifact: a trained GAME model packed for the online score path.

The training-side ``GameModel`` stores random effects as padded per-bucket
blocks in per-entity *local* feature space — the right layout for coordinate
descent, the wrong one for a per-request gather. Packing materializes, per
coordinate:

- fixed effect: one dense float32 coefficient vector ``[dim]``;
- random effect: one contiguous float32 table ``[n_entities, dim]`` of
  global-space coefficient rows (sorted by entity id), plus an
  entity-id → row-index map persisted as a PHIX off-heap store
  (``indexmap/offheap``) so million-entity maps never live on the heap.

The artifact directory reuses the ``io/model_io`` metadata file
(``model-metadata.json``; task, model name, configurations) with a
``serving`` section describing each packed coordinate:

    <dir>/model-metadata.json
    <dir>/fixed-effect/<cid>.npy
    <dir>/random-effect/<cid>/table.npy
    <dir>/random-effect/<cid>/entity-index/{metadata.json,partition-0.bin}
    <dir>/feature-index/<shard>/{metadata.json,partition-0.bin}

``feature-index`` stores are forward-lookup (name → index) maps used to
featurize raw records at serve time; they preserve the model's original
indices, so reverse lookup is only meaningful when those are dense.
"""

from __future__ import annotations

import dataclasses
import json
import os
import pathlib
from typing import Dict, Iterable, Optional, Tuple

import numpy as np

from photon_ml_tpu.indexmap import DefaultIndexMap, IndexMap
from photon_ml_tpu.indexmap.offheap import (
    METADATA_FILE as _PHIX_METADATA_FILE,
    OffHeapIndexMap,
    PARTITION_FILE as _PHIX_PARTITION_FILE,
    _build_partition,
)
from photon_ml_tpu.io.model_io import (
    load_game_model_metadata,
    save_game_model_metadata,
)
from photon_ml_tpu.models.game import GameModel
from photon_ml_tpu.models.glm import GeneralizedLinearModel
from photon_ml_tpu.models.random_effect import RandomEffectModel
from photon_ml_tpu.types import TaskType

FIXED_EFFECT_DIR = "fixed-effect"
RANDOM_EFFECT_DIR = "random-effect"
ENTITY_INDEX_DIR = "entity-index"
FEATURE_INDEX_DIR = "feature-index"
TABLE_FILE = "table.npy"
SERVING_FORMAT_VERSION = 1
# Serve-side tuning sidecar. Kept OUTSIDE model-metadata.json so a running
# --auto-tune can persist a winner without rewriting the model manifest,
# and excluded from fingerprint_dir so delta chains stay valid across it.
TUNED_CONFIG_FILE = "tuned-config.json"


@dataclasses.dataclass
class ServingTable:
    """One packed coordinate: FE vector or RE (entities × dim) matrix."""

    feature_shard: str
    random_effect_type: Optional[str]
    weights: np.ndarray  # FE: [dim] float32; RE: [n_entities, dim] float32
    entity_index: Optional[IndexMap] = None  # RE only: entity id -> table row

    @property
    def is_random_effect(self) -> bool:
        return self.random_effect_type is not None

    @property
    def dim(self) -> int:
        return int(self.weights.shape[-1])

    @property
    def n_entities(self) -> int:
        return int(self.weights.shape[0]) if self.is_random_effect else 0


@dataclasses.dataclass
class ServingArtifact:
    task: TaskType
    tables: Dict[str, ServingTable]  # coordinate id -> packed table
    model_name: str = "photon-ml-tpu"
    # the training model's configurations blob (feature shard -> bags etc.)
    # rides along so the serve CLI can read raw records the same way the
    # score CLI does
    configurations: Dict[str, object] = dataclasses.field(default_factory=dict)
    feature_index: Dict[str, IndexMap] = dataclasses.field(default_factory=dict)
    # winning knob values from --auto-tune (knob name -> value); None when
    # the artifact has never been tuned. Persisted in the metadata's
    # "tuned_config" section at pack time and overridable post-hoc by the
    # tuned-config.json sidecar (see save_tuned_config).
    tuned_config: Optional[Dict[str, object]] = None

    def entity_row(self, cid: str, entity_id: str) -> int:
        """Table row of an entity in one RE coordinate; -1 when cold/unknown
        (the caller scores FE-only for that coordinate — RE prior mean 0)."""
        table = self.tables[cid]
        if table.entity_index is None:
            raise ValueError(f"coordinate {cid!r} is not a random effect")
        return table.entity_index.get_index(str(entity_id))

    def shard_dims(self) -> Dict[str, int]:
        dims: Dict[str, int] = {}
        for t in self.tables.values():
            dims[t.feature_shard] = max(dims.get(t.feature_shard, 0), t.dim)
        return dims

    def random_effect_types(self) -> Tuple[str, ...]:
        return tuple(
            sorted(
                {
                    t.random_effect_type
                    for t in self.tables.values()
                    if t.random_effect_type
                }
            )
        )


def pack_game_model(
    model: GameModel,
    index_maps: Optional[Dict[str, IndexMap]] = None,
    model_name: str = "photon-ml-tpu",
    configurations: Optional[dict] = None,
) -> ServingArtifact:
    """Pack a trained GameModel into the serving layout.

    Random-effect rows are materialized in *global* shard space (one dense
    row per entity, sorted by entity id); a factored RE model is expanded
    through its projection matrix (``w = latent · Bᵀ``) so the packed table
    scores identically to the training model. Gathers of sharded arrays run
    on every host (they are collectives); packing itself is host-side.
    """
    from photon_ml_tpu.algorithm.factored_random_effect import (
        FactoredRandomEffectModel,
    )
    from photon_ml_tpu.parallel.mesh import fetch_global

    tables: Dict[str, ServingTable] = {}
    for cid, sub in model.models.items():
        meta = model.meta[cid]
        if isinstance(sub, GeneralizedLinearModel):
            w = np.asarray(fetch_global(sub.coefficients.means), dtype=np.float32)
            tables[cid] = ServingTable(
                feature_shard=meta.feature_shard,
                random_effect_type=None,
                weights=w,
            )
        elif isinstance(sub, RandomEffectModel):
            tables[cid] = _pack_random_effect(
                meta.feature_shard, sub.random_effect_type,
                sub.items(), sub.global_dim,
            )
        elif isinstance(sub, FactoredRandomEffectModel):
            B = np.asarray(fetch_global(sub.projection_matrix))  # [d, k]
            latent = sub.latent

            def _factored_items():
                for b, ids in enumerate(latent.entity_ids):
                    w_b = np.asarray(fetch_global(latent.coefficients[b]))
                    eff = w_b @ B.T  # [Eb, d]
                    for e, eid in enumerate(ids):
                        (nz,) = np.nonzero(eff[e])
                        yield eid, {int(i): float(eff[e, i]) for i in nz}

            tables[cid] = _pack_random_effect(
                meta.feature_shard, latent.random_effect_type,
                _factored_items(), B.shape[0],
            )
        else:
            raise ValueError(
                f"cannot pack sub-model type {type(sub).__name__} for {cid}"
            )
    configurations = dict(configurations or {})
    # a train-side --auto-tune winner rides along in the model metadata;
    # lift it into the artifact field so direct --model-dir serving boots
    # tuned exactly like artifact-dir serving
    tuned = configurations.pop("tuned_config", None)
    return ServingArtifact(
        task=model.task,
        tables=tables,
        model_name=model_name,
        configurations=configurations,
        feature_index=dict(index_maps or {}),
        tuned_config=tuned,
    )


def _pack_random_effect(
    feature_shard: str,
    re_type: str,
    items: Iterable[Tuple[str, Dict[int, float]]],
    global_dim: int,
) -> ServingTable:
    sparse = {str(eid): coefs for eid, coefs in items}
    ids = sorted(sparse)
    table = np.zeros((len(ids), global_dim), dtype=np.float32)
    for row, eid in enumerate(ids):
        for i, v in sparse[eid].items():
            table[row, i] = v
    return ServingTable(
        feature_shard=feature_shard,
        random_effect_type=re_type,
        weights=table,
        entity_index=DefaultIndexMap({eid: row for row, eid in enumerate(ids)}),
    )


def _index_map_items(imap: IndexMap) -> Iterable[Tuple[str, int]]:
    if isinstance(imap, DefaultIndexMap):
        return list(imap.items())
    # generic fallback: contiguous reverse scan (OffHeapIndexMap etc.)
    out = []
    for i in range(len(imap)):
        name = imap.get_feature_name(i)
        if name is not None:
            out.append((name, i))
    return out


def _write_phix_map(items: Iterable[Tuple[str, int]], out_dir: str) -> None:
    """Persist a name→index map as a single-partition PHIX store, PRESERVING
    the given indices (unlike ``build_offheap_index_map``, which reassigns
    them — the artifact's indices must keep matching the packed weights)."""
    items = sorted(items)
    out = pathlib.Path(out_dir)
    out.mkdir(parents=True, exist_ok=True)
    keys = [name.encode("utf-8") for name, _ in items]
    indices = np.asarray([i for _, i in items], dtype=np.uint32)
    _build_partition(str(out / _PHIX_PARTITION_FILE.format(i=0)), keys, indices)
    (out / _PHIX_METADATA_FILE).write_text(
        json.dumps(
            {
                "format": "PHIX",
                "version": 1,
                "num_partitions": 1,
                "num_entries": len(keys),
                "partition_offsets": [0],
            }
        )
    )


def save_artifact(artifact: ServingArtifact, output_dir: str) -> None:
    """Atomically write the artifact directory (layout in the module
    docstring): build in a tmp sibling dir, fsync the metadata file, rename
    over the target — same pattern as ``save_training_checkpoint``. A crash
    at any point leaves either the previous artifact or the new one, never
    a half-written directory that ``load_artifact`` would happily open (and
    a hot-swap watcher would happily serve)."""
    import shutil
    import tempfile

    from photon_ml_tpu.io.model_io import METADATA_FILE

    parent = os.path.dirname(os.path.abspath(output_dir)) or "."
    os.makedirs(parent, exist_ok=True)
    tmp = tempfile.mkdtemp(prefix=".artifact-tmp-", dir=parent)
    try:
        _write_artifact_contents(artifact, tmp)
        # the metadata file is written LAST and names every other file;
        # fsync it so the rename below never exposes an artifact whose
        # manifest is still in the page cache only
        fd = os.open(os.path.join(tmp, METADATA_FILE), os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)
        old = None
        if os.path.isdir(output_dir):
            old = tempfile.mkdtemp(prefix=".artifact-old-", dir=parent)
            os.rmdir(old)
            os.replace(output_dir, old)
        os.replace(tmp, output_dir)
        if old is not None:
            shutil.rmtree(old, ignore_errors=True)
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise


def _write_artifact_contents(artifact: ServingArtifact, output_dir: str) -> None:
    os.makedirs(output_dir, exist_ok=True)
    serving: Dict[str, object] = {
        "format_version": SERVING_FORMAT_VERSION,
        "coordinates": {},
    }
    for cid, table in artifact.tables.items():
        desc = {
            "kind": "random" if table.is_random_effect else "fixed",
            "feature_shard": table.feature_shard,
            "dim": table.dim,
        }
        if table.is_random_effect:
            desc["random_effect_type"] = table.random_effect_type
            desc["n_entities"] = table.n_entities
            cdir = os.path.join(output_dir, RANDOM_EFFECT_DIR, cid)
            os.makedirs(cdir, exist_ok=True)
            np.save(
                os.path.join(cdir, TABLE_FILE),
                np.asarray(table.weights, dtype=np.float32),
            )
            _write_phix_map(
                _index_map_items(table.entity_index),
                os.path.join(cdir, ENTITY_INDEX_DIR),
            )
        else:
            fdir = os.path.join(output_dir, FIXED_EFFECT_DIR)
            os.makedirs(fdir, exist_ok=True)
            np.save(
                os.path.join(fdir, f"{cid}.npy"),
                np.asarray(table.weights, dtype=np.float32),
            )
        serving["coordinates"][cid] = desc
    for shard, imap in artifact.feature_index.items():
        _write_phix_map(
            _index_map_items(imap),
            os.path.join(output_dir, FEATURE_INDEX_DIR, shard),
        )
    configurations = dict(artifact.configurations)
    configurations["serving"] = serving
    if artifact.tuned_config:
        configurations["tuned_config"] = dict(artifact.tuned_config)
    save_game_model_metadata(
        output_dir, artifact.task,
        model_name=artifact.model_name,
        configurations=configurations,
    )


def load_artifact(artifact_dir: str, mmap: bool = True) -> ServingArtifact:
    """Open an artifact directory.

    ``mmap=True`` memory-maps the RE coefficient tables (they are the
    host-side backing store behind the device cache, so the full tables
    need never be resident) and the PHIX entity stores (always mmap'd).
    """
    metadata = load_game_model_metadata(artifact_dir)
    task = TaskType[metadata["modelType"]]
    configurations = dict(metadata.get("configurations") or {})
    serving = configurations.pop("serving", None)
    if not serving:
        raise ValueError(
            f"{artifact_dir} has no 'serving' section in its metadata — "
            "not a serving artifact (export one with "
            "photon_ml_tpu.serving.save_artifact)"
        )
    mmap_mode = "r" if mmap else None
    tables: Dict[str, ServingTable] = {}
    for cid, desc in serving["coordinates"].items():
        if desc["kind"] == "random":
            cdir = os.path.join(artifact_dir, RANDOM_EFFECT_DIR, cid)
            weights = np.load(os.path.join(cdir, TABLE_FILE), mmap_mode=mmap_mode)
            entity_index: IndexMap = OffHeapIndexMap(
                os.path.join(cdir, ENTITY_INDEX_DIR)
            )
            tables[cid] = ServingTable(
                feature_shard=desc["feature_shard"],
                random_effect_type=desc["random_effect_type"],
                weights=weights,
                entity_index=entity_index,
            )
        else:
            weights = np.load(
                os.path.join(artifact_dir, FIXED_EFFECT_DIR, f"{cid}.npy"),
                mmap_mode=mmap_mode,
            )
            tables[cid] = ServingTable(
                feature_shard=desc["feature_shard"],
                random_effect_type=None,
                weights=weights,
            )
    feature_index: Dict[str, IndexMap] = {}
    fdir = os.path.join(artifact_dir, FEATURE_INDEX_DIR)
    if os.path.isdir(fdir):
        for shard in sorted(os.listdir(fdir)):
            feature_index[shard] = OffHeapIndexMap(os.path.join(fdir, shard))
    # tuned config: sidecar (serve-side --auto-tune) overrides the metadata
    # section (train-side --auto-tune carried through the pack flow)
    tuned = configurations.pop("tuned_config", None)
    sidecar = load_tuned_config(artifact_dir)
    if sidecar is not None:
        tuned = sidecar
    return ServingArtifact(
        task=task,
        tables=tables,
        model_name=metadata.get("modelName", "game-model"),
        configurations=configurations,
        feature_index=feature_index,
        tuned_config=tuned,
    )


def save_tuned_config(
    artifact_dir: str,
    tuned_config: Dict[str, object],
    provenance: Optional[Dict[str, object]] = None,
) -> str:
    """Atomically persist an --auto-tune winner next to an artifact.

    Written as the ``tuned-config.json`` sidecar (tmp file + fsync +
    rename) so a live artifact directory is never rewritten and a hot-swap
    watcher can't observe a half-written manifest. The sidecar is excluded
    from :func:`photon_ml_tpu.incremental.delta.fingerprint_dir`, so
    writing it does not invalidate an existing delta chain."""
    import tempfile

    doc: Dict[str, object] = {"tuned_config": dict(tuned_config)}
    if provenance:
        doc["provenance"] = dict(provenance)
    target = os.path.join(artifact_dir, TUNED_CONFIG_FILE)
    fd, tmp = tempfile.mkstemp(
        prefix=".tuned-config-", suffix=".json", dir=artifact_dir
    )
    try:
        with os.fdopen(fd, "w", encoding="utf-8") as f:
            json.dump(doc, f, indent=2, sort_keys=True)
            f.write("\n")
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, target)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    return target


def load_tuned_config(artifact_dir: str) -> Optional[Dict[str, object]]:
    """Read the tuned-config sidecar; None when the artifact is untuned."""
    path = os.path.join(artifact_dir, TUNED_CONFIG_FILE)
    if not os.path.exists(path):
        return None
    with open(path, "r", encoding="utf-8") as f:
        doc = json.load(f)
    tuned = doc.get("tuned_config")
    if not isinstance(tuned, dict):
        raise ValueError(f"{path}: missing 'tuned_config' object")
    return tuned
