"""Supervised background delta watcher: the ``--watch-deltas`` loop as a
daemon that survives its own crashes.

``serve_game --watch-deltas`` used to poll inline between request
batches; anything long-running (a sidecar thread, a notebook serving
loop) had to spin its own bare thread around
:meth:`HotSwapManager.poll_directory` — and one uncaught exception there
silently froze the model at its current generation forever.

:class:`DeltaWatcher` runs the poll on a :class:`SupervisedThread`
(mode="tick"): a crash in discovery or apply is recorded, the loop
restarts with backoff, and past the restart cap the watcher is declared
dead — serving keeps answering on the last good generation while
``health()`` reports the degraded reason for ``/healthz``.

Unreadable or partially-written deltas never reach the supervisor at
all: :meth:`HotSwapManager.poll_directory` already retries the load and
skips (without marking processed) on failure, so the common corruption
case costs a failure record, not a thread restart.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

from photon_ml_tpu.resilience.supervisor import SupervisedThread
from photon_ml_tpu.serving.hotswap import SwapReport

__all__ = ["DeltaWatcher"]

_MAX_KEPT_REPORTS = 64


class DeltaWatcher:
    """Polls ``watch_dir`` for published deltas and applies them through
    ``manager`` (a :class:`HotSwapManager` or :class:`CoordinatedHotSwap`
    — anything with ``poll_directory``) every ``interval_s`` seconds on a
    supervised daemon thread."""

    def __init__(
        self,
        manager,
        watch_dir: str,
        interval_s: float = 1.0,
        max_restarts: int = 5,
        emitter=None,
    ):
        if not hasattr(manager, "poll_directory"):
            raise TypeError(
                f"manager {type(manager).__name__} has no poll_directory"
            )
        self._manager = manager
        self.watch_dir = str(watch_dir)
        self.interval_s = float(interval_s)
        self._max_restarts = int(max_restarts)
        self._emitter = emitter
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self._thread: Optional[SupervisedThread] = None
        self.polls = 0
        self.swaps = 0
        self._reports: List[SwapReport] = []

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "DeltaWatcher":
        if self._thread is not None:
            raise RuntimeError("delta watcher already running")
        self._stop.clear()
        self._thread = SupervisedThread(
            "serving-deltawatch",
            self._tick,
            mode="tick",
            stop_event=self._stop,
            max_restarts=self._max_restarts,
            emitter=self._emitter,
        )
        self._thread.start()
        return self

    def stop(self, timeout: Optional[float] = 5.0) -> None:
        if self._thread is None:
            return
        self._thread.stop(timeout)
        self._thread = None

    def __enter__(self) -> "DeltaWatcher":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # ------------------------------------------------------------- the tick
    def _tick(self) -> None:
        reports = self._manager.poll_directory(self.watch_dir)
        with self._lock:
            self.polls += 1
            if reports:
                self.swaps += len(reports)
                self._reports.extend(reports)
                del self._reports[:-_MAX_KEPT_REPORTS]
        self._stop.wait(self.interval_s)

    def poll_now(self) -> List[SwapReport]:
        """One synchronous poll on the caller's thread (tests, warmup)."""
        reports = self._manager.poll_directory(self.watch_dir)
        with self._lock:
            self.polls += 1
            if reports:
                self.swaps += len(reports)
                self._reports.extend(reports)
                del self._reports[:-_MAX_KEPT_REPORTS]
        return reports

    def drain_reports(self) -> List[SwapReport]:
        with self._lock:
            out, self._reports = self._reports, []
        return out

    # -------------------------------------------------------------- readers
    def stats(self) -> Dict[str, Any]:
        with self._lock:
            doc: Dict[str, Any] = {
                "watch_dir": self.watch_dir,
                "polls": self.polls,
                "swaps": self.swaps,
                "running": self._thread is not None,
            }
        if self._thread is not None:
            doc["supervisor"] = self._thread.stats()
        return doc

    def health(self) -> Dict[str, Any]:
        if self._thread is None:
            return {"healthy": True, "name": "serving-deltawatch",
                    "running": False}
        doc = self._thread.health()
        doc["running"] = True
        return doc
