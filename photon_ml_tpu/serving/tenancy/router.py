"""Deterministic variant routing with hot-adjustable ramp percentages.

A ramped rollout needs two properties at once: the variant split must be
*hot-adjustable* (1% -> 50% -> 100% without restarting or draining the
server) and *sticky per request* (replaying a request id must land on the
same variant, so experiment buckets are reproducible and debuggable).

``VariantRouter`` gets both from one seeded hash: a request's position is
``crc32(seed || tenant/request_id) % 10_000`` (basis points), and the
tenant's ramp table is a walk over ``[0, 10_000)`` — each entry claims a
contiguous slice, the remainder falls to the tenant's default variant.
Ramp changes move only the boundary: raising a variant 1% -> 50% keeps
every request it already served on it (their positions are < the old
boundary, hence < the new one), which is exactly what a rollout wants.
"""

from __future__ import annotations

import threading
import zlib
from collections import Counter
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from photon_ml_tpu.serving.tenancy.variants import BASE_VARIANT

_BASIS = 10_000  # ramp resolution: basis points (0.01%)


class VariantRouter:
    """Maps ``(tenant, request_id) -> variant_id``, deterministically.

    ``default_variant`` serves every unramped request. Per-tenant ramps
    are set with :meth:`set_ramp` (and ``tenant=None`` sets the global
    ramp used by tenants without their own); :meth:`pin` short-circuits a
    tenant entirely (0%/100% holdouts, internal canary tenants)."""

    def __init__(
        self, default_variant: str = BASE_VARIANT, seed: int = 0
    ):
        self.default_variant = default_variant
        self.seed = int(seed)
        self._lock = threading.Lock()
        # tenant (None = global) -> [(variant_id, basis_points), ...]
        self._ramps: Dict[Optional[str], List[Tuple[str, int]]] = {}
        self._pins: Dict[str, str] = {}
        self.decisions: Dict[str, int] = {}

    # -------------------------------------------------------------- control

    def set_ramp(
        self,
        variant_id: str,
        percent: float,
        tenant: Optional[str] = None,
    ) -> None:
        """Route ``percent`` (0..100) of the tenant's traffic to
        ``variant_id`` (``tenant=None`` -> all tenants without their own
        ramp). Hot: takes effect on the next routed request; other
        variants' ramp slices and all pins are untouched."""
        if not 0.0 <= percent <= 100.0:
            raise ValueError(f"ramp percent must be in [0, 100], got {percent}")
        bp = int(round(percent * _BASIS / 100.0))
        with self._lock:
            ramp = [
                (v, b)
                for v, b in self._ramps.get(tenant, [])
                if v != variant_id
            ]
            if bp > 0:
                ramp.append((variant_id, bp))
            total = sum(b for _, b in ramp)
            if total > _BASIS:
                raise ValueError(
                    f"ramp shares for tenant {tenant!r} sum to "
                    f"{total / _BASIS:.1%} > 100%"
                )
            if ramp:
                self._ramps[tenant] = ramp
            else:
                self._ramps.pop(tenant, None)

    def clear_ramp(self, tenant: Optional[str] = None) -> None:
        with self._lock:
            self._ramps.pop(tenant, None)

    def pin(self, tenant: str, variant_id: Optional[str]) -> None:
        """Pin every request of ``tenant`` to one variant (``None``
        unpins)."""
        with self._lock:
            if variant_id is None:
                self._pins.pop(tenant, None)
            else:
                self._pins[tenant] = variant_id

    # -------------------------------------------------------------- routing

    def position(self, tenant: Optional[str], request_id: str) -> int:
        """The request's stable position in ``[0, 10_000)`` basis points.
        Seeded so distinct deployments (or reshuffles) get independent
        bucketings of the same ids."""
        key = f"{self.seed}|{tenant or ''}/{request_id}"
        return zlib.crc32(key.encode("utf-8")) % _BASIS

    def route(self, tenant: Optional[str], request_id: str) -> str:
        # lock-free read path (this is per-request): set_ramp/pin replace
        # whole list/dict values, so a concurrent reader sees either the
        # old or the new ramp atomically; the decision counter tolerates
        # benign races (it is reporting, not control flow)
        pinned = self._pins.get(tenant) if tenant is not None else None
        if pinned is not None:
            choice = pinned
        else:
            ramp = self._ramps.get(tenant)
            if ramp is None:
                ramp = self._ramps.get(None, ())
            choice = self.default_variant
            if ramp:
                pos = self.position(tenant, request_id)
                lo = 0
                for variant_id, bp in ramp:
                    if lo <= pos < lo + bp:
                        choice = variant_id
                        break
                    lo += bp
        self.decisions[choice] = self.decisions.get(choice, 0) + 1
        return choice

    def route_many(
        self, tenant: Optional[str], request_ids: Sequence[str]
    ) -> List[str]:
        """Bulk :meth:`route` for one tenant's request run — identical
        decisions (same positions, same boundary walk), but the hash runs
        in a generator feeding one vectorized boundary lookup instead of
        one Python frame per request. This is the replay hot path: per
        request it costs ~1 crc32 + 2 array ops, not a method call."""
        pinned = self._pins.get(tenant) if tenant is not None else None
        if pinned is not None:
            choices = [pinned] * len(request_ids)
        else:
            ramp = self._ramps.get(tenant)
            if ramp is None:
                ramp = self._ramps.get(None, ())
            if not ramp:
                choices = [self.default_variant] * len(request_ids)
            else:
                # crc32(prefix + rid) == crc32(rid, crc32(prefix)): chain
                # from the precomputed prefix CRC so the per-request work
                # is one encode + one C call, no string concat — positions
                # are bitwise identical to route()'s
                crc = zlib.crc32
                prefix_crc = crc(
                    f"{self.seed}|{tenant or ''}/".encode("utf-8")
                )
                positions = (
                    np.fromiter(
                        (
                            crc(rid.encode("utf-8"), prefix_crc)
                            for rid in request_ids
                        ),
                        dtype=np.int64,
                        count=len(request_ids),
                    )
                    % _BASIS
                )
                # searchsorted over the cumulative slice bounds reproduces
                # route()'s walk: pos < bounds[0] -> ramp[0], pos past the
                # last bound -> the default variant
                bounds = np.cumsum([bp for _, bp in ramp])
                names = [v for v, _ in ramp] + [self.default_variant]
                choices = [
                    names[i]
                    for i in np.searchsorted(bounds, positions, side="right")
                ]
        for variant_id, n in Counter(choices).items():
            self.decisions[variant_id] = (
                self.decisions.get(variant_id, 0) + n
            )
        return choices

    # ------------------------------------------------------------ reporting

    def shares(self) -> Dict[str, float]:
        """Observed routed-traffic share per variant (decision counts)."""
        with self._lock:
            total = sum(self.decisions.values())
            if not total:
                return {}
            return {
                v: n / total for v, n in sorted(self.decisions.items())
            }

    def status(self) -> Dict[str, object]:
        with self._lock:
            return {
                "default_variant": self.default_variant,
                "seed": self.seed,
                "ramps": {
                    ("*" if t is None else t): {
                        v: bp / _BASIS * 100.0 for v, bp in ramp
                    }
                    for t, ramp in sorted(
                        self._ramps.items(), key=lambda kv: kv[0] or ""
                    )
                },
                "pins": dict(sorted(self._pins.items())),
                "decisions": dict(sorted(self.decisions.items())),
            }
