"""Tenancy plane: quota -> router -> per-variant batchers over ONE scorer.

Ties the tenancy pieces into a serving path:

    request --(quota admit/shed)--> router --> variant's MicroBatcher
                                                  \\-> shared sharded scorer
                                                      (variant view per batch)

One sealed :class:`~photon_ml_tpu.serving.batcher.MicroBatcher` per
variant — a batch is scored under exactly one variant view, so buckets
never mix views (a view applies per batch) and the plain base variant
still takes the bitwise ``view=None`` path. Batchers share the one
``ServingMetrics``/:class:`~photon_ml_tpu.serving.requestplane.RequestPlane`,
so stage attribution, sealed-batch records, and the per-tenant SLO feed
come for free from the existing request plane.

Tenant identity travels IN the request id (``"<tenant>!<rid>"`` —
:data:`~photon_ml_tpu.serving.requestplane.TENANT_SEP`), so nothing
between admission and SLO attribution needs a new per-request field.
Quota sheds are charged to the shedding tenant's own error budget and
never reach the scorer, the global SLO, or any other tenant's budget.
"""

from __future__ import annotations

import dataclasses
import os
import threading
import time
from collections import Counter
from typing import Dict, List, Optional, Sequence

import numpy as np

from photon_ml_tpu.incremental.delta import (
    build_delta,
    delta_dir_name,
    save_delta,
)
from photon_ml_tpu.serving.batcher import DEFAULT_BUCKET_SIZES, MicroBatcher
from photon_ml_tpu.serving.requestplane import (
    TENANT_SEP,
    tenant_of_request_id,
)
from photon_ml_tpu.serving.scorer import ScoreRequest, ScoreResult
from photon_ml_tpu.serving.slo import SLOTracker
from photon_ml_tpu.serving.tenancy.quota import TenantQuota
from photon_ml_tpu.serving.tenancy.router import VariantRouter
from photon_ml_tpu.serving.tenancy.variants import VariantRegistry


def tag_request(request: ScoreRequest, tenant: str) -> ScoreRequest:
    """Return the request re-identified as ``tenant``'s (id prefixed)."""
    if TENANT_SEP in tenant:
        raise ValueError(
            f"tenant name {tenant!r} must not contain {TENANT_SEP!r}"
        )
    return dataclasses.replace(
        request, request_id=f"{tenant}{TENANT_SEP}{request.request_id}"
    )


def tag_requests(
    requests: Sequence[ScoreRequest], tenant: str
) -> List[ScoreRequest]:
    return [tag_request(r, tenant) for r in requests]


def build_tenant_slos(
    tenants: Sequence[str],
    registry=None,
    latency_threshold_s: float = 0.050,
    latency_objective: float = 0.99,
    availability_objective: float = 0.999,
    window_s: float = 300.0,
    clock=time.monotonic,
) -> Dict[str, SLOTracker]:
    """One independent SLO tracker (own error budget) per tenant. With a
    metrics ``registry``, each tracker writes its ``serving.slo.*`` gauges
    under a ``tenant="<t>"`` label scope — separate Prometheus series per
    tenant in ``/metrics``."""
    slos: Dict[str, SLOTracker] = {}
    for tenant in tenants:
        scoped = (
            registry.scoped({"tenant": tenant})
            if registry is not None
            else None
        )
        slos[tenant] = SLOTracker(
            latency_threshold_s=latency_threshold_s,
            latency_objective=latency_objective,
            availability_objective=availability_objective,
            window_s=window_s,
            clock=clock,
            registry=scoped,
        )
    return slos


class TenancyPlane:
    """The multi-tenant serving front: admit, route, batch per variant.

    ``plane`` is the shared ``RequestPlane`` (carry ``tenant_slos`` for
    per-tenant budgets); ``quota``/``router`` are optional — without a
    quota everything admits, without a router everything serves the base
    variant. ``metrics_registry`` adds per-tenant request/shed counters
    under tenant label scopes.

    ``quota_mode`` picks WHERE the token bucket is consulted:
    ``"submit"`` (default, the historical behavior) sheds at the plane's
    front door, before routing; ``"drain"`` admits everything into the
    per-variant batchers and lets each batcher consult the quota as
    buckets seal — an over-budget tenant's requests then drop out of the
    padded bucket at the last moment (charged to that tenant via the
    plane) instead of being rejected while device slots sit idle."""

    def __init__(
        self,
        registry: VariantRegistry,
        router: Optional[VariantRouter] = None,
        plane=None,
        quota: Optional[TenantQuota] = None,
        metrics=None,
        bucket_sizes: Sequence[int] = DEFAULT_BUCKET_SIZES,
        max_wait_s: float = 0.002,
        default_tenant: str = "default",
        metrics_registry=None,
        quota_mode: str = "submit",
    ):
        if quota_mode not in ("submit", "drain"):
            raise ValueError(
                f"quota_mode must be 'submit' or 'drain', got {quota_mode!r}"
            )
        self.registry = registry
        self.router = router if router is not None else VariantRouter()
        self.plane = plane
        self.quota = quota
        self.quota_mode = quota_mode
        self._metrics = metrics
        self._bucket_sizes = tuple(bucket_sizes)
        self._max_wait_s = max_wait_s
        self.default_tenant = default_tenant
        self._metrics_registry = metrics_registry
        self._tenant_scopes: Dict[str, object] = {}
        self._batchers: Dict[str, MicroBatcher] = {}
        self._lock = threading.RLock()
        self.tenant_submitted: Dict[str, int] = {}
        self.tenant_shed: Dict[str, int] = {}

    # ------------------------------------------------------------- plumbing

    def _batcher(self, variant_id: str) -> MicroBatcher:
        b = self._batchers.get(variant_id)
        if b is None:
            with self._lock:
                b = self._batchers.get(variant_id)
                if b is None:
                    b = MicroBatcher(
                        self.registry.scorer(variant_id),
                        bucket_sizes=self._bucket_sizes,
                        metrics=self._metrics,
                        max_wait_s=self._max_wait_s,
                        plane=self.plane,
                        quota=(
                            self.quota
                            if self.quota_mode == "drain"
                            else None
                        ),
                    )
                    self._batchers[variant_id] = b
        return b

    def _scope(self, tenant: str):
        reg = self._metrics_registry
        if reg is None:
            return None
        scope = self._tenant_scopes.get(tenant)
        if scope is None:
            scope = reg.scoped({"tenant": tenant})
            self._tenant_scopes[tenant] = scope
        return scope

    # ------------------------------------------------------------ the plane

    def submit(self, request: ScoreRequest) -> List[ScoreResult]:
        """Admit -> route -> enqueue one (already tenant-tagged) request.
        Returns any results a full bucket completed; shed requests return
        nothing and are charged to the shedding tenant's error budget."""
        tenant = tenant_of_request_id(request.request_id)
        if tenant is None:
            tenant = self.default_tenant
        self.tenant_submitted[tenant] = (
            self.tenant_submitted.get(tenant, 0) + 1
        )
        if (
            self.quota is not None
            and self.quota_mode == "submit"
            and not self.quota.try_admit(tenant)
        ):
            self.tenant_shed[tenant] = self.tenant_shed.get(tenant, 0) + 1
            if self.plane is not None:
                self.plane.observe_tenant_errors(tenant, 1)
            return []
        variant = self.router.route(tenant, request.request_id)
        return self._batcher(variant).submit(request)

    def poll(self, now: Optional[float] = None) -> List[ScoreResult]:
        out: List[ScoreResult] = []
        for b in list(self._batchers.values()):
            out.extend(b.poll(now))
        return out

    def flush(self) -> List[ScoreResult]:
        out: List[ScoreResult] = []
        for b in list(self._batchers.values()):
            out.extend(b.flush())
        return out

    def _submit_chunk(
        self, requests: Sequence[ScoreRequest]
    ) -> List[ScoreResult]:
        """Bulk :meth:`submit` for a run of requests — same admit/route/
        enqueue decisions, amortized Python: tenant parse and counters run
        as comprehensions, routing goes through ``route_many``, and each
        variant's batcher gets its whole sub-run in one ``submit_many``.
        The per-request quota walk survives only when a quota is
        installed (token buckets are order-dependent)."""
        sep, default = TENANT_SEP, self.default_tenant
        tenants = [
            rid.split(sep, 1)[0] if sep in rid else default
            for rid in (r.request_id for r in requests)
        ]
        submitted = self.tenant_submitted
        for tenant, n in Counter(tenants).items():
            submitted[tenant] = submitted.get(tenant, 0) + n
        quota = self.quota if self.quota_mode == "submit" else None
        if quota is not None:
            kept: List[ScoreRequest] = []
            kept_tenants: List[str] = []
            for request, tenant in zip(requests, tenants):
                if quota.try_admit(tenant):
                    kept.append(request)
                    kept_tenants.append(tenant)
                else:
                    self.tenant_shed[tenant] = (
                        self.tenant_shed.get(tenant, 0) + 1
                    )
                    if self.plane is not None:
                        self.plane.observe_tenant_errors(tenant, 1)
            requests, tenants = kept, kept_tenants
        by_tenant: Dict[str, List[ScoreRequest]] = {}
        for request, tenant in zip(requests, tenants):
            by_tenant.setdefault(tenant, []).append(request)
        by_variant: Dict[str, List[ScoreRequest]] = {}
        route_many = self.router.route_many
        for tenant, run in by_tenant.items():
            choices = route_many(tenant, [r.request_id for r in run])
            for request, variant_id in zip(run, choices):
                by_variant.setdefault(variant_id, []).append(request)
        out: List[ScoreResult] = []
        for variant_id, run in by_variant.items():
            out.extend(self._batcher(variant_id).submit_many(run))
        return out

    def replay(
        self,
        requests: Sequence[ScoreRequest],
        poll_every: int = 64,
    ) -> List[ScoreResult]:
        """Drive a pre-tagged request stream through the plane (the
        scenario harness's per-phase engine): deadline-poll all variants'
        batchers every ``poll_every`` submissions so a variant at 1% ramp
        is not starved waiting for a full bucket, final flush drains the
        rest (``poll_every=0`` = sealed, full buckets only). Per-tenant
        counters land in the metrics registry once per call, not per
        request."""
        results: List[ScoreResult] = []
        chunk = poll_every if poll_every else len(requests) or 1
        for start in range(0, len(requests), chunk):
            results.extend(
                self._submit_chunk(requests[start:start + chunk])
            )
            if poll_every:
                results.extend(self.poll())
        results.extend(self.flush())
        if self._metrics_registry is not None:
            for tenant, n in list(self.tenant_submitted.items()):
                scope = self._scope(tenant)
                shed = self.tenant_shed.get(tenant, 0)
                scope.count("serving.tenant.requests", n)
                if shed:
                    scope.count("serving.tenant.shed", shed)
            self.tenant_submitted = {}
            self.tenant_shed = {}
        return results

    # ------------------------------------------------------------ reporting

    def status(self) -> Dict[str, object]:
        doc: Dict[str, object] = {
            "variants": self.registry.stats(),
            "router": self.router.status(),
        }
        if self.quota is not None:
            doc["quota"] = self.quota.stats()
        if self.plane is not None and self.plane.tenant_slos:
            doc["tenants"] = {
                tenant: {
                    "requests": self.plane.tenant_requests.get(tenant, 0),
                    "errors": self.plane.tenant_errors.get(tenant, 0),
                    "slo": slo.status(),
                }
                for tenant, slo in sorted(self.plane.tenant_slos.items())
            }
        return doc


def make_nearline_fn(
    registry: VariantRegistry,
    variant_ids: Sequence[str],
    entity_pool: Dict[str, Sequence[str]],
    rows_per_delta: int = 8,
    scale: float = 0.01,
    seed: int = 0,
    watch_dir: Optional[str] = None,
):
    """A synthetic nearline trainer loop body for the ``nearline_loop``
    scenario: each call emits one generation of per-variant deltas —
    sampled sparse row updates for entities from ``entity_pool[cid]``,
    chained to each variant's CURRENT fingerprint head — and hot-swaps
    them into the serving registry while traffic flows. With
    ``watch_dir``, deltas take the full production path: saved to
    ``watch_dir/<variant>/delta-NNNNNN`` (atomic publish), then picked up
    by ``poll_directory`` (discover -> load -> chain-check -> apply);
    without it, they apply in-memory."""
    rng = np.random.default_rng(seed + 1013)
    generations: Dict[str, int] = {v: 0 for v in variant_ids}
    lead = registry.lead

    def _tick() -> List[object]:
        reports: List[object] = []
        for vid in variant_ids:
            state = registry.state(vid)
            artifact = state.artifact if state.diverged else lead.artifact
            re_updates: Dict[str, Dict[str, Dict[int, float]]] = {}
            for cid, pool in entity_pool.items():
                k = min(rows_per_delta, len(pool))
                picks = rng.choice(len(pool), size=k, replace=False)
                dim = artifact.tables[cid].dim
                per_entity: Dict[str, Dict[int, float]] = {}
                for p in picks:
                    nz = rng.integers(0, dim, size=min(4, dim))
                    per_entity[str(pool[int(p)])] = {
                        int(i): float(v)
                        for i, v in zip(
                            nz, rng.normal(0.0, scale, size=nz.size)
                        )
                    }
                re_updates[cid] = per_entity
            generations[vid] += 1
            delta = build_delta(
                re_updates,
                artifact,
                base_fingerprint=state.fingerprint,
                generation=generations[vid],
            )
            if watch_dir is not None:
                vdir = os.path.join(watch_dir, vid)
                os.makedirs(vdir, exist_ok=True)
                save_delta(
                    delta,
                    os.path.join(vdir, delta_dir_name(generations[vid])),
                )
                reports.extend(registry.poll_directory(vid, vdir))
            else:
                reports.append(registry.apply_delta(vid, delta))
        return reports

    return _tick
